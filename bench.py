"""Benchmarks: MNIST MLP + LeNet + wide-conv + char-LSTM + Word2Vec
(BASELINE configs #1/#2/#4 plus MXU-fill diagnostics) + the composed
transformer-LM flagship (lm_composed: multi-block, blockwise flash core via
the DL4J_TPU_ATTN_IMPL seam, with forced-dense and forced-CPU twins).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "detail"}.

- value: steady-state bf16 training samples/sec/chip for the MLP on the
  default platform (the real TPU chip under the driver).
- vs_baseline: ratio vs the same fp32 training step measured in a CPU
  subprocess — the stand-in for the reference's nd4j-native CPU backend
  (the reference publishes no numbers, BASELINE.md; its jblas CPU path is
  the comparison point named in BASELINE.json's north star, target >=5x).
- detail: per-precision throughput and MFU for each model, plus word2vec
  words/sec on TPU and CPU.

Precision honesty (round 4): on TPU v5e XLA's DEFAULT matmul precision
executes float32-input matmuls as a SINGLE bf16 MXU pass — measured on this
chip with tools/probe_matmul_precision.py (4096^3 matmul): bf16 185.7 TF/s,
fp32-DEFAULT 153.5 TF/s, fp32-HIGH 59.5 (bf16x3), fp32-HIGHEST 29.7 (bf16x6).
So the former "fp32" stage was never true fp32 — that is why round 3 saw
bf16 <= "fp32". Stages are now labeled by what actually runs:

  *_bf16      bf16 operands, 1 MXU pass          MFU vs 197 TF/s
  *_fp32      fp32 operands, DEFAULT precision   MFU vs 197 TF/s
              (1 bf16 MXU pass; extra HBM traffic only)
  *_fp32_true fp32 operands, HIGHEST precision   MFU vs 197/6 TF/s
              (bf16x6 passes ~ true fp32 accuracy)

Each precision's MFU is computed against ITS OWN achievable peak (fixes the
round-3 bench dividing everything by the bf16 peak).

Round-3 structure (fixes the round-2 rc=124 timeout): every stage runs in
its OWN subprocess with a hard timeout under a global deadline
(BENCH_BUDGET_SEC; default = sum of per-stage caps + 60 so no stage is
budget-starved by default), so one wedged compile can never forfeit the
whole bench. Stage results are flushed incrementally to bench_partial.json;
the summary line is printed even when later stages are skipped (marked
"skipped_budget") and the CPU baseline failure is loud (error text lands in
detail + stderr), never a silent 0.0.
"""

from __future__ import annotations

import json
import math
import os
import statistics
import subprocess
import sys
import time

BATCH = 512
WARMUP_CHUNKS = 2
# steps fused into ONE scan program per dispatch: through the axon tunnel a
# dispatch can cost several ms, so 20-step chunks were dispatch-bound (round-2
# instability); 200 steps amortize it to noise at ~0.2 ms/step device time
CHUNK = 200
HID1, HID2 = 500, 300

REPO = os.path.dirname(os.path.abspath(__file__))
PARTIAL_PATH = os.path.join(REPO, "bench_partial.json")

# TPU v5e (v5 lite) peak bf16 matmul throughput per chip. fp32-DEFAULT runs
# the same single-bf16-pass MXU path (see module docstring measurements);
# HIGHEST precision is 6 chained bf16 passes, so its achievable peak is /6.
PEAK_BF16_FLOPS = 197e12
PRECISION_PEAKS = {
    "bf16": PEAK_BF16_FLOPS,
    "fp32": PEAK_BF16_FLOPS,          # 1 bf16 MXU pass (DEFAULT precision)
    "fp32_true": PEAK_BF16_FLOPS / 6,  # bf16x6 (HIGHEST precision)
}

# Analytic model FLOPs per training sample (fwd matmul/conv FLOPs x3 for
# fwd + both backward matmuls; elementwise ops are bandwidth, not FLOP,
# bound and excluded — standard MFU accounting).
#
# ISSUE 9: the tables are PARAMETRIC formulas (``MODEL_FLOPS``), not baked
# constants — tests/test_xprofile.py cross-checks every formula against the
# XLA ``cost_analysis()`` FLOPs of the exact compiled train step (via
# telemetry/xprofile.py) at CPU-sized shapes, so a model edit that changes
# the FLOP content without updating the formula fails tier-1 instead of
# silently rotting the MFU numbers. ``TRAIN_FLOPS`` below evaluates the
# same formulas at the registered bench shapes.

LSTM_VOCAB = 128
LSTM_SEQ = 64
# WIDE char-LSTM (round 5): hidden 512 = 4 MXU tiles per gate — shows what
# the scan+pallas path does when shapes fill the unit (the 128-hidden stage
# is exactly one tile, VERDICT r04 weak #3). The *_nokernels twin runs the
# IDENTICAL stage with the pallas fused-gate + fused-dense kernels forced
# off, so the kernels' contribution is a measured delta, not a claim.
LSTM_WIDE_HID = 512
ATTN_VOCAB, ATTN_D, ATTN_SEQ = 128, 256, 64
# LONG-context causal LM (round-5 flagship): T=2048, d_model=512, 4 heads
# (head_dim 128 = one MXU lane tile). Same analytic form as the short stage.
ATTN_LONG_VOCAB, ATTN_LONG_D, ATTN_LONG_SEQ, ATTN_LONG_HEADS = 128, 512, 2048, 4
# COMPOSED-flagship LM (round 6): the multi-block transformer LM
# (models/transformer_lm.py) trained END TO END on one chip, attention core
# selected through the DL4J_TPU_ATTN_IMPL env seam.
LMC_VOCAB, LMC_D, LMC_HEADS, LMC_EXPERTS, LMC_DFF = 2048, 512, 4, 4, 1024
LMC_LAYERS, LMC_SEQ, LMC_BATCH = 2, 2048, 4


def mlp_fwd_flops(hid1: int = HID1, hid2: int = HID2) -> int:
    return 2 * (784 * hid1 + hid1 * hid2 + hid2 * 10)


def lenet_fwd_flops() -> int:
    """conv1 24^2x6x(5^2x1), conv2 8^2x16x(5^2x6), dense 256x120, 120x84,
    84x10 (fixed architecture — models/zoo.lenet takes no shape knobs)."""
    return 2 * (24 * 24 * 6 * 25 + 8 * 8 * 16 * 150
                + 256 * 120 + 120 * 84 + 84 * 10)


def conv_wide_fwd_flops() -> int:
    """conv_wide (models/zoo.py): conv1 28^2x128x(5^2x32), conv2
    10^2x128x(5^2x128), dense 3200x256, 256x10 — contractions 800/3200
    wide, 128 output channels (fixed architecture)."""
    return 2 * (28 * 28 * 128 * (25 * 32) + 10 * 10 * 128 * (25 * 128)
                + 3200 * 256 + 256 * 10)


def lstm_fwd_flops(hidden: int = LSTM_VOCAB, seq: int = LSTM_SEQ) -> int:
    """char-LSTM (hidden = vocab): per timestep the fused-gate matmul
    (1 + vocab + hidden) x 4*hidden plus the decoder hidden x vocab."""
    return seq * 2 * ((1 + hidden + hidden) * 4 * hidden + hidden * hidden)


def attn_fwd_flops(vocab: int = ATTN_VOCAB, d: int = ATTN_D,
                   seq: int = ATTN_SEQ) -> int:
    """causal attention char-LM (models/zoo.py char_attention_lm): per
    sample the embedding + qkv/out projections + decoder (matmul term) and
    the T^2 d score/value einsums (attention term).

    NOTE on accounting: the 4·T²·d attention term counts the FULL score
    rectangle; the blockwise core actually executes only the causal half
    (static block skip), and its flash-style backward recomputes block
    scores (7 attention matmuls vs the 4 the ×3 train factor assumes) —
    the two conventions roughly cancel, and this matches the r04 attn
    stage. ``attn_long`` evaluates the SAME formula at its shapes."""
    return 2 * seq * (2 * vocab * d + 4 * d * d) + 4 * seq * seq * d


def lmc_fwd_flops(vocab: int = LMC_VOCAB, d: int = LMC_D,
                  experts: int = LMC_EXPERTS, dff: int = LMC_DFF,
                  layers: int = LMC_LAYERS, seq: int = LMC_SEQ) -> int:
    """Composed-flagship LM FLOPs per sample: per layer the q/k/v/o
    projections, the FULL T² score rectangle (same convention as
    ``attn_fwd_flops`` — the blockwise core executes only the causal half
    but its backward recomputes block scores, the two roughly cancel),
    the router matmul, and dense_moe which runs ALL E experts on every
    token (that is what executes on one chip — the expert-parallel
    capacity path needs the mesh); plus the vocab decoder."""
    return layers * (
        2 * seq * 4 * d * d
        + 4 * seq * seq * d
        + 2 * seq * d * experts
        + experts * 2 * seq * 2 * d * dff
    ) + 2 * seq * d * vocab


def lmc_xla_flops_expectation(vocab: int, d: int, experts: int, dff: int,
                              seq: int, batch: int) -> int:
    """What XLA ``cost_analysis()`` should report for the compiled
    composed-LM TRAIN step: the layer stack runs as a ``lax.scan`` whose
    body XLA's cost model counts ONCE regardless of trip count (the
    convention documented in telemetry/xprofile.py and pinned in
    tests/test_xprofile.py), so the expectation is 3× the SINGLE-layer
    forward formula — independent of n_layers — times the batch. The MFU
    tables (``TRAIN_FLOPS``) still use the true per-sample count; the
    profile blobs record both numbers so the ratio is interpretable."""
    return 3 * lmc_fwd_flops(vocab, d, experts, dff, 1, seq) * batch


# model → parametric fwd-FLOPs formula (the cross-check surface; stage
# "conv_wide_*" → model "conv", lstm_wide/attn_long share their family's
# formula at different shapes)
MODEL_FLOPS = {
    "mlp": mlp_fwd_flops,
    "lenet": lenet_fwd_flops,
    "conv": conv_wide_fwd_flops,
    "lstm": lstm_fwd_flops,
    "lstm_wide": lstm_fwd_flops,
    "attn": attn_fwd_flops,
    "attn_long": attn_fwd_flops,
    "lm_composed": lmc_fwd_flops,
}
TRAIN_FLOPS = {
    "mlp": 3 * mlp_fwd_flops(),
    "lenet": 3 * lenet_fwd_flops(),
    "conv": 3 * conv_wide_fwd_flops(),
    "lstm": 3 * lstm_fwd_flops(),
    "lstm_wide": 3 * lstm_fwd_flops(LSTM_WIDE_HID),
    "attn": 3 * attn_fwd_flops(),
    "attn_long": 3 * attn_fwd_flops(ATTN_LONG_VOCAB, ATTN_LONG_D,
                                    ATTN_LONG_SEQ),
    "lm_composed": 3 * lmc_fwd_flops(),
}

# Per-model batch/chunk: the wide conv's im2col buffers and the LSTM's
# one-hot sequences are far bigger per sample than the MLP's 784 floats.
MODEL_BATCH = {"mlp": BATCH, "lenet": BATCH, "conv": 64, "lstm": 256,
               "lstm_wide": 64, "attn": 256, "attn_long": 4}
MODEL_CHUNK = {"mlp": CHUNK, "lenet": CHUNK, "conv": 32, "lstm": 16,
               "lstm_wide": 8, "attn": 16, "attn_long": 4}


def _time_of(fn) -> float:
    import jax

    t0 = time.perf_counter()
    jax.block_until_ready(fn())
    return time.perf_counter() - t0


def _conf(model: str):
    from deeplearning4j_tpu.models.zoo import (
        char_attention_lm,
        char_lstm,
        conv_wide,
        lenet,
        mnist_mlp,
    )

    if model == "mlp":
        return mnist_mlp(HID1, HID2)
    if model == "lenet":
        return lenet()
    if model == "conv":
        return conv_wide()
    if model == "lstm":
        return char_lstm(vocab=LSTM_VOCAB)
    if model == "lstm_wide":
        return char_lstm(vocab=LSTM_WIDE_HID)
    if model == "attn":
        return char_attention_lm(vocab=ATTN_VOCAB, d_model=ATTN_D,
                                 n_heads=8, num_iterations=1)
    if model == "attn_long":
        return char_attention_lm(vocab=ATTN_LONG_VOCAB, d_model=ATTN_LONG_D,
                                 n_heads=ATTN_LONG_HEADS, num_iterations=1)
    raise ValueError(model)


def _make_data(model: str, chunk: int, batch: int):
    """(xs, ys) shaped (chunk, batch, ...) for one scan dispatch."""
    import jax
    import jax.numpy as jnp

    if model in ("mlp", "lenet"):
        from deeplearning4j_tpu.datasets.fetchers import synthetic_mnist

        xs_np, ys_np = synthetic_mnist(batch * chunk)
        xs = jnp.asarray(xs_np).reshape(chunk, batch, -1)
        ys = jax.nn.one_hot(jnp.asarray(ys_np), 10, dtype=jnp.float32).reshape(
            chunk, batch, -1
        )
        return xs, ys
    if model == "conv":
        xs = jax.random.normal(
            jax.random.PRNGKey(2), (chunk, batch, 32, 32, 32), jnp.float32
        )
        ys = jax.nn.one_hot(
            jax.random.randint(jax.random.PRNGKey(3), (chunk, batch), 0, 10),
            10, dtype=jnp.float32,
        )
        return xs, ys
    if model in ("lstm", "lstm_wide"):
        vocab = LSTM_VOCAB if model == "lstm" else LSTM_WIDE_HID
        toks = jax.random.randint(
            jax.random.PRNGKey(2), (chunk, batch, LSTM_SEQ + 1), 0, vocab
        )
        xs = jax.nn.one_hot(toks[..., :-1], vocab, dtype=jnp.float32)
        ys = jax.nn.one_hot(toks[..., 1:], vocab, dtype=jnp.float32)
        return xs, ys
    if model in ("attn", "attn_long"):
        seq = ATTN_SEQ if model == "attn" else ATTN_LONG_SEQ
        vocab = ATTN_VOCAB if model == "attn" else ATTN_LONG_VOCAB
        toks = jax.random.randint(
            jax.random.PRNGKey(2), (chunk, batch, seq + 1), 0, vocab
        )
        xs = jax.nn.one_hot(toks[..., :-1], vocab, dtype=jnp.float32)
        ys = jax.nn.one_hot(toks[..., 1:], vocab, dtype=jnp.float32)
        return xs, ys
    raise ValueError(model)


def measure(model: str = "mlp", precision: str = "fp32",
            steps: int | None = None, batch: int | None = None,
            chunk: int | None = None) -> float:
    """Steady-state training samples/sec with the step loop kept ON DEVICE:
    `chunk` steps run as one lax.scan program per dispatch.

    Timing discipline (round-3 fix): on the axon platform
    ``jax.block_until_ready`` returns at ENQUEUE, not completion — the only
    true sync is a device->host fetch, which carries the tunnel's ~90-150 ms
    round-trip latency (measured jitter ±30 ms); a fresh host->device
    transfer inside the loop bills another ~20 ms per dispatch. Rounds 1/2
    timed enqueue rates (hence the absurd 17M-samples/s swings). Protocol
    here: all arguments staged on device first, run length DOUBLED until one
    timed run holds >=1.2 s of work (dwarfing the jitter), then
    rate = work / (median run wall - measured fetch latency) over 3 runs.

    ``precision``: "bf16" (mixed-precision policy), "fp32" (DEFAULT matmul
    precision — a single bf16 MXU pass, see module docstring), or
    "fp32_true" (HIGHEST — bf16x6 passes, true-fp32 accuracy; the caller
    must set jax_default_matmul_precision='highest' BEFORE tracing, which
    run_stage does in the stage subprocess).
    """
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn import functional as F
    from deeplearning4j_tpu.ops.dtypes import BF16_COMPUTE

    repeats = 3
    batch = batch if batch is not None else MODEL_BATCH[model]
    chunk = chunk if chunk is not None else MODEL_CHUNK[model]

    conf = _conf(model)
    policy = BF16_COMPUTE if precision == "bf16" else None
    params = F.init_params(conf, jax.random.PRNGKey(0))
    states = F.init_train_state(conf, params)
    epoch = F.make_train_epoch(conf, chunk, donate=True, policy=policy)

    x, y = _make_data(model, chunk, batch)
    key = jax.random.PRNGKey(1)

    # every argument device-resident BEFORE timing: a fresh host->device
    # transfer (e.g. a per-dispatch jnp.asarray(i)) costs ~20 ms through the
    # tunnel and would bill per dispatch, not per step
    iter0 = jnp.asarray(0)
    float(jnp.sum(x) + jnp.sum(y) + iter0)  # force + sync the transfers

    def run(k):
        nonlocal params, states
        t0 = time.perf_counter()
        for _ in range(k):
            params, states, scores = epoch(params, states, iter0, x, y, key)
        last = float(scores[-1])  # true sync: device->host fetch
        assert math.isfinite(last), "non-finite training score"
        return time.perf_counter() - t0

    for _ in range(WARMUP_CHUNKS):
        run(1)

    fetch_lat = statistics.median(
        _time_of(lambda: float(jnp.sum(iter0 + 1))) for _ in range(5)
    )

    # size the run by DOUBLING until its measured wall clears the target —
    # a single short probe is itself jitter-dominated through the tunnel,
    # so never trust one small sample to extrapolate
    target = 0.3 if _fast() else 1.2  # seconds of work per timed run
    k = max(steps // chunk, 1) if steps is not None else 4
    t = run(k)
    while t < target + fetch_lat and k < 256:
        k *= 2
        t = run(k)
    times = [t] + [run(k) for _ in range(repeats - 1)]
    t_med = statistics.median(times)
    # the doubling above guarantees t_med >> fetch_lat, so the subtraction
    # can never clamp into a fabricated rate
    return k * chunk * batch / max(t_med - fetch_lat, 0.2 * t_med)


def measure_moe() -> float:
    """A/B of the two MoE dispatch impls (parallel/moe.py) on a dp×ep mesh
    at G ∈ {1, 4} experts per device: one MoE layer (router + grouped
    expert FFNs, top-2, Switch aux) trained by a jitted SGD step, tokens/s
    per config plus an analytic per-device comm-volume estimate in the
    stage detail — the replicated path pays a dense (n_row, d) psum
    allreduce regardless of expert occupancy, the alltoall path pays the
    2×(E·C·d) capacity exchange. Headline value: alltoall tokens/s at G=4.

    Same timing discipline as ``measure``: device-staged args, measured
    fetch latency, run length doubled until a timed run dwarfs the tunnel
    jitter, median of 3."""
    from functools import partial

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from deeplearning4j_tpu.parallel.moe import (
        expected_dropped,
        load_balance_loss,
        moe_apply,
        route_shards,
    )

    repeats = 3
    if _fast():
        d, dff, n_tokens = 32, 64, 512
    else:
        d, dff, n_tokens = 512, 1024, 16384

    devs = jax.devices()
    n_use = min(len(devs), 8)
    ep = 2 if n_use >= 2 else 1
    dp = max(n_use // ep, 1)
    # top-2 is the flagship setting; a single-device run (ep=1, so the G=1
    # config has exactly one expert) can only route top-1
    top_k = 2 if ep >= 2 else 1
    mesh = Mesh(np.array(devs[: dp * ep]).reshape(dp, ep),
                ("data", "expert"))
    n_row = n_tokens // dp

    def expert_fn(p, t):
        return jax.nn.relu(t @ p["w1"] + p["b1"]) @ p["w2"] + p["b2"]

    key = jax.random.PRNGKey(0)
    x = jax.random.normal(jax.random.fold_in(key, 1), (n_tokens, d))
    tgt = jnp.tanh(jax.random.normal(jax.random.fold_in(key, 2),
                                     (n_tokens, d)))
    zero = jnp.asarray(0)
    float(jnp.sum(x) + jnp.sum(tgt) + zero)  # force + sync the transfers

    fetch_lat = statistics.median(
        _time_of(lambda: float(jnp.sum(zero + 1))) for _ in range(5)
    )
    target = 0.3 if _fast() else 1.2

    def bench_config(group: int, impl: str) -> dict:
        n_experts = group * ep
        # equal-E, equal capacity-FACTOR A/B (GShard factor 1.25): capacity
        # binds per (expert, sub-shard), so each impl gets the factor over
        # ITS routing unit — the whole token row for replicated, one
        # device's n_row/ep slice for alltoall. Same admitted global route
        # budget either way; the buffers just live where the tokens do.
        sub = n_row if impl == "replicated" else n_row // ep
        capacity = max(-(-int(1.25 * top_k * sub) // n_experts), 1)
        ks = jax.random.split(jax.random.fold_in(key, 10 + group), 2)
        router_w = jax.random.normal(ks[0], (d, n_experts)) / (d ** 0.5)
        ek = jax.random.split(ks[1], 4)
        experts = {
            "w1": jax.random.normal(ek[0], (n_experts, d, dff)) / (d ** 0.5),
            "b1": jnp.zeros((n_experts, dff)),
            "w2": jax.random.normal(ek[1], (n_experts, dff, d)) / (dff ** 0.5),
            "b2": jnp.zeros((n_experts, d)),
        }
        from deeplearning4j_tpu.parallel.sharding import shard_leading_axis

        experts = shard_leading_axis(experts, mesh, "expert")

        # the hot loop only rebinds the (router, experts) state, so the old
        # buffers donate into the update
        @partial(jax.jit, donate_argnums=(0,))
        def moe_step(state, xs, ys):
            rw, ps = state

            def loss_fn(rw, ps):
                out = moe_apply(rw, ps, xs, mesh, expert_fn, capacity,
                                top_k=top_k, token_axes=("data",), impl=impl)
                task = jnp.mean((out - ys) ** 2)
                return task + 1e-2 * load_balance_loss(rw, xs)

            loss, (gr, ge) = jax.value_and_grad(
                loss_fn, argnums=(0, 1))(rw, ps)
            new = (rw - 0.1 * gr,
                   jax.tree_util.tree_map(lambda p, g: p - 0.1 * g, ps, ge))
            return new, loss

        # drop stats on the INITIAL router (donation below retires the
        # original buffers; the init-time routing is the comparable stat)
        n_shards = route_shards(mesh, ("data",), "expert", n_tokens, impl)
        drop = expected_dropped(router_w, x, capacity, top_k,
                                n_shards=n_shards)

        state = (router_w, experts)
        for _ in range(2):  # compile + committed-sharding warmup
            state, loss = moe_step(state, x, tgt)
        float(loss)

        def run(k):
            nonlocal state
            t0 = time.perf_counter()
            for _ in range(k):
                state, loss = moe_step(state, x, tgt)
            last = float(loss)  # true sync: device->host fetch
            assert math.isfinite(last), "non-finite moe loss"
            return time.perf_counter() - t0

        k, t = 1, run(1)
        while t < target + fetch_lat and k < 256:
            k *= 2
            t = run(k)
        t_med = statistics.median([t] + [run(k) for _ in range(repeats - 1)])
        rate = k * n_tokens / max(t_med - fetch_lat, 0.2 * t_med)

        # analytic per-device FORWARD comm volume (backward transposes
        # mirror it); f32 = 4 bytes, ring-allreduce convention for psum
        if impl == "replicated":
            comm = 2 * (ep - 1) / ep * n_row * d * 4
        else:
            comm = 2 * (ep - 1) / ep * n_experts * capacity * d * 4
        return {
            "n_experts": n_experts,
            "capacity": capacity,
            "tokens_per_sec": round(rate, 1),
            "est_fwd_comm_bytes_per_dev": int(comm),
            "dropped_frac": round(drop / (n_tokens * top_k), 4),
        }

    detail = {
        "mesh": {"data": dp, "expert": ep},
        "d_model": d, "d_ff": dff, "tokens_per_step": n_tokens,
        "top_k": top_k,
        "comm_model": (
            "est_fwd_comm_bytes_per_dev: replicated = ring-allreduce of the "
            "dense (n_row, d) combine, 2(p-1)/p·n_row·d·4; alltoall = "
            "dispatch+return capacity exchange, 2(p-1)/p·E·C·d·4 — forward "
            "only, the backward transposes mirror the same volumes"
        ),
    }
    for group in (1, 4):
        for impl in ("alltoall", "replicated"):
            detail[f"{impl}_g{group}"] = bench_config(group, impl)
    for group in (1, 4):
        a2a = detail[f"alltoall_g{group}"]["tokens_per_sec"]
        rep = detail[f"replicated_g{group}"]["tokens_per_sec"]
        if rep:
            detail[f"alltoall_vs_replicated_g{group}"] = round(a2a / rep, 2)
    print("STAGE_DETAIL " + json.dumps(detail), flush=True)
    return detail["alltoall_g4"]["tokens_per_sec"]


def measure_word2vec(n_sentences: int = 2000, sent_len: int = 100,
                     vocab: int = 5000, layer_size: int = 100,
                     batch_size: int = 8192, mesh=None) -> float:
    """End-to-end Word2Vec skip-gram words/sec (BASELINE config #4): host
    tokenization + vectorized pair generation + device SGNS steps. Counted in
    corpus words per second, the reference's unit (Word2Vec.java:303-342).

    Two scales: the r01-r04 toy stage (V=5k, D=100, 200k words — small
    enough that post-round-5 the epoch is dispatch-latency-bound on BOTH
    platforms) and the `_large` stage (V=50k, D=256, 2M words) where
    compute dominates and the chip's advantage is visible.

    ``mesh``: a data-parallel mesh routes training through
    ``make_sharded_sgns_step`` (pair batches sharded over the data axis,
    in-graph psum over ICI) — the `word2vec_sharded` stage, the next lever
    the r05 bench note called out after the single-chip row-op work."""
    import numpy as np

    from deeplearning4j_tpu.models.word2vec import Word2Vec
    from deeplearning4j_tpu.text.sentence_iterator import (
        CollectionSentenceIterator,
    )

    rng = np.random.default_rng(0)
    # zipf-ish corpus so the unigram table and subsampling do real work
    words = np.array([f"w{i}" for i in range(vocab)])
    probs = 1.0 / np.arange(1, vocab + 1)
    probs /= probs.sum()
    ids = rng.choice(vocab, (n_sentences, sent_len), p=probs)
    sents = [" ".join(row) for row in words[ids]]
    vec = Word2Vec(
        sentence_iterator=CollectionSentenceIterator(sents),
        layer_size=layer_size, window=5, negative=5, iterations=1,
        sample=1e-3, batch_size=batch_size, seed=1, mesh=mesh,
    )
    vec.build_vocab()
    vec.fit()  # warmup: compiles the scan program (~25 s, one-time)
    t0 = time.perf_counter()
    vec.fit()
    # fence on the device-resident tables: fit() leaves the embeddings on
    # device (lazy host sync), so the clock must cover the actual training,
    # not its enqueue
    vec.block_until_ready()
    dt = time.perf_counter() - t0
    rate = n_sentences * sent_len / dt
    split = getattr(vec, "last_fit_timings", None)
    if split:
        print("W2V_SPLIT " + json.dumps(split), flush=True)
    return rate


TELEMETRY_INTERVAL = 10  # steps per device->host metrics fetch


def measure_lm_composed(steps: int | None = None,
                        batch: int | None = None,
                        telemetry: bool = True) -> float:
    """End-to-end training samples/sec of the COMPOSED-flagship LM: the
    multi-block (n_layers=2) transformer LM with causal MHA + top-2 MoE
    FFN, trained by models/transformer_lm.make_single_device_train_step.

    The attention core comes from the DL4J_TPU_ATTN_IMPL env seam —
    run_stage exports it BEFORE tracing ("blockwise" for the main stage and
    the forced-CPU baseline, "dense" for the _densecore A/B twin), so the
    A/B needs no code edits. Same timing discipline as ``measure``: warmup,
    measured fetch latency, run length doubled until a timed run dwarfs the
    tunnel jitter, median of 3.

    ``telemetry``: after the headline rate, A/B the metrics-threaded step
    (telemetry/) against the plain one — interleaved min-of-N runs at the
    same k, metrics fetched every TELEMETRY_INTERVAL steps — then run a
    short logged window through TrainTelemetry and report the step-log
    summary + measured overhead in the stage detail (the <5% budget is
    asserted by tests/test_bench_smoke.py)."""
    import tempfile

    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.transformer_lm import (
        init_lm_params,
        make_single_device_train_step,
        selected_attn_impl,
    )

    repeats = 3
    if _fast():
        vocab, d, heads, experts, dff = 256, 64, 2, 2, 128
        seq = 256
    else:
        vocab, d, heads, experts, dff = (LMC_VOCAB, LMC_D, LMC_HEADS,
                                         LMC_EXPERTS, LMC_DFF)
        seq = LMC_SEQ
    batch = batch if batch is not None else (2 if _fast() else LMC_BATCH)

    params = init_lm_params(jax.random.PRNGKey(0), vocab, d, heads, experts,
                            dff, n_layers=LMC_LAYERS)
    # the hot loop only ever rebinds params, so the step can donate the old
    # param buffers into the update (halves peak param HBM; the telemetry
    # A/B below builds its own non-donating steps and copies). profile=
    # (ISSUE 9) captures the compiled step's StepProfile at first call —
    # compile-time-only, the timed loop runs the same executable — so each
    # BENCH round embeds the cost/memory/collective blob profile_report.py
    # and bench_report.py diff across rounds.
    step = make_single_device_train_step(heads, donate=True,
                                         profile="lm_composed")
    toks = jax.random.randint(jax.random.PRNGKey(2), (batch, seq + 1), 0,
                              vocab)
    tk, tg = toks[:, :-1], toks[:, 1:]
    zero = jnp.asarray(0)
    float(jnp.sum(tk) + jnp.sum(tg) + zero)  # force + sync the transfers

    def run(k):
        nonlocal params
        t0 = time.perf_counter()
        for _ in range(k):
            params, loss = step(params, tk, tg)
        last = float(loss)  # true sync: device->host fetch
        assert math.isfinite(last), "non-finite lm_composed loss"
        return time.perf_counter() - t0

    for _ in range(2):
        run(1)  # compile + warmup

    fetch_lat = statistics.median(
        _time_of(lambda: float(jnp.sum(zero + 1))) for _ in range(5)
    )
    target = 0.3 if _fast() else 1.2
    k = max(steps, 1) if steps is not None else 1
    t = run(k)
    while t < target + fetch_lat and k < 256:
        k *= 2
        t = run(k)
    times = [t] + [run(k) for _ in range(repeats - 1)]
    t_med = statistics.median(times)
    rate = k * batch / max(t_med - fetch_lat, 0.2 * t_med)
    detail = {
        "tokens_per_sec": round(rate * seq, 1),
        "seq_len": seq, "n_layers": LMC_LAYERS,
        "attn_impl": os.environ.get("DL4J_TPU_ATTN_IMPL", "auto"),
    }
    prof = getattr(step, "step_profile", None)
    if prof is not None:
        from deeplearning4j_tpu.telemetry.xprofile import attribute

        detail["profile"] = prof.to_dict()
        analytic = 3 * lmc_fwd_flops(vocab, d, experts, dff, LMC_LAYERS,
                                     seq) * batch
        if prof.flops:
            detail["profile"]["analytic_train_flops"] = analytic
            # XLA counts the layer scan's body once (xprofile docstring),
            # so the like-for-like ratio is vs the scan-adjusted number
            detail["profile"]["xla_vs_analytic_flops"] = round(
                prof.flops / lmc_xla_flops_expectation(
                    vocab, d, experts, dff, seq, batch), 4)
        att = attribute(prof, batch / rate)
        detail["profile_attribution"] = {
            "measured_mfu": round(att["measured_mfu"], 4),
            "hbm_utilization": round(att["hbm_utilization"], 4),
            "comm_fraction": round(att["comm_fraction"], 6),
            "arithmetic_intensity": (round(att["arithmetic_intensity"], 2)
                                     if att["arithmetic_intensity"]
                                     else None),
            "ridge_intensity": round(att["ridge_intensity"], 2),
            "bound": att["bound"],
        }
    if telemetry:
        detail["telemetry"] = _lm_composed_telemetry(
            heads, params, tk, tg, k, batch, seq,
            selected_attn_impl(seq), tempfile, repeats)
    print("STAGE_DETAIL " + json.dumps(detail), flush=True)
    return rate


def _lm_composed_telemetry(heads, params, tk, tg, k, batch, seq,
                           attn_impl, tempfile, repeats) -> dict:
    """Telemetry-on vs telemetry-off A/B + a logged window (see
    measure_lm_composed). Returns the stage-detail telemetry block.

    A/B fairness: BOTH loops fetch at the same cadence — the telemetry-off
    twin pulls the loss scalar every TELEMETRY_INTERVAL steps (any real
    training loop logs its loss; an end-only-sync baseline would bill the
    logging sync, which telemetry-off runs pay too, to telemetry), the
    telemetry-on loop pulls the full metrics window. Overhead = median of
    per-pair on/off ratios over interleaved runs at the same k — pairing
    cancels drift; the median rides out a one-off scheduler hiccup that a
    min-based estimate inherits from whichever side it hits."""
    import jax

    from deeplearning4j_tpu.models.transformer_lm import (
        make_single_device_train_step,
    )
    from deeplearning4j_tpu.telemetry import (
        TrainTelemetry,
        read_step_log,
        summarize_step_log,
    )

    mstep = make_single_device_train_step(heads, with_metrics=True)
    step = make_single_device_train_step(heads)
    mparams = jax.tree_util.tree_map(lambda a: a, params)
    oparams = jax.tree_util.tree_map(lambda a: a, params)
    interval = TELEMETRY_INTERVAL

    def run_off(kk):
        nonlocal oparams
        t0 = time.perf_counter()
        for i in range(kk):
            oparams, loss = step(oparams, tk, tg)
            if (i + 1) % interval == 0:
                float(loss)  # the loss-logging sync every loop pays
        float(loss)
        return time.perf_counter() - t0

    def run_on(kk):
        nonlocal mparams
        buf = []
        t0 = time.perf_counter()
        for _ in range(kk):
            mparams, loss, m = mstep(mparams, tk, tg)
            buf.append(m)
            if len(buf) >= interval:  # the one sync per window
                jax.device_get(buf)
                buf.clear()
        if buf:
            jax.device_get(buf)
        float(loss)
        return time.perf_counter() - t0

    for _ in range(2):
        run_on(1)  # compile + warmup the metrics step
        run_off(1)
    ratios = []
    for _ in range(max(repeats, 5)):
        t_off = run_off(k)
        t_on = run_on(k)
        ratios.append(t_on / t_off)
    overhead_pct = (statistics.median(ratios) - 1.0) * 100.0

    # short logged window through the full host pipeline (session -> JSONL
    # -> summary) so the bench's telemetry claim is end-to-end, not synthetic
    log_path = os.path.join(tempfile.mkdtemp(prefix="lmc_telemetry_"),
                            "steps.jsonl")
    session = TrainTelemetry(
        step_log_path=log_path, interval=interval,
        tokens_per_step=batch * seq,
        static={"stage": "lm_composed", "attn_impl": attn_impl})
    log_steps = interval + 2  # spans a fetch boundary
    for i in range(log_steps):
        mparams, loss, m = mstep(mparams, tk, tg)
        session.record(i, m)
    session.close()
    summary = summarize_step_log(read_step_log(log_path))
    return {
        "interval": interval,
        "overhead_pct": round(overhead_pct, 2),
        "steps_logged": summary.get("steps", 0),
        "step_log_summary": summary,
    }


def measure_guardrails() -> float:
    """ISSUE 8 overhead budget + recovery demo. Two halves:

    (a) Guarded vs unguarded composed-flagship step A/B on one device —
    the in-graph guard (finiteness reductions + skip select,
    optimize/guardrails.py) must cost <5% vs the identical unguarded step.
    Same paired discipline as the PR 2 metrics budget: both loops fetch at
    the same cadence (the guarded loop pulls its guard block every
    TELEMETRY_INTERVAL steps, the plain loop pulls the loss scalar),
    interleaved runs at the same k, overhead = median of per-pair ratios.

    (b) Injected-NaN recovery demo on the guarded elastic reference model:
    a poisoned batch is skipped in-graph (params carried, finite), the
    faulting step is dumped as a replay bundle, and tools/step_replay.py
    re-executes it — asserting the non-finite result REPRODUCES. The demo
    results land in the stage detail (test_bench_smoke pins them).

    Headline = overhead percent (lower is better)."""
    import subprocess
    import tempfile

    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.transformer_lm import (
        init_lm_params,
        make_single_device_train_step,
    )

    repeats = 3
    if _fast():
        vocab, d, heads, experts, dff = 256, 64, 2, 2, 128
        seq, batch = 256, 2
    else:
        vocab, d, heads, experts, dff = (LMC_VOCAB, LMC_D, LMC_HEADS,
                                         LMC_EXPERTS, LMC_DFF)
        seq, batch = LMC_SEQ, LMC_BATCH

    params = init_lm_params(jax.random.PRNGKey(0), vocab, d, heads, experts,
                            dff, n_layers=LMC_LAYERS)
    step = make_single_device_train_step(heads, donate=True)
    gstep = make_single_device_train_step(heads, donate=True, guard=True)
    toks = jax.random.randint(jax.random.PRNGKey(2), (batch, seq + 1), 0,
                              vocab)
    tk, tg = toks[:, :-1], toks[:, 1:]
    zero = jnp.asarray(0)
    float(jnp.sum(tk) + jnp.sum(tg) + zero)  # force + sync the transfers
    # REAL copies: both steps donate their params, so the two loops must
    # not alias the init tree (a donated-away buffer would be deleted
    # under the other loop)
    oparams = jax.tree_util.tree_map(jnp.array, params)
    gparams = jax.tree_util.tree_map(jnp.array, params)
    interval = TELEMETRY_INTERVAL

    def run_off(kk):
        nonlocal oparams
        t0 = time.perf_counter()
        for i in range(kk):
            oparams, loss = step(oparams, tk, tg)
            if (i + 1) % interval == 0:
                float(loss)  # the loss-logging sync every loop pays
        float(loss)
        return time.perf_counter() - t0

    def run_on(kk):
        nonlocal gparams
        buf = []
        t0 = time.perf_counter()
        for _ in range(kk):
            gparams, loss, gm = gstep(gparams, tk, tg)
            buf.append(gm)
            if len(buf) >= interval:  # the watchdog-cadence sync
                jax.device_get(buf)
                buf.clear()
        if buf:
            jax.device_get(buf)
        float(loss)
        return time.perf_counter() - t0

    for _ in range(2):
        run_off(1)
        run_on(1)  # compile + warmup both programs

    fetch_lat = statistics.median(
        _time_of(lambda: float(jnp.sum(zero + 1))) for _ in range(5)
    )
    target = 0.3 if _fast() else 1.2
    k, t = 1, run_off(1)
    while t < target + fetch_lat and k < 256:
        k *= 2
        t = run_off(k)
    ratios = []
    for _ in range(max(repeats, 5)):
        t_off = run_off(k)
        t_on = run_on(k)
        ratios.append(t_on / t_off)
    overhead_pct = (statistics.median(ratios) - 1.0) * 100.0

    # ---- (b) injected-NaN recovery + replay forensics ----
    from deeplearning4j_tpu.optimize.guardrails import (
        dump_replay_bundle,
        tree_all_finite,
    )
    from deeplearning4j_tpu.scaleout.elastic import SyntheticRegressionModel

    model_kw = dict(d_in=8, d_hidden=16, batch=16, lr=0.05, mesh_devices=1)
    nan_step = 3
    model = SyntheticRegressionModel(guard=True, nan_at_step=nan_step,
                                     **model_kw)
    p = model.init_params()
    p, _ = model.run_steps(p, 0, nan_step, worker_seed=0)  # clean prefix
    pre = p  # run_steps returns a fresh host tree; this reference is stable
    x, y = model._batch_for(0, nan_step)
    p, _ = model.run_steps(p, nan_step, 1, worker_seed=0)  # the NaN step
    skipped = model.skipped_steps
    params_carried = all(
        a.tobytes() == b.tobytes()
        for a, b in zip(jax.tree_util.tree_leaves(pre),
                        jax.tree_util.tree_leaves(p)))
    p, post_loss = model.run_steps(p, nan_step + 1, 4, worker_seed=0)

    bundle_dir = tempfile.mkdtemp(prefix="guardrails_bench_")
    bundle = dump_replay_bundle(
        bundle_dir, nan_step, {"params": pre, "batch": {"x": x, "y": y}},
        {"demo": "bench guardrails stage"})
    replay = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "step_replay.py"),
         bundle, "--factory",
         "deeplearning4j_tpu.scaleout.elastic:synthetic_replay",
         "--kwargs-json", json.dumps(model_kw),
         "--expect-nonfinite", "--json"],
        capture_output=True, text=True, timeout=180, cwd=REPO)
    replay_rep = json.loads(replay.stdout) if replay.returncode == 0 else {}

    detail = {
        "interval": interval,
        "overhead_pct": round(overhead_pct, 2),
        "guarded_vs_unguarded_ratio": round(statistics.median(ratios), 4),
        "recovery": {
            "skipped_steps": skipped,
            "params_carried_bitwise": bool(params_carried),
            "params_finite_after_skip": bool(tree_all_finite(p)),
            "post_recovery_loss": round(float(post_loss), 6),
            "replay_rc": replay.returncode,
            "replay_reproduced": bool(replay_rep.get("reproduced")),
            "poisoned_leaves": [e["path"] for e in
                                replay_rep.get("forensics", [])
                                if e.get("nonfinite")],
        },
    }
    print("STAGE_DETAIL " + json.dumps(detail), flush=True)
    return overhead_pct


def measure_profile() -> float:
    """ISSUE 9 acceptance: profiling is COMPILE-TIME-ONLY. A/B of the
    composed-flagship single-device step with the ``profile=`` seam on
    (telemetry/xprofile.py ProfiledStep: AOT lower→compile once, then the
    same executable every call) vs the identical plain jitted step — same
    paired-median discipline as the telemetry/guardrails budgets, both
    loops fetching the loss at the same cadence. Headline = overhead
    percent (<5% budget, asserted in test_bench_smoke).

    The stage detail also carries the captured StepProfile (XLA FLOPs /
    bytes / memory / collective inventory), the analytic-vs-XLA FLOPs
    cross-check against ``lmc_fwd_flops`` at the stage shapes, the fused
    measured-MFU/roofline attribution, and a memory-watermark sampler
    pass over the timed window (empty watermarks on backends without
    memory_stats — explicitly, never fabricated)."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.transformer_lm import (
        init_lm_params,
        make_single_device_train_step,
    )
    from deeplearning4j_tpu.telemetry.xprofile import (
        MemoryWatermarkSampler,
        attribute,
    )

    repeats = 3
    if _fast():
        vocab, d, heads, experts, dff = 256, 64, 2, 2, 128
        seq, batch = 256, 2
    else:
        vocab, d, heads, experts, dff = (LMC_VOCAB, LMC_D, LMC_HEADS,
                                         LMC_EXPERTS, LMC_DFF)
        seq, batch = LMC_SEQ, LMC_BATCH

    params = init_lm_params(jax.random.PRNGKey(0), vocab, d, heads, experts,
                            dff, n_layers=LMC_LAYERS)
    step = make_single_device_train_step(heads, donate=True)
    pstep = make_single_device_train_step(heads, donate=True, profile=True)
    toks = jax.random.randint(jax.random.PRNGKey(2), (batch, seq + 1), 0,
                              vocab)
    tk, tg = toks[:, :-1], toks[:, 1:]
    zero = jnp.asarray(0)
    float(jnp.sum(tk) + jnp.sum(tg) + zero)  # force + sync the transfers
    # REAL copies: both steps donate, so the loops must not alias the init
    oparams = jax.tree_util.tree_map(jnp.array, params)
    pparams = jax.tree_util.tree_map(jnp.array, params)
    interval = TELEMETRY_INTERVAL

    def run_off(kk):
        nonlocal oparams
        t0 = time.perf_counter()
        for i in range(kk):
            oparams, loss = step(oparams, tk, tg)
            if (i + 1) % interval == 0:
                float(loss)  # the loss-logging sync every loop pays
        float(loss)
        return time.perf_counter() - t0

    def run_on(kk):
        nonlocal pparams
        t0 = time.perf_counter()
        for i in range(kk):
            pparams, loss = pstep(pparams, tk, tg)
            if (i + 1) % interval == 0:
                float(loss)
        float(loss)
        return time.perf_counter() - t0

    for _ in range(2):
        run_off(1)
        run_on(1)  # compile + AOT-profile warmup

    fetch_lat = statistics.median(
        _time_of(lambda: float(jnp.sum(zero + 1))) for _ in range(5)
    )
    target = 0.3 if _fast() else 1.2
    k, t = 1, run_off(1)
    while t < target + fetch_lat and k < 256:
        k *= 2
        t = run_off(k)
    ratios = []
    t_offs = []
    sampler = MemoryWatermarkSampler(interval_s=0.1)
    with sampler:
        for _ in range(max(repeats, 5)):
            t_off = run_off(k)
            t_on = run_on(k)
            t_offs.append(t_off)
            ratios.append(t_on / t_off)
    overhead_pct = (statistics.median(ratios) - 1.0) * 100.0

    prof = pstep.step_profile
    step_s = statistics.median(t_offs) / k
    analytic = 3 * lmc_fwd_flops(vocab, d, experts, dff, LMC_LAYERS,
                                 seq) * batch
    # XLA counts the layer scan's body once (xprofile docstring), so the
    # like-for-like cross-check divides by the scan-adjusted expectation
    expectation = lmc_xla_flops_expectation(vocab, d, experts, dff, seq,
                                            batch)
    att = attribute(prof, step_s)
    detail = {
        "interval": interval,
        "overhead_pct": round(overhead_pct, 2),
        "profiled_vs_plain_ratio": round(statistics.median(ratios), 4),
        "signature_fallbacks": pstep.signature_fallbacks,
        "profile": prof.to_dict(),
        "analytic_train_flops": analytic,
        "xla_vs_analytic_flops": (round(prof.flops / expectation, 4)
                                  if prof.flops else None),
        "attribution": {
            "step_seconds": round(att["step_seconds"], 6),
            "measured_mfu": round(att["measured_mfu"], 4),
            "hbm_utilization": round(att["hbm_utilization"], 4),
            "comm_fraction": round(att["comm_fraction"], 6),
            "bound": att["bound"],
        },
        "memory_watermarks": {
            "samples": sampler.samples,
            "devices": sampler.watermarks(),
        },
    }
    print("STAGE_DETAIL " + json.dumps(detail), flush=True)
    return overhead_pct


def measure_optimizer() -> float:
    """ISSUE 13: the in-graph optimizer A/B on the composed dp×ep
    flagship — SGD vs Adam(replicated update) vs Adam(ZeRO
    update-sharded) at identical math (optimize/updaters.py). For each
    config: steps/s (same fenced timing discipline as the moe stage) plus
    the compile-time StepProfile, so the memory claim is
    profiler-provable, not hand-waved: the headline is the
    replicated/sharded ``peak_bytes`` ratio (>1 = the ZeRO update is
    smaller), the per-replica at-rest moment bytes are measured off the
    actual device buffers, and the sharded blob lands as the stage's
    ``profile`` detail so ``tools/bench_report.py`` tracks
    ``optimizer_profile_peak_bytes`` LOWER-IS-BETTER across rounds.
    A 3-step sharded-vs-replicated parity check (max |Δparam|) rides in
    the detail — the A/B is only meaningful at identical math."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from jax.sharding import Mesh

    from deeplearning4j_tpu.models.transformer_lm import (
        init_lm_opt_state,
        init_lm_params,
        make_composed_train_step,
        shard_lm_batch,
        shard_lm_params,
    )
    from deeplearning4j_tpu.optimize.updaters import OptimizerConfig
    from deeplearning4j_tpu.telemetry.xprofile import profile_compiled

    repeats = 3
    if _fast():
        vocab, d, heads, dff = 256, 64, 2, 128
        seq, batch = 128, 8
    else:
        vocab, d, heads, dff = LMC_VOCAB, LMC_D, LMC_HEADS, LMC_DFF
        seq, batch = 512, 8

    devs = jax.devices()
    n_use = min(len(devs), 8)
    ep = 2 if n_use >= 2 else 1
    dp = max(n_use // ep, 1)
    mesh = Mesh(np.array(devs[: dp * ep]).reshape(dp, ep),
                ("data", "expert"))
    n_experts = 2 * ep
    # ample capacity (the full token row) — the A/B compares optimizers,
    # not drop semantics
    capacity = max((batch // dp) * seq, 4)

    params = init_lm_params(jax.random.PRNGKey(0), vocab, d, heads,
                            n_experts, dff, n_layers=LMC_LAYERS)
    toks = jax.random.randint(jax.random.PRNGKey(2), (batch, seq + 1), 0,
                              vocab)
    tk, tg = shard_lm_batch(toks[:, :-1], toks[:, 1:], mesh)
    zero = jnp.asarray(0)
    float(jnp.sum(tk) + jnp.sum(tg) + zero)  # force + sync the transfers
    fetch_lat = statistics.median(
        _time_of(lambda: float(jnp.sum(zero + 1))) for _ in range(5)
    )
    target = 0.3 if _fast() else 1.2

    configs = {
        "sgd": None,
        "adam_replicated": OptimizerConfig(
            name="adam", lr=1e-3, update_sharding="replicated"),
        "adam_sharded": OptimizerConfig(
            name="adam", lr=1e-3, update_sharding="sharded"),
        "lamb_sharded": OptimizerConfig(
            name="lamb", lr=1e-3, update_sharding="sharded"),
    }

    def per_replica_state_bytes(state) -> int:
        dev0 = jax.devices()[0]
        total = 0
        for leaf in jax.tree_util.tree_leaves(
                {"m": state["m"], "v": state["v"]}):
            total += sum(sh.data.nbytes for sh in leaf.addressable_shards
                         if sh.device == dev0)
        return total

    def bench_config(name, opt) -> dict:
        step = make_composed_train_step(mesh, heads, capacity,
                                        optimizer=opt, donate=True)
        # REAL copy before placing: device_put may alias the host tree's
        # buffers, and the donating step would delete them for every
        # config that follows
        p = shard_lm_params(jax.tree_util.tree_map(jnp.array, params), mesh)
        state = None if opt is None else init_lm_opt_state(opt, p, mesh)
        prof_args = (p, tk, tg) if opt is None else (p, state, tk, tg)
        # profile BEFORE the timed loop (donation retires the init args);
        # profile_compiled is one AOT compile, no execution
        prof = profile_compiled(step, *prof_args, label=f"optimizer_{name}")
        out = {"profile_peak_bytes": prof.peak_bytes,
               "profile_flops": prof.flops,
               "collectives": {k: v["count"]
                               for k, v in prof.collectives.items()}}
        if state is not None:
            out["moment_bytes_per_replica"] = per_replica_state_bytes(state)

        carry = [p, state]

        def one_step():
            if carry[1] is None:
                carry[0], loss = step(carry[0], tk, tg)
            else:
                carry[0], carry[1], loss = step(carry[0], carry[1], tk, tg)
            return loss

        for _ in range(2):  # compile + committed-sharding warmup
            loss = one_step()
        float(loss)

        def run(k):
            t0 = time.perf_counter()
            for _ in range(k):
                loss = one_step()
            last = float(loss)  # true sync: device->host fetch
            assert math.isfinite(last), f"non-finite {name} loss"
            return time.perf_counter() - t0

        k, t = 1, run(1)
        while t < target + fetch_lat and k < 256:
            k *= 2
            t = run(k)
        t_med = statistics.median([t] + [run(k) for _ in range(repeats - 1)])
        out["steps_per_sec"] = round(k / max(t_med - fetch_lat,
                                             0.2 * t_med), 2)
        return out

    detail = {
        "mesh": {"data": dp, "expert": ep},
        "model": {"vocab": vocab, "d_model": d, "d_ff": dff, "seq": seq,
                  "batch": batch, "n_experts": n_experts,
                  "n_layers": LMC_LAYERS},
    }
    profiles = {}
    for name, opt in configs.items():
        cfg_out = bench_config(name, opt)
        detail[name] = cfg_out
        profiles[name] = cfg_out

    # the sharded blob is THE tracked footprint row
    # (optimizer_profile_peak_bytes, LOWER-IS-BETTER in bench_report)
    sh_step = make_composed_train_step(mesh, heads, capacity,
                                       optimizer=configs["adam_sharded"])
    p0 = shard_lm_params(params, mesh)
    st0 = init_lm_opt_state(configs["adam_sharded"], p0, mesh)
    detail["profile"] = profile_compiled(
        sh_step, p0, st0, tk, tg, label="optimizer_adam_sharded").to_dict()

    # parity at identical math: 3 steps each mode from the same init
    rep_step = make_composed_train_step(mesh, heads, capacity,
                                        optimizer=configs["adam_replicated"])
    pr = shard_lm_params(params, mesh)
    sr = init_lm_opt_state(configs["adam_replicated"], pr, mesh)
    ps, ss = p0, st0
    for _ in range(3):
        pr, sr, lr_ = rep_step(pr, sr, tk, tg)
        ps, ss, ls_ = sh_step(ps, ss, tk, tg)
    parity = max(
        float(jnp.max(jnp.abs(a - b)))
        for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(pr)),
                        jax.tree_util.tree_leaves(jax.device_get(ps))))
    detail["adam_sharded_vs_replicated_parity_max_abs_diff"] = parity
    detail["adam_loss_delta"] = abs(float(lr_) - float(ls_))

    rep_peak = profiles["adam_replicated"]["profile_peak_bytes"]
    sh_peak = profiles["adam_sharded"]["profile_peak_bytes"]
    ratio = (rep_peak / sh_peak) if (rep_peak and sh_peak) else 0.0
    detail["peak_bytes_replicated"] = rep_peak
    detail["peak_bytes_sharded"] = sh_peak
    detail["moment_bytes_ratio"] = round(
        profiles["adam_replicated"].get("moment_bytes_per_replica", 0)
        / max(profiles["adam_sharded"].get("moment_bytes_per_replica", 1),
              1), 2)
    print("STAGE_DETAIL " + json.dumps(detail), flush=True)
    return ratio


def measure_comm_overlap() -> float:
    """ISSUE 14: the three comm/compute-overlap A/Bs, each a pure-schedule
    twin of a pinned-parity pair —

    (1) flat vs hierarchical 2D MoE all_to_all on dp×ep (the expert axis
        factorized into the (outer, inner) grid of arXiv:2112.01075;
        identical routed values, grouped wire schedule),
    (2) strict vs double-buffered-overlap pipeline ticks on dp×pp
        (ppermute issued for the previous tick's output while this
        tick's stage computes; bit-identical loss+params),
    (3) rotate-after-attend vs prefetch ring attention on dp×sp (the
        K/V rotation issued before the flash tiles consume the current
        block; bit-identical).

    Headline = strict/overlapped pipeline step-time ratio (>1 = overlap
    faster). Every config carries its compiled StepProfile; the measured
    comm fraction (xprofile.attribute at the v5e ICI model) gates which
    configs COUNT — on a comm-starved backend (CPU, tiny shapes, where
    the collectives are memcpys) the ratios are recorded but flagged
    informational rather than claimed as wins. The 2D a2a step's profile
    blob embeds as the stage profile and its wire bytes land on the
    LOWER-IS-BETTER ``comm_overlap_collective_wire_bytes`` bench_report
    row, so comm growth trips --fail-on-regression."""
    from functools import partial

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from deeplearning4j_tpu.telemetry.xprofile import (
        attribute,
        profile_compiled,
    )

    repeats = 3
    fast = _fast()
    target = 0.25 if fast else 1.0
    devs = jax.devices()
    if len(devs) < 8:
        raise RuntimeError("comm_overlap needs 8 devices (dp×ep 2×4)")

    zero = jnp.asarray(0)
    fetch_lat = statistics.median(
        _time_of(lambda: float(jnp.sum(zero + 1))) for _ in range(5))

    def time_step(step, state, *args):
        """Warm 2, double k until a run dwarfs fetch latency, median of
        3 → (ms/step, final state). The step donates+rebinds state."""
        for _ in range(2):
            state, loss = step(state, *args)
        float(loss)

        def run(k):
            nonlocal state
            t0 = time.perf_counter()
            for _ in range(k):
                state, loss = step(state, *args)
            last = float(loss)  # true sync: device->host fetch
            assert math.isfinite(last), "non-finite comm_overlap loss"
            return time.perf_counter() - t0

        k, t = 1, run(1)
        while t < target + fetch_lat and k < 128:
            k *= 2
            t = run(k)
        t_med = statistics.median([t] + [run(k) for _ in range(repeats - 1)])
        return max(t_med - fetch_lat, 0.2 * t_med) / k * 1000.0, state

    detail: dict = {"fast": fast}

    # ---- (1) flat vs 2D MoE all_to_all on dp×ep --------------------------
    from deeplearning4j_tpu.parallel.moe import (
        factor_expert_axis,
        load_balance_loss,
        moe_apply,
    )
    from deeplearning4j_tpu.parallel.sharding import shard_leading_axis

    dp, ep = 2, 4
    mesh = Mesh(np.array(devs[: dp * ep]).reshape(dp, ep),
                ("data", "expert"))
    d, dff = (32, 64) if fast else (256, 512)
    n_tokens = 512 if fast else 8192
    group = 2
    n_experts = group * ep
    sub = (n_tokens // dp) // ep
    capacity = max(-(-int(1.25 * 2 * sub) // n_experts), 1)
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(jax.random.fold_in(key, 1), (n_tokens, d))
    tgt = jnp.tanh(jax.random.normal(jax.random.fold_in(key, 2),
                                     (n_tokens, d)))
    router_w = jax.random.normal(jax.random.fold_in(key, 3),
                                 (d, n_experts)) / (d ** 0.5)
    ek = jax.random.split(jax.random.fold_in(key, 4), 2)
    experts = shard_leading_axis({
        "w1": jax.random.normal(ek[0], (n_experts, d, dff)) / (d ** 0.5),
        "b1": jnp.zeros((n_experts, dff)),
        "w2": jax.random.normal(ek[1], (n_experts, dff, d)) / (dff ** 0.5),
        "b2": jnp.zeros((n_experts, d)),
    }, mesh, "expert")
    float(jnp.sum(x) + jnp.sum(tgt))  # force + sync the transfers

    def expert_fn(p, t):
        return jax.nn.relu(t @ p["w1"] + p["b1"]) @ p["w2"] + p["b2"]

    def make_moe_step(impl):
        @partial(jax.jit, donate_argnums=(0,))
        def moe_step(state, xs, ys):
            rw, ps = state

            def loss_fn(rw, ps):
                out = moe_apply(rw, ps, xs, mesh, expert_fn, capacity,
                                top_k=2, token_axes=("data",), impl=impl)
                task = jnp.mean((out - ys) ** 2)
                return task + 1e-2 * load_balance_loss(rw, xs)

            loss, (gr, ge) = jax.value_and_grad(
                loss_fn, argnums=(0, 1))(rw, ps)
            return (rw - 0.1 * gr, jax.tree_util.tree_map(
                lambda p, g: p - 0.1 * g, ps, ge)), loss

        return moe_step

    a2a = {"mesh": {"data": dp, "expert": ep},
           "grid": list(factor_expert_axis(ep)),
           "n_experts": n_experts, "capacity": capacity,
           "d_model": d, "tokens_per_step": n_tokens}
    profiles = {}
    for impl in ("alltoall", "alltoall_2d"):
        step = make_moe_step(impl)
        state0 = (jnp.array(router_w),
                  jax.tree_util.tree_map(jnp.array, experts))
        prof = profile_compiled(step, state0, x, tgt,
                                label=f"comm_overlap_{impl}")
        ms, _ = time_step(step, state0, x, tgt)
        ops = prof.collectives.get("all-to-all", {})
        att = attribute(prof, ms / 1000.0)
        profiles[impl] = prof
        a2a[impl] = {
            "step_ms": round(ms, 3),
            "a2a_count": ops.get("count", 0),
            "a2a_group_sizes": ops.get("group_sizes", []),
            "a2a_wire_bytes": ops.get("wire_bytes", 0.0),
            "collective_wire_bytes": prof.collective_wire_bytes,
            "comm_fraction": round(att["comm_fraction"], 6),
        }
    # parity at identical init: one step each, losses within 1e-5
    l_f = float(make_moe_step("alltoall")(
        (jnp.array(router_w), jax.tree_util.tree_map(jnp.array, experts)),
        x, tgt)[1])
    l_2 = float(make_moe_step("alltoall_2d")(
        (jnp.array(router_w), jax.tree_util.tree_map(jnp.array, experts)),
        x, tgt)[1])
    a2a["parity_loss_abs_diff"] = abs(l_f - l_2)
    a2a["2d_vs_flat"] = round(a2a["alltoall"]["step_ms"]
                              / max(a2a["alltoall_2d"]["step_ms"], 1e-9), 3)
    detail["a2a"] = a2a

    # ---- (2) strict vs overlapped pipeline ticks on dp×pp ----------------
    from deeplearning4j_tpu.parallel.pipeline import (
        PIPE_AXIS,
        make_pipeline_train_step,
        shard_stage_params,
        stack_stage_params,
    )

    pmesh = Mesh(np.array(devs[:8]).reshape(2, 4), ("data", PIPE_AXIS))
    pd = 64 if fast else 256
    n_micro, mb = 8, 8
    ks = jax.random.split(jax.random.fold_in(key, 5), 4)
    per_stage = [{"w": jax.random.normal(k, (pd, pd)) / (pd ** 0.5),
                  "b": jnp.zeros((pd,))} for k in ks]
    stacked = shard_stage_params(stack_stage_params(per_stage), pmesh)
    px = jax.random.normal(jax.random.fold_in(key, 6), (n_micro, mb, pd))
    ptgt = jnp.tanh(jax.random.normal(jax.random.fold_in(key, 7),
                                      (n_micro, mb, pd)))
    stage_fn = lambda p, x: jnp.tanh(x @ p["w"] + p["b"])  # noqa: E731
    loss_fn = lambda y, t: jnp.mean((y - t) ** 2)  # noqa: E731

    pp = {"mesh": {"data": 2, "pipe": 4}, "d": pd,
          "n_micro": n_micro, "microbatch": mb}
    pp_params = {}
    for mode, overlap in (("strict", False), ("overlap", True)):
        step = make_pipeline_train_step(stage_fn, loss_fn, pmesh, lr=0.1,
                                        batch_axis="data", overlap=overlap)
        state0 = jax.tree_util.tree_map(jnp.array, stacked)
        prof = profile_compiled(step, state0, px, ptgt,
                                label=f"comm_overlap_pp_{mode}")
        ms, state = time_step(step, state0, px, ptgt)
        att = attribute(prof, ms / 1000.0)
        pp_params[mode] = state
        pp[mode] = {
            "step_ms": round(ms, 3),
            "collective_permute_count": prof.collectives.get(
                "collective-permute", {}).get("count", 0),
            "comm_fraction": round(att["comm_fraction"], 6),
        }
    # bit-parity of the timed endpoints: identical step counts either side
    # would be timing-dependent, so re-run 2 fixed steps from scratch
    s_s = make_pipeline_train_step(stage_fn, loss_fn, pmesh, lr=0.1,
                                   batch_axis="data")
    s_o = make_pipeline_train_step(stage_fn, loss_fn, pmesh, lr=0.1,
                                   batch_axis="data", overlap=True)
    ps_, po_ = (jax.tree_util.tree_map(jnp.array, stacked) for _ in "ab")
    for _ in range(2):
        ps_, l_s = s_s(ps_, px, ptgt)
        po_, l_o = s_o(po_, px, ptgt)
    pp["bit_identical"] = bool(float(l_s) == float(l_o) and all(
        bool(jnp.array_equal(a, b))
        for a, b in zip(jax.tree_util.tree_leaves(ps_),
                        jax.tree_util.tree_leaves(po_))))
    pp["overlap_vs_strict"] = round(
        pp["strict"]["step_ms"] / max(pp["overlap"]["step_ms"], 1e-9), 3)
    detail["pipeline"] = pp

    # ---- (3) rotate-after vs prefetch ring on dp×sp ----------------------
    from deeplearning4j_tpu.parallel.ring_attention import ring_attention

    rmesh = Mesh(np.array(devs[:8]).reshape(2, 4), ("data", "sp"))
    rb, rh, rt, rd = (2, 4, 256, 16) if fast else (2, 8, 2048, 64)
    rk = jax.random.split(jax.random.fold_in(key, 8), 3)
    q0, k0, v0 = (jax.random.normal(kk, (rb, rh, rt, rd)) * 0.5
                  for kk in rk)

    def make_ring_step(prefetch):
        @partial(jax.jit, donate_argnums=(0,))
        def ring_step(q, k, v):
            def loss(q):
                out = ring_attention(q, k, v, rmesh, "sp", causal=True,
                                     batch_axis="data", attn_impl="dense",
                                     prefetch=prefetch)
                return jnp.sum(out * out)

            l, g = jax.value_and_grad(loss)(q)
            return q - 1e-3 * g, l

        return ring_step

    ring = {"mesh": {"data": 2, "sp": 4},
            "shape": [rb, rh, rt, rd]}
    for mode, prefetch in (("rotate_after", False), ("prefetch", True)):
        step = make_ring_step(prefetch)
        prof = profile_compiled(step, jnp.array(q0), k0, v0,
                                label=f"ring_{mode}")
        ms, _ = time_step(step, jnp.array(q0), k0, v0)
        att = attribute(prof, ms / 1000.0)
        ring[mode] = {
            "step_ms": round(ms, 3),
            "collective_permute_count": prof.collectives.get(
                "collective-permute", {}).get("count", 0),
            "comm_fraction": round(att["comm_fraction"], 6),
        }
    o_ra = make_ring_step(False)(jnp.array(q0), k0, v0)
    o_pf = make_ring_step(True)(jnp.array(q0), k0, v0)
    ring["bit_identical"] = bool(
        float(o_ra[1]) == float(o_pf[1])
        and jnp.array_equal(o_ra[0], o_pf[0]))
    ring["prefetch_vs_rotate_after"] = round(
        ring["rotate_after"]["step_ms"]
        / max(ring["prefetch"]["step_ms"], 1e-9), 3)
    detail["ring"] = ring

    # comm-fraction gating: which A/Bs COUNT as overlap evidence (the
    # schedule can only win where comm is a visible step-time share)
    floor = 0.01
    detail["comm_fraction_floor"] = floor
    detail["counted_configs"] = sorted(
        name for name, frac in (
            ("a2a", a2a["alltoall"]["comm_fraction"]),
            ("pipeline", pp["strict"]["comm_fraction"]),
            ("ring", ring["rotate_after"]["comm_fraction"]),
        ) if frac >= floor)
    detail["headline_counted"] = "pipeline" in detail["counted_configs"]

    # the tracked blob: the 2D a2a step (its wire bytes are the
    # LOWER-IS-BETTER comm-growth tripwire)
    detail["profile"] = profiles["alltoall_2d"].to_dict()
    detail["collective_wire_bytes"] = profiles[
        "alltoall_2d"].collective_wire_bytes
    print("STAGE_DETAIL " + json.dumps(detail), flush=True)
    return pp["overlap_vs_strict"]


def mfu(model: str, samples_per_sec: float, precision: str) -> float:
    return (samples_per_sec * TRAIN_FLOPS[model]
            / PRECISION_PEAKS.get(precision, PEAK_BF16_FLOPS))


def measure_ckpt() -> float:
    """Sharded checkpoint save/restore wall time and bytes for the
    composed-LM params at dp×ep (scaleout/ckpt): warm save + restore,
    median of 3 each, through the real Checkpointer (manifest commit,
    retention, telemetry counters included — this is the path a training
    run pays). Returns save MB/s; restore timing, bytes, and chunk count
    land in the stage detail."""
    import tempfile

    import jax
    import numpy as np

    from deeplearning4j_tpu.models.transformer_lm import (
        init_lm_params,
        lm_param_shardings,
        shard_lm_params,
    )
    from deeplearning4j_tpu.scaleout.ckpt import Checkpointer
    from deeplearning4j_tpu.scaleout.ckpt.manifest import read_manifest
    from jax.sharding import Mesh

    if _fast():
        vocab, d, heads, experts, dff, layers = 256, 64, 2, 2, 128, 2
    else:
        vocab, d, heads, experts, dff, layers = (
            LMC_VOCAB, LMC_D, LMC_HEADS, LMC_EXPERTS, LMC_DFF, LMC_LAYERS)

    devs = jax.devices()
    ep = experts if (len(devs) >= experts
                     and len(devs) % experts == 0) else 1
    dp = max(len(devs) // ep, 1)
    mesh = Mesh(np.array(devs[: dp * ep]).reshape(dp, ep),
                ("data", "expert"))

    params = init_lm_params(jax.random.PRNGKey(0), vocab, d, heads, experts,
                            dff, n_layers=layers)
    sharded = shard_lm_params(params, mesh)
    state = {"params": sharded}
    jax.block_until_ready(sharded)  # nothing enqueued before the clocks

    root = tempfile.mkdtemp(prefix="ckpt_bench_")
    ck = Checkpointer(root, keep_last=2)
    ck.save(0, state, mesh=mesh)  # warmup: dir creation, allocator, caches

    def one_save(step):
        t0 = time.perf_counter()
        step_dir = ck.save(step, state, mesh=mesh)
        # graftlint: allow[untimed-dispatch] ck.save fetches every shard via np.asarray and fsyncs the files — host-synchronous IO, nothing enqueued
        return time.perf_counter() - t0, step_dir

    saves = [one_save(i + 1) for i in range(3)]
    save_s = statistics.median(t for t, _ in saves)
    step_dir = saves[-1][1]
    manifest = read_manifest(step_dir)
    n_bytes = manifest.total_bytes
    n_chunks = sum(len(e.chunks) for e in manifest.leaves)

    template = {"params": params}
    shardings = {"params": lm_param_shardings(params, mesh)}

    def one_restore():
        t0 = time.perf_counter()
        restored, _step, _meta = ck.restore(template, shardings)
        jax.block_until_ready(restored)  # fence the device placement
        return time.perf_counter() - t0

    restore_s = statistics.median(one_restore() for _ in range(3))
    mb = n_bytes / 1e6
    detail = {
        "save_ms": round(save_s * 1e3, 2),
        "restore_ms": round(restore_s * 1e3, 2),
        "mb": round(mb, 2),
        "chunks": n_chunks,
        "shard_files": len(manifest.files),
        "mesh": {"data": dp, "expert": ep},
        "save_mb_per_sec": round(mb / save_s, 1),
        "restore_mb_per_sec": round(mb / restore_s, 1),
    }
    print("STAGE_DETAIL " + json.dumps(detail), flush=True)
    return mb / save_s


def measure_ckpt_async() -> float:
    """Step-time jitter at save steps (ISSUE 6): the SAME composed-LM
    training loop checkpointed two ways — blocking ``Checkpointer.save``
    on the training thread vs ``AsyncCheckpointer`` (non-blocking
    device→host copy + background writer). Reported per mode: median
    plain-step ms, median save-step ms, and their difference (the jitter a
    save step adds). Headline = blocking/background save-step overhead
    ratio (>1 means the background writer keeps the training thread
    freer)."""
    import tempfile

    import jax
    import numpy as np

    from deeplearning4j_tpu.models.transformer_lm import (
        init_lm_params,
        make_composed_train_step,
        shard_lm_batch,
        shard_lm_params,
    )
    from deeplearning4j_tpu.scaleout.ckpt import AsyncCheckpointer, Checkpointer
    from jax.sharding import Mesh

    if _fast():
        vocab, d, heads, experts, dff, layers = 256, 64, 2, 2, 128, 2
        batch, seq, steps, save_every = 4, 64, 9, 3
    else:
        vocab, d, heads, experts, dff, layers = (
            LMC_VOCAB, LMC_D, LMC_HEADS, LMC_EXPERTS, LMC_DFF, LMC_LAYERS)
        batch, seq, steps, save_every = LMC_BATCH, LMC_SEQ, 24, 6

    devs = jax.devices()
    ep = experts if (len(devs) >= experts and len(devs) % experts == 0) else 1
    dp = max(len(devs) // ep, 1)
    mesh = Mesh(np.array(devs[: dp * ep]).reshape(dp, ep),
                ("data", "expert"))
    capacity = max((batch // dp) * seq // max(experts // ep, 1), 8)

    def run_mode(background: bool) -> dict:
        params = shard_lm_params(
            init_lm_params(jax.random.PRNGKey(0), vocab, d, heads, experts,
                           dff, n_layers=layers), mesh)
        # non-donating on purpose: an async snapshot must be able to hold
        # the saved buffers while the next step runs
        step = make_composed_train_step(mesh, heads, capacity)
        toks = np.random.default_rng(0).integers(
            0, vocab, (batch, seq + 1))
        tk, tg = shard_lm_batch(toks[:, :-1], toks[:, 1:], mesh)
        params, loss = step(params, tk, tg)  # warmup compile
        jax.block_until_ready(loss)
        root = tempfile.mkdtemp(prefix="ckpt_async_bench_")
        inner = Checkpointer(root, keep_last=2)
        ck = AsyncCheckpointer(inner) if background else inner
        ck.save(0, {"params": params}, mesh=mesh)  # warm the IO path
        plain_ms, save_ms = [], []
        for i in range(1, steps + 1):
            t0 = time.perf_counter()
            params, loss = step(params, tk, tg)
            jax.block_until_ready(loss)
            is_save = i % save_every == 0
            if is_save:
                ck.save(i, {"params": params}, mesh=mesh)
            # graftlint: allow[untimed-dispatch] loss is fenced above; the save tail is host-side IO (the thing this stage measures)
            dt = (time.perf_counter() - t0) * 1000.0
            (save_ms if is_save else plain_ms).append(dt)
        if background:
            ck.flush()
            ck.close()
        plain = statistics.median(plain_ms)
        save = statistics.median(save_ms)
        return {"plain_step_ms": round(plain, 2),
                "save_step_ms": round(save, 2),
                "save_overhead_ms": round(max(save - plain, 0.0), 3)}

    blocking = run_mode(background=False)
    background = run_mode(background=True)
    # floor at 0.1ms (timer noise): a background overhead measured as ~0
    # must not explode the ratio into a meaningless number
    ratio = ((blocking["save_overhead_ms"] + 0.1)
             / (max(background["save_overhead_ms"], 0.0) + 0.1))
    detail = {
        "blocking": blocking,
        "background": background,
        "save_every": save_every,
        "steps": steps,
        "mesh": {"data": dp, "expert": ep},
        "blocking_vs_background_overhead": round(ratio, 2),
    }
    print("STAGE_DETAIL " + json.dumps(detail), flush=True)
    return ratio


def measure_elastic_sync() -> float:
    """The SparkNet experiment (arXiv:1511.06051 §4): accuracy vs sync
    period. K simulated elastic workers train the same total number of
    local steps under ``sync_every`` ∈ {1, 8, 32} (parameter averaging
    every window, the exact ``scaleout.elastic`` round protocol via
    ``simulate_elastic``), and the A/B reports held-out loss per setting
    plus aggregate local steps/s — infrequent averaging buys throughput
    (fewer syncs) at a quantified accuracy cost. Headline = steps/s at
    sync_every=8."""
    from deeplearning4j_tpu.scaleout.elastic import (
        SyntheticRegressionModel,
        simulate_elastic,
    )

    # lr 0.2 keeps training mid-flight at these step counts, so the sync
    # period visibly moves the final loss (the SparkNet trade-off); at
    # small lr every setting converges and the A/B collapses
    if _fast():
        total_steps, workers = 32, 2
        model_kw = dict(d_in=8, d_hidden=16, batch=16, lr=0.2)
    else:
        total_steps, workers = 48, 4
        model_kw = dict(d_in=32, d_hidden=64, batch=128, lr=0.2)

    seeds = list(range(workers))
    results = {}
    for sync_every in (1, 8, 32):
        rounds = max(total_steps // sync_every, 1)
        model = SyntheticRegressionModel(**model_kw)
        t0 = time.perf_counter()
        final, _losses = simulate_elastic(model, seeds, sync_every, rounds)
        # graftlint: allow[untimed-dispatch] simulate_elastic is host-synchronous (device_get per round inside run_steps)
        wall = time.perf_counter() - t0
        results[str(sync_every)] = {
            "rounds": rounds,
            "final_eval_loss": round(model.eval_loss(final), 6),
            "steps_per_sec": round(workers * rounds * sync_every / wall, 1),
        }
    detail = {
        "workers": workers,
        "total_local_steps": total_steps,
        "per_sync_every": results,
        "loss_s1_over_s32": round(
            (results["1"]["final_eval_loss"] + 1e-12)
            / (results["32"]["final_eval_loss"] + 1e-12), 4),
    }
    print("STAGE_DETAIL " + json.dumps(detail), flush=True)
    return results["8"]["steps_per_sec"]


def measure_elastic_trace() -> float:
    """ISSUE 7 overhead budget: distributed tracing threaded through a
    REAL elastic round (master + worker + tracker RPCs + blob publishes +
    flight-recorder checkpoints) must cost <5% vs the identical untraced
    round. Estimator: ONE long-lived cluster, tracing flipped on/off on
    alternating rounds, and the overhead taken as the MEDIAN OF
    ADJACENT-PAIR DELTAS (traced round minus the untraced round right
    before it) — round-level interleaving plus pairing cancels the
    scheduler drift and the ±15% per-round jitter that run-level A/B
    (and even per-arm medians) cannot; the same paired-median discipline
    as the PR 2 metrics budget, one level finer. A second, fully-traced
    short run
    then exercises the forensic chain: span files →
    tools/trace_report.py timeline (every round committed) → Chrome
    export → flight dump. Headline = overhead percent (lower is
    better)."""
    import shutil
    import tempfile
    import threading

    from deeplearning4j_tpu.scaleout.elastic import (
        ElasticMaster,
        ElasticWorker,
        SyntheticRegressionModel,
    )
    from deeplearning4j_tpu.telemetry import trace as trace_mod
    from tools.trace_report import build_timeline, chrome_trace, \
        load_trace_dir

    # rounds sized so local compute dominates (a realistic cadence): the
    # tracing cost per round is O(spans) ≈ fixed, so a too-tiny round
    # would measure artifact IO against nothing but poll sleeps
    if _fast():
        ab_rounds, sync_every, warm = 44, 32, 4
        model_kw = dict(d_in=32, d_hidden=128, batch=256, lr=0.05,
                        mesh_devices=1)
    else:
        ab_rounds, sync_every, warm = 64, 48, 4
        model_kw = dict(d_in=64, d_hidden=256, batch=512, lr=0.05,
                        mesh_devices=1)

    base = tempfile.mkdtemp(prefix="bench_elastic_trace_")

    def start_cluster(tag: str):
        blob = f"file://{base}/blob_{tag}"
        master = ElasticMaster(
            SyntheticRegressionModel(**model_kw), blob,
            sync_every=sync_every, min_workers=1, round_timeout_s=120,
            tick_s=0.0005)  # fine tick: poll quantization would otherwise
        # amplify sub-ms tracing work into a whole extra poll cycle
        worker = ElasticWorker(
            master.address, blob, SyntheticRegressionModel(**model_kw),
            worker_id="w0", worker_seed=1, sync_every=sync_every,
            poll_s=0.0005, round_timeout_s=120)
        t = threading.Thread(target=worker.run, daemon=True)
        t.start()
        master.wait_for_workers(1)
        return master, t

    # ---- A/B: one cluster, tracing alternated per round ----
    tracer = trace_mod.Tracer("master",
                              trace_dir=os.path.join(base, "trace_ab"))
    master, t = start_cluster("ab")
    walls = []  # (traced?, wall) per round, in order
    try:
        for r in range(ab_rounds):
            on = r % 2 == 1
            trace_mod.set_tracer(tracer if on else None)
            master.tracer = tracer if on else None
            t0 = time.perf_counter()
            master.train(1, finish=(r == ab_rounds - 1))
            # graftlint: allow[untimed-dispatch] the elastic round protocol is host-synchronous (run_steps device_gets before publishing); nothing is enqueued when the clock stops
            wall = time.perf_counter() - t0
            if r >= warm:
                walls.append((on, wall))
    finally:
        trace_mod.set_tracer(None)
        master.shutdown()
        t.join(timeout=60)
    # adjacent (plain, traced) pairs → per-pair delta; 20%-trimmed mean
    # over pairs (drops the scheduler-hiccup outliers the shared-CPU box
    # produces, more sample-efficient than the median for the rest)
    deltas = sorted(tw - pw for (p_on, pw), (t_on, tw)
                    in zip(walls[::2], walls[1::2]) if not p_on and t_on)
    trim = len(deltas) // 5
    kept = deltas[trim:len(deltas) - trim] or deltas
    delta = statistics.fmean(kept)
    plain = statistics.median(w for on, w in walls if not on)
    traced = plain + delta
    overhead_pct = delta / plain * 100.0

    # ---- forensic chain smoke: a short fully-traced run ----
    trace_dir = os.path.join(base, "trace_full")
    trace_mod.set_tracer(trace_mod.Tracer("master", trace_dir=trace_dir))
    try:
        master, t = start_cluster("full")
        master.train(4)
        master.shutdown()
        t.join(timeout=60)
    finally:
        trace_mod.set_tracer(None)
    spans = load_trace_dir(trace_dir)
    timeline = build_timeline(spans)
    committed = [r for r in timeline["rounds"] if r["status"] == "committed"]
    chrome = chrome_trace(spans)
    detail = {
        "ab_rounds": ab_rounds,
        "sync_every": sync_every,
        "plain_round_ms": round(plain * 1000, 2),
        "traced_round_ms": round(traced * 1000, 2),
        "overhead_pct": round(overhead_pct, 2),
        "spans": len(spans),
        "rounds_committed_in_report": len(committed),
        "chrome_events": len(chrome["traceEvents"]),
        "flight_dump": os.path.exists(
            os.path.join(trace_dir, "flightrec_master.json")),
    }
    print("STAGE_DETAIL " + json.dumps(detail), flush=True)
    shutil.rmtree(base, ignore_errors=True)
    return overhead_pct


def measure_ref_micro() -> float:
    """ISSUE 16 bench-noise reference: a fixed deterministic jitted
    matmul+relu loop that NEVER changes across rounds, so its rate
    measures the MACHINE (thermal state, co-tenancy, tunnel latency),
    not the code. tools/bench_report.py divides every tracked metric's
    round-over-round delta by this row's drift when the drift is within
    ±10% — a slow bench box stops reading as a code regression — and
    when the reference itself moved MORE than 10% it flags the round
    pair and suppresses regression-gating for it instead (normalizing
    by a broken reference would hide real regressions).

    Sized to be cheap (sub-second compute) but long enough that jit
    dispatch overhead doesn't dominate: one (n,n) fp32 matmul+relu per
    iteration, chained so nothing can be constant-folded away."""
    import jax
    import jax.numpy as jnp

    n = 256 if _fast() else 512
    iters = 80 if _fast() else 200

    a = jax.random.normal(jax.random.PRNGKey(0), (n, n), jnp.float32)
    b = jax.random.normal(jax.random.PRNGKey(1), (n, n), jnp.float32)

    @jax.jit
    def ref_step(x):
        # the /n keeps the chained activations O(1) so 200 iterations
        # can't overflow to inf (an inf would still time the same, but
        # a NaN-guard change elsewhere must not alter this stage's work)
        return jnp.maximum(x @ b, 0.0) * (1.0 / n)

    ref_step(a).block_until_ready()  # compile + warmup outside the clock
    t0 = time.perf_counter()
    x = a
    for _ in range(iters):
        x = ref_step(x)
    x.block_until_ready()
    return iters / (time.perf_counter() - t0)


def measure_serve() -> float:
    """ISSUE 10 serving bench: the continuous-batching decode engine
    (deeplearning4j_tpu/serve/) under the synthetic open-loop traffic
    generator vs the naive recompute-per-token baseline that ``cli
    predict`` used to be.

    Both sides run the SAME bf16-prepared weights (serve/quant.py), so the
    headline ratio isolates what the KV cache + iteration-level batching
    buy, not a dtype change. The naive baseline is the honest fixed-shape
    version of full-forward generation: one jitted full forward over the
    padded decode window per token, batch 1, requests served sequentially
    — O(window) work per token where the decode step does O(1).

    Headline value = engine generated-tokens/sec under the open-loop run;
    the detail carries exact p50/p95/mean request latency (LOWER-IS-BETTER
    rows in tools/bench_report.py — latency growth trips
    ``--fail-on-regression``), the naive baseline rate, the
    ``serve_vs_naive`` ratio (>1 asserted in test_bench_smoke), occupancy,
    and the int8 weight-only-quantized A/B twin (tokens/s + at-rest weight
    bytes vs bf16).

    ISSUE 16 adds the ``fast_path`` block: prefix-cache on/off under
    shared-system-prompt traffic, speculative on/off under the same
    traffic, and chunked-vs-unchunked prefill under a long-prompt
    barrage (with inter-token p99 — chunking's actual win). The ratios
    land as HIGHER-IS-BETTER ``serve_fastpath_*`` rows in
    tools/bench_report.py; the p99s as LOWER-IS-BETTER rows."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu.models.transformer_lm import (
        init_lm_params,
        lm_prefill,
    )
    from deeplearning4j_tpu.serve import (
        DecodeEngine,
        prepare_serve_params,
        run_open_loop,
    )

    if _fast():
        vocab, d, heads, experts, dff, layers = 128, 32, 2, 2, 64, 2
        slots, max_len, max_new, n_req, rate = 4, 64, 8, 12, 400.0
        prompt_lo, prompt_hi = 4, 12
        naive_req = 4
        slo_ms = 25.0
    else:
        vocab, d, heads, experts, dff, layers = LMC_VOCAB, 256, 4, 4, 512, 2
        slots, max_len, max_new, n_req, rate = 8, 256, 32, 32, 50.0
        prompt_lo, prompt_hi = 16, 48
        naive_req = 8
        slo_ms = 250.0

    params = init_lm_params(jax.random.PRNGKey(0), vocab, d, heads, experts,
                            dff, n_layers=layers)
    rng = np.random.RandomState(3)
    prompts = [list(rng.randint(0, vocab,
                                rng.randint(prompt_lo, prompt_hi)))
               for _ in range(n_req)]

    # ---- naive recompute-per-token baseline (same bf16 weights): the
    # full-prompt pass re-run per token with the K/V outputs thrown away —
    # exactly the work a cache-less fixed-shape serving loop does ----
    bf16_params = prepare_serve_params(params, "bf16")

    def _naive_next(p, toks, pos):
        logits, _ks, _vs = lm_prefill(p, toks, heads)
        return jnp.argmax(
            jax.lax.dynamic_index_in_dim(logits[0], pos, 0, keepdims=False),
            -1)

    naive_next = jax.jit(_naive_next, donate_argnums=())

    def naive_run(reqs):
        total = 0
        t0 = time.perf_counter()
        for prompt in reqs:
            toks = np.zeros((1, max_len), np.int32)
            toks[0, :len(prompt)] = prompt
            pos = len(prompt) - 1
            for _ in range(max_new):
                nxt = int(np.asarray(  # per-token sync IS the baseline
                    naive_next(bf16_params, jnp.asarray(toks), pos)))
                pos += 1
                toks[0, pos] = nxt
                total += 1
        return total, time.perf_counter() - t0

    naive_run(prompts[:1])  # compile + warmup
    naive_total, naive_t = naive_run(prompts[:naive_req])
    naive_rate = naive_total / naive_t

    # ---- the engine under open-loop load (bf16 headline) ----
    def warm(eng):
        # warm every prefill bucket the traffic will hit (a bucket-length
        # prompt compiles exactly that bucket) + the decode step, outside
        # the timed run
        for b in sorted({eng.bucket_for(len(p)) for p in prompts}):
            eng.generate([1] * min(b, max_len - 1), max_new_tokens=2)

    engine = DecodeEngine(params, heads, n_slots=slots, max_len=max_len,
                          serve_dtype="bf16")
    warm(engine)
    report = run_open_loop(engine, prompts, rate_rps=rate,
                           max_new_tokens=max_new, slo_ms=slo_ms)
    stats = engine.stats()

    # ---- int8 weight-only A/B twin ----
    engine8 = DecodeEngine(params, heads, n_slots=slots, max_len=max_len,
                           serve_dtype="int8")
    warm(engine8)
    report8 = run_open_loop(engine8, prompts[:max(n_req // 2, 2)],
                            rate_rps=rate, max_new_tokens=max_new)

    # ---- watch overhead twins (ISSUES 11/12/18): the SAME bf16
    # open-loop run with each runtime watch armed — the lock-order
    # watchdog (lockwatch: the engine's scheduler lock, the registry
    # under it, and the condition handoff all become watched
    # primitives), the process tracer (every request a serve.request
    # span tree, every scheduler iteration an engine.step span, eager
    # JSONL), and the socket watchdog (netwatch: enforced default
    # timeouts, per-endpoint counters, the blocked-too-long stall
    # dumper). Budgets (asserted in test_bench_smoke with one shared
    # noise retry): <5% tokens/s for lockwatch and netwatch; <10% for
    # tracing in fast mode, where the eager line-buffered JSONL sink —
    # the write-ahead durability posture ISSUE 12 chose on purpose —
    # is a fixed per-span cost that a ~0.1s micro-run can't amortize
    # (full-length runs sit well under 5%).
    #
    # Estimator: SAME-ENGINE paired A/B, median-of-5 per side, rounds
    # alternating off/on back to back, each leg replaying the prompt
    # list up to >=36 requests. One fast-mode open-loop run is ~0.2s
    # on CPU, where a single GC pause reads as ±10% "overhead" — the
    # longer legs amortize that, and five rounds give the median room
    # to shed the stragglers. Comparing a twin engine against the
    # headline engine is also out: an engine driven more often keeps
    # its prefix pages and allocator hotter, which measured as a
    # systematic ~1.5% phantom overhead. So each watch is A/B'd on
    # its OWN engine: the off leg runs with the watch disarmed, the
    # on leg re-runs the same engine armed, and the ratio of the two
    # medians isolates pure arming cost. For lockwatch that means
    # armed accounting vs the disarmed WatchedLock flag check (the
    # interception wrapper itself is a few ns per acquire — built in
    # once, identical on both legs). Engine request ids are per-engine
    # monotonic, so the traced rounds share one trace dir without
    # attribution collisions.
    import tempfile

    from deeplearning4j_tpu.scaleout.remote_tracker import (
        StateTrackerClient,
        StateTrackerServer,
    )
    from deeplearning4j_tpu.telemetry import trace as trace_mod
    from deeplearning4j_tpu.utils import lockwatch, netwatch

    lockwatch.reset()
    lockwatch.enable(raise_on_cycle=True)
    try:
        engine_w = DecodeEngine(params, heads, n_slots=slots,
                                max_len=max_len, serve_dtype="bf16")
        warm(engine_w)
    finally:
        lockwatch.disable()
    trace_dir = tempfile.mkdtemp(prefix="bench_serve_trace_")
    tracer = trace_mod.Tracer("serve-bench", trace_dir=trace_dir)
    engine_t = DecodeEngine(params, heads, n_slots=slots,
                            max_len=max_len, serve_dtype="bf16")
    warm(engine_t)
    netwatch.reset()

    # replay the prompt list so every leg carries >=36 requests (a
    # no-op in full mode, where the headline list is already bigger)
    twin_prompts = prompts * max(1, -(-36 // max(len(prompts), 1)))
    trials = {name: {"off": [], "on": []}
              for name in ("lockwatch", "tracing", "netwatch")}
    report_t = None
    for _ in range(5):
        rep = run_open_loop(engine_w, twin_prompts, rate_rps=rate,
                            max_new_tokens=max_new)
        trials["lockwatch"]["off"].append(round(rep.tokens_per_sec, 1))
        lockwatch.enable(raise_on_cycle=True)
        try:
            rep = run_open_loop(engine_w, twin_prompts, rate_rps=rate,
                                max_new_tokens=max_new)
        finally:
            lockwatch.disable()
        trials["lockwatch"]["on"].append(round(rep.tokens_per_sec, 1))
        rep = run_open_loop(engine_t, twin_prompts, rate_rps=rate,
                            max_new_tokens=max_new)
        trials["tracing"]["off"].append(round(rep.tokens_per_sec, 1))
        prev_tracer = trace_mod.set_tracer(tracer)
        try:
            report_t = run_open_loop(engine_t, twin_prompts, rate_rps=rate,
                                     max_new_tokens=max_new)
        finally:
            trace_mod.set_tracer(prev_tracer)
        trials["tracing"]["on"].append(round(report_t.tokens_per_sec, 1))
        rep = run_open_loop(engine, twin_prompts, rate_rps=rate,
                            max_new_tokens=max_new)
        trials["netwatch"]["off"].append(round(rep.tokens_per_sec, 1))
        netwatch.enable()
        try:
            rep = run_open_loop(engine, twin_prompts, rate_rps=rate,
                                max_new_tokens=max_new)
            # a REAL tracker RPC roundtrip inside the armed window so
            # the detail carries live per-endpoint counters: both the
            # client socket and the server handler socket cross the
            # wrap_socket seam
            with StateTrackerServer() as _tsrv:
                _tcli = StateTrackerClient(_tsrv.address)
                _tcli.add_worker("bench")
                _tcli.increment("netwatch_bench", 1.0)
                _tcli.close()
        finally:
            netwatch.disable()
        trials["netwatch"]["on"].append(round(rep.tokens_per_sec, 1))

    watch = lockwatch.summary()
    watch_rec = lockwatch.metrics_record()
    lockwatch.reset()
    nwatch = netwatch.summary()
    nwatch_rec = netwatch.metrics_record()
    netwatch.reset()
    tracer.close()

    def _paired(name):
        off = sorted(trials[name]["off"])[len(trials[name]["off"]) // 2]
        on = sorted(trials[name]["on"])[len(trials[name]["on"]) // 2]
        return off, on, round((1.0 - on / off) * 100.0, 2)

    lock_base_tps, lock_tps, lockwatch_overhead_pct = _paired("lockwatch")
    trace_base_tps, trace_tps, trace_overhead_pct = _paired("tracing")
    nw_base_tps, nw_tps, netwatch_overhead_pct = _paired("netwatch")

    from tools.trace_report import load_trace_dir, serve_attribution

    attribution = serve_attribution(load_trace_dir(trace_dir))
    # the acceptance sum: queue+prefill+decode+gap within 1ms of latency
    attribution_max_err_ms = max(
        (abs(r["total_ms"] - r["queue_wait_ms"] - r["prefill_ms"]
             - r["decode_ms"] - r["gap_ms"])
         for r in attribution if r["status"] != "open"), default=None)

    # ---- ISSUE 16 fast-path twins: the three serve-engine fast paths
    # A/B'd against the plain engine on the traffic shape each exists
    # for, at a SATURATING offered rate (the paced headline rate keeps
    # both sides idle-bound and the ratio reads pure noise — a capacity
    # A/B has to queue work). All greedy, all token-identical by
    # construction (pinned in tests/test_serve.py) — the twins measure
    # ONLY the speed side.
    #
    # (1) prefix on/off: every request carries the SAME hot page-aligned
    #     system prompt (the fleet shape prefix caching exists for, at
    #     its extreme); the on-engine admits each via full-hit page
    #     seeding — zero prefill dispatches — where the off-engine pays
    #     the full-bucket prefill per request. Short generations keep
    #     the run admission-dominated: that's the phase this path
    #     accelerates.
    # (2) spec on/off: the headline prompt mix, decode-heavy, on a
    #     speculative engine (layer-truncated draft, k=2) vs plain.
    #     accepted_per_verify is the quality number (accepted draft
    #     tokens per verify dispatch); with this bench's random-token
    #     prompts the truncated draft accepts little, so expect the
    #     honest <1 ratio here on CPU — the row exists to track drift.
    # (3) chunked vs unchunked: a long-prompt barrage near the decode
    #     window. Chunking is NOT a throughput play — its win is the
    #     inter-token p99 (decode ticks interleave with prefill chunks
    #     instead of stalling behind a monolithic one), so both p99s
    #     ride along as LOWER-IS-BETTER rows.
    from deeplearning4j_tpu.serve import SpeculativeConfig
    from deeplearning4j_tpu.telemetry.registry import MetricsRegistry

    if _fast():
        sys_len, page_tokens, n_fp, fp_new = 56, 8, 24, 4
        long_lo, long_hi, n_long, chunk = 40, 49, 6, 8
    else:
        sys_len, page_tokens, n_fp, fp_new = 224, 16, 24, 8
        long_lo, long_hi, n_long, chunk = 160, 201, 8, 32
    spec_k = 2
    sat_rate = 1e5  # all arrivals effectively immediate → queue saturates
    sys_prompt = list(rng.randint(0, vocab, sys_len))
    fp_prompts = [list(sys_prompt) for _ in range(n_fp)]
    long_prompts = [list(rng.randint(0, vocab,
                                     rng.randint(long_lo, long_hi)))
                    for _ in range(n_long)]

    def _twin(prompts_t, new_tokens, warm_hit=False, **engine_kw):
        # fresh registry per twin so counters (prefill dispatches, cache
        # hits, accepts) are this run's alone, not the process total
        eng = DecodeEngine(params, heads, n_slots=slots, max_len=max_len,
                           serve_dtype="bf16", registry=MetricsRegistry(),
                           **engine_kw)
        for b in sorted({eng.bucket_for(len(p)) for p in prompts_t}):
            eng.generate([1] * min(b, max_len - 1), max_new_tokens=2)
        if warm_hit:
            # two generates: the first inserts the system prompt's pages
            # (the resident steady state), the second takes the hit path
            # so seed-from-pages compiles outside the clock
            eng.generate(sys_prompt, max_new_tokens=1)
            eng.generate(sys_prompt, max_new_tokens=1)
        rep = run_open_loop(eng, prompts_t, rate_rps=sat_rate,
                            max_new_tokens=new_tokens)
        return eng, rep

    # median-of-3 per side for the tracked prefix ratio: one saturated
    # run is ~tens of ms on CPU, where a single GC pause flips the
    # ratio's sign — the median is the honest central tendency (all
    # trials land in the detail so a noisy box is visible, not hidden)
    px_off_trials, px_on_trials = [], []
    for _ in range(3):
        _, rep_off = _twin(fp_prompts, fp_new)
        eng_px, rep_px = _twin(fp_prompts, fp_new, warm_hit=True,
                               prefix_cache=True,
                               prefix_page_tokens=page_tokens)
        px_off_trials.append(round(rep_off.tokens_per_sec, 1))
        px_on_trials.append(round(rep_px.tokens_per_sec, 1))
    px_off = sorted(px_off_trials)[1]
    px_on = sorted(px_on_trials)[1]
    _, rep_soff = _twin(prompts, max_new)
    eng_sp, rep_sp = _twin(prompts, max_new,
                           speculative=SpeculativeConfig(k=spec_k))
    _, rep_coff = _twin(long_prompts, max_new)
    _, rep_ch = _twin(long_prompts, max_new, prefill_chunk=chunk)

    px_stats = eng_px.stats()["prefix_cache"]
    sp_stats = eng_sp.stats()["speculative"]
    fast_path = {
        "traffic": {"sys_tokens": sys_len, "n_requests": n_fp,
                    "fp_new_tokens": fp_new, "page_tokens": page_tokens,
                    "long_prompt_range": [long_lo, long_hi - 1],
                    "n_long_requests": n_long, "prefill_chunk": chunk},
        "baseline_tokens_per_sec": px_off,
        "prefix_on_tokens_per_sec": px_on,
        "prefix_on_vs_off": round(px_on / px_off, 3),
        "prefix_trials": {"off": px_off_trials, "on": px_on_trials},
        "cache_hit_rate": round(px_stats["hit_rate"], 4),
        "cache_tokens_reused": px_stats["tokens_reused"],
        "spec_k": spec_k,
        "spec_off_tokens_per_sec": round(rep_soff.tokens_per_sec, 1),
        "spec_on_tokens_per_sec": round(rep_sp.tokens_per_sec, 1),
        "spec_on_vs_off": round(
            rep_sp.tokens_per_sec / rep_soff.tokens_per_sec, 3),
        "accepted_per_verify": round(
            sp_stats["accepted_tokens"]
            / max(1, sp_stats["verify_steps"]), 3),
        "spec_accept_rate": round(sp_stats["accept_rate"], 4),
        "unchunked_tokens_per_sec": round(rep_coff.tokens_per_sec, 1),
        "chunked_tokens_per_sec": round(rep_ch.tokens_per_sec, 1),
        "chunk_vs_unchunked": round(
            rep_ch.tokens_per_sec / rep_coff.tokens_per_sec, 3),
        "inter_token_p99_ms_unchunked": (
            round(rep_coff.inter_token_p99_ms, 2)
            if rep_coff.inter_token_p99_ms is not None else None),
        "inter_token_p99_ms_chunked": (
            round(rep_ch.inter_token_p99_ms, 2)
            if rep_ch.inter_token_p99_ms is not None else None),
    }

    detail = {
        "slots": slots, "max_len": max_len, "n_requests": n_req,
        "max_new_tokens": max_new, "offered_rps": rate,
        "serve_dtype": "bf16",
        "tokens_per_sec": round(report.tokens_per_sec, 1),
        "latency": {
            "p50_ms": round(report.latency_p50_ms, 2),
            "p95_ms": round(report.latency_p95_ms, 2),
            "p99_ms": round(report.latency_p99_ms, 2),
            "mean_ms": round(report.latency_mean_ms, 2),
            "first_token_p50_ms": (
                round(report.first_token_p50_ms, 2)
                if report.first_token_p50_ms is not None else None),
            "first_token_p99_ms": (
                round(report.first_token_p99_ms, 2)
                if report.first_token_p99_ms is not None else None),
        },
        "completed": report.completed,
        # goodput under SLO (ISSUE 15 satellite): requests completing
        # WITHIN slo_ms per second — the HIGHER-IS-BETTER bench_report
        # row (serve_goodput_rps) ROADMAP 2's fleet bench will gate on
        "goodput": {
            "slo_ms": slo_ms,
            "goodput_rps": round(report.goodput_rps, 3),
            "slo_attainment": round(report.slo_attainment, 4),
        },
        "naive_tokens_per_sec": round(naive_rate, 1),
        "naive_requests": naive_req,
        "serve_vs_naive": round(report.tokens_per_sec / naive_rate, 2),
        "occupancy_mean": round(stats["occupancy_mean"], 2),
        "decode_steps": stats["decode_steps"],
        "prefill_buckets": stats["prefill_buckets"],
        "weight_bytes": stats["weight_bytes"],
        "int8": {
            "tokens_per_sec": round(report8.tokens_per_sec, 1),
            "p50_ms": round(report8.latency_p50_ms, 2),
            "weight_bytes": engine8.weight_bytes,
            "weight_bytes_vs_bf16": round(
                engine8.weight_bytes / max(engine.weight_bytes, 1), 3),
        },
        "watch_twin_trials": trials,
        "lockwatch": {
            "overhead_pct": lockwatch_overhead_pct,
            "tokens_per_sec_unwatched": lock_base_tps,
            "tokens_per_sec_watched": lock_tps,
            "cycles": watch["cycles"],
            "watchdog_dumps": watch["watchdog_dumps"],
            "graph": watch["graph"],
            "engine_lock": watch["locks"].get("serve.engine", {}),
            "metrics": watch_rec,
        },
        "tracing": {
            "overhead_pct": trace_overhead_pct,
            "tokens_per_sec_untraced": trace_base_tps,
            "tokens_per_sec_traced": trace_tps,
            "requests_traced": len(attribution),
            "open_requests": sum(1 for r in attribution
                                 if r["status"] == "open"),
            "attribution_max_err_ms": attribution_max_err_ms,
            "latency_p99_ms_traced": round(report_t.latency_p99_ms, 2),
            "sample_attribution": attribution[-1] if attribution else None,
        },
        "netwatch": {
            "overhead_pct": netwatch_overhead_pct,
            "tokens_per_sec_unwatched": nw_base_tps,
            "tokens_per_sec_watched": nw_tps,
            "endpoints": nwatch["endpoints"],
            "stall_dumps": nwatch["stall_dumps"],
            "default_timeout_s": nwatch["default_timeout_s"],
            "metrics": nwatch_rec,
        },
        "fast_path": fast_path,
    }
    print("STAGE_DETAIL " + json.dumps(detail), flush=True)
    return report.tokens_per_sec


def measure_fleet() -> float:
    """ISSUE 19 fleet bench: the multi-replica router (serve/router.py)
    over real TCP-tracker membership, two in-process replicas each
    running the full FleetReplica serve/heartbeat loops.

    Two phases:

    - healthy: the serve-stage open-loop traffic routed through the
      fleet with session keys (affinity exercised), measured exactly
      like ``serve`` so the ``latency``/``goodput`` detail blocks land
      as fleet_latency_* / fleet_goodput_rps rows in bench_report.
    - chaos: a second batch of longer requests, one replica ``die()``d
      mid-stream (no deregistration — the router must detect it off
      heartbeat staleness), a replacement cold-started from live params
      through the burial callback. Every accepted request must complete
      token-identical to a single-engine oracle; the ``requeue`` block
      carries requeue_to_first_token_ms — the recovery-latency number
      this PR's LOWER-IS-BETTER row tracks (how long a client stream
      stalls across a replica death).

    Headline value = healthy-phase generated-tokens/sec through the
    router (fleet_tokens_per_sec)."""
    import jax
    import numpy as np

    from deeplearning4j_tpu.models.transformer_lm import init_lm_params
    from deeplearning4j_tpu.scaleout.remote_tracker import (
        StateTrackerClient,
        StateTrackerServer,
    )
    from deeplearning4j_tpu.serve import (
        DecodeEngine,
        FleetReplica,
        FleetRouter,
        run_open_loop,
    )

    if _fast():
        vocab, d, heads, experts, dff, layers = 128, 32, 2, 2, 64, 2
        slots, max_len, max_new, n_req, rate = 4, 64, 8, 12, 200.0
        prompt_lo, prompt_hi = 4, 12
        slo_ms = 50.0
        chaos_n, chaos_new = 8, 16
    else:
        vocab, d, heads, experts, dff, layers = LMC_VOCAB, 256, 4, 4, 512, 2
        slots, max_len, max_new, n_req, rate = 8, 256, 32, 24, 50.0
        prompt_lo, prompt_hi = 16, 48
        slo_ms = 250.0
        chaos_n, chaos_new = 12, 32

    params = init_lm_params(jax.random.PRNGKey(0), vocab, d, heads, experts,
                            dff, n_layers=layers)
    rng = np.random.RandomState(7)
    prompts = [list(rng.randint(0, vocab,
                                rng.randint(prompt_lo, prompt_hi)))
               for _ in range(n_req)]
    chaos_prompts = [list(rng.randint(0, vocab,
                                      rng.randint(prompt_lo, prompt_hi)))
                     for _ in range(chaos_n)]
    engine_kw = dict(n_slots=slots, max_len=max_len, serve_dtype="bf16")

    def warm(eng):
        for b in sorted({eng.bucket_for(len(p))
                         for p in prompts + chaos_prompts}):
            eng.generate([1] * min(b, max_len - 1), max_new_tokens=2)

    # the single-engine oracle the chaos phase's outputs are pinned to
    oracle = DecodeEngine(params, heads, **engine_kw)
    warm(oracle)
    expected = [oracle.generate(p, max_new_tokens=chaos_new)
                for p in chaos_prompts]

    with StateTrackerServer() as tsrv:
        replicas = []
        for rid in ("r1", "r2"):
            eng = DecodeEngine(params, heads, **engine_kw)
            warm(eng)
            rep = FleetReplica(eng, tsrv.address, rid,
                               heartbeat_s=0.05, poll_s=0.005,
                               publish_s=0.1)
            rep.start()
            replicas.append(rep)

        spawned = []

        def cold_start(_failed_rid):
            # device-to-device replacement: adopt the live tree through
            # the redistribution plans, rejoin the same membership
            rep = FleetReplica.from_live_params(
                params, heads, tsrv.address, "r3",
                engine_kwargs=engine_kw,
                heartbeat_s=0.05, poll_s=0.005, publish_s=0.1)
            rep.start()
            spawned.append(rep)

        rtracker = StateTrackerClient(tsrv.address)
        router = FleetRouter(rtracker, stale_after_s=0.3, dead_after_s=0.8,
                             poll_s=0.005, cold_start=cold_start)
        # let both replicas publish a first heartbeat + load row so the
        # healthy phase starts with full membership
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            router.step()
            if router.fleet_snapshot()["alive"] >= 2:
                break
            time.sleep(0.02)

        # ---- healthy phase: open-loop through the router, with
        # session keys so affinity is on the measured path ----
        sessions = [f"s{i % 4}" for i in range(n_req)]
        report = run_open_loop(router, prompts, rate_rps=rate,
                               max_new_tokens=max_new, slo_ms=slo_ms,
                               sessions=sessions)
        healthy_snap = router.fleet_snapshot()

        # ---- chaos phase: kill r1 once it is mid-stream on at least
        # one request, let the burial requeue + cold-start machinery
        # finish every request anyway ----
        tok0 = replicas[0].engine.stats()["tokens_total"]
        reqs = [router.submit(p, max_new_tokens=chaos_new,
                              session=f"c{i % 3}")
                for i, p in enumerate(chaos_prompts)]
        t_kill = None
        deadline = time.monotonic() + 120.0
        while router.has_work():
            if time.monotonic() > deadline:
                raise TimeoutError("fleet chaos phase did not drain")
            router.step()
            if t_kill is None:
                # kill off the victim's OWN counters, not the router's
                # sweep-sampled view: fires the instant r1 has generated
                # >= 2 chaos tokens while still holding active work, so
                # the death is mid-stream even when a whole request
                # drains between two router sweeps
                st = replicas[0].engine.stats()
                if st["tokens_total"] >= tok0 + 2 and (
                        st["active_slots"] > 0 or st["queue_depth"] > 0):
                    replicas[0].die()
                    t_kill = time.monotonic()
        snap = router.fleet_snapshot()

        requeued = [r for r in reqs if r.requeues > 0]
        gaps_ms = [(r.t_first_after_requeue - r.t_requeue) * 1000.0
                   for r in requeued
                   if r.t_requeue is not None
                   and r.t_first_after_requeue is not None]
        token_identical = all(r.generated == exp
                              for r, exp in zip(reqs, expected))

        for rep in replicas + spawned:
            rep.stop()
        rtracker.close()

    detail = {
        "replicas": 2, "slots": slots, "max_len": max_len,
        "n_requests": n_req, "max_new_tokens": max_new,
        "offered_rps": rate, "serve_dtype": "bf16",
        "tokens_per_sec": round(report.tokens_per_sec, 1),
        "completed": report.completed,
        "latency": {
            "p50_ms": round(report.latency_p50_ms, 2),
            "p95_ms": round(report.latency_p95_ms, 2),
            "p99_ms": round(report.latency_p99_ms, 2),
            "mean_ms": round(report.latency_mean_ms, 2),
            "first_token_p50_ms": (
                round(report.first_token_p50_ms, 2)
                if report.first_token_p50_ms is not None else None),
            "first_token_p99_ms": (
                round(report.first_token_p99_ms, 2)
                if report.first_token_p99_ms is not None else None),
        },
        "goodput": {
            "slo_ms": slo_ms,
            "goodput_rps": round(report.goodput_rps, 3),
            "slo_attainment": round(report.slo_attainment, 4),
        },
        "healthy": {
            "alive": healthy_snap["alive"],
            "dispatches": {r["replica_id"]: r["dispatches"]
                           for r in healthy_snap["replicas"]},
            "affinity_sessions": len(healthy_snap["affinity"]),
        },
        "chaos": {
            "n_requests": chaos_n, "max_new_tokens": chaos_new,
            "killed_replica": "r1",
            "kill_fired": t_kill is not None,
            "completed": sum(1 for r in reqs if r.t_done is not None),
            "requeued_requests": len(requeued),
            "token_identical": token_identical,
            "failed_replicas": snap["failed_replicas"],
            "alive_after": snap["alive"],
            "replacement_joined": any(
                r["replica_id"] == "r3" and r["state"] == "alive"
                for r in snap["replicas"]),
        },
        # the recovery number: how long a requeued client stream waits
        # between its replica dying and its first post-requeue token
        "requeue": {
            "requeued_requests": len(gaps_ms),
            "requeue_to_first_token_ms": (
                round(float(np.mean(gaps_ms)), 2) if gaps_ms else None),
            "requeue_to_first_token_max_ms": (
                round(max(gaps_ms), 2) if gaps_ms else None),
        },
    }
    print("STAGE_DETAIL " + json.dumps(detail), flush=True)
    return report.tokens_per_sec


def measure_observability() -> float:
    """ISSUE 15 watchtower bench: the SAME open-loop decode-engine run
    twice — unarmed vs with the full watch layer armed (a MetricsHistory
    sampler snapshotting the engine registry on a tight cadence plus an
    AlertEngine evaluating the default rule pack over it, both on
    background threads) — so the headline isolates what *being watched*
    costs the serving hot path.

    Headline value = overhead_pct (armed vs unarmed tokens/s; <5%
    budget asserted in test_bench_smoke with the shared noise retry).
    The detail also proves the chain end to end: the armed run's history
    answers live rate/percentile queries, a deterministic injected-fault
    demo drives nonfinite_step_rate and serve_latency_slo_burn through
    pending→firing with transitions logged, and the alert/history JSONL
    artifacts render through the REAL tools/alert_report.py."""
    import tempfile

    import jax
    import numpy as np

    from deeplearning4j_tpu.models.transformer_lm import init_lm_params
    from deeplearning4j_tpu.serve import DecodeEngine, run_open_loop
    from deeplearning4j_tpu.telemetry.alerts import (
        AlertEngine,
        AlertRule,
        default_rules,
    )
    from deeplearning4j_tpu.telemetry.history import MetricsHistory
    from deeplearning4j_tpu.telemetry.registry import MetricsRegistry

    if _fast():
        vocab, d, heads, experts, dff, layers = 128, 32, 2, 2, 64, 2
        slots, max_len, max_new, n_req, rate = 4, 64, 8, 12, 400.0
        prompt_lo, prompt_hi = 4, 12
    else:
        vocab, d, heads, experts, dff, layers = LMC_VOCAB, 256, 4, 4, 512, 2
        slots, max_len, max_new, n_req, rate = 8, 256, 32, 32, 50.0
        prompt_lo, prompt_hi = 16, 48

    params = init_lm_params(jax.random.PRNGKey(0), vocab, d, heads, experts,
                            dff, n_layers=layers)
    rng = np.random.RandomState(7)
    prompts = [list(rng.randint(0, vocab,
                                rng.randint(prompt_lo, prompt_hi)))
               for _ in range(n_req)]

    def warm(eng):
        for b in sorted({eng.bucket_for(len(p)) for p in prompts}):
            eng.generate([1] * min(b, max_len - 1), max_new_tokens=2)

    # ---- unarmed baseline ----
    reg_base = MetricsRegistry()
    engine = DecodeEngine(params, heads, n_slots=slots, max_len=max_len,
                          serve_dtype="bf16", registry=reg_base)
    warm(engine)
    report = run_open_loop(engine, prompts, rate_rps=rate,
                           max_new_tokens=max_new)

    # ---- armed twin: history sampler + alert evaluator on background
    # threads, sampling/evaluating at a cadence far above production
    # (20Hz/10Hz vs the 1Hz default) so the measured overhead brackets
    # any real deployment ----
    watch_dir = tempfile.mkdtemp(prefix="bench_observability_")
    reg_w = MetricsRegistry()
    engine_w = DecodeEngine(params, heads, n_slots=slots, max_len=max_len,
                            serve_dtype="bf16", registry=reg_w)
    warm(engine_w)
    history = MetricsHistory(
        registry=reg_w, interval_s=0.05,
        spill_path=os.path.join(watch_dir, "history_serve.jsonl"))
    alert_engine = AlertEngine(
        history, rules=default_rules(), registry=reg_w, process="serve",
        interval_s=0.1,
        log_path=os.path.join(watch_dir, "alerts_serve.jsonl"))
    history.start()
    alert_engine.start()
    try:
        report_w = run_open_loop(engine_w, prompts, rate_rps=rate,
                                 max_new_tokens=max_new)
        history.sample_once()  # deterministic final edge for the queries
        states_armed = alert_engine.evaluate_once()
    finally:
        alert_engine.close()
        history.close()
    overhead_pct = round(
        (1.0 - report_w.tokens_per_sec / report.tokens_per_sec) * 100.0, 2)

    # live-query proof off the armed run's real history
    token_rate = history.rate("serve_tokens_total", window_s=300.0)
    p95_windowed = history.percentile_over("serve_request_ms", 95.0,
                                           window_s=300.0)
    quiet = {s["rule"]: s["state"] for s in states_armed}

    # ---- deterministic firing demo: inject the faults the pack watches
    # (guard skips + SLO-busting latencies) into the SAME registry and
    # tick the watch layer — pending→firing transitions land in the log
    # and the alert_report renders them ----
    reg_w.counter("guard_skipped_steps_total").inc(0)
    history.sample_once()
    reg_w.counter("guard_skipped_steps_total").inc(5)
    for _ in range(60):
        reg_w.histogram("serve_request_ms").observe(2600.0)
    time.sleep(0.05)  # a strictly later sample timestamp for the window
    history.sample_once()
    demo_rules = [r for r in default_rules()
                  if r.name in ("nonfinite_step_rate",
                                "serve_latency_slo_burn")]
    demo_engine = AlertEngine(
        history, rules=demo_rules, registry=reg_w, process="serve-demo",
        log_path=os.path.join(watch_dir, "alerts_serve-demo.jsonl"))
    demo_states = {s["rule"]: s["state"]
                   for s in demo_engine.evaluate_once()}
    demo_engine.close()

    from tools.alert_report import collect as alert_collect

    art = alert_collect(watch_dir)
    fired = [t for t in art["transitions"] if t["to"] == "firing"]

    detail = {
        "slots": slots, "max_len": max_len, "n_requests": n_req,
        "offered_rps": rate,
        "tokens_per_sec": round(report.tokens_per_sec, 1),
        "tokens_per_sec_watched": round(report_w.tokens_per_sec, 1),
        "overhead_pct": overhead_pct,
        "history": {
            "samples": int(reg_w.counter("history_samples_total").value),
            "series": int(reg_w.gauge("history_series").value),
            "serve_tokens_rate_per_s": (round(token_rate, 1)
                                        if token_rate is not None
                                        else None),
            "serve_request_p95_windowed_ms": p95_windowed,
        },
        "alerts": {
            "rules": len(default_rules()),
            "quiet_run_firing": sorted(r for r, st in quiet.items()
                                       if st == "firing"),
            "demo_states": demo_states,
            "report_transitions": len(art["transitions"]),
            "report_fired": sorted({t["rule"] for t in fired}),
        },
    }
    print("STAGE_DETAIL " + json.dumps(detail), flush=True)
    return overhead_pct


def measure_runprof() -> float:
    """ISSUE 17 runtime-profiler bench, three proofs in one stage:

    1. **Headline = overhead_pct**: the SAME open-loop decode-engine run
       twice — unarmed vs with the runprof seam armed on the scheduler
       loop (per-tick phase timing + streaming gauge flushes) — the <5%
       budget asserted in test_bench_smoke with the shared noise retry.
    2. **Measured-MFU cross-check**: the composed-flagship single-device
       LM step behind ``runprof=`` for a timed window; the
       ``runprof_measured_mfu`` gauge (XLA FLOPs / fenced device
       seconds / peak) is compared against the same wall-clock MFU
       arithmetic every train stage's headline uses (XLA FLOPs / wall
       step seconds / peak). measured >= wall by construction (the
       fenced device wall excludes host gaps); the ratio lands in the
       detail and tier-1 pins it at test shapes.
    3. **Session -> report chain**: an N-step capture session opened
       over the LM window, the final JSON reloaded through the REAL
       telemetry.runprof.load_session and rendered through the REAL
       tools/profile_report runtime section."""
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu.models.transformer_lm import (
        init_lm_params,
        make_single_device_train_step,
    )
    from deeplearning4j_tpu.serve import DecodeEngine, run_open_loop
    from deeplearning4j_tpu.telemetry.registry import (
        MetricsRegistry,
        flat_record,
    )
    from deeplearning4j_tpu.telemetry.runprof import (
        RunProfiler,
        load_session,
    )
    from deeplearning4j_tpu.telemetry.xprofile import DEFAULT_PEAK_FLOPS

    if _fast():
        vocab, d, heads, experts, dff, layers = 128, 32, 2, 2, 64, 2
        slots, max_len, max_new, n_req, rate = 4, 64, 8, 12, 400.0
        prompt_lo, prompt_hi = 4, 12
        lm_steps = 24
    else:
        vocab, d, heads, experts, dff, layers = LMC_VOCAB, 256, 4, 4, 512, 2
        slots, max_len, max_new, n_req, rate = 8, 256, 32, 32, 50.0
        prompt_lo, prompt_hi = 16, 48
        lm_steps = 48

    params = init_lm_params(jax.random.PRNGKey(0), vocab, d, heads, experts,
                            dff, n_layers=layers)
    rng = np.random.RandomState(7)
    prompts = [list(rng.randint(0, vocab,
                                rng.randint(prompt_lo, prompt_hi)))
               for _ in range(n_req)]

    def warm(eng):
        for b in sorted({eng.bucket_for(len(p)) for p in prompts}):
            eng.generate([1] * min(b, max_len - 1), max_new_tokens=2)

    # ---- unarmed baseline ----
    reg_base = MetricsRegistry()
    engine = DecodeEngine(params, heads, n_slots=slots, max_len=max_len,
                          serve_dtype="bf16", registry=reg_base)
    warm(engine)
    report = run_open_loop(engine, prompts, rate_rps=rate,
                           max_new_tokens=max_new)

    # ---- armed twin: the runprof seam on the scheduler loop ----
    sess_dir = tempfile.mkdtemp(prefix="bench_runprof_")
    reg_p = MetricsRegistry()
    serve_prof = RunProfiler(registry=reg_p, session_dir=sess_dir)
    engine_p = DecodeEngine(params, heads, n_slots=slots, max_len=max_len,
                            serve_dtype="bf16", registry=reg_p,
                            runprof=serve_prof)
    warm(engine_p)
    report_p = run_open_loop(engine_p, prompts, rate_rps=rate,
                             max_new_tokens=max_new)
    overhead_pct = round(
        (1.0 - report_p.tokens_per_sec / report.tokens_per_sec) * 100.0, 2)
    serve_gauges = {
        k: round(v, 4) for k, v in flat_record(
            reg_p, prefixes=("runprof_",)).items()}

    # ---- measured-MFU cross-check on the composed-flagship LM step,
    # with the capture session riding the same window ----
    lm_reg = MetricsRegistry()
    lm_prof = RunProfiler(registry=lm_reg, update_every=4,
                          session_dir=sess_dir)
    lm_step = make_single_device_train_step(heads, donate=True,
                                            runprof=lm_prof)
    toks = jax.random.randint(jax.random.PRNGKey(2),
                              (2, (256 if _fast() else LMC_SEQ) + 1),
                              0, vocab)
    tk, tg = toks[:, :-1], toks[:, 1:]
    lm_params = init_lm_params(jax.random.PRNGKey(1), vocab, d, heads,
                               experts, dff, n_layers=layers)
    lm_params = jax.tree_util.tree_map(jnp.array, lm_params)
    lm_params, loss = lm_step(lm_params, tk, tg)  # compile + AOT profile
    float(loss)
    sid = lm_prof.start_session(steps=lm_steps)
    t0 = time.perf_counter()
    for _ in range(lm_steps):
        lm_params, loss = lm_step(lm_params, tk, tg)
    float(loss)
    wall_step_s = (time.perf_counter() - t0) / lm_steps
    lm_prof.stop_session()  # idempotent vs the steps=N auto-stop

    xprof = lm_step.step_profile
    measured_mfu = flat_record(lm_reg, prefixes=("runprof_",)).get(
        "runprof_measured_mfu")
    wall_mfu = (xprof.flops / wall_step_s / DEFAULT_PEAK_FLOPS
                if xprof is not None and xprof.flops else None)

    # ---- session -> report chain, through the real readers ----
    final_path = lm_prof.sessions_completed[-1]
    sess = load_session(final_path)
    from tools.profile_report import render_runtime_text

    rendered = render_runtime_text([sess])
    summ = sess.get("summary") or {}

    detail = {
        "slots": slots, "max_len": max_len, "n_requests": n_req,
        "offered_rps": rate,
        "tokens_per_sec": round(report.tokens_per_sec, 1),
        "tokens_per_sec_runprof": round(report_p.tokens_per_sec, 1),
        "overhead_pct": overhead_pct,
        "serve_gauges": serve_gauges,
        "lm_steps": lm_steps,
        "wall_step_ms": round(wall_step_s * 1000.0, 3),
        "measured_mfu": (round(measured_mfu, 6)
                         if measured_mfu is not None else None),
        "wall_mfu": round(wall_mfu, 6) if wall_mfu is not None else None,
        "measured_vs_wall_mfu": (round(measured_mfu / wall_mfu, 4)
                                 if measured_mfu and wall_mfu else None),
        "session": {
            "id": sid,
            "steps": summ.get("steps"),
            "partial": sess.get("partial"),
            "device_ms_mean": summ.get("device_ms_mean"),
            "host_ms_mean": summ.get("host_ms_mean"),
            "session_mfu": summ.get("measured_mfu"),
            "chrome_events": len(sess.get("chrome_trace") or []),
            "report_rendered": ("runtime sessions" in rendered
                                and str(sid) in rendered),
        },
    }
    print("STAGE_DETAIL " + json.dumps(detail), flush=True)
    return overhead_pct


def measure_autotune() -> float:
    """ISSUE 20 roofline-guided autotuner A/B: run the real two-phase
    search (AOT-profile every candidate, prune strictly-dominated
    configs without ever executing them, wall-clock only the Pareto
    frontier with paired-median timing) on the composed LM step and the
    decode engine, and report the winner's tuned-vs-default step-time
    ratio. The LM seam's candidates flow through the SAME ``tuned=``
    seam the cache feeds (make_single_device_train_step(tuned=cfg)), so
    the headline measures the production adoption path, not a side
    harness, and every candidate that cannot reproduce the default's
    numerics is disqualified before it can win.

    Headline = LM tuned_vs_default, which is >= 1.0 by construction
    (the default config is always a candidate, so the worst case is
    "tuning found nothing better"). On a CPU round the margin can sit
    inside the ref_micro +/-10% noise band; the detail marks that case
    informational instead of claiming a win.
    """
    from deeplearning4j_tpu.tune import seams as tune_seams
    from deeplearning4j_tpu.tune.search import search
    from deeplearning4j_tpu.tune.space import get_space

    fast = _fast()
    repeats = 3 if fast else 5

    def _run(h):
        return search(get_space(h.seam), h.context, h.default_config,
                      h.compile_fn, h.measure_fn, h.outputs_match,
                      repeats=repeats)

    lm = _run(tune_seams.lm_seam(seq_len=128 if fast else 256,
                                 n_layers=1 if fast else 2))
    sv = _run(tune_seams.serve_seam(n_prompts=3 if fast else 6,
                                    max_new_tokens=4 if fast else 8))

    detail: dict = {"seams": {}, "repeats": repeats}
    for res in (lm, sv):
        detail["seams"][res.seam] = {
            "default": res.default_config,
            "winner": res.winner_config,
            "tuned_vs_default": (round(res.tuned_vs_default, 4)
                                 if res.tuned_vs_default else None),
            "counts": res.counts,
            "rank_correlation": (round(res.rank_correlation, 3)
                                 if res.rank_correlation is not None
                                 else None),
        }
    headline = lm.tuned_vs_default or 1.0
    # informational flag: a sub-10% margin is within the band
    # bench_report treats as machine drift (the ref_micro reference),
    # so a CPU round should read the headline as "search ran, default
    # held" rather than as a measured speedup
    detail["headline_within_noise"] = bool(headline - 1.0 < 0.10)
    detail["note"] = (
        "tuned_vs_default >= 1.0 by construction (default is always a "
        "candidate); headline_within_noise=true means the margin is "
        "inside the ref_micro +/-10% drift band and is informational"
    )
    print("STAGE_DETAIL " + json.dumps(detail), flush=True)
    return headline



# ---------------------------------------------------------------------------
# Stage orchestration. Each stage is `python bench.py --stage NAME`, run by
# main() in a subprocess with a timeout, so a wedged XLA compile is contained.

def _fast() -> bool:
    return os.environ.get("BENCH_FAST") == "1"


def _split_stage(name: str) -> tuple:
    """'conv_wide_bf16' → ('conv', 'bf16'); 'mlp_fp32_true' → ('mlp',
    'fp32_true'); 'attn_long_bf16[_densecore]' → ('attn_long', 'bf16');
    'lm_composed[_densecore]' → ('lm_composed', 'fp32')."""
    if name.startswith("lm_composed"):
        # the flagship LM runs f32 params at DEFAULT matmul precision
        return "lm_composed", "fp32"
    if name.startswith("conv_wide_"):
        precision = name[len("conv_wide_"):]
        if precision.endswith("_im2col"):
            precision = precision[: -len("_im2col")]
        return "conv", precision
    for prefix, variants in (("attn_long_", ("_densecore",)),
                             ("lstm_wide_", ("_nokernels",)),
                             ("mlp_", ("_nofused",))):
        if name.startswith(prefix):
            precision = name[len(prefix):]
            for v in variants:
                if precision.endswith(v):
                    precision = precision[: -len(v)]
            return prefix[:-1], precision
    model, _, precision = name.partition("_")
    return model, precision


def _attn_long_memory_detail() -> dict:
    """Compiled temp-allocation footprint of the T=2048 train step with the
    blockwise core vs the materializing dense core — the O(T)-memory
    evidence for the long-context claim (no execution; the shared
    telemetry/xprofile.py compiled-step introspection of the exact jitted
    program)."""
    import jax

    from deeplearning4j_tpu.nn import functional as F
    from deeplearning4j_tpu.ops.flash_attention import set_attention_impl
    from deeplearning4j_tpu.telemetry.xprofile import profile_compiled

    conf = _conf("attn_long")
    params = F.init_params(conf, jax.random.PRNGKey(0))
    states = F.init_train_state(conf, params)
    x, y = _make_data("attn_long", 1, 2)
    out = {}
    for impl in ("blockwise", "dense"):
        set_attention_impl(impl)
        try:
            step = F.make_train_step(conf)
            prof = profile_compiled(step, params, states, 0, x[0], y[0],
                                    jax.random.PRNGKey(1),
                                    label=f"attn_long_{impl}")
            if prof.temp_bytes is not None:
                out[f"{impl}_temp_mb"] = round(prof.temp_bytes / 1e6, 1)
        finally:
            set_attention_impl(None)
    return out


def run_stage(name: str) -> float:
    steps = 2 * CHUNK if _fast() else None
    if name in ("cpu_mlp_fp32", "cpu_word2vec", "cpu_word2vec_large",
                "cpu_lm_composed"):
        if name == "cpu_mlp_fp32":
            return measure("mlp", "fp32", steps=CHUNK,
                           batch=64 if _fast() else None)
        name = name[len("cpu_"):]
        if name == "lm_composed":
            # forced-CPU baseline: SAME stage, blockwise core, tiny batch
            # (a CPU full-shape step is seconds — per-sample rate is what
            # the vs_cpu ratio needs); telemetry A/B only on the main stage
            os.environ["DL4J_TPU_ATTN_IMPL"] = "blockwise"
            return measure_lm_composed(batch=None if _fast() else 1,
                                       telemetry=False)
    if name.startswith("lm_composed"):
        # the env seam (not set_attention_impl) on purpose: proves the
        # no-code-edit switch the driver's dryrun can use too
        os.environ["DL4J_TPU_ATTN_IMPL"] = (
            "dense" if name.endswith("_densecore") else "blockwise")
        return measure_lm_composed(
            telemetry=not name.endswith("_densecore"))
    if name == "ckpt":
        return measure_ckpt()
    if name == "ckpt_async":
        return measure_ckpt_async()
    if name == "elastic_sync":
        return measure_elastic_sync()
    if name == "elastic_trace":
        return measure_elastic_trace()
    if name == "guardrails":
        return measure_guardrails()
    if name == "profile":
        return measure_profile()
    if name == "optimizer":
        return measure_optimizer()
    if name == "moe":
        return measure_moe()
    if name == "comm_overlap":
        return measure_comm_overlap()
    if name == "ref_micro":
        return measure_ref_micro()
    if name == "serve":
        return measure_serve()
    if name == "fleet":
        return measure_fleet()
    if name == "observability":
        return measure_observability()
    if name == "runprof":
        return measure_runprof()
    if name == "autotune":
        return measure_autotune()
    if name == "word2vec":
        if _fast():
            return measure_word2vec(n_sentences=100, sent_len=20, vocab=200)
        return measure_word2vec()
    if name == "word2vec_sharded":
        from deeplearning4j_tpu.parallel.mesh import data_parallel_mesh
        import jax

        mesh = data_parallel_mesh(min(len(jax.devices()), 8))
        if _fast():
            return measure_word2vec(n_sentences=100, sent_len=20, vocab=200,
                                    mesh=mesh)
        return measure_word2vec(mesh=mesh)
    if name == "word2vec_large":
        if _fast():
            return measure_word2vec(n_sentences=200, sent_len=20, vocab=500,
                                    layer_size=64, batch_size=4096)
        return measure_word2vec(n_sentences=20_000, sent_len=100,
                                vocab=50_000, layer_size=256,
                                batch_size=65_536)
    if name == "mlp_bf16_nofused":
        # A/B: the MLP stage with the pallas fused-dense epilogue forced off
        from deeplearning4j_tpu.ops.pallas_kernels import set_fused_dense

        set_fused_dense(False)
        return measure("mlp", "bf16", steps=steps,
                       batch=64 if _fast() else None)
    model, precision = _split_stage(name)
    if model == "conv" and name.endswith("_im2col"):
        # A/B: the legacy im2col slice+einsum conv core (rounds 2-4) on the
        # same stage — quantifies the round-5 switch to the conv emitter
        from deeplearning4j_tpu.nn.layers.convolution import set_conv_emitter

        set_conv_emitter(False)
        return measure("conv", precision, steps=steps,
                       batch=8 if _fast() else None)
    if model == "attn_long":
        if name.endswith("_densecore"):
            # A/B: force the (T,T)-materializing core on the same model
            from deeplearning4j_tpu.ops.flash_attention import (
                set_attention_impl,
            )

            set_attention_impl("dense")
        rate = measure(model, precision, steps=8 if _fast() else None,
                       batch=2 if _fast() else None)
        if not name.endswith("_densecore") and not _fast():
            print("STAGE_DETAIL " + json.dumps(_attn_long_memory_detail()),
                  flush=True)
        return rate
    if model == "lstm_wide":
        if name.endswith("_nokernels"):
            # A/B: identical stage, pallas kernels forced off
            from deeplearning4j_tpu.ops.pallas_kernels import (
                set_fused_dense,
                set_lstm_gates,
            )

            set_fused_dense(False)
            set_lstm_gates(False)
        return measure(model, precision, steps=16 if _fast() else None,
                       batch=8 if _fast() else None)
    return measure(model, precision, steps=steps,
                   batch=64 if _fast() else None)


# (stage, per-stage cap seconds). CPU baseline runs FIRST: it is the
# vs_baseline denominator and must land even if the TPU tunnel is slow.
# caps sized for a slow tunnel day: the axon link's compile+fetch latency
# varies ~2x by time of day (mlp_bf16 was observed to need >110s under load)
STAGES = [
    # the ISSUE 16 noise reference runs before everything: its rate is
    # the machine-drift denominator bench_report normalizes every other
    # row by, so it must land even on a round that later runs out of
    # budget (and running first means it samples the same box state the
    # expensive stages are about to see)
    ("ref_micro", 60),
    ("cpu_mlp_fp32", 180),
    ("mlp_bf16", 180),
    ("mlp_bf16_nofused", 150),
    ("mlp_fp32", 150),
    ("mlp_fp32_true", 150),
    ("lenet_bf16", 150),
    ("conv_wide_bf16", 170),
    ("conv_wide_bf16_im2col", 150),
    ("lstm_bf16", 170),
    ("lstm_fp32", 130),
    ("lstm_wide_bf16", 200),
    ("lstm_wide_bf16_nokernels", 170),
    ("attn_bf16", 170),
    ("attn_long_bf16", 220),
    ("attn_long_bf16_densecore", 170),
    ("cpu_lm_composed", 280),
    ("lm_composed", 280),
    ("lm_composed_densecore", 240),
    ("ckpt", 150),
    ("ckpt_async", 200),
    ("elastic_sync", 200),
    ("elastic_trace", 200),
    ("guardrails", 220),
    ("profile", 220),
    ("optimizer", 240),
    ("moe", 220),
    ("comm_overlap", 240),
    ("serve", 300),
    ("fleet", 300),
    ("observability", 240),
    ("runprof", 260),
    ("autotune", 420),
    ("cpu_word2vec", 150),
    ("word2vec", 120),
    ("word2vec_sharded", 150),
    ("cpu_word2vec_large", 300),
    ("word2vec_large", 200),
]


def _flush_partial(detail: dict) -> None:
    with open(PARTIAL_PATH, "w") as f:
        json.dump(detail, f, indent=1)


def _spawn(stage: str, timeout: float) -> tuple:
    """Run one stage in a subprocess; (rate, split_dict|None, error|None)."""
    env = dict(os.environ)
    if stage.startswith("cpu_"):
        # JAX_PLATFORMS env does NOT stick here (the ambient sitecustomize
        # pins the TPU programmatically) — the child flips jax.config before
        # first backend use instead, keyed off this variable.
        env["BENCH_FORCE_CPU"] = "1"
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--stage", stage],
            capture_output=True, text=True, timeout=timeout, cwd=REPO, env=env,
        )
    except subprocess.TimeoutExpired:
        return None, None, f"timeout>{timeout:.0f}s"
    rate, split = None, None
    for line in out.stdout.splitlines():
        if line.startswith("STAGE_RESULT "):
            rate = float(line.split()[1])
        elif line.startswith("W2V_SPLIT "):
            split = json.loads(line[len("W2V_SPLIT "):])
        elif line.startswith("STAGE_DETAIL "):
            split = json.loads(line[len("STAGE_DETAIL "):])
    if rate is not None:
        return rate, split, None
    tail = (out.stderr or out.stdout or "").strip().splitlines()[-3:]
    return None, None, f"rc={out.returncode}: " + " | ".join(tail)


def main() -> None:
    default_budget = sum(cap for _, cap in STAGES) + 60
    budget = float(os.environ.get("BENCH_BUDGET_SEC", str(default_budget)))
    deadline = time.monotonic() + budget
    detail: dict = {
        "precision_note": (
            "fp32 = DEFAULT matmul precision (one bf16 MXU pass; measured "
            "153.5 TF/s on 4096^3 vs 185.7 bf16 — tools/"
            "probe_matmul_precision.py); fp32_true = HIGHEST (bf16x6, "
            "29.7 TF/s). Each MFU is vs its own peak: bf16/fp32 197 TF/s, "
            "fp32_true 32.8 TF/s."
        ),
    }

    # BENCH_ONLY="a,b" runs just those stages through the same budget/
    # subprocess discipline — how test_bench_smoke guards a new stage
    # without paying for the whole suite
    only = [s.strip() for s in os.environ.get("BENCH_ONLY", "").split(",")
            if s.strip()]
    for stage, cap in STAGES:
        if only and stage not in only:
            continue
        if "word2vec" in stage:
            key = f"{stage}_words_per_sec"
        elif stage == "ckpt":
            key = f"{stage}_save_mb_per_sec"
        elif stage == "ckpt_async":
            key = f"{stage}_blocking_vs_background"
        elif stage == "elastic_sync":
            key = f"{stage}_steps_per_sec"
        elif stage in ("elastic_trace", "guardrails", "profile",
                       "observability", "runprof"):
            key = f"{stage}_overhead_pct"
        elif stage == "optimizer":
            # replicated/sharded compiled peak-bytes ratio: >1 means the
            # ZeRO-sharded update's footprint is smaller (tracked by
            # bench_report; the sharded blob's absolute peak rides the
            # LOWER-IS-BETTER optimizer_profile_peak_bytes row)
            key = f"{stage}_peak_bytes_ratio"
        elif stage in ("moe", "serve", "fleet"):
            key = f"{stage}_tokens_per_sec"
        elif stage == "comm_overlap":
            # strict/overlapped pp step-time ratio (>1 = overlap faster)
            key = f"{stage}_overlap_vs_strict"
        elif stage == "autotune":
            # default/tuned LM step-time ratio (>1 = search found a
            # faster numerics-identical config; 1.0 = default held)
            key = f"{stage}_tuned_vs_default"
        else:
            key = f"{stage}_samples_per_sec"
        remaining = deadline - time.monotonic()
        if remaining < 25:
            detail[key] = None
            detail[f"{stage}_status"] = "skipped_budget"
            _flush_partial(detail)
            continue
        rate, split, err = _spawn(stage, min(cap, remaining - 5))
        if rate is None:
            detail[key] = None
            detail[f"{stage}_status"] = f"failed: {err}"
            print(f"bench stage {stage} FAILED: {err}", file=sys.stderr)
        else:
            detail[key] = round(rate, 1)
            if split:
                subkey = ("host_device_split" if "word2vec" in stage
                          else "detail")
                detail[f"{stage}_{subkey}"] = split
            model, precision = _split_stage(stage)
            if model in TRAIN_FLOPS:
                detail[f"{stage}_mfu"] = round(mfu(model, rate, precision), 4)
        _flush_partial(detail)

    cpu = detail.get("cpu_mlp_fp32_samples_per_sec")
    value = detail.get("mlp_bf16_samples_per_sec")
    if value is None:  # fall back so the line always carries a number
        value = detail.get("mlp_fp32_samples_per_sec") or 0.0
    vs = round(value / cpu, 2) if (cpu and value) else None
    w2v_tpu = detail.get("word2vec_words_per_sec")
    w2v_cpu = detail.get("cpu_word2vec_words_per_sec")
    if w2v_tpu and w2v_cpu:
        detail["word2vec_vs_cpu"] = round(w2v_tpu / w2v_cpu, 2)
    w2vl_tpu = detail.get("word2vec_large_words_per_sec")
    w2vl_cpu = detail.get("cpu_word2vec_large_words_per_sec")
    if w2vl_tpu and w2vl_cpu:
        detail["word2vec_large_vs_cpu"] = round(w2vl_tpu / w2vl_cpu, 2)
    w2vs = detail.get("word2vec_sharded_words_per_sec")
    if w2vs and w2v_tpu:
        detail["word2vec_sharded_vs_single"] = round(w2vs / w2v_tpu, 2)
    co = detail.get("comm_overlap_detail", {})
    if co:
        # lift the stage's two other A/B ratios to tracked top-level rows
        # (the headline already carries pp overlap_vs_strict)
        if "a2a" in co:
            detail["comm_overlap_a2a_2d_vs_flat"] = co["a2a"]["2d_vs_flat"]
        if "ring" in co:
            detail["comm_overlap_ring_prefetch_vs_rotate_after"] = \
                co["ring"]["prefetch_vs_rotate_after"]
    at = detail.get("autotune_detail", {})
    sv_ratio = ((at.get("seams") or {}).get("serve") or {}).get(
        "tuned_vs_default")
    if sv_ratio:
        # lift the serve engine's tuned-vs-default to a tracked row next
        # to the LM headline (both HIGHER-IS-BETTER, >= 1.0 by design)
        detail["autotune_serve_tuned_vs_default"] = sv_ratio
    rp = detail.get("runprof_detail", {})
    if rp and rp.get("measured_mfu") is not None:
        # lift the cross-check MFU to a tracked top-level row so
        # bench_report trends it next to runprof_overhead_pct
        detail["runprof_measured_mfu"] = rp["measured_mfu"]
    lmc = detail.get("lm_composed_samples_per_sec")
    lmc_dense = detail.get("lm_composed_densecore_samples_per_sec")
    if lmc and lmc_dense:
        detail["lm_composed_vs_densecore"] = round(lmc / lmc_dense, 2)
    lmc_cpu = detail.get("cpu_lm_composed_samples_per_sec")
    if lmc and lmc_cpu:
        detail["lm_composed_vs_cpu"] = round(lmc / lmc_cpu, 2)
    detail["lm_composed_note"] = (
        "lm_composed = the multi-block (n_layers=2) transformer-LM "
        "flagship (causal MHA + top-2 MoE FFN, T=2048, d_model=512, "
        "V=2048, E=4 dense experts) trained end to end on one chip with "
        "the blockwise flash core forced via DL4J_TPU_ATTN_IMPL; "
        "_densecore is the same stage with the (T,T)-materializing core; "
        "cpu_lm_composed is the same blockwise stage in a forced-CPU "
        "child (batch=1). MFU is vs the fp32-DEFAULT peak; dense_moe "
        "executes all E experts per token and the FLOP model counts that."
    )
    detail["moe_note"] = (
        "moe = one grouped MoE layer (top-2 router + E expert FFNs, "
        "E = G x expert-axis size) trained on a dp×ep mesh, A/B-ing the "
        "two dispatch impls (parallel/moe.py): alltoall = GShard capacity "
        "exchange (tokens sharded over the expert axis too, comm "
        "proportional to E·C·d), replicated = replicated-token compute + "
        "dense psum combine (comm O(n_row·d) regardless of occupancy). "
        "Value is alltoall tokens/s at G=4; the detail blob carries every "
        "(impl, G) config's tokens/s, estimated per-device comm bytes, "
        "capacity, and measured drop fraction."
    )
    detail["comm_overlap_note"] = (
        "comm_overlap = ISSUE 14 comm/compute-overlap A/Bs: (1) flat vs "
        "hierarchical 2D MoE all_to_all on dp×ep (the expert axis "
        "factorized per arXiv:2112.01075 — identical routed values, two "
        "group-factorized exchange definitions replacing each flat one), "
        "(2) strict vs double-buffered-overlap pipeline ticks on dp×pp "
        "(ppermute of tick t's output issued while tick t+1 computes; "
        "bit-identical loss+params), (3) rotate-after vs prefetch ring "
        "attention on dp×sp (bit-identical). Value is the strict/"
        "overlapped pp step-time ratio; each config records its compiled "
        "StepProfile comm fraction, and counted_configs gates which A/Bs "
        "are claimable as overlap wins (CPU collectives are memcpys, so "
        "ratios there are informational). The 2D a2a step profile embeds "
        "as the stage blob; comm_overlap_collective_wire_bytes rides the "
        "LOWER-IS-BETTER bench_report row."
    )
    detail["serve_note"] = (
        "serve = ISSUE 10 decode engine (deeplearning4j_tpu/serve/): the "
        "flagship LM generating under a synthetic open-loop (Poisson) "
        "traffic generator through the KV-cached continuous-batching "
        "scheduler, bf16 weights. Value is generated tokens/s; the detail "
        "carries exact p50/p95 request latency (LOWER-IS-BETTER rows in "
        "bench_report), the naive recompute-per-token baseline at the SAME "
        "bf16 weights (one full forward over the padded window per token, "
        "sequential — what cli predict used to do), the serve_vs_naive "
        "ratio, mean slot occupancy, the int8 weight-only A/B twin "
        "(serve_dtype seam, serve/quant.py), and the ISSUE 12 tracing "
        "twin: the same open-loop run with request-scoped spans armed "
        "(trace_overhead_pct <5% budget) plus the per-request latency "
        "attribution reconstructed through tools/trace_report.py. "
        "Latency rows carry p50/p95/p99 (ISSUE 12: the SLO tail)."
    )
    detail["word2vec_sharded_note"] = (
        "word2vec_sharded = the toy word2vec stage driven through "
        "make_sharded_sgns_step on the data-parallel mesh (pair batches "
        "sharded over the data axis, one in-graph psum per step over ICI) "
        "— the next lever the r05 word2vec note called out; "
        "word2vec_sharded_vs_single compares it to the single-chip "
        "device-epoch stage at the same corpus."
    )
    detail["guardrails_note"] = (
        "guardrails = ISSUE 8 numerical-fault guard A/B: the composed-"
        "flagship single-device step with the in-graph guard (loss/grad "
        "finiteness + skip-on-nonfinite select, optimize/guardrails.py) "
        "vs the identical unguarded step, paired-median overhead percent "
        "(<5% budget, asserted in test_bench_smoke); the detail's "
        "recovery block demos an injected-NaN batch being skipped "
        "(params carried bitwise, finite) and replayed from its bundle "
        "via tools/step_replay.py."
    )
    detail["runprof_note"] = (
        "runprof = ISSUE 17 runtime-profiler A/B: the open-loop serve "
        "stage unarmed vs with the runprof= seam timing every scheduler "
        "tick (telemetry/runprof.py ring buffers + streaming gauges), "
        "overhead percent (<5% budget, asserted in test_bench_smoke); "
        "the detail carries the composed-LM measured-MFU cross-check "
        "(runprof_measured_mfu gauge — XLA FLOPs / fenced device "
        "seconds — vs the wall-clock MFU arithmetic; measured >= wall "
        "by construction) and an N-step capture session reloaded and "
        "rendered through the real load_session/profile_report chain. "
        "runprof_measured_mfu rides its own tracked row."
    )
    detail["profile_note"] = (
        "profile = ISSUE 9 compiled-step profiler A/B: the composed-"
        "flagship single-device step behind the profile= seam "
        "(telemetry/xprofile.py — AOT lower/compile once, StepProfile "
        "captured from XLA cost/memory analysis + the HLO collective "
        "inventory, then the SAME executable every call) vs the identical "
        "plain step, paired-median overhead percent (<5% budget, asserted "
        "in test_bench_smoke). The detail embeds the StepProfile blob, "
        "the analytic-vs-XLA FLOPs cross-check, the measured-MFU/roofline "
        "attribution, and the memory-watermark sampler pass; "
        "tools/profile_report.py diffs these blobs across rounds."
    )
    detail["optimizer_note"] = (
        "optimizer = ISSUE 13 in-graph optimizer A/B on the composed "
        "dp×ep flagship: SGD vs Adam(replicated update) vs Adam/LAMB "
        "(ZeRO-style update-sharded per arXiv:2004.13336 — each dp "
        "replica stores+updates 1/dp of the moments and allgathers "
        "params; optimize/updaters.py). Value is the replicated/sharded "
        "compiled peak-bytes ratio (>1 = sharded smaller); the detail "
        "carries per-config steps/s + StepProfile footprint + measured "
        "per-replica moment bytes, the sharded-vs-replicated parity "
        "check at identical math, and the sharded Adam profile blob "
        "(optimizer_profile_peak_bytes, LOWER-IS-BETTER in bench_report)."
    )
    detail["ckpt_note"] = (
        "ckpt = sharded save/restore (scaleout/ckpt) of the composed-LM "
        "params at dp×ep through the real Checkpointer (per-shard npz + "
        "atomic manifest + retention); value is save MB/s, detail carries "
        "restore MB/s, bytes, and chunk/file counts."
    )
    detail["attn_note"] = (
        "attn_bf16 (T=64, d=256) is the r04-continuity stage and is "
        "model-bound at that sequence length (the score matmuls are 64x64; "
        "the dense core is correct there — blockwise dispatch starts at "
        "T>=1024). attn_long_bf16 (T=2048, d_model=512) is the "
        "representative long-context stage: blockwise core, O(T) temps "
        "(see attn_long_bf16_detail), with the _densecore twin as the A/B."
    )
    detail["word2vec_note"] = (
        "r05 attribution (on-chip ablations, models/word2vec.py): scatter-"
        "adds were 67-69% of the r04 SGNS epoch at both scales, row-"
        "serialized; shared negatives (pWord2Vec recipe) + window-reduced "
        "center rows cut scatter/gather row ops ~4x, and fit() no longer "
        "downloads the embedding tables (device-authoritative, lazy host "
        "sync — the 2x51 MB download WAS the large-scale drain). Single "
        "chip: 119k -> 890k words/s on the identical toy stage (7.5x "
        "r04); the same code also lifts the 1-core XLA-CPU baseline "
        "(55.8k -> 154k), and at the realistic scale (V=50k, D=256, 2M "
        "words) the chip holds ~800k vs 41k CPU — the row-op bound "
        "crushes a single core while the chip streams it. SGNS at D<=256 "
        "has ~0 MXU content; the next lever is the data-parallel mesh "
        "path (make_sharded_sgns_step, psum over ICI), not more "
        "single-chip row-op tuning."
    )
    print(json.dumps({
        "metric": "mnist_mlp_train_samples_per_sec_per_chip",
        "value": value,
        "unit": "samples/sec",
        "vs_baseline": vs,
        "detail": detail,
    }))


if __name__ == "__main__":
    if len(sys.argv) == 3 and sys.argv[1] == "--stage":
        if os.environ.get("BENCH_FORCE_CPU") == "1":
            import jax

            jax.config.update("jax_platforms", "cpu")
            if sys.argv[2] in ("moe", "word2vec_sharded", "optimizer",
                               "comm_overlap"):
                # mesh stages need multiple devices; fake 8 CPU devices
                # BEFORE first backend use (same trick as tests/conftest)
                from deeplearning4j_tpu.compat import set_host_device_count

                set_host_device_count(8)
        if sys.argv[2].endswith("_fp32_true"):
            import jax

            # must precede tracing: HIGHEST = bf16x6 passes ~ true fp32
            jax.config.update("jax_default_matmul_precision", "highest")
        print("STAGE_RESULT", run_stage(sys.argv[2]), flush=True)
    else:
        main()
