"""Benchmark: MNIST MLP training throughput (BASELINE config #1).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

- value: steady-state training samples/sec/chip on the default platform
  (the real TPU chip under the driver).
- vs_baseline: ratio vs the same training step measured in a CPU subprocess —
  the stand-in for the reference's nd4j-native CPU backend (the reference
  publishes no numbers, BASELINE.md; its jblas CPU path is the comparison
  point named in BASELINE.json's north star, target ≥5×).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

BATCH = 512
WARMUP = 5
MEASURE = 30
HID1, HID2 = 500, 300


def measure(steps: int = MEASURE, batch: int = BATCH,
            chunk: int = 10) -> float:
    """Steady-state training samples/sec with the step loop kept ON DEVICE:
    `chunk` steps run as one lax.scan program per dispatch, so the metric
    reflects device throughput rather than host→device dispatch latency
    (which dominates per-step dispatch through a remote tunnel)."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.datasets.fetchers import synthetic_mnist
    from deeplearning4j_tpu.models.zoo import mnist_mlp
    from deeplearning4j_tpu.nn import functional as F

    conf = mnist_mlp(HID1, HID2)
    params = F.init_params(conf, jax.random.PRNGKey(0))
    states = F.init_train_state(conf, params)
    epoch = F.make_train_epoch(conf, chunk, donate=True)

    xs, ys = synthetic_mnist(batch * chunk)
    x = jnp.asarray(xs).reshape(chunk, batch, -1)
    y = jax.nn.one_hot(jnp.asarray(ys), 10, dtype=jnp.float32).reshape(
        chunk, batch, -1
    )
    key = jax.random.PRNGKey(1)

    for i in range(WARMUP):
        params, states, scores = epoch(params, states, jnp.asarray(i), x, y, key)
    jax.block_until_ready(params)

    n_chunks = max(steps // chunk, 1)
    t0 = time.perf_counter()
    for i in range(n_chunks):
        params, states, scores = epoch(
            params, states, jnp.asarray(i * chunk), x, y, key
        )
    jax.block_until_ready(params)
    dt = time.perf_counter() - t0
    assert bool(jnp.isfinite(scores[-1])), "non-finite training score"
    return n_chunks * chunk * batch / dt


def _cpu_baseline() -> float:
    """Run the same measurement on CPU in a subprocess (jax config must be
    flipped before backend init; the ambient sitecustomize pins the TPU)."""
    code = (
        "import jax\n"
        "jax.config.update('jax_platforms','cpu')\n"
        f"import sys; sys.path.insert(0, {os.path.dirname(os.path.abspath(__file__))!r})\n"
        "from bench import measure\n"
        "print('CPS', measure(steps=10))\n"
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=600,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        for line in out.stdout.splitlines():
            if line.startswith("CPS "):
                return float(line.split()[1])
    except Exception:
        pass
    return 0.0


def main() -> None:
    value = measure()
    cpu = _cpu_baseline()
    vs = value / cpu if cpu > 0 else 0.0
    print(json.dumps({
        "metric": "mnist_mlp_train_samples_per_sec_per_chip",
        "value": round(value, 1),
        "unit": "samples/sec",
        "vs_baseline": round(vs, 2),
    }))


if __name__ == "__main__":
    main()
