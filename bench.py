"""Benchmarks: MNIST MLP + LeNet training throughput (BASELINE configs #1, #2).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "detail"}.

- value: steady-state bf16 training samples/sec/chip for the MLP on the
  default platform (the real TPU chip under the driver). Mixed precision =
  bf16 compute on the MXU with fp32 master params (ops/dtypes.py Policy);
  a loss-parity test (tests/test_mixed_precision.py) gates bf16 vs fp32
  accuracy.
- vs_baseline: ratio vs the same fp32 training step measured in a CPU
  subprocess — the stand-in for the reference's nd4j-native CPU backend
  (the reference publishes no numbers, BASELINE.md; its jblas CPU path is
  the comparison point named in BASELINE.json's north star, target ≥5×).
- detail: fp32/bf16 throughput for both models plus model FLOP utilization
  (MFU) against the chip's bf16 peak.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

BATCH = 512
WARMUP = 5
MEASURE = 30
HID1, HID2 = 500, 300

# TPU v5e (v5 lite) peak bf16 matmul throughput per chip.
PEAK_BF16_FLOPS = 197e12

# Analytic model FLOPs per training sample (fwd matmul/conv FLOPs ×3 for
# fwd + both backward matmuls; elementwise ops are bandwidth, not FLOP,
# bound and excluded — standard MFU accounting).
MLP_FWD_FLOPS = 2 * (784 * HID1 + HID1 * HID2 + HID2 * 10)
# LeNet: conv1 24²×6×(5²×1), conv2 8²×16×(5²×6), dense 256×120, 120×84, 84×10
LENET_FWD_FLOPS = 2 * (
    24 * 24 * 6 * 25 + 8 * 8 * 16 * 150 + 256 * 120 + 120 * 84 + 84 * 10
)
TRAIN_FLOPS = {"mlp": 3 * MLP_FWD_FLOPS, "lenet": 3 * LENET_FWD_FLOPS}


def _conf(model: str):
    from deeplearning4j_tpu.models.zoo import lenet, mnist_mlp

    return mnist_mlp(HID1, HID2) if model == "mlp" else lenet()


def measure(model: str = "mlp", precision: str = "fp32",
            steps: int = MEASURE, batch: int = BATCH,
            chunk: int = 10) -> float:
    """Steady-state training samples/sec with the step loop kept ON DEVICE:
    `chunk` steps run as one lax.scan program per dispatch, so the metric
    reflects device throughput rather than host→device dispatch latency
    (which dominates per-step dispatch through a remote tunnel)."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.datasets.fetchers import synthetic_mnist
    from deeplearning4j_tpu.nn import functional as F
    from deeplearning4j_tpu.ops.dtypes import BF16_COMPUTE

    conf = _conf(model)
    policy = BF16_COMPUTE if precision == "bf16" else None
    params = F.init_params(conf, jax.random.PRNGKey(0))
    states = F.init_train_state(conf, params)
    epoch = F.make_train_epoch(conf, chunk, donate=True, policy=policy)

    xs, ys = synthetic_mnist(batch * chunk)
    x = jnp.asarray(xs).reshape(chunk, batch, -1)
    y = jax.nn.one_hot(jnp.asarray(ys), 10, dtype=jnp.float32).reshape(
        chunk, batch, -1
    )
    key = jax.random.PRNGKey(1)

    for i in range(WARMUP):
        params, states, scores = epoch(params, states, jnp.asarray(i), x, y, key)
    jax.block_until_ready(params)

    n_chunks = max(steps // chunk, 1)
    t0 = time.perf_counter()
    for i in range(n_chunks):
        params, states, scores = epoch(
            params, states, jnp.asarray(i * chunk), x, y, key
        )
    jax.block_until_ready(params)
    dt = time.perf_counter() - t0
    assert bool(jnp.isfinite(scores[-1])), "non-finite training score"
    return n_chunks * chunk * batch / dt


def _cpu_baseline() -> float:
    """Run the fp32 MLP measurement on CPU in a subprocess (jax config must
    be flipped before backend init; the ambient sitecustomize pins the TPU)."""
    code = (
        "import jax\n"
        "jax.config.update('jax_platforms','cpu')\n"
        f"import sys; sys.path.insert(0, {os.path.dirname(os.path.abspath(__file__))!r})\n"
        "from bench import measure\n"
        "print('CPS', measure(steps=10))\n"
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=600,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        for line in out.stdout.splitlines():
            if line.startswith("CPS "):
                return float(line.split()[1])
    except Exception:
        pass
    return 0.0


def mfu(model: str, samples_per_sec: float) -> float:
    return samples_per_sec * TRAIN_FLOPS[model] / PEAK_BF16_FLOPS


def measure_word2vec(n_sentences: int = 2000, sent_len: int = 100,
                     vocab: int = 5000) -> float:
    """End-to-end Word2Vec skip-gram words/sec (BASELINE config #4): host
    tokenization + vectorized pair generation + device SGNS steps. Counted in
    corpus words per second, the reference's unit (Word2Vec.java:303-342)."""
    import time as _time

    import numpy as np

    from deeplearning4j_tpu.models.word2vec import Word2Vec
    from deeplearning4j_tpu.text.sentence_iterator import (
        CollectionSentenceIterator,
    )

    rng = np.random.default_rng(0)
    # zipf-ish corpus so the unigram table and subsampling do real work
    words = [f"w{i}" for i in range(vocab)]
    probs = 1.0 / np.arange(1, vocab + 1)
    probs /= probs.sum()
    sents = [
        " ".join(np.array(words)[rng.choice(vocab, sent_len, p=probs)])
        for _ in range(n_sentences)
    ]
    vec = Word2Vec(
        sentence_iterator=CollectionSentenceIterator(sents),
        layer_size=100, window=5, negative=5, iterations=1,
        sample=1e-3, batch_size=8192, seed=1,
    )
    vec.build_vocab()
    t0 = _time.perf_counter()
    vec.fit()
    dt = _time.perf_counter() - t0
    return n_sentences * sent_len / dt


def main() -> None:
    detail = {}
    for model in ("mlp", "lenet"):
        for precision in ("fp32", "bf16"):
            sps = measure(model, precision)
            detail[f"{model}_{precision}_samples_per_sec"] = round(sps, 1)
            detail[f"{model}_{precision}_mfu"] = round(mfu(model, sps), 4)
    detail["word2vec_words_per_sec"] = round(measure_word2vec(), 1)
    cpu = _cpu_baseline()
    detail["cpu_fp32_mlp_samples_per_sec"] = round(cpu, 1)
    value = detail["mlp_bf16_samples_per_sec"]
    vs = value / cpu if cpu > 0 else 0.0
    print(json.dumps({
        "metric": "mnist_mlp_train_samples_per_sec_per_chip",
        "value": value,
        "unit": "samples/sec",
        "vs_baseline": round(vs, 2),
        "detail": detail,
    }))


if __name__ == "__main__":
    main()
