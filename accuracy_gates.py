"""Real-data accuracy gates (BASELINE north star: "train to reference accuracy").

The reference proves model quality by downloading MNIST and training to
accuracy (ref: datasets/fetchers/MnistDataFetcher.java:39-85, examples in
MultiLayerTest). This environment has no egress, so the gates run on the real
datasets that ARE available locally:

- Fisher's Iris (embedded, the same 150-sample data the reference ships as
  iris.dat in dl4j-test-resources),
- the UCI handwritten digits set bundled with scikit-learn (1,797 genuine
  8x8 scans — the closest real MNIST-class data available offline).

MNIST-sized gates additionally run on the synthetic MNIST surrogate and are
LABELED synthetic — they are convergence proofs for the 784-input configs,
never claimed as real-data accuracy. Real-MNIST gates are recorded as
``pending`` with the reason.

Run:  python accuracy_gates.py  →  prints JSON and writes ACCURACY_r05.json
"""

from __future__ import annotations

import json
import time

import numpy as np


def _split(x: np.ndarray, y: np.ndarray, n_train: int, seed: int = 0):
    perm = np.random.default_rng(seed).permutation(x.shape[0])
    x, y = x[perm], y[perm]
    return (x[:n_train], y[:n_train]), (x[n_train:], y[n_train:])


def _one_hot(y: np.ndarray, k: int) -> np.ndarray:
    out = np.zeros((y.shape[0], k), np.float32)
    out[np.arange(y.shape[0]), y] = 1.0
    return out


def _accuracy(net, x: np.ndarray, y: np.ndarray, num_classes: int) -> float:
    # num_classes is the KNOWN class count — inferring it from the test
    # split's max label would shrink the one-hot matrix (and corrupt the
    # Evaluation) whenever the split happens to lack the top class
    from deeplearning4j_tpu.eval import Evaluation

    ev = Evaluation()
    ev.eval(_one_hot(y, num_classes), np.asarray(net.label_probabilities(x)))
    return ev.accuracy()


def gate_iris(epochs: int = 300, threshold: float = 0.93) -> dict:
    """MLP on real Iris, 120/30 split."""
    import jax

    from deeplearning4j_tpu.datasets.fetchers import iris_data
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    x, y = iris_data()
    (xtr, ytr), (xte, yte) = _split(x, y, 120)
    conf = (
        NeuralNetConfiguration.Builder()
        .n_in(4).n_out(16).activation_function("tanh")
        .lr(0.05).momentum(0.9).use_ada_grad(True)
        .num_iterations(1).seed(42).weight_init("VI")
        .list(2)
        .override(1, layer_type="OUTPUT", n_in=16, n_out=3,
                  activation_function="softmax", loss_function="MCXENT")
        .pretrain(False).backward(True).build()
    )
    net = MultiLayerNetwork(conf).init()
    t0 = time.perf_counter()
    net.fit_epochs(xtr, num_epochs=epochs, labels=_one_hot(ytr, 3))
    jax.block_until_ready(net.params())  # fence: time training, not enqueue
    wall = time.perf_counter() - t0
    acc = _accuracy(net, xte, yte, 3)
    return {"gate": "iris_mlp", "dataset": "iris (real, Fisher 1936, embedded)",
            "provenance": "real", "test_accuracy": round(acc, 4),
            "threshold": threshold, "passed": acc >= threshold,
            "train_wall_sec": round(wall, 2)}


def _run_digits(conf_fn, name: str, epochs: int, threshold: float,
                batch_size: int = 128, **conf_kw) -> dict:
    import jax

    from deeplearning4j_tpu.datasets.fetchers import digits_data
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    x, y = digits_data()
    (xtr, ytr), (xte, yte) = _split(x, y, 1500)
    net = MultiLayerNetwork(conf_fn(**conf_kw)).init()
    t0 = time.perf_counter()
    net.fit_epochs(xtr, num_epochs=epochs, labels=_one_hot(ytr, 10),
                   batch_size=batch_size)
    jax.block_until_ready(net.params())  # fence: time training, not enqueue
    wall = time.perf_counter() - t0
    acc = _accuracy(net, xte, yte, 10)
    return {"gate": name,
            "dataset": "sklearn digits (real, UCI optdigits 8x8, 1797 scans)",
            "provenance": "real", "test_accuracy": round(acc, 4),
            "threshold": threshold, "passed": acc >= threshold,
            "train_wall_sec": round(wall, 2)}


def gate_digits_mlp(epochs: int = 40, threshold: float = 0.96) -> dict:
    from deeplearning4j_tpu.models.zoo import digits_mlp

    return _run_digits(digits_mlp, "digits_mlp", epochs, threshold)


def gate_digits_conv(epochs: int = 40, threshold: float = 0.96) -> dict:
    from deeplearning4j_tpu.models.zoo import digits_conv

    return _run_digits(digits_conv, "digits_conv", epochs, threshold)


def gate_sda_digits(threshold: float = 0.90) -> dict:
    """Stacked denoising AE pretrain+finetune+backprop on real digits —
    the wall-clock-to-accuracy protocol of BASELINE config #3
    (ref workflow: MultiLayerNetwork.java:150-191)."""
    import jax

    from deeplearning4j_tpu.datasets.fetchers import digits_data
    from deeplearning4j_tpu.models.zoo import stacked_denoising_autoencoder
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    x, y = digits_data()
    (xtr, ytr), (xte, yte) = _split(x, y, 1500)
    conf = stacked_denoising_autoencoder(
        n_in=64, hidden=(96, 48), n_out=10, corruption_level=0.2,
        lr=0.1, num_iterations=15,
    )
    net = MultiLayerNetwork(conf).init()
    t0 = time.perf_counter()
    net.fit(xtr, labels=_one_hot(ytr, 10), batch_size=250)  # pretrain+finetune+bp
    net.fit_epochs(xtr, num_epochs=30, labels=_one_hot(ytr, 10), batch_size=128)
    jax.block_until_ready(net.params())  # fence: time training, not enqueue
    wall = time.perf_counter() - t0
    acc = _accuracy(net, xte, yte, 10)
    return {"gate": "sda_digits",
            "dataset": "sklearn digits (real, UCI optdigits 8x8, 1797 scans)",
            "provenance": "real", "test_accuracy": round(acc, 4),
            "threshold": threshold, "passed": acc >= threshold,
            "wall_clock_to_accuracy_sec": round(wall, 2)}


def _run_synthetic_mnist(conf_fn, name: str, epochs: int, threshold: float,
                         n: int = 6000, n_train: int = 5000) -> dict:
    import jax

    from deeplearning4j_tpu.datasets.fetchers import synthetic_mnist
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    x, y = synthetic_mnist(n)
    (xtr, ytr), (xte, yte) = _split(x, y, n_train)
    net = MultiLayerNetwork(conf_fn()).init()
    t0 = time.perf_counter()
    net.fit_epochs(xtr, num_epochs=epochs, labels=_one_hot(ytr, 10),
                   batch_size=256)
    jax.block_until_ready(net.params())  # fence: time training, not enqueue
    wall = time.perf_counter() - t0
    acc = _accuracy(net, xte, yte, 10)
    return {"gate": name, "dataset": "synthetic_mnist (SYNTHETIC surrogate)",
            "provenance": "synthetic",
            "note": "convergence proof only — NOT a real-data accuracy claim",
            "test_accuracy": round(acc, 4), "threshold": threshold,
            "passed": acc >= threshold, "train_wall_sec": round(wall, 2)}


def gate_mnist_mlp_synthetic(epochs: int = 5, threshold: float = 0.97) -> dict:
    from deeplearning4j_tpu.models.zoo import mnist_mlp

    return _run_synthetic_mnist(mnist_mlp, "mnist_mlp_synthetic", epochs, threshold)


def gate_lenet_synthetic(epochs: int = 2, threshold: float = 0.97) -> dict:
    from deeplearning4j_tpu.models.zoo import lenet

    return _run_synthetic_mnist(lenet, "lenet_synthetic", epochs, threshold,
                                n=4000, n_train=3200)


def gate_word2vec_real_corpus(iterations: int = 5) -> dict:
    """Word2Vec on the reference's REAL 757k-word English corpus
    (dl4j-test-resources raw_sentences.txt, mounted read-only — usable as
    data with zero egress; ref Word2Vec tests train on this same file).
    Asserts semantic clusters: numbers and day/night/week time words."""
    from deeplearning4j_tpu.models.word2vec import Word2Vec
    from deeplearning4j_tpu.text.sentence_iterator import LineSentenceIterator

    path = ("/root/reference/dl4j-test-resources/src/main/resources/"
            "raw_sentences.txt")
    import os
    if not os.path.exists(path):
        # PENDING-style record: excluded from all_passed (see main)
        return {"gate": "word2vec_real_corpus", "provenance": "real",
                "skipped": "reference fixtures not mounted"}
    vec = Word2Vec(sentence_iterator=LineSentenceIterator(path),
                   layer_size=100, window=5, negative=5,
                   iterations=iterations, min_word_frequency=5,
                   sample=1e-3, batch_size=2048, lr=0.05, seed=7)
    t0 = time.perf_counter()
    vec.build_vocab()
    vocab_wall = time.perf_counter() - t0  # graftlint: allow[untimed-dispatch] host-only tokenize/count phase; nothing on device yet
    t0 = time.perf_counter()
    vec.fit()
    vec.block_until_ready()  # fence: time training, not enqueue
    wall = time.perf_counter() - t0
    near_two = set(vec.words_nearest("two", 10))
    near_day = set(vec.words_nearest("day", 10))
    number_ok = bool(near_two & {"three", "four", "five", "six", "ten",
                                 "Two", "Three"})
    time_ok = bool(near_day & {"night", "week", "year", "time", "season",
                               "morning", "days", "Today", "today", "every"})
    return {"gate": "word2vec_real_corpus",
            "dataset": "raw_sentences.txt (real English, 757k words, "
                       "reference test fixture)",
            "provenance": "real", "vocab_size": vec.vocab.num_words(),
            "nearest_two": sorted(near_two), "nearest_day": sorted(near_day),
            "number_cluster": number_ok, "time_cluster": time_ok,
            "passed": number_ok and time_ok,
            "train_pairs_per_sec": round(
                vec.total_words_trained / max(wall, 1e-9), 1),
            "vocab_build_wall_sec": round(vocab_wall, 2),
            "train_wall_sec": round(wall, 2)}


PENDING = [
    {"gate": "mnist_mlp_real", "reason": "MNIST IDX files absent and no "
     "network egress; fetcher auto-uses them at $MNIST_DIR or ~/MNIST when "
     "present (datasets/fetchers.py)"},
    {"gate": "lenet_mnist_real", "reason": "same — real-MNIST gate pending "
     "dataset availability"},
]


def main() -> None:
    gates = [
        gate_iris(),
        gate_word2vec_real_corpus(),
        gate_digits_mlp(),
        gate_digits_conv(),
        gate_sda_digits(),
        gate_mnist_mlp_synthetic(),
        gate_lenet_synthetic(),
    ]
    skipped = [g for g in gates if "skipped" in g]
    gates = [g for g in gates if "skipped" not in g]
    out = {
        "real_data_gates": [g for g in gates if g["provenance"] == "real"],
        "synthetic_gates": [g for g in gates if g["provenance"] == "synthetic"],
        "pending": PENDING + [
            {"gate": g["gate"], "reason": g["skipped"]} for g in skipped
        ],
        "all_passed": all(g["passed"] for g in gates),
    }
    with open("ACCURACY_r05.json", "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
