"""Real-data accuracy gates (BASELINE north star: "train to reference accuracy").

The reference proves model quality by downloading MNIST and training to
accuracy (ref: datasets/fetchers/MnistDataFetcher.java:39-85, examples in
MultiLayerTest). This environment has no egress, so the gates run on the real
datasets that ARE available locally:

- Fisher's Iris (embedded, the same 150-sample data the reference ships as
  iris.dat in dl4j-test-resources),
- the UCI handwritten digits set bundled with scikit-learn (1,797 genuine
  8x8 scans — the closest real MNIST-class data available offline).

MNIST-sized gates additionally run on the synthetic MNIST surrogate and are
LABELED synthetic — they are convergence proofs for the 784-input configs,
never claimed as real-data accuracy. Real-MNIST gates are recorded as
``pending`` with the reason.

Run:  python accuracy_gates.py  →  prints JSON and writes ACCURACY_r02.json
"""

from __future__ import annotations

import json
import time

import numpy as np


def _split(x: np.ndarray, y: np.ndarray, n_train: int, seed: int = 0):
    perm = np.random.default_rng(seed).permutation(x.shape[0])
    x, y = x[perm], y[perm]
    return (x[:n_train], y[:n_train]), (x[n_train:], y[n_train:])


def _one_hot(y: np.ndarray, k: int) -> np.ndarray:
    out = np.zeros((y.shape[0], k), np.float32)
    out[np.arange(y.shape[0]), y] = 1.0
    return out


def _accuracy(net, x: np.ndarray, y: np.ndarray, num_classes: int) -> float:
    # num_classes is the KNOWN class count — inferring it from the test
    # split's max label would shrink the one-hot matrix (and corrupt the
    # Evaluation) whenever the split happens to lack the top class
    from deeplearning4j_tpu.eval import Evaluation

    ev = Evaluation()
    ev.eval(_one_hot(y, num_classes), np.asarray(net.label_probabilities(x)))
    return ev.accuracy()


def gate_iris(epochs: int = 300, threshold: float = 0.93) -> dict:
    """MLP on real Iris, 120/30 split."""
    from deeplearning4j_tpu.datasets.fetchers import iris_data
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    x, y = iris_data()
    (xtr, ytr), (xte, yte) = _split(x, y, 120)
    conf = (
        NeuralNetConfiguration.Builder()
        .n_in(4).n_out(16).activation_function("tanh")
        .lr(0.05).momentum(0.9).use_ada_grad(True)
        .num_iterations(1).seed(42).weight_init("VI")
        .list(2)
        .override(1, layer_type="OUTPUT", n_in=16, n_out=3,
                  activation_function="softmax", loss_function="MCXENT")
        .pretrain(False).backward(True).build()
    )
    net = MultiLayerNetwork(conf).init()
    t0 = time.perf_counter()
    net.fit_epochs(xtr, num_epochs=epochs, labels=_one_hot(ytr, 3))
    wall = time.perf_counter() - t0
    acc = _accuracy(net, xte, yte, 3)
    return {"gate": "iris_mlp", "dataset": "iris (real, Fisher 1936, embedded)",
            "provenance": "real", "test_accuracy": round(acc, 4),
            "threshold": threshold, "passed": acc >= threshold,
            "train_wall_sec": round(wall, 2)}


def _run_digits(conf_fn, name: str, epochs: int, threshold: float,
                batch_size: int = 128, **conf_kw) -> dict:
    from deeplearning4j_tpu.datasets.fetchers import digits_data
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    x, y = digits_data()
    (xtr, ytr), (xte, yte) = _split(x, y, 1500)
    net = MultiLayerNetwork(conf_fn(**conf_kw)).init()
    t0 = time.perf_counter()
    net.fit_epochs(xtr, num_epochs=epochs, labels=_one_hot(ytr, 10),
                   batch_size=batch_size)
    wall = time.perf_counter() - t0
    acc = _accuracy(net, xte, yte, 10)
    return {"gate": name,
            "dataset": "sklearn digits (real, UCI optdigits 8x8, 1797 scans)",
            "provenance": "real", "test_accuracy": round(acc, 4),
            "threshold": threshold, "passed": acc >= threshold,
            "train_wall_sec": round(wall, 2)}


def gate_digits_mlp(epochs: int = 40, threshold: float = 0.96) -> dict:
    from deeplearning4j_tpu.models.zoo import digits_mlp

    return _run_digits(digits_mlp, "digits_mlp", epochs, threshold)


def gate_digits_conv(epochs: int = 40, threshold: float = 0.96) -> dict:
    from deeplearning4j_tpu.models.zoo import digits_conv

    return _run_digits(digits_conv, "digits_conv", epochs, threshold)


def gate_sda_digits(threshold: float = 0.90) -> dict:
    """Stacked denoising AE pretrain+finetune+backprop on real digits —
    the wall-clock-to-accuracy protocol of BASELINE config #3
    (ref workflow: MultiLayerNetwork.java:150-191)."""
    from deeplearning4j_tpu.datasets.fetchers import digits_data
    from deeplearning4j_tpu.models.zoo import stacked_denoising_autoencoder
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    x, y = digits_data()
    (xtr, ytr), (xte, yte) = _split(x, y, 1500)
    conf = stacked_denoising_autoencoder(
        n_in=64, hidden=(96, 48), n_out=10, corruption_level=0.2,
        lr=0.1, num_iterations=15,
    )
    net = MultiLayerNetwork(conf).init()
    t0 = time.perf_counter()
    net.fit(xtr, labels=_one_hot(ytr, 10), batch_size=250)  # pretrain+finetune+bp
    net.fit_epochs(xtr, num_epochs=30, labels=_one_hot(ytr, 10), batch_size=128)
    wall = time.perf_counter() - t0
    acc = _accuracy(net, xte, yte, 10)
    return {"gate": "sda_digits",
            "dataset": "sklearn digits (real, UCI optdigits 8x8, 1797 scans)",
            "provenance": "real", "test_accuracy": round(acc, 4),
            "threshold": threshold, "passed": acc >= threshold,
            "wall_clock_to_accuracy_sec": round(wall, 2)}


def _run_synthetic_mnist(conf_fn, name: str, epochs: int, threshold: float,
                         n: int = 6000, n_train: int = 5000) -> dict:
    from deeplearning4j_tpu.datasets.fetchers import synthetic_mnist
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    x, y = synthetic_mnist(n)
    (xtr, ytr), (xte, yte) = _split(x, y, n_train)
    net = MultiLayerNetwork(conf_fn()).init()
    t0 = time.perf_counter()
    net.fit_epochs(xtr, num_epochs=epochs, labels=_one_hot(ytr, 10),
                   batch_size=256)
    wall = time.perf_counter() - t0
    acc = _accuracy(net, xte, yte, 10)
    return {"gate": name, "dataset": "synthetic_mnist (SYNTHETIC surrogate)",
            "provenance": "synthetic",
            "note": "convergence proof only — NOT a real-data accuracy claim",
            "test_accuracy": round(acc, 4), "threshold": threshold,
            "passed": acc >= threshold, "train_wall_sec": round(wall, 2)}


def gate_mnist_mlp_synthetic(epochs: int = 5, threshold: float = 0.97) -> dict:
    from deeplearning4j_tpu.models.zoo import mnist_mlp

    return _run_synthetic_mnist(mnist_mlp, "mnist_mlp_synthetic", epochs, threshold)


def gate_lenet_synthetic(epochs: int = 2, threshold: float = 0.97) -> dict:
    from deeplearning4j_tpu.models.zoo import lenet

    return _run_synthetic_mnist(lenet, "lenet_synthetic", epochs, threshold,
                                n=4000, n_train=3200)


PENDING = [
    {"gate": "mnist_mlp_real", "reason": "MNIST IDX files absent and no "
     "network egress; fetcher auto-uses them at $MNIST_DIR or ~/MNIST when "
     "present (datasets/fetchers.py)"},
    {"gate": "lenet_mnist_real", "reason": "same — real-MNIST gate pending "
     "dataset availability"},
]


def main() -> None:
    gates = [
        gate_iris(),
        gate_digits_mlp(),
        gate_digits_conv(),
        gate_sda_digits(),
        gate_mnist_mlp_synthetic(),
        gate_lenet_synthetic(),
    ]
    out = {
        "real_data_gates": [g for g in gates if g["provenance"] == "real"],
        "synthetic_gates": [g for g in gates if g["provenance"] == "synthetic"],
        "pending": PENDING,
        "all_passed": all(g["passed"] for g in gates),
    }
    with open("ACCURACY_r02.json", "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
