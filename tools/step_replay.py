#!/usr/bin/env python
"""Deterministically re-execute a guardrails replay bundle for forensics.

Usage:
    python tools/step_replay.py BUNDLE.npz [--json]
    python tools/step_replay.py BUNDLE.npz \
        --factory deeplearning4j_tpu.scaleout.elastic:synthetic_replay \
        --kwargs-json '{"d_in": 8, "d_hidden": 16}' [--expect-nonfinite]

A bundle is what ``optimize/guardrails.dump_replay_bundle`` (or the
``DivergenceWatchdog``) wrote when a train step went non-finite: one
atomic npz holding the pre-step params + batch plus meta (step id, RNG
key, observed loss). This CLI:

1. loads the bundle and prints its meta + a per-leaf non-finite forensics
   table (which leaf of the batch/params carries the poison, how many
   elements, the finite min/max around them);
2. with ``--factory pkg.module:fn`` (the same spec convention as the
   elastic worker CLI), re-executes the step: the factory is called with
   ``--kwargs-json`` and must return ``run(payload) -> dict`` of result
   scalars (loss, grad_norm, ...) — e.g.
   ``deeplearning4j_tpu.scaleout.elastic:synthetic_replay`` or
   ``deeplearning4j_tpu.models.transformer_lm:lm_replay``;
3. reports whether the non-finite result REPRODUCED. ``--expect-nonfinite``
   turns a clean replay into exit code 1 (the bench's recovery demo and
   the fault-matrix tests pin reproduction with it).

Exit codes: 0 ok, 1 ``--expect-nonfinite`` not reproduced, 2 bad bundle
path / unreadable bundle.
"""

from __future__ import annotations

import argparse
import importlib
import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning4j_tpu.optimize.guardrails import (  # noqa: E402
    load_replay_bundle,
    nonfinite_report,
)


def _resolve_factory(spec: str, kwargs: dict):
    module_name, _, attr = spec.partition(":")
    factory = getattr(importlib.import_module(module_name), attr)
    return factory(**kwargs)


def _result_nonfinite(result: dict) -> bool:
    return any(isinstance(v, float) and not math.isfinite(v)
               for v in result.values())


def format_report(meta: dict, forensics: list, result, path: str) -> str:
    lines = [f"step replay — {path}"]
    lines.append("-" * max(len(lines[0]), 40))
    for k in sorted(meta):
        lines.append(f"meta {k:<18} {meta[k]!r}")
    poisoned = [e for e in forensics if e.get("nonfinite")]
    lines.append(f"leaves: {len(forensics)} total, {len(poisoned)} with "
                 "non-finite values")
    for e in poisoned:
        rng = ""
        if "finite_min" in e:
            rng = f"  finite range [{e['finite_min']:.6g}, " \
                  f"{e['finite_max']:.6g}]"
        lines.append(f"  !! {e['path']}  {e['dtype']}{e['shape']}  "
                     f"{e['nonfinite']} non-finite{rng}")
    if result is not None:
        lines.append("re-execution:")
        for k in sorted(result):
            lines.append(f"  {k:<18} {result[k]!r}")
        lines.append("non-finite result REPRODUCED"
                     if _result_nonfinite(result)
                     else "replay came out FINITE (fault not reproduced)")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("bundle", help="replay bundle (.npz) path")
    ap.add_argument("--factory", default=None,
                    help="pkg.module:fn returning run(payload) -> dict; "
                         "re-executes the faulting step")
    ap.add_argument("--kwargs-json", default="{}",
                    help="JSON kwargs for the factory")
    ap.add_argument("--expect-nonfinite", action="store_true",
                    help="exit 1 unless the re-executed step reproduces a "
                         "non-finite result")
    ap.add_argument("--json", action="store_true",
                    help="emit a JSON report instead of the table")
    args = ap.parse_args(argv)
    if not os.path.isfile(args.bundle):
        print(f"no such replay bundle: {args.bundle}", file=sys.stderr)
        return 2
    try:
        payload, meta = load_replay_bundle(args.bundle)
    except (ValueError, OSError, KeyError) as exc:
        print(f"unreadable replay bundle {args.bundle}: {exc}",
              file=sys.stderr)
        return 2
    forensics = nonfinite_report(payload)
    result = None
    if args.factory:
        run = _resolve_factory(args.factory, json.loads(args.kwargs_json))
        result = run(payload)
    if args.json:
        print(json.dumps({
            "bundle": args.bundle,
            "meta": meta,
            "forensics": forensics,
            "result": ({k: repr(v) if isinstance(v, float)
                        and not math.isfinite(v) else v
                        for k, v in result.items()}
                       if result is not None else None),
            "reproduced": (_result_nonfinite(result)
                           if result is not None else None),
        }, indent=1))
    else:
        print(format_report(meta, forensics, result, args.bundle))
    if args.expect_nonfinite:
        if result is None:
            print("--expect-nonfinite needs --factory to re-execute",
                  file=sys.stderr)
            return 1
        if not _result_nonfinite(result):
            print("expected a non-finite replay result but the step came "
                  "out finite", file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
