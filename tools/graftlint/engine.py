"""graftlint core: findings, rule registry, and the per-module context.

Zero dependencies beyond the stdlib ``ast`` module. Each rule is a
function ``rule(ctx) -> Iterable[Finding]`` registered under a stable
rule id; ``ModuleContext`` does the shared work every JAX-aware rule
needs — which functions are *traced* (reachable inside ``jax.jit`` /
``shard_map`` / ``lax.scan`` bodies), which module names dispatch
compiled programs when called, and in-file constant resolution.

Findings are keyed for the baseline by ``(rule, path, snippet)`` where
``snippet`` is the whitespace-normalized source line — stable across
unrelated edits that only move code, unlike line numbers.

Inline suppression (colocated allowlist, reason REQUIRED)::

    cost = float(cost)  # graftlint: allow[jit-host-sync] convergence check needs the host value

A suppression comment without a reason does not suppress.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Callable, Dict, Iterable, List, Optional

# --------------------------------------------------------------- findings ----

@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative, posix separators
    line: int
    message: str
    hint: str
    snippet: str  # whitespace-normalized source line (baseline key)

    def key(self):
        return (self.rule, self.path, self.snippet)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.rule}] {self.message}\n"
                f"    {self.snippet}\n    hint: {self.hint}")


# ---------------------------------------------------------------- registry ----

RULES: Dict[str, Callable] = {}


def register(rule_id: str):
    def deco(fn):
        RULES[rule_id] = fn
        fn.rule_id = rule_id
        return fn
    return deco


# ------------------------------------------------------------- module ctx ----

# callables whose function-valued arguments are traced with abstract values
TRACE_WRAPPERS = {
    "jit", "pmap", "vmap", "grad", "value_and_grad", "checkpoint", "remat",
    "shard_map", "scan", "while_loop", "fori_loop", "cond", "switch",
    "associative_scan", "custom_vjp", "custom_jvp", "named_call",
}
# decorators that make the decorated body traced
TRACE_DECORATORS = {"jit", "pmap", "vmap", "checkpoint", "remat",
                    "custom_vjp", "custom_jvp", "shard_map"}

_FuncNode = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def dotted(node: ast.AST) -> str:
    """'jax.random.split' for the Attribute chain, '' when not a chain."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    return ""


def last_part(node: ast.AST) -> str:
    return dotted(node).rsplit(".", 1)[-1]


class ModuleContext:
    """Parsed module + the shared analyses rules build on."""

    def __init__(self, src: str, path: str):
        self.path = path
        self.src_lines = src.splitlines()
        self.tree = ast.parse(src, filename=path)
        self.parents: Dict[ast.AST, ast.AST] = {}
        self.functions: List[ast.AST] = []
        self.defs_by_name: Dict[str, List[ast.AST]] = {}
        self.module_str_constants: Dict[str, str] = {}
        self._index()
        self.traced: set = set()
        self.jitted_names: set = set()
        self._find_traced()

    # ---- indexing ----
    def _index(self) -> None:
        stack = [self.tree]
        while stack:
            node = stack.pop()
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
                stack.append(child)
                if isinstance(child, _FuncNode):
                    self.functions.append(child)
                    name = getattr(child, "name", None)
                    if name:
                        self.defs_by_name.setdefault(name, []).append(child)
        for stmt in self.tree.body:
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and isinstance(stmt.value, ast.Constant)
                    and isinstance(stmt.value.value, str)):
                self.module_str_constants[stmt.targets[0].id] = stmt.value.value

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None and not isinstance(cur, _FuncNode):
            cur = self.parents.get(cur)
        return cur

    def src_line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.src_lines):
            return self.src_lines[lineno - 1]
        return ""

    def snippet(self, lineno: int) -> str:
        return " ".join(self.src_line(lineno).split())

    def resolve_str(self, node: ast.AST) -> Optional[str]:
        """A string literal, or an in-file module-level str constant name."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        if isinstance(node, ast.Name):
            return self.module_str_constants.get(node.id)
        return None

    # ---- traced-function analysis ----
    def _decorator_traces(self, deco: ast.AST) -> bool:
        """True when any name inside the decorator expression is a tracer
        wrapper — covers @jax.jit, @jit, @partial(jax.jit, ...)."""
        return any(last_part(n) in TRACE_DECORATORS
                   for n in ast.walk(deco)
                   if isinstance(n, (ast.Name, ast.Attribute)))

    def _decorator_jits(self, deco: ast.AST) -> bool:
        return any(last_part(n) == "jit" for n in ast.walk(deco)
                   if isinstance(n, (ast.Name, ast.Attribute)))

    def _find_traced(self) -> None:
        # seed 1: decorated defs
        for fn in self.functions:
            for deco in getattr(fn, "decorator_list", []):
                if self._decorator_traces(deco):
                    self.traced.add(fn)
                if self._decorator_jits(deco) and getattr(fn, "name", None):
                    self.jitted_names.add(fn.name)
        # seed 2: functions passed to tracer wrappers; names bound to jit(...)
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                name = last_part(node.func)
                if name == "map" and not dotted(node.func).endswith("lax.map"):
                    continue
                if name in TRACE_WRAPPERS or (
                        name == "map" and dotted(node.func).endswith("lax.map")):
                    for arg in list(node.args) + [kw.value for kw in
                                                  node.keywords]:
                        if isinstance(arg, ast.Lambda):
                            self.traced.add(arg)
                        elif isinstance(arg, ast.Name):
                            for d in self.defs_by_name.get(arg.id, []):
                                self.traced.add(d)
            if (isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)
                    and last_part(node.value.func) == "jit"):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self.jitted_names.add(tgt.id)
        # propagate: nested defs inside traced fns + local callees of traced fns
        for _ in range(10):
            before = len(self.traced)
            for fn in list(self.traced):
                for node in ast.walk(fn):
                    if node is fn:
                        continue
                    if isinstance(node, _FuncNode):
                        self.traced.add(node)
                    if isinstance(node, ast.Call) and isinstance(node.func,
                                                                 ast.Name):
                        for d in self.defs_by_name.get(node.func.id, []):
                            self.traced.add(d)
            if len(self.traced) == before:
                break

    def walk_in_function(self, fn: ast.AST, node_type) -> Iterable[ast.AST]:
        """Nodes of ``node_type`` whose *directly* enclosing function is
        ``fn`` (nested function bodies are excluded — they run on their own
        schedule, not in ``fn``'s)."""
        for node in ast.walk(fn):
            if isinstance(node, node_type) and (
                    self.enclosing_function(node) is fn):
                yield node


# ------------------------------------------------------------ suppression ----

_ALLOW_RE = re.compile(
    r"#\s*graftlint:\s*allow\[([a-z0-9\-, ]+)\]\s+(\S.*)")


def _suppressed(ctx: ModuleContext, finding: Finding) -> bool:
    """Inline allow on the finding's line or the line above, reason
    required (a bare tag without a why does not suppress)."""
    for lineno in (finding.line, finding.line - 1):
        m = _ALLOW_RE.search(ctx.src_line(lineno))
        if m and finding.rule in [r.strip() for r in m.group(1).split(",")]:
            return True
    return False


# ------------------------------------------------------------ entrypoints ----

def lint_source(src: str, path: str,
                rule_ids: Optional[Iterable[str]] = None) -> List[Finding]:
    """Lint one module's source; returns findings after inline suppression.
    Files that do not parse yield a single ``parse-error`` finding (a
    linter must never crash the gate on bad input)."""
    try:
        ctx = ModuleContext(src, path)
    except SyntaxError as e:
        return [Finding("parse-error", path, e.lineno or 1,
                        f"file does not parse: {e.msg}",
                        "fix the syntax error", "")]
    out: List[Finding] = []
    for rid, rule in RULES.items():
        if rule_ids is not None and rid not in rule_ids:
            continue
        out.extend(rule(ctx))
    out = [f for f in out if not _suppressed(ctx, f)]
    seen: set = set()
    deduped = []
    for f in out:  # nested scans (e.g. loop-in-loop) can re-derive a finding
        k = (f.rule, f.path, f.line, f.message)
        if k not in seen:
            seen.add(k)
            deduped.append(f)
    deduped.sort(key=lambda f: (f.path, f.line, f.rule))
    return deduped


def lint_file(path: str, rel_path: Optional[str] = None,
              rule_ids: Optional[Iterable[str]] = None) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as f:
        src = f.read()
    return lint_source(src, rel_path or path, rule_ids=rule_ids)


def lint_paths(paths: Iterable[str], root: str,
               rule_ids: Optional[Iterable[str]] = None) -> List[Finding]:
    """Lint every ``.py`` under each path (file or directory), reporting
    repo-relative posix paths. ``rule_ids`` restricts to those rules
    (the lint_gate ``--rule`` triage filter)."""
    import os

    files: List[str] = []
    for p in paths:
        ap = os.path.join(root, p) if not os.path.isabs(p) else p
        if os.path.isdir(ap):
            for dirpath, dirnames, filenames in os.walk(ap):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in ("__pycache__", ".git"))
                files.extend(os.path.join(dirpath, fn)
                             for fn in sorted(filenames)
                             if fn.endswith(".py"))
        elif ap.endswith(".py") and os.path.exists(ap):
            files.append(ap)
    out: List[Finding] = []
    for fp in files:
        rel = os.path.relpath(fp, root).replace(os.sep, "/")
        out.extend(lint_file(fp, rel, rule_ids=rule_ids))
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out
