"""Network/RPC hygiene rule set (ISSUE 18): transport faults bounded in
time, on every thread.

ROADMAP items 1-2 turn one serving process into a routed fleet, which
multiplies sockets, retry loops, and background RPC threads the same way
PR 11 anticipated threads multiplying locks. The reference DL4J scaleout
stack died by a thousand hung sockets and silent retries; these rules
encode the transport fault model remote_tracker.py already practices —
every socket carries a timeout, every retry is bounded and backed off,
every retried method is *declared* idempotent, and no thread swallows
the exception that killed it.

Each rule rides the per-module :class:`tools.graftlint.threads.
ThreadModel` (thread-entrypoint reachability, handler classes) plus a
socket dataflow pass (:class:`NetModel`): which names hold sockets,
which of those provably carry a timeout (``settimeout``, a
``create_connection(timeout=...)``, or the ``utils.netwatch``
``make_socket``/``wrap_socket`` seam — watched sockets get the enforced
process default), with aliasing through assignment and through in-file
call parameters. The runtime half — timeouts/retries that only exist at
run time, stalls on sockets statics cannot see — lives in
``deeplearning4j_tpu/utils/netwatch.py``.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.graftlint.engine import (
    Finding,
    ModuleContext,
    dotted,
    last_part,
    register,
)
from tools.graftlint.threads import thread_model

# the utils.netwatch seam: sockets created/wrapped through it carry the
# watched process default timeout, so they count as timed by construction
_SEAM_CTORS = {"make_socket", "wrap_socket"}
_BLOCKING_OPS = {"recv", "recv_into", "accept", "connect", "sendall"}
# exception types whose catch marks a handler as absorbing a TRANSPORT
# fault (the retry rules) — or, with the broad pair, swallowing anything
_TRANSPORT_EXCS = {
    "OSError", "IOError", "EnvironmentError", "ConnectionError",
    "ConnectionResetError", "ConnectionRefusedError",
    "ConnectionAbortedError", "BrokenPipeError", "EOFError",
    "TimeoutError", "error", "timeout", "herror", "gaierror",
    "TrackerUnavailable",
}
_BROAD_EXCS = {"Exception", "BaseException"}
# unambiguously-network exceptions: catching one of THESE around a loop
# body is what marks the loop as a transport retry. OSError/Exception
# alone stay out — file-IO skip-scans (`except OSError: continue` over a
# directory listing) are not retries, they advance to the next item.
_NET_EXCS = {
    "ConnectionError", "ConnectionResetError", "ConnectionRefusedError",
    "ConnectionAbortedError", "BrokenPipeError", "EOFError",
    "TrackerUnavailable", "error", "timeout", "gaierror", "herror",
}
# a call whose dotted name carries one of these tokens counts as
# REPORTING the swallowed exception (logging, flight-recorder dump,
# printing, explicit failure accounting)
_REPORT_TOKENS = ("log", "print", "warn", "error", "debug", "info",
                  "exception", "critical", "dump", "report", "audit")
_GUARD_TOKENS = ("deadline", "monotonic", "perf_counter", "attempt",
                 "retr", "tries", "budget", "timeout", "expire",
                 "give_up")
_IDEM_NAMES = {"_IDEMPOTENT", "IDEMPOTENT"}
_NONIDEM_NAMES = {"_NONIDEMPOTENT", "NONIDEMPOTENT",
                  "_NON_IDEMPOTENT", "NON_IDEMPOTENT"}


def _finding(ctx: ModuleContext, rule: str, node: ast.AST, message: str,
             hint: str) -> Finding:
    return Finding(rule, ctx.path, node.lineno, message, hint,
                   ctx.snippet(node.lineno))


def _timeout_arg(call: ast.Call) -> Optional[ast.AST]:
    """The timeout expression of a ``create_connection``-shaped call
    (second positional or ``timeout=`` keyword), None when absent."""
    if len(call.args) >= 2:
        return call.args[1]
    for kw in call.keywords:
        if kw.arg == "timeout":
            return kw.value
    return None


def _is_none(node: Optional[ast.AST]) -> bool:
    return node is None or (isinstance(node, ast.Constant)
                            and node.value is None)


# ---------------------------------------------------------------- NetModel ----

class NetModel:
    """Socket dataflow for one module: which names are socket-valued and
    which of those provably carry a timeout.

    Timed-ness sources: ``x.settimeout(<non-None>)`` on the name,
    creation via ``create_connection(..., timeout=...)``, creation
    through the netwatch seam (``make_socket``/``wrap_socket`` enforce
    the watched default), or a ``timeout = <n>`` class attribute on a
    ``StreamRequestHandler``-family handler (``setup()`` applies it to
    the connection). Propagation: assignment aliasing (both directions —
    two names, one OS socket) and in-file call parameters (a parameter
    is timed only when EVERY socket-passing call site passes a timed
    expression). ``socket.setdefaulttimeout(...)`` at module scope turns
    the whole module timed.
    """

    def __init__(self, ctx: ModuleContext):
        self.ctx = ctx
        self.tm = thread_model(ctx)
        # key: ("local", enclosing_fn_or_None, name) | ("attr", cls, name)
        self.sockets: Dict[tuple, ast.AST] = {}
        self.timed: Set[tuple] = set()
        self.aliases: List[Tuple[tuple, tuple]] = []
        self.default_timeout = any(
            isinstance(n, ast.Call)
            and last_part(n.func) == "setdefaulttimeout"
            and n.args and not _is_none(n.args[0])
            for n in ast.walk(ctx.tree))
        self._collect()
        self._propagate()

    # -- naming --
    def key_of(self, node: ast.AST) -> Optional[tuple]:
        if isinstance(node, ast.Attribute) and isinstance(
                node.value, ast.Name) and node.value.id == "self":
            cls = self.tm._scope_class(node)
            if cls is None:
                return None
            return ("attr", cls, node.attr)
        if isinstance(node, ast.Name):
            return ("local", self.ctx.enclosing_function(node), node.id)
        return None

    @staticmethod
    def render(key: tuple) -> str:
        return f"self.{key[2]}" if key[0] == "attr" else key[2]

    # -- creation classification --
    def _ctor(self, call: ast.Call) -> Optional[bool]:
        """None: not a socket constructor; else the created socket's
        timed-ness."""
        lp = last_part(call.func)
        d = dotted(call.func)
        if lp in _SEAM_CTORS:
            return True  # netwatch seam enforces the watched default
        if d == "socket.socket" or (lp == "socket"
                                    and isinstance(call.func, ast.Name)):
            return False
        if lp == "create_connection":
            return not _is_none(_timeout_arg(call))
        return None

    def _collect(self) -> None:
        ctx = self.ctx
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign):
                val = node.value
                if isinstance(val, ast.Call):
                    timed = self._ctor(val)
                    if timed is not None:
                        for tgt in node.targets:
                            k = self.key_of(tgt)
                            if k is not None:
                                self.sockets.setdefault(k, node)
                                if timed:
                                    self.timed.add(k)
                    elif (isinstance(val.func, ast.Attribute)
                          and val.func.attr == "accept"):
                        # conn, addr = srv.accept(): the accepted socket
                        # does NOT inherit the listener's timeout
                        tgt = node.targets[0]
                        first = (tgt.elts[0] if isinstance(tgt, ast.Tuple)
                                 and tgt.elts else None)
                        k = self.key_of(first) if first is not None else None
                        if k is not None:
                            self.sockets.setdefault(k, node)
                elif isinstance(val, (ast.Name, ast.Attribute)):
                    vk = self.key_of(val)
                    if vk is not None:
                        for tgt in node.targets:
                            tk = self.key_of(tgt)
                            if tk is not None and tk != vk:
                                self.aliases.append((tk, vk))
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "settimeout"
                    and node.args and not _is_none(node.args[0])):
                k = self.key_of(node.func.value)
                if k is not None:
                    self.sockets.setdefault(k, node)
                    self.timed.add(k)
        # server handler classes: self.request/self.connection IS the
        # accepted socket; a `timeout = <n>` class attribute is applied
        # by StreamRequestHandler.setup()
        for cls in self.tm.handler_classes:
            timed = any(
                isinstance(stmt, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "timeout"
                        for t in stmt.targets)
                and not _is_none(stmt.value)
                for stmt in cls.body)
            for attr in ("request", "connection"):
                k = ("attr", cls, attr)
                self.sockets.setdefault(k, cls)
                if timed:
                    self.timed.add(k)

    # -- propagation --
    def _param_sites(self) -> Dict[tuple, List[ast.AST]]:
        """param key -> the argument expressions passed at every in-file
        call site that binds it."""
        sites: Dict[tuple, List[ast.AST]] = {}
        for fn in self.ctx.functions:
            for call in ast.walk(fn):
                if not (isinstance(call, ast.Call)
                        and self.ctx.enclosing_function(call) is fn):
                    continue
                for callee in self.tm._resolve_callable(call.func, fn):
                    params = [a.arg for a in
                              getattr(callee.args, "args", [])]
                    start = 1 if params and params[0] in ("self",
                                                          "cls") else 0
                    for i, arg in enumerate(call.args):
                        if start + i < len(params):
                            sites.setdefault(
                                ("local", callee, params[start + i]),
                                []).append(arg)
                    for kw in call.keywords:
                        if kw.arg in params:
                            sites.setdefault(("local", callee, kw.arg),
                                             []).append(kw.value)
        return sites

    def _expr_socketness(self, expr: ast.AST
                         ) -> Tuple[bool, bool]:
        """(is_socket, is_timed) for an argument expression."""
        if isinstance(expr, ast.Call):
            timed = self._ctor(expr)
            if timed is not None:
                return True, timed
            return False, False
        k = self.key_of(expr)
        if k is not None and k in self.sockets:
            return True, k in self.timed
        return False, False

    def _propagate(self) -> None:
        sites = self._param_sites()
        for _ in range(10):
            changed = False
            for a, b in self.aliases:  # two names, one OS socket
                if a in self.sockets or b in self.sockets:
                    for k, other in ((a, b), (b, a)):
                        if k not in self.sockets:
                            self.sockets[k] = self.sockets[other]
                            changed = True
                    if (a in self.timed) != (b in self.timed):
                        self.timed.update((a, b))
                        changed = True
            for pkey, exprs in sites.items():
                socky = [self._expr_socketness(e) for e in exprs]
                if not any(s for s, _ in socky):
                    continue
                if pkey not in self.sockets:
                    self.sockets[pkey] = pkey[1]
                    changed = True
                if pkey not in self.timed and all(
                        t for s, t in socky if s):
                    self.timed.add(pkey)
                    changed = True
            if not changed:
                break


def net_model(ctx: ModuleContext) -> NetModel:
    """Get-or-build the module's NetModel (cached on the context, like
    :func:`tools.graftlint.threads.thread_model`)."""
    nm = getattr(ctx, "_net_model", None)
    if nm is None:
        nm = NetModel(ctx)
        ctx._net_model = nm
    return nm


# --------------------------------------------------------- socket-no-timeout ----

@register("socket-no-timeout")
def socket_no_timeout(ctx: ModuleContext) -> Iterable[Finding]:
    """A blocking socket operation (``recv``/``accept``/``connect``/
    ``sendall``) on a socket with no provable timeout, reachable from a
    thread entrypoint or a server handler — a dead peer parks that
    thread forever (the hung-handler class the PR 10 deflake
    documented). ``create_connection``/``urlopen`` without a timeout
    argument on the same paths fire too. Sockets routed through the
    ``utils.netwatch`` seam are timed by construction (the watch
    enforces a process default)."""
    nm = net_model(ctx)
    if nm.default_timeout:
        return []
    tm = nm.tm
    out: List[Finding] = []
    for fn in ctx.functions:
        if fn not in tm.thread_fns:
            continue
        for call in ctx.walk_in_function(fn, ast.Call):
            lp = last_part(call.func)
            if (isinstance(call.func, ast.Attribute)
                    and call.func.attr in _BLOCKING_OPS):
                k = nm.key_of(call.func.value)
                if (k is not None and k in nm.sockets
                        and k not in nm.timed):
                    out.append(_finding(
                        ctx, "socket-no-timeout", call,
                        f"socket `{nm.render(k)}`.{call.func.attr}() with "
                        "no timeout on a thread/handler path — a dead "
                        "peer blocks this thread forever",
                        "settimeout() the socket at creation (or route "
                        "it through utils.netwatch.make_socket/"
                        "wrap_socket — watched sockets get the enforced "
                        "process default)"))
            elif lp == "create_connection" and _is_none(_timeout_arg(call)):
                out.append(_finding(
                    ctx, "socket-no-timeout", call,
                    "create_connection() with no timeout on a thread/"
                    "handler path — connect to a dead host blocks for "
                    "the kernel default (minutes)",
                    "pass timeout= (and settimeout() for the request "
                    "phase), or create through utils.netwatch."
                    "make_socket"))
            elif lp == "urlopen" and not any(
                    kw.arg == "timeout" for kw in call.keywords):
                out.append(_finding(
                    ctx, "socket-no-timeout", call,
                    "urlopen() with no timeout on a thread/handler path "
                    "— a stalled HTTP peer parks this thread forever",
                    "pass timeout= to every urlopen on a background "
                    "thread"))
    return out


# ------------------------------------------------------- retry loop plumbing ----

def _caught_names(handler: ast.ExceptHandler) -> List[str]:
    if handler.type is None:
        return ["<bare>"]
    nodes = (handler.type.elts if isinstance(handler.type, ast.Tuple)
             else [handler.type])
    return [last_part(n) for n in nodes]


def _catches_transport(handler: ast.ExceptHandler) -> Optional[str]:
    names = _caught_names(handler)
    hit = [n for n in names
           if n in _TRANSPORT_EXCS or n in _BROAD_EXCS or n == "<bare>"]
    return "/".join(hit) if hit else None


def _catches_network(handler: ast.ExceptHandler) -> Optional[str]:
    """Network-only: the retry rules key on this narrower set so a
    file-IO ``except OSError: continue`` scan never reads as a retry."""
    names = _caught_names(handler)
    hit = [n for n in names if n in _NET_EXCS]
    return "/".join(hit) if hit else None


def _handler_exits(handler: ast.ExceptHandler) -> bool:
    """True when the handler leaves the loop (raise/return/break, or a
    process exit call) instead of re-entering it."""
    for stmt in handler.body:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.Raise, ast.Return, ast.Break)):
                return True
            if isinstance(node, ast.Call) and last_part(node.func) in (
                    "exit", "_exit", "abort"):
                return True
    return False


def _enclosing_loop(ctx: ModuleContext, node: ast.AST,
                    fn: ast.AST) -> Optional[ast.AST]:
    cur = ctx.parents.get(node)
    while cur is not None and cur is not fn:
        if isinstance(cur, (ast.While, ast.For)):
            return cur
        cur = ctx.parents.get(cur)
    return None


def _loop_unbounded(loop: ast.AST) -> bool:
    if isinstance(loop, ast.While):
        return isinstance(loop.test, ast.Constant) and bool(loop.test.value)
    if isinstance(loop, ast.For):
        return (isinstance(loop.iter, ast.Call)
                and last_part(loop.iter.func) == "count")
    return False


def _loop_has_deadline_guard(loop: ast.AST) -> bool:
    """An ``if`` in the loop that mentions a deadline/attempt-shaped name
    (or a clock call) and raises/returns/breaks — the bounded-poll idiom
    (``if time.monotonic() > deadline: raise``)."""
    for node in ast.walk(loop):
        if not isinstance(node, ast.If):
            continue
        mention = " ".join(
            dotted(n).lower() for n in ast.walk(node.test)
            if isinstance(n, (ast.Name, ast.Attribute)))
        if any(tok in mention for tok in _GUARD_TOKENS):
            if any(isinstance(x, (ast.Raise, ast.Return, ast.Break))
                   for x in ast.walk(node)):
                return True
    return False


def _loop_has_backoff(loop: ast.AST) -> bool:
    for node in ast.walk(loop):
        if isinstance(node, ast.Call):
            lp = last_part(node.func)
            if lp in ("sleep", "wait") or "backoff" in lp.lower():
                return True
    return False


def _retry_loops(ctx: ModuleContext):
    """(loop, handler, caught) for every loop whose nearest Try absorbs a
    NETWORK exception and re-enters the loop — the retry shape both
    retry rules police. ``for`` loops only count when iterating
    ``range()``/``count()`` (an attempt budget): a for-each over a
    collection that skips a failed item advances, it does not re-issue
    the same call."""
    seen = set()
    for fn in ctx.functions:
        for node in ctx.walk_in_function(fn, ast.Try):
            for h in node.handlers:
                caught = _catches_network(h)
                if caught is None or _handler_exits(h):
                    continue
                loop = _enclosing_loop(ctx, node, fn)
                if loop is None:
                    continue
                if isinstance(loop, ast.For) and not (
                        isinstance(loop.iter, ast.Call)
                        and last_part(loop.iter.func) in ("range",
                                                          "count")):
                    continue
                key = (loop.lineno, h.lineno)
                if key not in seen:
                    seen.add(key)
                    yield loop, h, caught


# ------------------------------------------------------------ unbounded-retry ----

@register("unbounded-retry")
def unbounded_retry(ctx: ModuleContext) -> Iterable[Finding]:
    """A ``while True`` (or ``itertools.count``) loop that catches a
    transport exception and re-enters with no attempt cap and no
    deadline: a dead peer turns into an infinite spin instead of a loud,
    bounded failure. The sanctioned shape is a ``for attempt in
    range(n)`` budget or a ``monotonic() > deadline`` check that
    raises (remote_tracker / elastic both practice it)."""
    out: List[Finding] = []
    for loop, h, caught in _retry_loops(ctx):
        if _loop_unbounded(loop) and not _loop_has_deadline_guard(loop):
            out.append(_finding(
                ctx, "unbounded-retry", h,
                f"unbounded retry: `{caught}` is absorbed and the loop "
                "re-enters with no attempt cap or deadline — a dead "
                "peer becomes an infinite spin",
                "bound it: `for attempt in range(n)` with the failure "
                "raised after the budget, or a deadline check "
                "(`if time.monotonic() > deadline: raise`) inside the "
                "loop"))
    return out


# ----------------------------------------------------------- retry-no-backoff ----

@register("retry-no-backoff")
def retry_no_backoff(ctx: ModuleContext) -> Iterable[Finding]:
    """A retry loop (bounded or not) that re-enters the call with no
    sleep/backoff between attempts hammers a struggling peer at CPU
    speed — the retry storm that turns one slow master into a dead one.
    Any ``sleep``/``wait``/backoff call inside the loop counts."""
    out: List[Finding] = []
    for loop, h, caught in _retry_loops(ctx):
        if not _loop_has_backoff(loop):
            out.append(_finding(
                ctx, "retry-no-backoff", h,
                f"retry re-enters the call immediately after `{caught}` "
                "with no sleep/backoff — failures are retried at CPU "
                "speed against an already-struggling peer",
                "sleep a jittered, exponentially growing delay between "
                "attempts (see StateTrackerClient._call_locked)"))
    return out


# ------------------------------------------------- swallowed-thread-exception ----

def _uses_bound_exc(handler: ast.ExceptHandler) -> bool:
    if handler.name is None:
        return False
    return any(isinstance(n, ast.Name) and n.id == handler.name
               for stmt in handler.body for n in ast.walk(stmt))


def _handler_reports(handler: ast.ExceptHandler) -> bool:
    for stmt in handler.body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Call):
                name = dotted(node.func).lower()
                if any(tok in name for tok in _REPORT_TOKENS):
                    return True
    return False


@register("swallowed-thread-exception")
def swallowed_thread_exception(ctx: ModuleContext) -> Iterable[Finding]:
    """``except: pass`` (or a log-less broad/transport except) inside a
    thread entrypoint or anything it reaches: the exception that killed
    the background pusher is dropped on the floor, and a dead heartbeat
    becomes an invisible fleet outage. A handler reports by raising,
    logging/printing/dumping, or keeping the bound exception for later
    use; a counter alone is not a report — nobody watches a counter they
    don't know exists."""
    tm = thread_model(ctx)
    if not tm.thread_fns:
        return []
    out: List[Finding] = []
    for fn in ctx.functions:
        if fn not in tm.thread_fns:
            continue
        for node in ctx.walk_in_function(fn, ast.Try):
            for h in node.handlers:
                caught = _catches_transport(h)
                if caught is None:
                    continue
                if _handler_reports(h) or _uses_bound_exc(h):
                    continue
                out.append(_finding(
                    ctx, "swallowed-thread-exception", h,
                    f"`{caught}` swallowed with no log on a thread path "
                    "— the thread dies (or degrades) invisibly",
                    "log it (log.warning with the exception) before "
                    "absorbing, or re-raise; if the silence is "
                    "deliberate, inline-allow with the why"))
    return out


# --------------------------------------------------------- nonidempotent-retry ----

def _declared_strs(node: ast.AST) -> Set[str]:
    return {n.value for n in ast.walk(node)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)}


@register("nonidempotent-retry")
def nonidempotent_retry(ctx: ModuleContext) -> Iterable[Finding]:
    """In a module that declares an RPC idempotency contract (a
    module-level ``_IDEMPOTENT`` set — remote_tracker's retry
    classification), every method dispatched through ``_call`` must be
    classified: ``_IDEMPOTENT`` (safe to re-issue after an ambiguous
    failure) or ``_NONIDEMPOTENT`` (fail fast — a replay could
    double-apply). An unclassified method means the retry decision was
    never made, which is how ``increment`` double-counts."""
    idem: Set[str] = set()
    nonidem: Set[str] = set()
    declared = False
    for stmt in ctx.tree.body:
        if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)):
            continue
        name = stmt.targets[0].id
        if name in _IDEM_NAMES:
            idem = _declared_strs(stmt.value)
            declared = True
        elif name in _NONIDEM_NAMES:
            nonidem = _declared_strs(stmt.value)
    if not declared:
        return []
    out: List[Finding] = []
    for call in ast.walk(ctx.tree):
        if not (isinstance(call, ast.Call)
                and last_part(call.func) == "_call" and call.args):
            continue
        method = ctx.resolve_str(call.args[0])
        if method is not None and method not in idem \
                and method not in nonidem:
            out.append(_finding(
                ctx, "nonidempotent-retry", call,
                f"RPC method {method!r} rides the retry dispatcher but "
                "is classified neither idempotent nor non-idempotent — "
                "whether it may be replayed was never decided",
                "add it to _IDEMPOTENT (safe to re-issue) or "
                "_NONIDEMPOTENT (fail fast; a replay could "
                "double-apply) next to the other declarations"))
    return out
