"""Concurrency rule set (ISSUE 11): thread-safety for the host control
plane.

The compute path is single-controller SPMD, but the host side — decode
scheduler, async checkpoint writer, elastic master, tracker server, UI —
is exactly the concurrency-heavy actor runtime the reference built on
scaleout-akka + Hazelcast, and it fails the same ways: shared attributes
mutated off-lock, lock cycles, blocking syscalls under a lock, threads
started with no shutdown path (the PR 10 tracker flake), and condition
waits that trust a single wakeup. Each rule builds on the per-module
:class:`tools.graftlint.threads.ThreadModel` (thread-entrypoint
reachability, lock aliasing, call-graph lock propagation); the runtime
half — true cross-module lock orders, hold times, contention — lives in
``deeplearning4j_tpu/utils/lockwatch.py``.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from tools.graftlint.engine import (
    Finding,
    ModuleContext,
    dotted,
    last_part,
    register,
)
from tools.graftlint.threads import thread_model

_SAFE_ATTR_KINDS = {"lock", "condition", "threadsafe"}
_PRE_START_METHODS = {"__init__", "__new__", "__del__", "__repr__"}


def _finding(ctx: ModuleContext, rule: str, node: ast.AST, message: str,
             hint: str) -> Finding:
    return Finding(rule, ctx.path, node.lineno, message, hint,
                   ctx.snippet(node.lineno))


# ----------------------------------------------------- unguarded-shared-state ----

@register("unguarded-shared-state")
def unguarded_shared_state(ctx: ModuleContext) -> Iterable[Finding]:
    """In a class that spawns threads, a ``self.*`` attribute written on
    the thread side (an entrypoint or anything it reaches) and also
    touched on the main side, where some pair of cross-side accesses holds
    no common lock. Lock/Condition/Event/Queue-valued attributes are
    exempt (the object IS the synchronization), as are ``__init__``
    accesses (construction happens-before ``start()``) and attributes
    never written after construction."""
    tm = thread_model(ctx)
    out: List[Finding] = []
    for cls in tm.spawning_classes():
        if not tm.thread_fns:
            continue
        accesses = [a for a in tm.attr_accesses(cls)
                    if tm.attr_types.get((cls, a.attr))
                    not in _SAFE_ATTR_KINDS
                    and a.attr not in tm.methods.get(cls, {})
                    and getattr(a.fn, "name", "") not in _PRE_START_METHODS]
        by_attr: Dict[str, List] = {}
        for a in accesses:
            by_attr.setdefault(a.attr, []).append(a)
        for attr, accs in sorted(by_attr.items()):
            if not any(a.is_write for a in accs):
                continue  # read-only after construction
            thread_side = [a for a in accs if a.fn in tm.thread_fns]
            main_side = [a for a in accs if a.fn not in tm.thread_fns]
            if not thread_side or not main_side:
                continue
            bad = None
            for t in thread_side:
                for m in main_side:
                    if not (t.is_write or m.is_write):
                        continue
                    if not (t.locks_held & m.locks_held):
                        bad = t if t.is_write else m
                        break
                if bad:
                    break
            if bad:
                out.append(_finding(
                    ctx, "unguarded-shared-state", bad.node,
                    f"`self.{attr}` is shared between the thread "
                    f"entrypoint path and other methods of "
                    f"`{cls.name}` with no common lock held",
                    "guard every access with one lock (`with self._lock:`)"
                    " or hand the value over via a queue/Event; if the "
                    "access is provably pre-start or GIL-atomic, add an "
                    "inline allow with the why"))
    return out


# ---------------------------------------------------------------- lock-order ----

def _acquires_transitive(tm) -> Dict[ast.AST, Set[str]]:
    """fn -> every lock it (or an in-file callee) may acquire lexically."""
    direct: Dict[ast.AST, Set[str]] = {}
    for fn in tm.ctx.functions:
        acq: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.With) and \
                    tm.ctx.enclosing_function(node) is fn:
                for item in node.items:
                    lk = tm.canonical_lock(item.context_expr, node)
                    if lk is not None:
                        acq.add(lk)
        direct[fn] = acq
    callees: Dict[ast.AST, Set[ast.AST]] = {}
    for fn in tm.ctx.functions:
        cs: Set[ast.AST] = set()
        for call in ast.walk(fn):
            if isinstance(call, ast.Call) and \
                    tm.ctx.enclosing_function(call) is fn:
                cs.update(tm._resolve_callable(call.func, fn))
        callees[fn] = cs
    trans = {fn: set(acq) for fn, acq in direct.items()}
    for _ in range(10):
        changed = False
        for fn in tm.ctx.functions:
            before = len(trans[fn])
            for c in callees.get(fn, ()):
                trans[fn] |= trans.get(c, set())
            if len(trans[fn]) != before:
                changed = True
        if not changed:
            break
    return trans


@register("lock-order")
def lock_order(ctx: ModuleContext) -> Iterable[Finding]:
    """Static lock-acquisition graph: an edge A→B when B is acquired (in
    this function or an in-file callee) while A is held. A cycle means two
    threads taking the locks in opposite orders can deadlock. The runtime
    lockwatch watchdog covers the cross-module orders this in-file pass
    cannot see."""
    tm = thread_model(ctx)
    if not (tm.locks or tm.conditions):
        return []
    trans = _acquires_transitive(tm)
    edges: Dict[Tuple[str, str], ast.AST] = {}
    for fn in ctx.functions:
        for node in ast.walk(fn):
            if not (isinstance(node, ast.With)
                    and ctx.enclosing_function(node) is fn):
                continue
            inner: Set[str] = set()
            for stmt in node.body:
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.With):
                        for item in sub.items:
                            lk = tm.canonical_lock(item.context_expr, sub)
                            if lk is not None:
                                inner.add(lk)
                    if isinstance(sub, ast.Call):
                        for callee in tm._resolve_callable(sub.func, fn):
                            inner |= trans.get(callee, set())
            for item in node.items:
                outer = tm.canonical_lock(item.context_expr, node)
                if outer is None:
                    continue
                for b in inner - {outer}:
                    edges.setdefault((outer, b), node)
    # cycle detection over the edge set
    graph: Dict[str, Set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)

    def reaches(src: str, dst: str) -> bool:
        seen, stack = set(), [src]
        while stack:
            cur = stack.pop()
            if cur == dst:
                return True
            if cur in seen:
                continue
            seen.add(cur)
            stack.extend(graph.get(cur, ()))
        return False

    out: List[Finding] = []
    for (a, b), node in sorted(edges.items(),
                               key=lambda kv: kv[1].lineno):
        if reaches(b, a):  # the reverse order is also taken somewhere
            out.append(_finding(
                ctx, "lock-order", node,
                f"lock-order cycle: `{a}` is held while acquiring `{b}`, "
                f"but elsewhere `{b}` is held while acquiring `{a}` — two "
                "threads in opposite orders deadlock",
                "pick one global order (document it) and release the "
                "outer lock before taking the inner one on the reversed "
                "path"))
    return out


# --------------------------------------------------------- blocking-under-lock ----

_SOCKET_BLOCKING = {"recv", "recv_into", "accept", "connect",
                    "create_connection"}
_SUBPROCESS_BLOCKING = {"run", "call", "check_call", "check_output",
                        "communicate", "wait"}
_DEVICE_SYNC = {"block_until_ready", "device_get"}
_NP_PREFIXES = ("np.", "numpy.", "onp.")


def _is_blocking_call(call: ast.Call) -> str:
    lp = last_part(call.func)
    d = dotted(call.func)
    if lp in _SOCKET_BLOCKING:
        return f"socket {lp}()"
    if lp in _DEVICE_SYNC:
        return f"device sync {lp}()"
    if d.startswith("subprocess.") and lp in _SUBPROCESS_BLOCKING:
        return f"{d}()"
    if d == "time.sleep":
        return "time.sleep()"
    if lp == "join" and not call.args:
        return ".join()"  # thread/queue join (str.join has an argument)
    if lp == "open" and isinstance(call.func, ast.Name):
        return "file open()"
    if d.startswith(_NP_PREFIXES) and lp in ("asarray", "array") \
            and call.args and not isinstance(call.args[0], ast.Constant):
        return f"{d}() device fetch"
    return ""


@register("blocking-under-lock")
def blocking_under_lock(ctx: ModuleContext) -> Iterable[Finding]:
    """A blocking operation — socket recv/accept/connect, file open,
    thread/queue ``join()``, ``block_until_ready``/``device_get`` (and
    ``np.asarray`` of a device value), ``subprocess``, ``time.sleep`` —
    executed while holding a lock stalls every thread contending for that
    lock for the full duration (and a join on a thread that needs the
    lock deadlocks outright). ``Condition.wait`` on the held lock is the
    sanctioned exception: it releases while waiting."""
    tm = thread_model(ctx)
    if not (tm.locks or tm.conditions):
        return []
    out: List[Finding] = []
    for fn in ctx.functions:
        for call in ast.walk(fn):
            if not (isinstance(call, ast.Call)
                    and ctx.enclosing_function(call) is fn):
                continue
            held = tm.locks_held(call)
            if not held:
                continue
            what = _is_blocking_call(call)
            if not what:
                continue
            # cond.wait()/ev.wait() released the held lock by design
            if (isinstance(call.func, ast.Attribute)
                    and call.func.attr == "wait"):
                continue
            out.append(_finding(
                ctx, "blocking-under-lock", call,
                f"{what} while holding {', '.join(sorted(held))} — every "
                "thread contending for the lock stalls for the full "
                "duration",
                "move the blocking work outside the critical section "
                "(snapshot under the lock, block after release); if the "
                "lock deliberately serializes this operation, add an "
                "inline allow with the why"))
    return out


# ------------------------------------------------------------- unjoined-thread ----

@register("unjoined-thread")
def unjoined_thread(ctx: ModuleContext) -> Iterable[Finding]:
    """A ``threading.Thread`` that is started but never joined anywhere in
    the module has no deterministic shutdown: interpreter teardown races
    the thread's last writes — the exact shape of the PR 10
    tracker-shutdown flake. Daemon threads are NOT exempt; daemonhood
    suppresses the hang, not the race."""
    tm = thread_model(ctx)
    if not tm.started_threads:
        return []

    # names/attrs something calls .join() on (zero positional args — a
    # str.join always passes the iterable)
    joined_locals: Set[str] = set()
    joined_attrs: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join" and not node.args):
            base = node.func.value
            if isinstance(base, ast.Name):
                joined_locals.add(base.id)
            elif (isinstance(base, ast.Attribute)
                  and isinstance(base.value, ast.Name)
                  and base.value.id == "self"):
                joined_attrs.add(base.attr)
    # propagate: `for t in threads: t.join()` joins `threads`;
    # `t, self._thread = self._thread, None` + `t.join()` joins `_thread`
    for _ in range(3):
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.For,)) and isinstance(
                    node.target, ast.Name) and \
                    node.target.id in joined_locals and isinstance(
                        node.iter, ast.Name):
                joined_locals.add(node.iter.id)
            if isinstance(node, ast.Assign):
                tgt_names = {el.id for t in node.targets
                             for el in ast.walk(t)
                             if isinstance(el, ast.Name)}
                if tgt_names & joined_locals:
                    for el in ast.walk(node.value):
                        if (isinstance(el, ast.Attribute)
                                and isinstance(el.value, ast.Name)
                                and el.value.id == "self"):
                            joined_attrs.add(el.attr)
                        elif isinstance(el, ast.Name):
                            joined_locals.add(el.id)

    out: List[Finding] = []
    for call in tm.started_threads:
        par = ctx.parents.get(call)
        bound_locals: Set[str] = set()
        bound_attrs: Set[str] = set()
        returned = False
        node = call
        while node in ctx.parents and not isinstance(node, ast.stmt):
            node = ctx.parents[node]
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    bound_locals.add(t.id)
                elif (isinstance(t, ast.Attribute)
                      and isinstance(t.value, ast.Name)
                      and t.value.id == "self"):
                    bound_attrs.add(t.attr)
        elif isinstance(node, ast.Return):
            returned = True
        # list-comp / append-into-list bindings: the list's name
        comp = ctx.parents.get(call)
        while comp is not None and not isinstance(
                comp, (ast.stmt, ast.ListComp)):
            comp = ctx.parents.get(comp)
        if isinstance(comp, ast.ListComp):
            stmt = comp
            while stmt in ctx.parents and not isinstance(stmt, ast.stmt):
                stmt = ctx.parents[stmt]
            if isinstance(stmt, ast.Assign):
                bound_locals.update(t.id for t in stmt.targets
                                    if isinstance(t, ast.Name))
        if returned:
            continue  # the caller owns the handle
        if bound_locals & joined_locals or bound_attrs & joined_attrs:
            continue
        out.append(_finding(
            ctx, "unjoined-thread", call,
            "thread is started but never joined in this module — shutdown "
            "is nondeterministic (interpreter teardown races the thread)",
            "keep the handle and join it (with a timeout) from the "
            "owner's stop()/close()/finally path; signal the loop to "
            "exit first (Event/sentinel)"))
    return out


# -------------------------------------------------- condition-wait-no-predicate ----

@register("condition-wait-no-predicate")
def condition_wait_no_predicate(ctx: ModuleContext) -> Iterable[Finding]:
    """``Condition.wait()`` can wake spuriously and can lose a race to
    another consumer — the predicate MUST be re-checked in a ``while``
    loop around the wait. ``Event.wait(timeout)`` whose boolean result is
    discarded outside a loop has the same bug: the caller proceeds
    whether or not the event fired."""
    tm = thread_model(ctx)
    if not (tm.conditions or tm.events):
        return []
    out: List[Finding] = []
    for call in ast.walk(ctx.tree):
        if not (isinstance(call, ast.Call)
                and isinstance(call.func, ast.Attribute)
                and call.func.attr in ("wait", "wait_for")):
            continue
        name = tm._lock_name_of(call.func.value, call)
        if name in tm.conditions:
            kind = "condition"
        elif name in tm.events:
            kind = "event"
        else:
            continue
        if call.func.attr == "wait_for":
            continue  # wait_for loops on the predicate internally
        in_while = False
        cur = call
        fn = ctx.enclosing_function(call)
        while cur in ctx.parents and cur is not fn:
            cur = ctx.parents[cur]
            if isinstance(cur, ast.While):
                in_while = True
                break
        if in_while:
            continue
        if kind == "event":
            par = ctx.parents.get(call)
            if not isinstance(par, ast.Expr):
                continue  # result is checked — a timed one-shot wait
        out.append(_finding(
            ctx, "condition-wait-no-predicate", call,
            f"{kind} `{name}`.wait() outside a while loop — spurious "
            "wakeups and lost races make a single un-re-checked wait "
            "incorrect",
            "wrap it: `while not <predicate>: cond.wait(timeout)` (or "
            "use `wait_for(predicate, timeout)`); for Events, check the "
            "returned bool"))
    return out
