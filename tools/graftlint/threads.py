"""Thread-model analysis for the concurrency rules (ISSUE 11).

Mirrors the traced-context analysis in ``engine.ModuleContext``: a
per-module, import-free AST pass answering the questions every
host-concurrency rule needs —

- which functions run on **spawned threads**: ``threading.Thread(
  target=...)`` targets, everything transitively reachable from them
  through in-file calls, and the methods of server **handler classes**
  (``BaseRequestHandler`` / ``BaseHTTPRequestHandler`` subclasses ride
  ``ThreadingTCPServer`` / ``ThreadingHTTPServer`` worker threads);
- which **locks** exist (``threading.Lock/RLock/Condition`` and the
  ``utils.lockwatch`` seam's ``make_lock/make_rlock/make_condition``),
  with ``Condition(self._lock)`` aliased to the lock it wraps — holding
  the condition IS holding that lock;
- which locks are **held** at any given node: the lexical ``with lock:``
  nesting, plus a call-graph fixpoint so a helper only ever invoked from
  inside lock regions (``DecodeEngine._accept_token``) counts as
  guarded;
- which ``self.*`` attributes each class's methods read/write (subscript
  stores and mutating method calls like ``.append``/``.pop`` count as
  writes).

Like the traced analysis, this is deliberately in-file: the idioms it
polices — a class that owns both its threads and its locks — are local
by construction in this tree, and the runtime half
(``utils/lockwatch.py``) covers the cross-module lock orders statics
cannot see.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from tools.graftlint.engine import ModuleContext, dotted, last_part

# constructors whose result is itself thread-safe (or a lock): sharing the
# OBJECT across threads is the point, so accesses to these attrs are not
# "unguarded shared state"
_LOCK_CTORS = {"Lock", "RLock", "make_lock", "make_rlock"}
_CONDITION_CTORS = {"Condition", "make_condition"}
_THREADSAFE_CTORS = _LOCK_CTORS | _CONDITION_CTORS | {
    "Event", "Semaphore", "BoundedSemaphore", "Barrier", "Queue",
    "LifoQueue", "PriorityQueue", "SimpleQueue", "Thread", "Timer",
    "local", "ThreadPoolExecutor", "count",  # itertools.count: GIL-atomic
}
_HANDLER_BASES = {"BaseRequestHandler", "StreamRequestHandler",
                  "DatagramRequestHandler", "BaseHTTPRequestHandler",
                  "SimpleHTTPRequestHandler"}
_MUTATING_METHODS = {"append", "appendleft", "extend", "insert", "remove",
                     "pop", "popleft", "clear", "add", "discard", "update",
                     "setdefault", "sort", "reverse", "__setitem__"}

_FuncDef = (ast.FunctionDef, ast.AsyncFunctionDef)


@dataclasses.dataclass
class AttrAccess:
    """One ``self.X`` touch inside a method of a thread-owning class."""

    cls: ast.ClassDef
    fn: ast.AST
    attr: str
    is_write: bool
    locks_held: frozenset  # canonical lock names held at the access
    node: ast.AST

    @property
    def lineno(self) -> int:
        return self.node.lineno


class ThreadModel:
    """The shared concurrency analyses, built once per module and cached
    on the ``ModuleContext`` (rules call :func:`thread_model`)."""

    def __init__(self, ctx: ModuleContext):
        self.ctx = ctx
        self.classes: List[ast.ClassDef] = [
            n for n in ast.walk(ctx.tree) if isinstance(n, ast.ClassDef)]
        self.class_of: Dict[ast.AST, ast.ClassDef] = {}
        self.methods: Dict[ast.ClassDef, Dict[str, ast.AST]] = {}
        for cls in self.classes:
            meths: Dict[str, ast.AST] = {}
            for node in cls.body:  # direct methods only — nested defs run
                if isinstance(node, _FuncDef):  # in their method's scope
                    meths[node.name] = node
                    self.class_of[node] = cls
            self.methods[cls] = meths
        self.handler_classes = [
            cls for cls in self.classes
            if any(last_part(b) in _HANDLER_BASES for b in cls.bases)]
        # lock discovery + Condition-wraps-lock aliasing
        self.locks: Set[str] = set()
        self.conditions: Set[str] = set()
        self.events: Set[str] = set()
        self.alias: Dict[str, str] = {}
        self.attr_types: Dict[Tuple[Optional[ast.ClassDef], str], str] = {}
        self._find_locks()
        # thread entrypoints and the reachable-from-thread closure
        self.thread_targets: Set[ast.AST] = set()
        self.started_threads: List[ast.Call] = []
        self._find_threads()
        self.thread_fns: Set[ast.AST] = self._reachable(self.thread_targets)
        # call-graph lock propagation: fn -> locks guaranteed held at entry
        self.guaranteed: Dict[ast.AST, frozenset] = self._propagate_locks()

    # ------------------------------------------------------------ naming ----
    def canonical_lock(self, node: ast.AST,
                       scope: Optional[ast.AST] = None) -> Optional[str]:
        """Canonical name for a lock-valued expression at ``node``:
        ``ClassName.attr`` for ``self.attr``, the bare name for locals and
        module globals — ``None`` when the expression is not a known lock.
        Condition aliases resolve to the lock they wrap."""
        name = self._lock_name_of(node, scope)
        if name is None:
            return None
        seen = set()
        while name in self.alias and name not in seen:
            seen.add(name)
            name = self.alias[name]
        return name if name in self.locks or name in self.conditions else None

    def _lock_name_of(self, node: ast.AST,
                      scope: Optional[ast.AST]) -> Optional[str]:
        if isinstance(node, ast.Attribute) and isinstance(node.value,
                                                          ast.Name) \
                and node.value.id == "self":
            cls = self._scope_class(scope or node)
            if cls is not None:
                return f"{cls.name}.{node.attr}"
            return f"?.{node.attr}"
        if isinstance(node, ast.Name):
            return node.id
        return None

    def _scope_class(self, node: ast.AST) -> Optional[ast.ClassDef]:
        cur = node
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur
            cur = self.ctx.parents.get(cur)
        return None

    # ------------------------------------------------------------- locks ----
    def _find_locks(self) -> None:
        for node in ast.walk(self.ctx.tree):
            if not (isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call)):
                continue
            ctor = last_part(node.value.func)
            for tgt in node.targets:
                name = self._lock_name_of(tgt, tgt)
                if name is None:
                    continue
                if ctor in _LOCK_CTORS:
                    self.locks.add(name)
                    self._note_attr_type(tgt, "lock")
                elif ctor in _CONDITION_CTORS:
                    self.conditions.add(name)
                    self._note_attr_type(tgt, "condition")
                    # Condition(self._lock): holding the condition holds
                    # the wrapped lock — alias them to one node
                    wrapped = (self._lock_name_of(node.value.args[0],
                                                  node.value.args[0])
                               if node.value.args else None)
                    if wrapped is not None:
                        self.alias[name] = wrapped
                    else:
                        # a bare Condition() owns a private lock: treat the
                        # condition name itself as the lock node
                        self.locks.add(name)
                elif ctor == "Event":
                    self.events.add(name)
                    self._note_attr_type(tgt, "threadsafe")
                elif ctor in _THREADSAFE_CTORS:
                    self._note_attr_type(tgt, "threadsafe")

    def _note_attr_type(self, tgt: ast.AST, kind: str) -> None:
        if isinstance(tgt, ast.Attribute) and isinstance(tgt.value,
                                                         ast.Name) \
                and tgt.value.id == "self":
            cls = self._scope_class(tgt)
            self.attr_types[(cls, tgt.attr)] = kind

    # ----------------------------------------------------------- threads ----
    def _resolve_callable(self, node: ast.AST,
                          scope: ast.AST) -> List[ast.AST]:
        """Function defs a callable expression may refer to: ``self.m`` →
        the method, a bare name → same-name defs in the module."""
        if isinstance(node, ast.Attribute) and isinstance(node.value,
                                                          ast.Name) \
                and node.value.id == "self":
            cls = self._scope_class(scope)
            if cls is not None and node.attr in self.methods.get(cls, {}):
                return [self.methods[cls][node.attr]]
            return []
        if isinstance(node, ast.Name):
            return list(self.ctx.defs_by_name.get(node.id, []))
        if isinstance(node, ast.Lambda):
            return [node]
        return []

    def _find_threads(self) -> None:
        for node in ast.walk(self.ctx.tree):
            if not (isinstance(node, ast.Call)
                    and last_part(node.func) in ("Thread", "Timer")):
                continue
            self.started_threads.append(node)
            target = next((kw.value for kw in node.keywords
                           if kw.arg == "target"), None)
            if target is None and last_part(node.func) == "Timer" \
                    and len(node.args) >= 2:
                target = node.args[1]
            if target is not None:
                for fn in self._resolve_callable(target, node):
                    self.thread_targets.add(fn)
        for cls in self.handler_classes:
            for fn in self.methods.get(cls, {}).values():
                self.thread_targets.add(fn)
        # executor.submit(fn, ...) / executor.map(fn, ...): fn runs on a
        # pool thread
        for node in ast.walk(self.ctx.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("submit",)
                    and node.args):
                for fn in self._resolve_callable(node.args[0], node):
                    self.thread_targets.add(fn)

    def _reachable(self, seeds: Set[ast.AST]) -> Set[ast.AST]:
        out = set(seeds)
        for _ in range(10):
            before = len(out)
            for fn in list(out):
                for node in ast.walk(fn):
                    if isinstance(node, _FuncDef) and node is not fn:
                        out.add(node)  # nested defs run on the same thread
                    if isinstance(node, ast.Call):
                        for d in self._resolve_callable(node.func, fn):
                            if isinstance(d, _FuncDef + (ast.Lambda,)):
                                out.add(d)
            if len(out) == before:
                break
        return out

    # ------------------------------------------------------- locks held ----
    def lexical_locks(self, node: ast.AST) -> frozenset:
        """Canonical locks held at ``node`` by enclosing ``with`` blocks
        within the same function."""
        held = set()
        cur = node
        fn = self.ctx.enclosing_function(node)
        while cur is not None and cur is not fn:
            par = self.ctx.parents.get(cur)
            if isinstance(par, ast.With) and cur in par.body:
                for item in par.items:
                    lk = self.canonical_lock(item.context_expr, par)
                    if lk is not None:
                        held.add(lk)
            cur = par
        return frozenset(held)

    def _propagate_locks(self) -> Dict[ast.AST, frozenset]:
        """fn → locks held at EVERY in-file call site (intersection);
        thread targets and never-called functions start at the empty set.
        One fixpoint pass over the in-file call graph."""
        callsites: Dict[ast.AST, List[Tuple[ast.AST, ast.Call]]] = {}
        for fn in self.ctx.functions:
            for call in ast.walk(fn):
                if not isinstance(call, ast.Call) or \
                        self.ctx.enclosing_function(call) is not fn:
                    continue
                for callee in self._resolve_callable(call.func, fn):
                    callsites.setdefault(callee, []).append((fn, call))
        guaranteed: Dict[ast.AST, frozenset] = {
            fn: frozenset() for fn in self.ctx.functions}
        for _ in range(10):
            changed = False
            for fn in self.ctx.functions:
                sites = callsites.get(fn)
                if not sites or fn in self.thread_targets:
                    new = frozenset()
                else:
                    sets = [guaranteed.get(caller, frozenset())
                            | self.lexical_locks(call)
                            for caller, call in sites]
                    new = frozenset.intersection(*sets) if sets \
                        else frozenset()
                if new != guaranteed.get(fn):
                    guaranteed[fn] = new
                    changed = True
            if not changed:
                break
        return guaranteed

    def locks_held(self, node: ast.AST) -> frozenset:
        fn = self.ctx.enclosing_function(node)
        base = self.guaranteed.get(fn, frozenset()) if fn is not None \
            else frozenset()
        return base | self.lexical_locks(node)

    # ----------------------------------------------------- attr accesses ----
    def spawning_classes(self) -> List[ast.ClassDef]:
        """Classes that start threads (a ``Thread(...)`` call inside one of
        their methods) — the scope the shared-state rule polices."""
        out = []
        for cls in self.classes:
            for call in self.started_threads:
                fn = self.ctx.enclosing_function(call)
                if fn is not None and self.class_of.get(fn) is cls:
                    out.append(cls)
                    break
        return out

    def attr_accesses(self, cls: ast.ClassDef) -> List[AttrAccess]:
        out: List[AttrAccess] = []
        for fn in self.methods.get(cls, {}).values():
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Attribute)
                        and isinstance(node.value, ast.Name)
                        and node.value.id == "self"):
                    continue
                out.append(AttrAccess(cls, fn, node.attr,
                                      self._is_write(node),
                                      self.locks_held(node), node))
        return out

    def _is_write(self, attr_node: ast.Attribute) -> bool:
        if isinstance(attr_node.ctx, (ast.Store, ast.Del)):
            return True
        par = self.ctx.parents.get(attr_node)
        # self.x[...] = v  /  self.x[...] += v
        if isinstance(par, ast.Subscript) and isinstance(
                par.ctx, (ast.Store, ast.Del)):
            return True
        if isinstance(par, ast.AugAssign) and par.target is attr_node:
            return True
        # self.x.append(...) and friends mutate in place
        if isinstance(par, ast.Attribute) and par.attr in _MUTATING_METHODS:
            grand = self.ctx.parents.get(par)
            if isinstance(grand, ast.Call) and grand.func is par:
                return True
        return False


def thread_model(ctx: ModuleContext) -> ThreadModel:
    """Get-or-build the module's ThreadModel (cached on the context so the
    five concurrency rules share one analysis pass)."""
    tm = getattr(ctx, "_thread_model", None)
    if tm is None:
        tm = ThreadModel(ctx)
        ctx._thread_model = tm
    return tm
