"""Checked-in baseline/allowlist for graftlint.

Format (tools/graftlint_baseline.json)::

    {"version": 1,
     "entries": [
       {"rule": "env-read-in-trace",
        "path": "deeplearning4j_tpu/parallel/multihost.py",
        "snippet": "coordinator = os.environ.get(",
        "why": "distributed bootstrap seam; host-side at process init"}]}

Matching is by ``(rule, path)`` plus ``snippet`` being a *substring* of
the finding's normalized source line — stable across line-number churn
and surrounding edits. Every entry MUST carry a non-empty ``why``;
``--update-baseline`` seeds new entries with a FIXME why that the
tier-1 gate refuses, so an unjustified allowlist can't land.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.graftlint.engine import Finding

FIXME_WHY = "FIXME: justify this entry or fix the finding"


def load_baseline(path: str) -> List[Dict]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except FileNotFoundError:
        return []
    entries = data.get("entries", [])
    for e in entries:
        for field in ("rule", "path", "snippet", "why"):
            if not str(e.get(field, "")).strip():
                raise ValueError(
                    f"baseline entry missing required '{field}': {e!r}")
    return entries


def _matches(entry: Dict, finding: Finding) -> bool:
    return (entry["rule"] == finding.rule
            and entry["path"] == finding.path
            and entry["snippet"] in finding.snippet)


def match_entry(entries: List[Dict], finding: Finding) -> Optional[Dict]:
    """The baseline entry covering ``finding``, or None — the per-finding
    status the gate's ``--json`` output reports."""
    for e in entries:
        if _matches(e, finding):
            return e
    return None


def prune_baseline(entries: List[Dict], repo_root: Optional[str] = None,
                   rules: Optional[Set[str]] = None,
                   ) -> Tuple[List[Dict], List[Dict]]:
    """Split ``entries`` into (kept, pruned): an entry whose file no
    longer exists or whose rule is no longer registered can never match
    a finding again — it is dead weight that would otherwise sit in the
    allowlist forever looking like a justified exception. Each pruned
    dict gains a ``pruned_because`` reason for the ``--update-baseline``
    report."""
    kept: List[Dict] = []
    pruned: List[Dict] = []
    for e in entries:
        if rules is not None and e["rule"] not in rules:
            pruned.append(dict(
                e, pruned_because=f"rule {e['rule']!r} is no longer "
                "registered"))
        elif repo_root is not None and not os.path.exists(
                os.path.join(repo_root, e["path"])):
            pruned.append(dict(
                e, pruned_because=f"file {e['path']} no longer exists"))
        else:
            kept.append(e)
    return kept, pruned


def apply_baseline(findings: Iterable[Finding], entries: List[Dict],
                   ) -> Tuple[List[Finding], List[Dict], List[Dict]]:
    """(non-baselined findings, used entries, stale entries). A stale
    entry matched nothing — the underlying code was fixed or moved; prune
    it (``--update-baseline``) so the allowlist can only shrink honestly."""
    fresh: List[Finding] = []
    used: List[Dict] = []
    for f in findings:
        hit = next((e for e in entries if _matches(e, f)), None)
        if hit is None:
            fresh.append(f)
        elif hit not in used:
            used.append(hit)
    stale = [e for e in entries if not any(_matches(e, f) for f in findings)]
    return fresh, used, stale


def write_baseline(path: str, findings: Iterable[Finding],
                   old_entries: List[Dict]) -> List[Dict]:
    """Regenerate the baseline from current findings, carrying forward the
    why of any old entry that still matches; new entries get FIXME whys."""
    entries: List[Dict] = []
    for f in findings:
        old = next((e for e in old_entries if _matches(e, f)), None)
        entry = {
            "rule": f.rule,
            "path": f.path,
            "snippet": f.snippet,
            "why": old["why"] if old else FIXME_WHY,
        }
        if entry not in entries:
            entries.append(entry)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": 1, "entries": entries}, fh, indent=1)
        fh.write("\n")
    return entries
