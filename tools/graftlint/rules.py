"""The JAX-aware rule set.

Each rule targets a failure class the reviews keep re-finding (see
tools/graftlint/__init__.py). Rules are deliberately *in-file* analyses:
cross-module call graphs would need imports (slow, fragile in a lint
gate); the idioms these rules police — jitted step definitions, timed
bench loops, PRNG threading — are local by construction in this tree.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from tools.graftlint.engine import (
    Finding,
    ModuleContext,
    dotted,
    last_part,
    register,
)

_SYNC_LAST = {"block_until_ready", "device_get", "item", "tolist"}
_NP_PREFIXES = ("np.", "numpy.", "onp.")
_TIMER_LAST = {"perf_counter", "monotonic", "perf_counter_ns"}
_HARMLESS_CALLS = {"append", "perf_counter", "monotonic", "perf_counter_ns",
                   "time", "range", "len", "print", "clear", "split", "join",
                   "round", "min", "max", "format"}
# first-arg names that mark a jitted function as a train step carrying
# donatable state
_STATE_ARG_NAMES = {"params", "state", "states", "opt_state", "train_state",
                    "syn0", "syn1", "syn1neg", "hist", "weights", "carry"}


def _is_timer_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and (last_part(node.func) in _TIMER_LAST
                 or dotted(node.func) == "time.time"))


def _is_sync_call(node: ast.Call) -> bool:
    lp = last_part(node.func)
    if lp in _SYNC_LAST:
        return True
    d = dotted(node.func)
    if d.startswith(_NP_PREFIXES) and lp in ("asarray", "array"):
        return True
    if isinstance(node.func, ast.Name) and node.func.id in ("float", "int"):
        return bool(node.args) and not isinstance(node.args[0], ast.Constant)
    return False


def _finding(ctx: ModuleContext, rule: str, node: ast.AST, message: str,
             hint: str) -> Finding:
    return Finding(rule, ctx.path, node.lineno, message, hint,
                   ctx.snippet(node.lineno))


# ------------------------------------------------------------ jit-host-sync ----

@register("jit-host-sync")
def jit_host_sync(ctx: ModuleContext) -> Iterable[Finding]:
    """float()/int()/.item()/np.asarray() on values inside traced bodies
    (breaks or silently syncs at trace time), and per-step device fetches
    in host loops around in-file jitted steps (serializes dispatch: every
    iteration waits for the device before enqueueing the next)."""
    out: List[Finding] = []
    for fn in ctx.traced:
        for call in ctx.walk_in_function(fn, ast.Call):
            flagged = None
            if (isinstance(call.func, ast.Name)
                    and call.func.id in ("float", "int") and call.args
                    and not isinstance(call.args[0], ast.Constant)):
                flagged = f"{call.func.id}() on a traced value"
            elif (isinstance(call.func, ast.Attribute)
                  and call.func.attr in ("item", "tolist")):
                flagged = f".{call.func.attr}() on a traced value"
            elif (dotted(call.func).startswith(_NP_PREFIXES)
                  and last_part(call.func) in ("asarray", "array")):
                flagged = f"{dotted(call.func)}() materializes inside a " \
                          "traced body"
            if flagged:
                out.append(_finding(
                    ctx, "jit-host-sync", call,
                    f"host sync inside traced code: {flagged}",
                    "keep the value as a jax array inside jit/shard_map/scan; "
                    "fetch on the host after the step returns"))
    # host-side loops: per-iteration fetch of an in-file jitted step's result
    for fn in ctx.functions:
        if fn in ctx.traced:
            continue
        for loop in ctx.walk_in_function(fn, (ast.For, ast.While)):
            bound: set = set()
            for node in ast.walk(loop):
                if (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)
                        and isinstance(node.value.func, ast.Name)
                        and node.value.func.id in ctx.jitted_names):
                    for tgt in node.targets:
                        for el in ast.walk(tgt):
                            if isinstance(el, ast.Name):
                                bound.add(el.id)
            if not bound:
                continue
            for call in ast.walk(loop):
                if not isinstance(call, ast.Call):
                    continue
                is_fetch = (
                    (isinstance(call.func, ast.Name)
                     and call.func.id in ("float", "int") and call.args
                     and isinstance(call.args[0], ast.Name)
                     and call.args[0].id in bound)
                    or (isinstance(call.func, ast.Attribute)
                        and call.func.attr == "item"
                        and isinstance(call.func.value, ast.Name)
                        and call.func.value.id in bound))
                if is_fetch:
                    out.append(_finding(
                        ctx, "jit-host-sync", call,
                        "per-step device fetch inside the step loop "
                        "serializes dispatch (one round-trip per iteration)",
                        "accumulate on device and fetch once after the loop, "
                        "or fetch every N steps"))
    return out


# ---------------------------------------------------------- untimed-dispatch ----

@register("untimed-dispatch")
def untimed_dispatch(ctx: ModuleContext) -> Iterable[Finding]:
    """A perf_counter window that times calls without a device sync before
    the clock stops measures *enqueue*, not compute (JAX dispatch is
    async; on some transports even block_until_ready-free fetch paths
    return at enqueue — the class of bench bug BASELINE.md round 2 hit)."""
    out: List[Finding] = []
    for fn in ctx.functions:
        starts = {}  # var name -> max start lineno
        for node in ctx.walk_in_function(fn, ast.Assign):
            if (_is_timer_call(node.value) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                var = node.targets[0].id
                starts.setdefault(var, []).append(node.lineno)
        if not starts:
            continue
        for node in ctx.walk_in_function(fn, ast.BinOp):
            if not (isinstance(node.op, ast.Sub)
                    and isinstance(node.right, ast.Name)
                    and node.right.id in starts
                    and _is_timer_call(node.left)):
                continue
            stop_line = node.lineno
            cands = [ln for ln in starts[node.right.id] if ln < stop_line]
            if not cands:
                continue
            start_line = max(cands)
            work = False
            synced = False
            for call in ctx.walk_in_function(fn, ast.Call):
                if not (start_line < call.lineno <= stop_line):
                    continue
                if _is_timer_call(call):
                    continue
                if _is_sync_call(call):
                    synced = True
                elif last_part(call.func) not in _HARMLESS_CALLS:
                    work = True
            if work and not synced:
                out.append(_finding(
                    ctx, "untimed-dispatch", node,
                    "timed region stops the clock without a device sync — "
                    "this measures dispatch enqueue, not compute",
                    "block_until_ready the stage result (or fetch a scalar) "
                    "before reading the stop time"))
    return out


# --------------------------------------------------------------- prng-reuse ----

_NONCONSUMING = {"fold_in", "PRNGKey", "device_put", "block_until_ready",
                 "asarray", "print", "len", "str"}


def _is_key_source(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    d = dotted(node.func)
    return (d in ("PRNGKey", "split", "fold_in")
            or d.endswith(("random.PRNGKey", "random.split",
                           "random.fold_in", "random.key")))


@register("prng-reuse")
def prng_reuse(ctx: ModuleContext) -> Iterable[Finding]:
    """A PRNG key consumed twice without a split/fold_in in between draws
    the SAME randomness twice — silently correlated noise/negatives/
    dropout. Consumption = passing the key to any call that is not a
    derivation; ``key, sub = split(key)`` is the canonical advance and
    resets the count. Branch-aware: consumptions in different arms of the
    same ``if`` are mutually exclusive; a consumption inside a ``return``
    cannot flow to later code. A consumption inside a loop whose key was
    bound outside and never advanced in the loop body repeats randomness
    every iteration and is flagged."""
    out: List[Finding] = []
    for fn in ctx.functions:
        if isinstance(fn, ast.Lambda):
            continue
        uses_jax_random = any(
            dotted(n).startswith("jax.random")
            for n in ast.walk(fn) if isinstance(n, ast.Attribute))
        key_vars: set = set()
        args = fn.args
        if uses_jax_random:  # seed from key-ish param names only when the
            for a in (args.posonlyargs + args.args + args.kwonlyargs):
                if a.arg == "key" or a.arg.endswith("_key"):
                    key_vars.add(a.arg)
        rebind_stmts: List[ast.Assign] = []
        for node in ctx.walk_in_function(fn, ast.Assign):
            if _is_key_source(node.value):
                rebind_stmts.append(node)
                for tgt in node.targets:
                    for el in ast.walk(tgt):
                        if isinstance(el, ast.Name):
                            key_vars.add(el.id)
        if not key_vars:
            continue

        def stmt_targets(stmt: ast.Assign) -> set:
            return {el.id for t in stmt.targets for el in ast.walk(t)
                    if isinstance(el, ast.Name)}

        def rebinds(scope: ast.AST, var: str) -> bool:
            return any(var in stmt_targets(s) for s in rebind_stmts
                       if scope.lineno <= s.lineno
                       <= getattr(scope, "end_lineno", 1 << 30))

        def branch_sig(node: ast.AST):
            """[(id(if_node), arm), ...] for every enclosing If/Try arm."""
            sig = []
            cur = node
            while cur in ctx.parents:
                par = ctx.parents[cur]
                if isinstance(par, (ast.If, ast.Try)):
                    for arm_name in ("body", "orelse", "handlers",
                                     "finalbody"):
                        if cur in getattr(par, arm_name, []):
                            sig.append((id(par), arm_name))
                cur = par
            return sig

        def sigs_exclusive(a, b) -> bool:
            """True when the two consumptions sit in different arms of the
            same conditional — they cannot both execute."""
            arms_a = dict(a)
            return any(arms_a.get(if_id, arm) != arm for if_id, arm in b)

        def inside_return(node: ast.AST) -> bool:
            cur = node
            while cur in ctx.parents:
                cur = ctx.parents[cur]
                if isinstance(cur, (ast.Return, ast.Raise)):
                    return True
                if isinstance(cur, ast.stmt):
                    return False
            return False

        def terminal(node: ast.AST) -> bool:
            """The consumption's statement block ends in return/raise at or
            after it — the value cannot flow past this block (the
            sequential early-return dispatch pattern)."""
            stmt = node
            while stmt in ctx.parents and not isinstance(stmt, ast.stmt):
                stmt = ctx.parents[stmt]
            par = ctx.parents.get(stmt)
            for arm in ("body", "orelse", "handlers", "finalbody"):
                block = getattr(par, arm, None)
                if isinstance(block, list) and stmt in block:
                    rest = block[block.index(stmt):]
                    return any(isinstance(s, (ast.Return, ast.Raise))
                               for s in rest)
            return False

        def sig_within(outer, inner) -> bool:
            """Every arm of ``outer`` also encloses ``inner`` (the second
            consumption is in the same branch chain, or deeper)."""
            return all(item in inner for item in outer)

        loops = list(ctx.walk_in_function(fn, (ast.For, ast.While)))
        # (lineno, col, kind, var, node): rebinds clear, consumptions count
        events = []
        for stmt in rebind_stmts:
            for var in stmt_targets(stmt):
                events.append((stmt.lineno, getattr(stmt, "col_offset", 0),
                               0, var, stmt))
        for call in ctx.walk_in_function(fn, ast.Call):
            callee = last_part(call.func)
            if callee in _NONCONSUMING:
                continue
            for arg in list(call.args) + [kw.value for kw in call.keywords]:
                if not (isinstance(arg, ast.Name) and arg.id in key_vars):
                    continue
                if callee == "split":
                    stmt = ctx.parents.get(call)
                    while stmt is not None and not isinstance(stmt, ast.stmt):
                        stmt = ctx.parents.get(stmt)
                    if (isinstance(stmt, ast.Assign)
                            and arg.id in stmt_targets(stmt)):
                        continue  # `key, sub = split(key)`: the advance
                events.append((call.lineno, getattr(call, "col_offset", 0),
                               1, arg.id, call))

        consumed: dict = {}  # var -> (lineno, branch sig, terminal?)
        for lineno, _col, kind, var, node in sorted(events,
                                                    key=lambda e: e[:3]):
            if kind == 0:
                consumed.pop(var, None)
                continue
            sig = branch_sig(node)
            prior = consumed.get(var)
            loop_reuse = any(not rebinds(lp, var) for lp in loops
                             if lp.lineno <= lineno
                             <= getattr(lp, "end_lineno", 1 << 30))
            conflict = (prior is not None
                        and not sigs_exclusive(prior[1], sig)
                        # a terminal prior only flows to code in its own arm
                        and (not prior[2] or sig_within(prior[1], sig)))
            if conflict or loop_reuse:
                where = (f"already consumed at line {prior[0]}" if conflict
                         else "re-consumed every loop iteration without a "
                              "split/fold_in advance")
                out.append(_finding(
                    ctx, "prng-reuse", node,
                    f"PRNG key '{var}' {where} — identical randomness is "
                    "drawn twice",
                    "advance the key: `key, sub = jax.random.split(key)` "
                    "per use, or derive with fold_in"))
            elif prior is None and not inside_return(node):
                consumed[var] = (lineno, sig, terminal(node))
    return out


# -------------------------------------------------------------- stray-debug ----

@register("stray-debug")
def stray_debug(ctx: ModuleContext) -> Iterable[Finding]:
    """print()/jax.debug.* inside traced bodies: prints fire at TRACE time
    (misleading) or, for jax.debug.print, add host callbacks to the hot
    compiled step."""
    out: List[Finding] = []
    for fn in ctx.traced:
        for call in ctx.walk_in_function(fn, ast.Call):
            d = dotted(call.func)
            if (d == "print"
                    or d.endswith("debug.print")
                    or d.endswith("debug.breakpoint")
                    or d == "breakpoint"):
                out.append(_finding(
                    ctx, "stray-debug", call,
                    f"debug output `{d}` inside traced train-step code",
                    "remove it, or route through the telemetry metrics dict "
                    "fetched every N steps"))
    return out


# ------------------------------------------------------------ nondet-pytree ----

@register("nondet-pytree")
def nondet_pytree(ctx: ModuleContext) -> Iterable[Finding]:
    """Iterating a set where the order can reach a pytree/program structure
    makes tracing/compilation nondeterministic across processes (hash
    randomization) — the multi-host killer: two hosts compile different
    programs for 'the same' step."""
    out: List[Finding] = []

    def is_set_expr(node: ast.AST) -> bool:
        if isinstance(node, ast.Set):
            return True
        return (isinstance(node, ast.Call)
                and last_part(node.func) in ("set", "frozenset"))

    for node in ast.walk(ctx.tree):
        iters = []
        if isinstance(node, (ast.For, ast.AsyncFor)):
            iters = [node.iter]
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            iters = [gen.iter for gen in node.generators]
        for it in iters:
            if is_set_expr(it):
                out.append(_finding(
                    ctx, "nondet-pytree", it,
                    "iteration over a set — order is nondeterministic across "
                    "processes and can leak into pytree/program structure",
                    "iterate `sorted(...)` of the set, or use a list/dict "
                    "(insertion-ordered)"))
    return out


# -------------------------------------------------------- env-read-in-trace ----

_BLESSED_ENV_PREFIX = "DL4J_TPU_"
_BLESSED_FILES = ("compat.py",)


@register("env-read-in-trace")
def env_read(ctx: ModuleContext) -> Iterable[Finding]:
    """os.environ/os.getenv reads outside the blessed seams (compat.py, or
    keys under the documented ``DL4J_TPU_*`` namespace — currently
    ``DL4J_TPU_ATTN_IMPL`` (ops/flash_attention.py attention-core chain),
    ``DL4J_TPU_MOE_IMPL`` (parallel/moe.py dispatch chain:
    alltoall | alltoall_2d | replicated),
    ``DL4J_TPU_UPDATE_SHARDING`` (optimize/updaters.py ZeRO
    update-sharding chain), ``DL4J_TPU_RUNPROF`` /
    ``DL4J_TPU_RUNPROF_DIR`` (telemetry/runprof.py ``runprof=`` seam
    default + session dump directory), and ``DL4J_TPU_FLEET_STALE_S`` /
    ``DL4J_TPU_FLEET_DEAD_S`` / ``DL4J_TPU_FLEET_POLL_S`` /
    ``DL4J_TPU_FLEET_HEARTBEAT_S`` (serve/router.py + serve/fleet.py
    membership timing defaults), all read host-side at
    trace/resolve time, never inside a traced body). Ad-hoc env reads are invisible config:
    they fork behavior between hosts and leak into traced code paths
    where a retrace won't see the change."""
    if ctx.path.replace("\\", "/").rsplit("/", 1)[-1] in _BLESSED_FILES:
        return []
    out: List[Finding] = []

    def blessed(key_node) -> bool:
        key = ctx.resolve_str(key_node) if key_node is not None else None
        return key is not None and key.startswith(_BLESSED_ENV_PREFIX)

    for node in ast.walk(ctx.tree):
        key_node = None
        hit = None
        if (isinstance(node, ast.Subscript)
                and dotted(node.value) == "os.environ"
                and isinstance(node.ctx, ast.Load)):
            key_node, hit = node.slice, "os.environ[...]"
        elif isinstance(node, ast.Call):
            d = dotted(node.func)
            if d == "os.environ.get" and node.args:
                key_node, hit = node.args[0], "os.environ.get"
            elif d == "os.getenv" and node.args:
                key_node, hit = node.args[0], "os.getenv"
        elif (isinstance(node, ast.Compare)
              and any(dotted(c) == "os.environ" for c in node.comparators)
              and len(node.ops) == 1
              and isinstance(node.ops[0], (ast.In, ast.NotIn))):
            key_node, hit = node.left, "`in os.environ`"
        if hit and not blessed(key_node):
            out.append(_finding(
                ctx, "env-read-in-trace", node,
                f"environment read ({hit}) outside the blessed seams",
                "route through compat.py or a DL4J_TPU_*-namespaced knob; "
                "if this seam is deliberate, baseline it with a why"))
    return out


# ------------------------------------------------------------ missing-donate ----

@register("missing-donate")
def missing_donate(ctx: ModuleContext) -> Iterable[Finding]:
    """A jitted step whose leading args are params/opt-state must make an
    explicit donation decision: without ``donate_argnums`` every step
    holds two copies of the model (old + new params) in HBM. An explicit
    ``donate_argnums=()`` documents 'considered, declined' and passes."""
    out: List[Finding] = []

    def fn_carries_state(fn: ast.AST) -> bool:
        args = getattr(fn, "args", None)
        if args is None:
            return False
        names = [a.arg for a in (args.posonlyargs + args.args)][:3]
        return any(n in _STATE_ARG_NAMES for n in names)

    def call_has_donate(call: ast.Call) -> bool:
        return any(kw.arg in ("donate_argnums", "donate_argnames")
                   for kw in call.keywords)

    def flag(node, what):
        out.append(_finding(
            ctx, "missing-donate", node,
            f"jitted step {what} carries params/state with no "
            "donate_argnums decision",
            "donate the state args (`donate_argnums=(0,...)`) or declare "
            "`donate_argnums=()` to record that callers reuse the buffers"))

    # decorated defs
    for fn in ctx.functions:
        for deco in getattr(fn, "decorator_list", []):
            jit_names = [n for n in ast.walk(deco)
                         if isinstance(n, (ast.Name, ast.Attribute))
                         and last_part(n) == "jit"]
            if not jit_names or not fn_carries_state(fn):
                continue
            donate = (isinstance(deco, ast.Call) and call_has_donate(deco))
            if not donate:
                flag(fn, f"`{fn.name}`")
    # expression form: jax.jit(f, ...)
    for call in ast.walk(ctx.tree):
        if not (isinstance(call, ast.Call) and last_part(call.func) == "jit"
                and call.args):
            continue
        target = call.args[0]
        fns = []
        if isinstance(target, ast.Lambda):
            fns = [target]
        elif isinstance(target, ast.Name):
            fns = ctx.defs_by_name.get(target.id, [])
        if any(fn_carries_state(f) for f in fns) and not call_has_donate(call):
            name = getattr(target, "id", "<lambda>")
            flag(call, f"`jit({name})`")
    return out
