"""graftlint — JAX-aware static analysis for the jax_graft tree.

The reference DL4J leaned on the JVM type system for its correctness
story; this rebuild's recurring failure classes are *performance*
semantics the Python type system cannot see: hidden host↔device syncs
inside jitted code, per-step device fetches that serialize dispatch,
benchmark timers stopped at enqueue instead of completion, PRNG keys
consumed twice, nondeterministic pytree structure from set iteration,
un-blessed environment seams, and train steps that never declare a
donation decision. graftlint encodes each as an AST rule.

Public surface::

    from tools.graftlint import lint_source, lint_paths, Finding, RULES
    from tools.graftlint.baseline import load_baseline, apply_baseline

``tools/lint_gate.py`` is the CLI / CI gate; tests/test_graftlint_repo.py
runs the same gate as a tier-1 test with the checked-in baseline.
"""

from tools.graftlint.baseline import (  # noqa: F401
    apply_baseline,
    load_baseline,
    match_entry,
    prune_baseline,
    write_baseline,
)
from tools.graftlint.engine import (  # noqa: F401
    Finding,
    RULES,
    lint_file,
    lint_paths,
    lint_source,
)
from tools.graftlint import rules as _rules  # noqa: F401  (registers RULES)
from tools.graftlint import (  # noqa: F401  (registers concurrency RULES)
    concurrency_rules as _concurrency_rules,
)
from tools.graftlint import (  # noqa: F401  (registers net/RPC RULES)
    net_rules as _net_rules,
)
