#!/usr/bin/env python
"""Summarize an autotuner run (ISSUE 20): per-knob winner table,
pruned/measured counts, predicted-vs-measured rank correlation, and the
tuning cache's current entries.

Usage:
    python tools/tune_report.py [DIR] [--cache PATH] [--json]

DIR is a decisions directory written by ``python -m
deeplearning4j_tpu.tune --out DIR`` (default: ``tuning_out``). For each
searched seam the report shows every knob's default vs winning value,
how much of the space the roofline pruner disposed of without executing
anything, the measured tuned-vs-default speedup, and the Spearman rank
correlation between the cost model's predicted ordering and the
measured one — the number that says whether phase 1's pruning can be
trusted. ``--cache`` additionally lists the tuning cache's entries with
their knob-space versions, flagging stale ones (the watchtower
``tune_cache_stale`` signal, readable offline).

The per-candidate audit trail (who pruned whom and why) lives in
``tools/profile_report.py --tuning DIR``.

Exit code 0 with a "no decisions" message when DIR is empty — missing
data is reported, never invented.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_decisions(path: str) -> List[Dict]:
    paths = (sorted(glob.glob(os.path.join(path, "tuning_*.json")))
             if os.path.isdir(path) else [path])
    out = []
    for p in paths:
        try:
            with open(p) as fh:
                rec = json.load(fh)
        except (OSError, json.JSONDecodeError) as exc:
            print(f"skipping unreadable tuning file {p}: {exc}",
                  file=sys.stderr)
            continue
        if isinstance(rec, dict) and rec.get("winner_config") is not None:
            out.append(rec)
    return out


def build_report(decisions: List[Dict]) -> Dict:
    seams = []
    for rec in decisions:
        default = rec.get("default_config") or {}
        winner = rec.get("winner_config") or {}
        knobs = [{
            "knob": k,
            "default": default.get(k),
            "winner": winner.get(k),
            "changed": winner.get(k) != default.get(k),
        } for k in sorted(set(default) | set(winner))]
        seams.append({
            "seam": rec.get("seam"),
            "space_version": rec.get("space_version"),
            "context": rec.get("context"),
            "knobs": knobs,
            "tuned_vs_default": rec.get("tuned_vs_default"),
            "counts": rec.get("counts") or {},
            "rank_correlation": rec.get("rank_correlation"),
        })
    return {"seams": seams}


def load_cache_entries(path: str) -> List[Dict]:
    """Cache entries + staleness verdicts via the library (the live
    ``space_version`` is the comparison anchor)."""
    sys.path.insert(0, REPO_ROOT)
    from deeplearning4j_tpu.tune.cache import TuningCache  # noqa: E402
    from deeplearning4j_tpu.tune.space import (  # noqa: E402
        space_names,
        space_version,
    )

    live = {s: space_version(s) for s in space_names()}
    rows = []
    for key, entry in sorted(TuningCache(path).entries().items()):
        seam = entry.get("seam")
        rows.append({
            "key": key,
            "seam": seam,
            "config": entry.get("config"),
            "space_version": entry.get("space_version"),
            "live_version": live.get(seam),
            "stale": (seam in live
                      and entry.get("space_version") != live[seam]),
        })
    return rows


def render_text(report: Dict, cache_rows=None) -> str:
    if not report["seams"]:
        return ("no tuning decisions found — run "
                "python -m deeplearning4j_tpu.tune --out <dir> first")
    lines = ["autotuner summary (ISSUE 20):"]
    for s in report["seams"]:
        c = s["counts"]
        ratio = s["tuned_vs_default"]
        corr = s["rank_correlation"]
        lines.append(
            f"\nseam {s['seam']} (space v{s['space_version']}): "
            f"tuned-vs-default "
            + (f"{ratio:.3f}x" if ratio is not None else "-")
            + f" | {c.get('total', 0)} candidates -> "
              f"{c.get('invalid', 0)} invalid, {c.get('pruned', 0)} pruned "
              f"without executing, {c.get('measured', 0)} measured"
            + (f" | rank corr {corr:.3f}" if corr is not None else ""))
        lines.append(f"  {'knob':<18} {'default':>10} {'winner':>10}")
        for k in s["knobs"]:
            mark = "  <-- tuned" if k["changed"] else ""
            lines.append(f"  {k['knob']:<18} {str(k['default']):>10} "
                         f"{str(k['winner']):>10}{mark}")
    if cache_rows is not None:
        lines.append("\ntuning cache entries:")
        if not cache_rows:
            lines.append("  (empty)")
        for row in cache_rows:
            flag = (f"  <-- STALE (live v{row['live_version']})"
                    if row["stale"] else "")
            lines.append(f"  {row['key']:<40} v{row['space_version']} "
                         f"{json.dumps(row['config'], sort_keys=True)}"
                         f"{flag}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dir", nargs="?", default="tuning_out",
                    help="decisions directory (default: tuning_out)")
    ap.add_argument("--cache", default=None, metavar="PATH",
                    help="also list this tuning cache's entries with "
                         "staleness verdicts")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    report = build_report(load_decisions(args.dir))
    cache_rows = None
    if args.cache is not None:
        cache_rows = load_cache_entries(args.cache)
        report["cache_entries"] = cache_rows
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print(render_text(report, cache_rows))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
