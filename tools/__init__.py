"""Repo tooling (lint gate, benchmarks helpers, reports).

A real package so ``[project.scripts]`` entries (graftlint) can resolve
``tools.lint_gate:main`` from an installed wheel as well as a checkout.
"""
