#!/usr/bin/env python
"""Render a watchtower directory (alert transitions + metrics history)
into an alert/history timeline.

Usage:
    python tools/alert_report.py WATCH_DIR [--json] [--window-s N]
    python tools/alert_report.py --alerts-log F [--history F2] [--json]

``WATCH_DIR`` is what ``telemetry.alerts.arm_watchtower(out_dir=...)``
(or ``ElasticMaster(watch=True, watch_dir=...)``, or the elastic worker
CLI's ``--watch-dir``) wrote: ``alerts_<process>.jsonl`` transition logs
plus ``history_<process>.jsonl`` write-ahead spill files. Both are
crash-readable — a killed process leaves every completed line — so this
report reconstructs what the watch layer saw right up to the death.

Output:

- the **alert timeline**: every state transition in wall-clock order
  (rule, from→to, measured value, severity) across every process;
- the **final verdict table**: each rule's last-known state per process;
- a **history digest** per process: for every metric a firing/resolved
  rule referenced, first→last / min / max over the spill (replayed
  through the REAL telemetry.history query code, so the report can never
  disagree with what the live engine computed).

``--json`` emits the raw structure (CI-friendly). Exit codes: 2 when
inputs are missing, 3 when they hold no records.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning4j_tpu.telemetry.alerts import SCHEMA  # noqa: E402
from deeplearning4j_tpu.telemetry.history import replay_spill  # noqa: E402


def read_alert_log(path: str) -> List[Dict]:
    """Parse one transitions JSONL (tolerant of a torn tail line — the
    writer may have died mid-transition; everything earlier is complete
    by the line-buffered write contract)."""
    out: List[Dict] = []
    with open(path) as fh:
        lines = fh.read().splitlines()
    for lineno, line in enumerate(lines, start=1):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as exc:
            if lineno == len(lines):
                break
            raise ValueError(f"alert log {path} is corrupt at line "
                             f"{lineno}: {exc}") from exc
        if isinstance(rec, dict) and rec.get("schema") == SCHEMA:
            out.append(rec)
    return out


def _process_of(path: str, prefix: str) -> str:
    base = os.path.basename(path)
    return base[len(prefix):-len(".jsonl")] if base.startswith(prefix) \
        else base


def collect(watch_dir: Optional[str] = None,
            alerts_logs: Optional[List[str]] = None,
            history_spills: Optional[List[str]] = None,
            window_s: Optional[float] = None) -> Dict:
    """The report structure: timeline + per-rule last states + history
    digests (module docstring)."""
    alerts_logs = list(alerts_logs or [])
    history_spills = list(history_spills or [])
    if watch_dir:
        alerts_logs += sorted(glob.glob(
            os.path.join(watch_dir, "alerts_*.jsonl")))
        history_spills += sorted(glob.glob(
            os.path.join(watch_dir, "history_*.jsonl")))
    timeline: List[Dict] = []
    for path in alerts_logs:
        process = _process_of(path, "alerts_")
        for rec in read_alert_log(path):
            timeline.append(dict(rec, process=process))
    timeline.sort(key=lambda r: r.get("ts", 0.0))
    if window_s is not None and timeline:
        cut = timeline[-1]["ts"] - float(window_s)
        timeline = [r for r in timeline if r.get("ts", 0.0) >= cut]
    # final verdicts: last transition per (process, rule)
    last: Dict[tuple, Dict] = {}
    for rec in timeline:
        last[(rec["process"], rec["rule"])] = rec
    verdicts = [{"process": p, "rule": r, "state": rec["to"],
                 "severity": rec.get("severity"),
                 "value": rec.get("value"), "ts": rec.get("ts")}
                for (p, r), rec in sorted(last.items())]
    histories = []
    for path in history_spills:
        process = _process_of(path, "history_")
        try:
            hist = replay_spill(path)
        except ValueError as exc:
            histories.append({"process": process, "error": str(exc)})
            continue
        digest = []
        for row in hist.series_index():
            if row["kind"] == "histogram":
                digest.append({"name": row["name"], "kind": "histogram",
                               "labels": row["labels"],
                               "observations": row["last_value"],
                               "points": row["points"]})
                continue
            pts = hist.points(row["name"], row["labels"] or None,
                              now=row["last_ts"])
            vals = [v for _, v in pts]
            digest.append({
                "name": row["name"], "kind": row["kind"],
                "labels": row["labels"], "points": len(pts),
                "first": vals[0] if vals else None,
                "last": vals[-1] if vals else None,
                "min": min(vals) if vals else None,
                "max": max(vals) if vals else None,
            })
        histories.append({"process": process, "samples": hist._samples,
                          "series": digest})
    return {"schema": SCHEMA, "ts": time.time(),
            "transitions": timeline, "verdicts": verdicts,
            "histories": histories,
            "n_alert_logs": len(alerts_logs),
            "n_history_spills": len(history_spills)}


def render_text(report: Dict, source: str) -> str:
    lines = [f"alert report — {source}",
             f"{len(report['transitions'])} transition(s), "
             f"{report['n_alert_logs']} alert log(s), "
             f"{report['n_history_spills']} history spill(s)"]
    if report["transitions"]:
        hdr = (f"{'when':<21}  {'process':<12}  {'rule':<28}  "
               f"{'transition':<20}  {'value':>12}  severity")
        lines += ["", hdr, "-" * len(hdr)]
        for rec in report["transitions"]:
            when = time.strftime("%Y-%m-%d %H:%M:%S",
                                 time.localtime(rec.get("ts", 0.0)))
            val = rec.get("value")
            val = f"{val:.4g}" if isinstance(val, (int, float)) else "-"
            lines.append(
                f"{when:<21}  {rec['process']:<12}  {rec['rule']:<28}  "
                f"{rec['from']+' -> '+rec['to']:<20}  {val:>12}  "
                f"{rec.get('severity', '-')}")
    if report["verdicts"]:
        lines += ["", "final verdicts (last transition per rule)"]
        for v in report["verdicts"]:
            flag = "!! " if v["state"] == "firing" else "   "
            lines.append(f"{flag}{v['process']}/{v['rule']}: {v['state']} "
                         f"({v['severity']})")
    for h in report["histories"]:
        if "error" in h:
            lines += ["", f"history [{h['process']}]: UNREADABLE — "
                      f"{h['error']}"]
            continue
        lines += ["", f"history [{h['process']}] — {h['samples']} "
                  f"sample(s)"]
        width = max((len(r["name"]) for r in h["series"]), default=4)
        for r in h["series"]:
            lbl = ("{" + ",".join(f"{k}={v}" for k, v in
                                  sorted(r["labels"].items())) + "}"
                   if r["labels"] else "")
            if r["kind"] == "histogram":
                lines.append(f"  {r['name']:<{width}}{lbl} "
                             f"histogram, {r['observations']:g} obs")
            else:
                lines.append(
                    f"  {r['name']:<{width}}{lbl} "
                    f"{r['first']:g} -> {r['last']:g} "
                    f"(min {r['min']:g}, max {r['max']:g})")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("watch_dir", nargs="?", default=None,
                    help="directory of alerts_*.jsonl / history_*.jsonl")
    ap.add_argument("--alerts-log", action="append", default=[],
                    help="explicit alert transitions JSONL (repeatable)")
    ap.add_argument("--history", action="append", default=[],
                    help="explicit history spill JSONL (repeatable)")
    ap.add_argument("--window-s", type=float, default=None,
                    help="keep only transitions within N seconds of the "
                         "latest one")
    ap.add_argument("--json", action="store_true",
                    help="emit the raw report structure")
    args = ap.parse_args(argv)
    if args.watch_dir and not os.path.isdir(args.watch_dir):
        print(f"no such watch dir: {args.watch_dir}", file=sys.stderr)
        return 2
    for path in list(args.alerts_log) + list(args.history):
        if not os.path.isfile(path):
            print(f"no such file: {path}", file=sys.stderr)
            return 2
    if not args.watch_dir and not args.alerts_log and not args.history:
        print("nothing to report: pass WATCH_DIR, --alerts-log, or "
              "--history", file=sys.stderr)
        return 2
    try:
        report = collect(args.watch_dir, args.alerts_log, args.history,
                         window_s=args.window_s)
    except ValueError as exc:
        print(str(exc), file=sys.stderr)
        return 3
    if (not report["transitions"] and not report["histories"]):
        print("no alert transitions or history samples found "
              "(was the watchtower armed with an out_dir?)",
              file=sys.stderr)
        return 3
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print(render_text(report,
                          args.watch_dir or "explicit files"))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
