#!/usr/bin/env python
"""Aggregate the per-round ``BENCH_r*.json`` artifacts into a per-stage
trajectory table with regression flagging.

Usage:
    python tools/bench_report.py [--dir REPO] [--json]
                                 [--threshold PCT] [--fail-on-regression]

Each round's driver snapshot is ``{n, cmd, rc, tail, parsed}`` where
``parsed`` is bench.py's summary line (``{metric, value, detail: {...}}``).
Some rounds have ``parsed: null`` (driver timeout, or a tail that
truncated the summary line — round 2's rc=124, round 5's clipped tail);
those are **recovered** where possible by regexing stage-metric keys out
of the tail fragment, and flagged ``partial`` rather than silently
dropped — a missing round must never read as "no regression".

The table shows one row per stage metric (``*_per_sec``, ``*_mfu``,
ratio keys), one column per round, plus the delta of the latest value vs
the previous round that has one. Deltas below ``-threshold`` (default
10%) are flagged as regressions; ``--fail-on-regression`` turns them into
exit code 1 for CI use. ``--json`` emits the raw structure.

ISSUE 9: rounds whose stage details embed compiled-step profile blobs
(telemetry/xprofile.py StepProfile dicts under ``<stage>_detail.profile``)
contribute ``<stage>_profile_peak_bytes`` / ``<stage>_profile_collective_
bytes`` / ``<stage>_profile_flops`` rows. Peak-memory and collective-byte
rows are LOWER-IS-BETTER: ``--fail-on-regression`` also trips when one of
them GROWS past the threshold — a PR fattening the compiled step's
footprint fails the gate before it ever runs on a chip.

ISSUE 10/12: stage details carrying a ``latency`` block (the serving
bench's ``serve_detail.latency`` — p50/p95/p99/mean milliseconds under
the open-loop traffic generator) contribute
``<stage>_latency_{p50,p95,p99,mean}_ms`` rows, also LOWER-IS-BETTER —
serving-latency growth past the threshold trips ``--fail-on-regression``
exactly like a throughput drop.

ISSUE 14: the ``comm_overlap_*`` step-time ratio rows (overlap_vs_strict,
2d_vs_flat, prefetch_vs_rotate_after — higher is better) track the
comm/compute-overlap A/Bs, and a stage detail's top-level
``collective_wire_bytes`` contributes the LOWER-IS-BETTER
``<stage>_collective_wire_bytes`` row so a PR growing the compiled step's
comm volume trips the regression gate both directions.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# stage metrics worth tracking round over round: rates, MFU, A/B ratios
# (peak_bytes_ratio: ISSUE 13's replicated/sharded optimizer footprint
# headline — HIGHER is better, a shrinking ratio means the ZeRO win
# eroded; the ISSUE 14 comm_overlap_* rows are the overlap/factorization
# step-time ratios — overlap_vs_strict, 2d_vs_flat,
# prefetch_vs_rotate_after — also higher-is-better)
_METRIC_RE = re.compile(
    r"_(?:per_sec|per_chip|mfu|vs_cpu|vs_single|vs_densecore|vs_baseline|"
    r"blocking_vs_background|overhead_pct|peak_bytes_ratio|"
    r"overlap_vs_strict|2d_vs_flat|prefetch_vs_rotate_after)$")
# metrics where an INCREASE is the regression (ISSUE 9 footprint rows,
# ISSUE 10 serving-latency rows, ISSUE 14 stage wire-byte rows)
_LOWER_IS_BETTER_RE = re.compile(
    r"_profile_(?:peak_bytes|collective_bytes)$"
    r"|_latency_(?:p50|p95|p99|mean)_ms$"
    r"|_collective_wire_bytes$")
# recovery regex for a truncated tail: top-level "key": number pairs
_TAIL_PAIR_RE = re.compile(
    r'"([a-z0-9_]+)":\s*(-?[0-9]+(?:\.[0-9]+)?(?:[eE][-+]?[0-9]+)?)')


def _is_metric_key(key: str) -> bool:
    return bool(_METRIC_RE.search(key))


def _recover_from_tail(tail: str) -> Dict[str, float]:
    """Best-effort stage metrics from a clipped output tail."""
    out: Dict[str, float] = {}
    for key, val in _TAIL_PAIR_RE.findall(tail or ""):
        if _is_metric_key(key):
            out[key] = float(val)  # last occurrence wins (closest to end)
    return out


def _profile_metrics(detail: Dict) -> Dict[str, float]:
    """Trackable numbers from the StepProfile blobs stage details embed:
    ``<stage>_detail.profile`` → ``<stage>_profile_{peak_bytes,
    collective_bytes,flops}`` (absent blobs contribute nothing — an old
    round must never read as 'footprint went to zero')."""
    out: Dict[str, float] = {}
    for key, val in detail.items():
        if not key.endswith("_detail") or not isinstance(val, dict):
            continue
        prof = val.get("profile")
        if not isinstance(prof, dict):
            continue
        stage = key[: -len("_detail")]
        for metric, src in (("profile_peak_bytes", "peak_bytes"),
                            ("profile_collective_bytes",
                             "collective_wire_bytes"),
                            ("profile_flops", "flops")):
            v = prof.get(src)
            if isinstance(v, (int, float)):
                out[f"{stage}_{metric}"] = float(v)
    return out


def _wire_metrics(detail: Dict) -> Dict[str, float]:
    """Stage-level collective wire bytes (ISSUE 14): a stage detail
    carrying a top-level ``collective_wire_bytes`` number (the
    comm_overlap stage's tracked 2D-dispatch wire total) contributes the
    ``<stage>_collective_wire_bytes`` row — LOWER-IS-BETTER, so comm
    growth past the threshold trips ``--fail-on-regression`` exactly like
    a footprint regression."""
    out: Dict[str, float] = {}
    for key, val in detail.items():
        if not key.endswith("_detail") or not isinstance(val, dict):
            continue
        wire = val.get("collective_wire_bytes")
        if isinstance(wire, (int, float)):
            stage = key[: -len("_detail")]
            out[f"{stage}_collective_wire_bytes"] = float(wire)
    return out


def _latency_metrics(detail: Dict) -> Dict[str, float]:
    """Serving-latency rows from stage details carrying a ``latency``
    block (ISSUE 10; p99 added by ISSUE 12 — the tail the SLO is written
    against): ``<stage>_detail.latency.{p50_ms,p95_ms,p99_ms,mean_ms}``
    → ``<stage>_latency_{p50,p95,p99,mean}_ms`` — tracked
    LOWER-IS-BETTER."""
    out: Dict[str, float] = {}
    for key, val in detail.items():
        if not key.endswith("_detail") or not isinstance(val, dict):
            continue
        lat = val.get("latency")
        if not isinstance(lat, dict):
            continue
        stage = key[: -len("_detail")]
        for src, metric in (("p50_ms", "latency_p50_ms"),
                            ("p95_ms", "latency_p95_ms"),
                            ("p99_ms", "latency_p99_ms"),
                            ("mean_ms", "latency_mean_ms")):
            v = lat.get(src)
            if isinstance(v, (int, float)):
                out[f"{stage}_{metric}"] = float(v)
    return out


def _goodput_metrics(detail: Dict) -> Dict[str, float]:
    """Goodput-under-SLO rows (ISSUE 15 satellite): a stage detail
    carrying a ``goodput`` block (the serving bench's open-loop run with
    ``slo_ms`` set) contributes ``<stage>_goodput_rps`` — requests that
    completed WITHIN the SLO per second, tracked HIGHER-IS-BETTER (the
    default regression direction), the metric the fleet bench gates on:
    raw tokens/s can grow while the SLO-violating tail grows faster,
    goodput cannot."""
    out: Dict[str, float] = {}
    for key, val in detail.items():
        if not key.endswith("_detail") or not isinstance(val, dict):
            continue
        gp = val.get("goodput")
        if not isinstance(gp, dict):
            continue
        v = gp.get("goodput_rps")
        if isinstance(v, (int, float)):
            out[f"{key[: -len('_detail')]}_goodput_rps"] = float(v)
    return out


def load_rounds(bench_dir: str) -> List[Dict]:
    """One record per BENCH_r*.json: {round, source, metrics, headline}."""
    rounds = []
    for path in sorted(glob.glob(os.path.join(bench_dir, "BENCH_r*.json"))):
        m = re.search(r"BENCH_r(\d+)\.json$", os.path.basename(path))
        if not m:
            continue
        try:
            with open(path) as fh:
                rec = json.load(fh)
        except (OSError, json.JSONDecodeError) as exc:
            rounds.append({"round": int(m.group(1)), "source": "unreadable",
                           "error": str(exc), "metrics": {},
                           "headline": None})
            continue
        parsed = rec.get("parsed")
        if isinstance(parsed, dict):
            detail = parsed.get("detail") or {}
            metrics = {k: float(v) for k, v in detail.items()
                       if _is_metric_key(k) and isinstance(v, (int, float))}
            metrics.update(_profile_metrics(detail))
            metrics.update(_latency_metrics(detail))
            metrics.update(_wire_metrics(detail))
            metrics.update(_goodput_metrics(detail))
            rounds.append({"round": int(m.group(1)), "source": "parsed",
                           "metrics": metrics,
                           "headline": parsed.get("value")})
        else:
            metrics = _recover_from_tail(rec.get("tail", ""))
            rounds.append({"round": int(m.group(1)), "source": "partial",
                           "metrics": metrics,
                           "headline": metrics.get("value")})
    rounds.sort(key=lambda r: r["round"])
    return rounds


def build_trajectory(rounds: List[Dict], threshold_pct: float = 10.0
                     ) -> Dict:
    """Per-metric series across rounds + latest-vs-previous deltas."""
    keys = sorted({k for r in rounds for k in r["metrics"]})
    table = []
    regressions = []
    for key in keys:
        series = [(r["round"], r["metrics"].get(key)) for r in rounds]
        present = [(n, v) for n, v in series if v is not None]
        delta_pct: Optional[float] = None
        if len(present) >= 2:
            (prev_n, prev), (last_n, last) = present[-2], present[-1]
            if prev:
                delta_pct = round((last - prev) / abs(prev) * 100.0, 2)
        lower_better = bool(_LOWER_IS_BETTER_RE.search(key))
        regressed = (delta_pct is not None
                     and (delta_pct > threshold_pct if lower_better
                          else delta_pct < -threshold_pct))
        row = {"metric": key, "series": series, "delta_pct": delta_pct,
               "lower_is_better": lower_better, "regression": regressed}
        if row["regression"]:
            regressions.append({"metric": key, "delta_pct": delta_pct,
                                "lower_is_better": lower_better,
                                "from_round": present[-2][0],
                                "to_round": present[-1][0]})
        table.append(row)
    return {
        "rounds": [{"round": r["round"], "source": r["source"],
                    "headline": r["headline"],
                    "n_metrics": len(r["metrics"])} for r in rounds],
        "headline_series": [(r["round"], r["headline"]) for r in rounds],
        "threshold_pct": threshold_pct,
        "table": table,
        "regressions": regressions,
    }


def render_text(traj: Dict) -> str:
    round_ids = [r["round"] for r in traj["rounds"]]
    lines = ["bench trajectory — rounds " +
             ", ".join(f"r{r['round']}({r['source']})"
                       for r in traj["rounds"])]
    head = ", ".join(f"r{n}={v}" if v is not None else f"r{n}=?"
                     for n, v in traj["headline_series"])
    lines.append(f"headline (mnist mlp samples/s/chip): {head}")
    if not traj["table"]:
        return "\n".join(lines + ["no stage metrics found"])
    width = max(len(row["metric"]) for row in traj["table"])
    cols = "  ".join(f"{('r%d' % n):>10}" for n in round_ids)
    lines += ["", f"{'metric':<{width}}  {cols}  {'Δ last %':>9}  flag"]
    for row in traj["table"]:
        vals = {n: v for n, v in row["series"]}
        cells = "  ".join(
            f"{vals[n]:>10.1f}" if vals.get(n) is not None else f"{'-':>10}"
            for n in round_ids)
        delta = (f"{row['delta_pct']:>+9.1f}"
                 if row["delta_pct"] is not None else f"{'-':>9}")
        flag = "REGRESSION" if row["regression"] else ""
        lines.append(f"{row['metric']:<{width}}  {cells}  {delta}  {flag}")
    if traj["regressions"]:
        lines += ["", f"{len(traj['regressions'])} regression(s) past "
                  f"±{traj['threshold_pct']}% vs previous round:"]
        lines += [f"  {r['metric']}: {r['delta_pct']}% "
                  f"(r{r['from_round']} -> r{r['to_round']})"
                  + (" [lower is better — footprint grew]"
                     if r.get("lower_is_better") else "")
                  for r in traj["regressions"]]
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=REPO_ROOT,
                    help="directory holding BENCH_r*.json (default: repo)")
    ap.add_argument("--json", action="store_true",
                    help="emit the trajectory as JSON")
    ap.add_argument("--threshold", type=float, default=10.0,
                    help="flag deltas below -PCT%% as regressions")
    ap.add_argument("--fail-on-regression", action="store_true",
                    help="exit 1 when any metric regressed past threshold")
    args = ap.parse_args(argv)
    rounds = load_rounds(args.dir)
    if not rounds:
        print(f"no BENCH_r*.json files under {args.dir}", file=sys.stderr)
        return 2
    traj = build_trajectory(rounds, threshold_pct=args.threshold)
    if args.json:
        print(json.dumps(traj, indent=1))
    else:
        print(render_text(traj))
    if args.fail_on_regression and traj["regressions"]:
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
