#!/usr/bin/env python
"""Aggregate the per-round ``BENCH_r*.json`` artifacts into a per-stage
trajectory table with regression flagging.

Usage:
    python tools/bench_report.py [--dir REPO] [--json]
                                 [--threshold PCT] [--fail-on-regression]

Each round's driver snapshot is ``{n, cmd, rc, tail, parsed}`` where
``parsed`` is bench.py's summary line (``{metric, value, detail: {...}}``).
Some rounds have ``parsed: null`` (driver timeout, or a tail that
truncated the summary line — round 2's rc=124, round 5's clipped tail);
those are **recovered** where possible by regexing stage-metric keys out
of the tail fragment, and flagged ``partial`` rather than silently
dropped — a missing round must never read as "no regression".

The table shows one row per stage metric (``*_per_sec``, ``*_mfu``,
ratio keys), one column per round, plus the delta of the latest value vs
the previous round that has one. Deltas below ``-threshold`` (default
10%) are flagged as regressions; ``--fail-on-regression`` turns them into
exit code 1 for CI use. ``--json`` emits the raw structure.

ISSUE 9: rounds whose stage details embed compiled-step profile blobs
(telemetry/xprofile.py StepProfile dicts under ``<stage>_detail.profile``)
contribute ``<stage>_profile_peak_bytes`` / ``<stage>_profile_collective_
bytes`` / ``<stage>_profile_flops`` rows. Peak-memory and collective-byte
rows are LOWER-IS-BETTER: ``--fail-on-regression`` also trips when one of
them GROWS past the threshold — a PR fattening the compiled step's
footprint fails the gate before it ever runs on a chip.

ISSUE 10/12: stage details carrying a ``latency`` block (the serving
bench's ``serve_detail.latency`` — p50/p95/p99/mean milliseconds under
the open-loop traffic generator) contribute
``<stage>_latency_{p50,p95,p99,mean}_ms`` rows, also LOWER-IS-BETTER —
serving-latency growth past the threshold trips ``--fail-on-regression``
exactly like a throughput drop.

ISSUE 14: the ``comm_overlap_*`` step-time ratio rows (overlap_vs_strict,
2d_vs_flat, prefetch_vs_rotate_after — higher is better) track the
comm/compute-overlap A/Bs, and a stage detail's top-level
``collective_wire_bytes`` contributes the LOWER-IS-BETTER
``<stage>_collective_wire_bytes`` row so a PR growing the compiled step's
comm volume trips the regression gate both directions.

ISSUE 16: (1) stage details carrying a ``fast_path`` block (the serving
bench's prefix/speculative/chunked A/B twins) contribute
``<stage>_fastpath_*`` rows — the on/off throughput ratios,
cache_hit_rate and accepted_per_verify HIGHER-IS-BETTER, the inter-token
p99s LOWER-IS-BETTER. (2) Bench-noise carry-over: rounds that ran the
fixed ``ref_micro`` reference stage (a jitted loop that never changes,
so its rate measures the machine, not the code) have every OTHER
metric's latest-vs-previous delta normalized by the reference's drift
factor ``f = ref_last/ref_prev`` when ``|f-1| <= 10%``; when the
reference itself drifted MORE than 10% between the two rounds, deltas
stay raw, regression-gating for that pair is SUPPRESSED (flag
``REF-NOISE``), and the pair is listed under ``ref_flags`` — a broken
reference must never silently normalize (or silently gate) anything.
Rounds without the reference row (pre-ISSUE-16) behave exactly as
before.

ISSUE 19: the fleet stage's ``latency``/``goodput`` blocks land through
the existing extractors as ``fleet_latency_*`` (LOWER) and
``fleet_goodput_rps`` (HIGHER); its ``requeue`` block contributes
``fleet_requeue_to_first_token_ms`` — how long a requeued client stream
stalls between its replica dying and its first post-requeue token —
tracked LOWER-IS-BETTER, so a recovery-latency regression (slower death
detection, slower cold start, slower re-prefill) trips
``--fail-on-regression`` like any latency row.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# stage metrics worth tracking round over round: rates, MFU, A/B ratios
# (peak_bytes_ratio: ISSUE 13's replicated/sharded optimizer footprint
# headline — HIGHER is better, a shrinking ratio means the ZeRO win
# eroded; the ISSUE 14 comm_overlap_* rows are the overlap/factorization
# step-time ratios — overlap_vs_strict, 2d_vs_flat,
# prefetch_vs_rotate_after — also higher-is-better)
_METRIC_RE = re.compile(
    r"_(?:per_sec|per_chip|mfu|vs_cpu|vs_single|vs_densecore|vs_baseline|"
    r"blocking_vs_background|overhead_pct|peak_bytes_ratio|"
    r"overlap_vs_strict|2d_vs_flat|prefetch_vs_rotate_after|"
    r"tuned_vs_default)$")
# metrics where an INCREASE is the regression (ISSUE 9 footprint rows,
# ISSUE 10 serving-latency rows, ISSUE 14 stage wire-byte rows, ISSUE 16
# inter-token-stream p99 rows)
_LOWER_IS_BETTER_RE = re.compile(
    r"_profile_(?:peak_bytes|collective_bytes)$"
    r"|_latency_(?:p50|p95|p99|mean)_ms$"
    r"|_collective_wire_bytes$"
    r"|_inter_token_p99_ms(?:_chunked|_unchunked)?$"
    r"|_requeue_to_first_token_ms$")

# ISSUE 16 bench-noise carry-over: the fixed reference micro-stage's row.
# Its drift between two rounds is machine noise by construction (the
# stage never changes), so it divides every other row's delta — unless it
# drifted past REF_STABILITY_PCT, in which case the pair is flagged and
# regression-gating suppressed instead of normalizing by a broken
# reference. The ref row itself is tracked but never normalized and never
# gates (a slower machine is not a code regression).
REF_METRIC = "ref_micro_samples_per_sec"
REF_STABILITY_PCT = 10.0
# recovery regex for a truncated tail: top-level "key": number pairs
_TAIL_PAIR_RE = re.compile(
    r'"([a-z0-9_]+)":\s*(-?[0-9]+(?:\.[0-9]+)?(?:[eE][-+]?[0-9]+)?)')


def _is_metric_key(key: str) -> bool:
    return bool(_METRIC_RE.search(key))


def _recover_from_tail(tail: str) -> Dict[str, float]:
    """Best-effort stage metrics from a clipped output tail."""
    out: Dict[str, float] = {}
    for key, val in _TAIL_PAIR_RE.findall(tail or ""):
        if _is_metric_key(key):
            out[key] = float(val)  # last occurrence wins (closest to end)
    return out


def _profile_metrics(detail: Dict) -> Dict[str, float]:
    """Trackable numbers from the StepProfile blobs stage details embed:
    ``<stage>_detail.profile`` → ``<stage>_profile_{peak_bytes,
    collective_bytes,flops}`` (absent blobs contribute nothing — an old
    round must never read as 'footprint went to zero')."""
    out: Dict[str, float] = {}
    for key, val in detail.items():
        if not key.endswith("_detail") or not isinstance(val, dict):
            continue
        prof = val.get("profile")
        if not isinstance(prof, dict):
            continue
        stage = key[: -len("_detail")]
        for metric, src in (("profile_peak_bytes", "peak_bytes"),
                            ("profile_collective_bytes",
                             "collective_wire_bytes"),
                            ("profile_flops", "flops")):
            v = prof.get(src)
            if isinstance(v, (int, float)):
                out[f"{stage}_{metric}"] = float(v)
    return out


def _wire_metrics(detail: Dict) -> Dict[str, float]:
    """Stage-level collective wire bytes (ISSUE 14): a stage detail
    carrying a top-level ``collective_wire_bytes`` number (the
    comm_overlap stage's tracked 2D-dispatch wire total) contributes the
    ``<stage>_collective_wire_bytes`` row — LOWER-IS-BETTER, so comm
    growth past the threshold trips ``--fail-on-regression`` exactly like
    a footprint regression."""
    out: Dict[str, float] = {}
    for key, val in detail.items():
        if not key.endswith("_detail") or not isinstance(val, dict):
            continue
        wire = val.get("collective_wire_bytes")
        if isinstance(wire, (int, float)):
            stage = key[: -len("_detail")]
            out[f"{stage}_collective_wire_bytes"] = float(wire)
    return out


def _latency_metrics(detail: Dict) -> Dict[str, float]:
    """Serving-latency rows from stage details carrying a ``latency``
    block (ISSUE 10; p99 added by ISSUE 12 — the tail the SLO is written
    against): ``<stage>_detail.latency.{p50_ms,p95_ms,p99_ms,mean_ms}``
    → ``<stage>_latency_{p50,p95,p99,mean}_ms`` — tracked
    LOWER-IS-BETTER."""
    out: Dict[str, float] = {}
    for key, val in detail.items():
        if not key.endswith("_detail") or not isinstance(val, dict):
            continue
        lat = val.get("latency")
        if not isinstance(lat, dict):
            continue
        stage = key[: -len("_detail")]
        for src, metric in (("p50_ms", "latency_p50_ms"),
                            ("p95_ms", "latency_p95_ms"),
                            ("p99_ms", "latency_p99_ms"),
                            ("mean_ms", "latency_mean_ms")):
            v = lat.get(src)
            if isinstance(v, (int, float)):
                out[f"{stage}_{metric}"] = float(v)
    return out


def _goodput_metrics(detail: Dict) -> Dict[str, float]:
    """Goodput-under-SLO rows (ISSUE 15 satellite): a stage detail
    carrying a ``goodput`` block (the serving bench's open-loop run with
    ``slo_ms`` set) contributes ``<stage>_goodput_rps`` — requests that
    completed WITHIN the SLO per second, tracked HIGHER-IS-BETTER (the
    default regression direction), the metric the fleet bench gates on:
    raw tokens/s can grow while the SLO-violating tail grows faster,
    goodput cannot."""
    out: Dict[str, float] = {}
    for key, val in detail.items():
        if not key.endswith("_detail") or not isinstance(val, dict):
            continue
        gp = val.get("goodput")
        if not isinstance(gp, dict):
            continue
        v = gp.get("goodput_rps")
        if isinstance(v, (int, float)):
            out[f"{key[: -len('_detail')]}_goodput_rps"] = float(v)
    return out


def _requeue_metrics(detail: Dict) -> Dict[str, float]:
    """Fleet recovery-latency row (ISSUE 19): a stage detail carrying a
    ``requeue`` block (the fleet bench's chaos phase) contributes
    ``<stage>_requeue_to_first_token_ms`` — the mean gap between a
    replica death requeueing a request and that request's first token
    from its replacement dispatch, tracked LOWER-IS-BETTER."""
    out: Dict[str, float] = {}
    for key, val in detail.items():
        if not key.endswith("_detail") or not isinstance(val, dict):
            continue
        rq = val.get("requeue")
        if not isinstance(rq, dict):
            continue
        v = rq.get("requeue_to_first_token_ms")
        if isinstance(v, (int, float)):
            out[f"{key[: -len('_detail')]}_requeue_to_first_token_ms"] = \
                float(v)
    return out


def _fastpath_metrics(detail: Dict) -> Dict[str, float]:
    """Serve fast-path twin rows (ISSUE 16): a stage detail carrying a
    ``fast_path`` block (the serving bench's prefix/spec/chunked A/Bs)
    contributes ``<stage>_fastpath_*`` rows. The on/off ratios,
    cache_hit_rate and accepted_per_verify are HIGHER-IS-BETTER (the
    default direction); the inter-token p99s match the LOWER-IS-BETTER
    regex, so a chunk-scheduling change that re-introduces the long-
    prompt stream stall trips ``--fail-on-regression``."""
    tracked = ("prefix_on_vs_off", "spec_on_vs_off", "chunk_vs_unchunked",
               "cache_hit_rate", "accepted_per_verify",
               "inter_token_p99_ms_chunked", "inter_token_p99_ms_unchunked")
    out: Dict[str, float] = {}
    for key, val in detail.items():
        if not key.endswith("_detail") or not isinstance(val, dict):
            continue
        fp = val.get("fast_path")
        if not isinstance(fp, dict):
            continue
        stage = key[: -len("_detail")]
        for src in tracked:
            v = fp.get(src)
            if isinstance(v, (int, float)):
                out[f"{stage}_fastpath_{src}"] = float(v)
    return out


def load_rounds(bench_dir: str) -> List[Dict]:
    """One record per BENCH_r*.json: {round, source, metrics, headline}."""
    rounds = []
    for path in sorted(glob.glob(os.path.join(bench_dir, "BENCH_r*.json"))):
        m = re.search(r"BENCH_r(\d+)\.json$", os.path.basename(path))
        if not m:
            continue
        try:
            with open(path) as fh:
                rec = json.load(fh)
        except (OSError, json.JSONDecodeError) as exc:
            rounds.append({"round": int(m.group(1)), "source": "unreadable",
                           "error": str(exc), "metrics": {},
                           "headline": None})
            continue
        parsed = rec.get("parsed")
        if isinstance(parsed, dict):
            detail = parsed.get("detail") or {}
            metrics = {k: float(v) for k, v in detail.items()
                       if _is_metric_key(k) and isinstance(v, (int, float))}
            metrics.update(_profile_metrics(detail))
            metrics.update(_latency_metrics(detail))
            metrics.update(_wire_metrics(detail))
            metrics.update(_goodput_metrics(detail))
            metrics.update(_requeue_metrics(detail))
            metrics.update(_fastpath_metrics(detail))
            rounds.append({"round": int(m.group(1)), "source": "parsed",
                           "metrics": metrics,
                           "headline": parsed.get("value")})
        else:
            metrics = _recover_from_tail(rec.get("tail", ""))
            rounds.append({"round": int(m.group(1)), "source": "partial",
                           "metrics": metrics,
                           "headline": metrics.get("value")})
    rounds.sort(key=lambda r: r["round"])
    return rounds


def build_trajectory(rounds: List[Dict], threshold_pct: float = 10.0
                     ) -> Dict:
    """Per-metric series across rounds + latest-vs-previous deltas.

    ISSUE 16 noise carry-over: when BOTH rounds of a metric's delta pair
    ran the fixed reference stage (:data:`REF_METRIC`), the delta is
    computed on ``last / f`` where ``f = ref_last / ref_prev`` — machine
    drift divides out. A reference drift past
    :data:`REF_STABILITY_PCT` instead flags the pair (``ref_flags``)
    and suppresses regression-gating for it: deltas stay raw and rows
    that would have gated carry ``suppressed_by_ref``. Pairs where
    either round lacks the reference row behave exactly as before."""
    keys = sorted({k for r in rounds for k in r["metrics"]})
    ref_series = {r["round"]: r["metrics"].get(REF_METRIC) for r in rounds}
    table = []
    regressions = []
    ref_flag_pairs: Dict[tuple, float] = {}
    for key in keys:
        series = [(r["round"], r["metrics"].get(key)) for r in rounds]
        present = [(n, v) for n, v in series if v is not None]
        delta_pct: Optional[float] = None
        ref_factor: Optional[float] = None
        ref_unstable = False
        if len(present) >= 2:
            (prev_n, prev), (last_n, last) = present[-2], present[-1]
            if prev:
                ref_prev = ref_series.get(prev_n)
                ref_last = ref_series.get(last_n)
                if key != REF_METRIC and ref_prev and ref_last:
                    f = ref_last / ref_prev
                    if abs(f - 1.0) <= REF_STABILITY_PCT / 100.0:
                        ref_factor = round(f, 4)
                        last = last / f  # divide the machine drift out
                    else:
                        ref_unstable = True
                        ref_flag_pairs[(prev_n, last_n)] = round(f, 4)
                delta_pct = round((last - prev) / abs(prev) * 100.0, 2)
        lower_better = bool(_LOWER_IS_BETTER_RE.search(key))
        would_regress = (delta_pct is not None and key != REF_METRIC
                         and (delta_pct > threshold_pct if lower_better
                              else delta_pct < -threshold_pct))
        regressed = would_regress and not ref_unstable
        row = {"metric": key, "series": series, "delta_pct": delta_pct,
               "lower_is_better": lower_better, "regression": regressed,
               "ref_factor": ref_factor,
               "suppressed_by_ref": would_regress and ref_unstable}
        if row["regression"]:
            regressions.append({"metric": key, "delta_pct": delta_pct,
                                "lower_is_better": lower_better,
                                "from_round": present[-2][0],
                                "to_round": present[-1][0]})
        table.append(row)
    return {
        "rounds": [{"round": r["round"], "source": r["source"],
                    "headline": r["headline"],
                    "n_metrics": len(r["metrics"])} for r in rounds],
        "headline_series": [(r["round"], r["headline"]) for r in rounds],
        "threshold_pct": threshold_pct,
        "table": table,
        "regressions": regressions,
        "ref_metric": REF_METRIC,
        "ref_flags": [{"from_round": a, "to_round": b, "ref_factor": f}
                      for (a, b), f in sorted(ref_flag_pairs.items())],
    }


def render_text(traj: Dict) -> str:
    round_ids = [r["round"] for r in traj["rounds"]]
    lines = ["bench trajectory — rounds " +
             ", ".join(f"r{r['round']}({r['source']})"
                       for r in traj["rounds"])]
    head = ", ".join(f"r{n}={v}" if v is not None else f"r{n}=?"
                     for n, v in traj["headline_series"])
    lines.append(f"headline (mnist mlp samples/s/chip): {head}")
    if not traj["table"]:
        return "\n".join(lines + ["no stage metrics found"])
    width = max(len(row["metric"]) for row in traj["table"])
    cols = "  ".join(f"{('r%d' % n):>10}" for n in round_ids)
    lines += ["", f"{'metric':<{width}}  {cols}  {'Δ last %':>9}  flag"]
    for row in traj["table"]:
        vals = {n: v for n, v in row["series"]}
        cells = "  ".join(
            f"{vals[n]:>10.1f}" if vals.get(n) is not None else f"{'-':>10}"
            for n in round_ids)
        delta = (f"{row['delta_pct']:>+9.1f}"
                 if row["delta_pct"] is not None else f"{'-':>9}")
        if row["regression"]:
            flag = "REGRESSION"
        elif row.get("suppressed_by_ref"):
            flag = "REF-NOISE"
        elif row.get("ref_factor") is not None:
            flag = f"ref f={row['ref_factor']:.3f}"
        else:
            flag = ""
        lines.append(f"{row['metric']:<{width}}  {cells}  {delta}  {flag}")
    if traj.get("ref_flags"):
        lines += ["", "reference stage drifted past the stability window "
                  "— deltas raw, gating suppressed for:"]
        lines += [f"  r{f['from_round']} -> r{f['to_round']}: "
                  f"{traj['ref_metric']} moved x{f['ref_factor']}"
                  for f in traj["ref_flags"]]
    if traj["regressions"]:
        lines += ["", f"{len(traj['regressions'])} regression(s) past "
                  f"±{traj['threshold_pct']}% vs previous round:"]
        lines += [f"  {r['metric']}: {r['delta_pct']}% "
                  f"(r{r['from_round']} -> r{r['to_round']})"
                  + (" [lower is better — footprint grew]"
                     if r.get("lower_is_better") else "")
                  for r in traj["regressions"]]
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=REPO_ROOT,
                    help="directory holding BENCH_r*.json (default: repo)")
    ap.add_argument("--json", action="store_true",
                    help="emit the trajectory as JSON")
    ap.add_argument("--threshold", type=float, default=10.0,
                    help="flag deltas below -PCT%% as regressions")
    ap.add_argument("--fail-on-regression", action="store_true",
                    help="exit 1 when any metric regressed past threshold")
    args = ap.parse_args(argv)
    rounds = load_rounds(args.dir)
    if not rounds:
        print(f"no BENCH_r*.json files under {args.dir}", file=sys.stderr)
        return 2
    traj = build_trajectory(rounds, threshold_pct=args.threshold)
    if args.json:
        print(json.dumps(traj, indent=1))
    else:
        print(render_text(traj))
    if args.fail_on_regression and traj["regressions"]:
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
