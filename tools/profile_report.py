#!/usr/bin/env python
"""Render the compiled-step profile blobs bench rounds embed into a
per-stage roofline/attribution report (ISSUE 9).

Usage:
    python tools/profile_report.py [--dir REPO] [--json] [--round N]
                                   [--runtime PATH] [--tuning DIR]

Data source: the ``BENCH_r*.json`` driver artifacts (same files
tools/bench_report.py reads). Since ISSUE 9 the ``lm_composed`` stage and
the dedicated ``profile`` stage embed a ``profile`` blob in their stage
detail — the :class:`~deeplearning4j_tpu.telemetry.xprofile.StepProfile`
dict (XLA cost/memory analysis + HLO collective inventory) plus the
analytic-vs-XLA FLOPs cross-check and the measured-MFU attribution. This
tool renders, for the selected round (default: latest with blobs):

- a per-stage **roofline table**: XLA FLOPs, bytes accessed, arithmetic
  intensity, peak/temp bytes, collective wire bytes, donated args,
  compile seconds, and the attribution block when the stage embedded one
  (measured MFU, HBM utilization, comm fraction, bound);
- the **analytic-vs-XLA FLOPs cross-check** per stage (the hand-table
  honesty signal — tier-1 pins the same ratio at test shapes);
- **cross-round deltas** of FLOPs / peak bytes / collective wire bytes
  per stage — the cheap way to see a PR quietly fattening the compiled
  step before it ever runs on a chip;
- a **per-collective delta table** (ISSUE 14: op kind × count × payload
  bytes × wire bytes × replica-group sizes, prev round → last) so a
  factorization's per-op shape change — one flat all-to-all becoming two
  smaller-group definitions — shows up in the trajectory, not just the
  aggregate wire total.

``--runtime PATH`` (ISSUE 17) adds a **runtime sessions** section next
to the AOT roofline: PATH is a runprof session dump (``.json`` final or
``.jsonl`` write-ahead of a killed session) or a directory of them.
Each session renders its measured phase breakdown (host / dispatch /
device / comm-wait / input-wait means, wall p50/p95), steps/s, and
measured MFU; a reconstructed partial dump is flagged ``PARTIAL`` with
its torn-line count — the measured half beside the modeled half, so
"the model says compute-bound" and "the run spent 40% in host" sit in
one report.

``--tuning DIR`` (ISSUE 20) renders the autotuner's pruning decisions:
DIR is a decisions directory written by ``python -m
deeplearning4j_tpu.tune --out DIR`` (one ``tuning_<seam>.json`` per
searched seam). For every candidate the section shows its validity
verdict, roofline position (implied compute/memory/comm seconds, the
binding resource, peak/wire bytes), and — when it was pruned — WHICH
config dominated it and on which cost components, so "why did my config
never execute" is answerable after the fact. Winners and measured
ratios ride along; ``tools/tune_report.py`` renders the summary tables.

Exit code 0 with "no profile blobs" when the rounds predate ISSUE 9 —
missing data is reported, never invented.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_DETAIL_KEY_RE = re.compile(r"^(.*)_detail$")


def load_profile_rounds(bench_dir: str) -> List[Dict]:
    """[{round, stages: {stage: {profile, attribution?, crosscheck?}}}]
    for every BENCH_r*.json whose parsed detail embeds profile blobs."""
    rounds = []
    for path in sorted(glob.glob(os.path.join(bench_dir, "BENCH_r*.json"))):
        m = re.search(r"BENCH_r(\d+)\.json$", os.path.basename(path))
        if not m:
            continue
        try:
            with open(path) as fh:
                rec = json.load(fh)
        except (OSError, json.JSONDecodeError):
            continue
        parsed = rec.get("parsed")
        if not isinstance(parsed, dict):
            continue
        detail = parsed.get("detail") or {}
        stages: Dict[str, Dict] = {}
        for key, val in detail.items():
            dm = _DETAIL_KEY_RE.match(key)
            if not dm or not isinstance(val, dict):
                continue
            prof = val.get("profile")
            if not isinstance(prof, dict):
                continue
            stages[dm.group(1)] = {
                "profile": prof,
                "attribution": (val.get("profile_attribution")
                                or val.get("attribution")),
                "xla_vs_analytic": (prof.get("xla_vs_analytic_flops")
                                    or val.get("xla_vs_analytic_flops")),
                "analytic_flops": (prof.get("analytic_train_flops")
                                   or val.get("analytic_train_flops")),
            }
        if stages:
            rounds.append({"round": int(m.group(1)), "stages": stages})
    rounds.sort(key=lambda r: r["round"])
    return rounds


def _fmt_bytes(n: Optional[float]) -> str:
    if n is None:
        return "-"
    for unit, div in (("GB", 1e9), ("MB", 1e6), ("kB", 1e3)):
        if abs(n) >= div:
            return f"{n / div:.1f}{unit}"
    return f"{n:.0f}B"


def _fmt_flops(n: Optional[float]) -> str:
    if n is None:
        return "-"
    for unit, div in (("TF", 1e12), ("GF", 1e9), ("MF", 1e6), ("kF", 1e3)):
        if abs(n) >= div:
            return f"{n / div:.2f}{unit}"
    return f"{n:.0f}F"


def build_report(rounds: List[Dict],
                 round_id: Optional[int] = None) -> Dict:
    """The selected round's roofline rows + cross-round deltas."""
    if not rounds:
        return {"rounds": [], "selected": None, "stages": [], "deltas": []}
    sel = rounds[-1]
    if round_id is not None:
        matches = [r for r in rounds if r["round"] == round_id]
        if not matches:
            raise ValueError(
                f"round {round_id} has no profile blobs; rounds with "
                f"blobs: {[r['round'] for r in rounds]}")
        sel = matches[0]

    stages = []
    for stage in sorted(sel["stages"]):
        entry = sel["stages"][stage]
        prof = entry["profile"]
        flops = prof.get("flops")
        bytes_acc = prof.get("bytes_accessed")
        collectives = prof.get("collectives") or {}
        stages.append({
            "stage": stage,
            "platform": prof.get("platform"),
            "flops": flops,
            "bytes_accessed": bytes_acc,
            "arithmetic_intensity": (round(flops / bytes_acc, 2)
                                     if flops and bytes_acc else None),
            "peak_bytes": prof.get("peak_bytes"),
            "temp_bytes": prof.get("temp_bytes"),
            "collective_wire_bytes": prof.get("collective_wire_bytes"),
            "collective_counts": {k: v.get("count")
                                  for k, v in collectives.items()},
            "donated_args": prof.get("donated_args"),
            "compile_seconds": prof.get("compile_seconds"),
            "xla_vs_analytic_flops": entry["xla_vs_analytic"],
            "attribution": entry["attribution"],
        })

    tracked = ("flops", "peak_bytes", "collective_wire_bytes")
    deltas = []
    collective_deltas = []
    for stage in sorted(sel["stages"]):
        series = [(r["round"], r["stages"][stage]["profile"])
                  for r in rounds if stage in r["stages"]]
        if len(series) < 2:
            continue
        (prev_n, prev), (last_n, last) = series[-2], series[-1]
        row = {"stage": stage, "from_round": prev_n, "to_round": last_n}
        for key in tracked:
            a, b = prev.get(key), last.get(key)
            row[key] = {
                "prev": a, "last": b,
                "delta_pct": (round((b - a) / abs(a) * 100.0, 2)
                              if a and b is not None else None),
            }
        deltas.append(row)
        # ISSUE 14: per-collective (op kind × payload × wire) deltas so a
        # factorization's per-op shape change — e.g. one flat all-to-all
        # becoming two smaller-group definitions — is visible in the
        # trajectory, not just the aggregate wire total
        prev_c = prev.get("collectives") or {}
        last_c = last.get("collectives") or {}
        for kind in sorted(set(prev_c) | set(last_c)):
            a, b = prev_c.get(kind) or {}, last_c.get(kind) or {}
            crow = {"stage": stage, "kind": kind,
                    "from_round": prev_n, "to_round": last_n,
                    "group_sizes": {"prev": a.get("group_sizes"),
                                    "last": b.get("group_sizes")}}
            for key in ("count", "payload_bytes", "wire_bytes"):
                va, vb = a.get(key), b.get(key)
                crow[key] = {
                    "prev": va, "last": vb,
                    "delta_pct": (round((vb - va) / abs(va) * 100.0, 2)
                                  if va and vb is not None else None),
                }
            collective_deltas.append(crow)
    return {
        "rounds": [r["round"] for r in rounds],
        "selected": sel["round"],
        "stages": stages,
        "deltas": deltas,
        "collective_deltas": collective_deltas,
    }


def load_runtime_sessions(path: str) -> List[Dict]:
    """ISSUE 17: runprof session dumps for the ``--runtime`` section —
    a directory is scanned (finals preferred, killed sessions
    reconstructed from their JSONL write-ahead), a file loaded directly."""
    sys.path.insert(0, REPO_ROOT)
    from deeplearning4j_tpu.telemetry.runprof import (  # noqa: E402
        find_sessions,
        load_session,
    )

    if os.path.isdir(path):
        return find_sessions(path)
    return [load_session(path)]


def render_runtime_text(sessions: List[Dict]) -> str:
    if not sessions:
        return ("no runtime sessions found — capture one with "
                "POST /api/profiling or DL4J_TPU_RUNPROF=<N>")
    lines = ["", "runtime sessions (measured step phases):"]
    for sess in sessions:
        summ = sess.get("summary") or {}
        flags = ""
        if sess.get("partial"):
            flags = (f"  PARTIAL (reconstructed write-ahead, "
                     f"{sess.get('torn_lines', 0)} torn lines)")
        lines.append(f"  session {sess.get('session')}: "
                     f"{summ.get('steps', 0)} steps{flags}")
        if not summ.get("steps"):
            continue
        wall = summ.get("wall_ms") or {}
        lines.append(
            f"    wall {wall.get('mean', 0):.3f}ms mean / "
            f"{wall.get('p50', 0):.3f} p50 / {wall.get('p95', 0):.3f} p95"
            + (f", {summ['steps_per_s']:.1f} steps/s"
               if summ.get("steps_per_s") is not None else ""))
        lines.append(
            "    phases: " + ", ".join(
                f"{key[:-len('_ms_mean')]} {summ.get(key, 0):.3f}ms"
                for key in ("host_ms_mean", "dispatch_ms_mean",
                            "device_ms_mean", "comm_wait_ms_mean",
                            "input_wait_ms_mean")))
        bits = []
        if summ.get("host_fraction") is not None:
            bits.append(f"host frac {summ['host_fraction']:.4f}")
        if summ.get("input_wait_fraction") is not None:
            bits.append(f"input-wait frac "
                        f"{summ['input_wait_fraction']:.4f}")
        if summ.get("measured_mfu") is not None:
            bits.append(f"measured MFU {summ['measured_mfu']:.4f}")
        if bits:
            lines.append("    " + ", ".join(bits))
    return "\n".join(lines)


def load_tuning_decisions(path: str) -> List[Dict]:
    """ISSUE 20: the searcher's decisions files (``tuning_<seam>.json``)
    from a ``python -m deeplearning4j_tpu.tune --out`` directory (or one
    file given directly). Unreadable files are skipped; an empty list
    means "nothing to audit", reported downstream rather than invented."""
    paths = (sorted(glob.glob(os.path.join(path, "tuning_*.json")))
             if os.path.isdir(path) else [path])
    decisions = []
    for p in paths:
        try:
            with open(p) as fh:
                rec = json.load(fh)
        except (OSError, json.JSONDecodeError) as exc:
            print(f"skipping unreadable tuning file {p}: {exc}",
                  file=sys.stderr)
            continue
        if isinstance(rec, dict) and rec.get("candidates") is not None:
            decisions.append(rec)
    return decisions


def render_tuning_text(decisions: List[Dict]) -> str:
    """Candidates × (validity, roofline position, pruned-by reason)."""
    if not decisions:
        return ("no tuning decisions found — run "
                "python -m deeplearning4j_tpu.tune --out <dir> first")
    lines = ["", "autotuner pruning decisions (ISSUE 20):"]
    for rec in decisions:
        c = rec.get("counts") or {}
        lines.append(
            f"  seam {rec.get('seam')} (space v{rec.get('space_version')}): "
            f"{c.get('total', 0)} candidates, {c.get('invalid', 0)} "
            f"invalid, {c.get('pruned', 0)} pruned by dominance, "
            f"{c.get('measured', 0)} measured")
        lines.append(f"    {'config':<34} {'verdict':<10} {'bound':<8} "
                     f"{'pred(s)':>9} {'peak':>9} {'wire':>9}  why")
        for cand in rec.get("candidates") or []:
            cfg = json.dumps(cand.get("config"), sort_keys=True)
            cost = cand.get("cost") or {}
            pred = cand.get("predicted_seconds")
            pred_s = f"{pred:.3e}" if pred is not None else "-"
            if cand.get("invalid_reason"):
                verdict, why = "invalid", cand["invalid_reason"]
            elif cand.get("pruned_by") is not None:
                verdict = "pruned"
                why = (f"dominated by "
                       f"{json.dumps(cand['pruned_by'], sort_keys=True)}"
                       + (f" ({cand.get('pruned_reason')})"
                          if cand.get("pruned_reason") else ""))
            elif cand.get("winner"):
                r = cand.get("ratio_vs_default")
                verdict = "WINNER"
                why = (f"measured {r:.3f}x default"
                       if r is not None else "measured")
            elif cand.get("measured"):
                r = cand.get("ratio_vs_default")
                why = (f"measured {r:.3f}x default"
                       if r is not None else "measured")
                if cand.get("numerics_match") is False:
                    why += " — NUMERICS MISMATCH, cannot win"
                verdict = "measured"
            else:
                verdict, why = "frontier", "not measured"
            lines.append(
                f"    {cfg:<34} {verdict:<10} {cand.get('bound') or '-':<8} "
                f"{pred_s:>9} {_fmt_bytes(cost.get('peak_bytes')):>9} "
                f"{_fmt_bytes(cost.get('wire_bytes')):>9}  {why}")
        if rec.get("rank_correlation") is not None:
            lines.append(f"    predicted-vs-measured rank correlation: "
                         f"{rec['rank_correlation']:.3f}")
    return "\n".join(lines)


def render_text(report: Dict) -> str:
    if not report["stages"]:
        return ("no profile blobs found in any BENCH_r*.json — rounds "
                "predate ISSUE 9 or the bench has not run since")
    lines = [f"compiled-step profiles — round r{report['selected']:02d} "
             f"(rounds with blobs: "
             + ", ".join(f"r{n}" for n in report["rounds"]) + ")", ""]
    lines.append(f"{'stage':<14} {'flops':>10} {'bytes':>9} {'AI':>7} "
                 f"{'peak':>9} {'wire':>9}  collectives")
    for row in report["stages"]:
        colls = ", ".join(f"{k}x{v}" for k, v in
                          sorted(row["collective_counts"].items())) or "-"
        ai = (f"{row['arithmetic_intensity']:.1f}"
              if row["arithmetic_intensity"] is not None else "-")
        lines.append(
            f"{row['stage']:<14} {_fmt_flops(row['flops']):>10} "
            f"{_fmt_bytes(row['bytes_accessed']):>9} {ai:>7} "
            f"{_fmt_bytes(row['peak_bytes']):>9} "
            f"{_fmt_bytes(row['collective_wire_bytes']):>9}  {colls}")
    lines.append("")
    for row in report["stages"]:
        att = row["attribution"]
        xc = row["xla_vs_analytic_flops"]
        bits = []
        if xc is not None:
            bits.append(f"xla/analytic flops {xc:.3f}")
        if att:
            if att.get("measured_mfu") is not None:
                bits.append(f"measured MFU {att['measured_mfu']:.4f}")
            if att.get("hbm_utilization") is not None:
                bits.append(f"HBM util {att['hbm_utilization']:.4f}")
            if att.get("comm_fraction") is not None:
                bits.append(f"comm frac {att['comm_fraction']:.4f}")
            if att.get("bound"):
                bits.append(f"{att['bound']}-bound")
        if bits:
            lines.append(f"  {row['stage']}: " + ", ".join(bits))
    if report["deltas"]:
        lines += ["", "cross-round deltas (prev -> last):"]
        for row in report["deltas"]:
            for key in ("flops", "peak_bytes", "collective_wire_bytes"):
                d = row[key]
                if d["delta_pct"] is None:
                    continue
                flag = "  <-- GREW" if d["delta_pct"] > 10.0 else ""
                fmt = _fmt_flops if key == "flops" else _fmt_bytes
                lines.append(
                    f"  {row['stage']} {key}: {fmt(d['prev'])} -> "
                    f"{fmt(d['last'])} ({d['delta_pct']:+.1f}% "
                    f"r{row['from_round']}->r{row['to_round']}){flag}")
    if report.get("collective_deltas"):
        lines += ["", "per-collective deltas (op kind × payload × wire, "
                  "prev -> last):"]
        lines.append(f"  {'stage':<16} {'kind':<19} {'count':>11} "
                     f"{'payload':>19} {'wire':>19}  groups")
        for row in report["collective_deltas"]:
            def cell(key, fmt):
                d = row[key]
                if d["prev"] is None and d["last"] is None:
                    return "-"
                a = fmt(d["prev"]) if d["prev"] is not None else "-"
                b = fmt(d["last"]) if d["last"] is not None else "-"
                return f"{a}->{b}"

            groups = row["group_sizes"]
            ga = groups["prev"] if groups["prev"] is not None else "-"
            gb = groups["last"] if groups["last"] is not None else "-"
            lines.append(
                f"  {row['stage']:<16} {row['kind']:<19} "
                f"{cell('count', lambda v: str(int(v))):>11} "
                f"{cell('payload_bytes', _fmt_bytes):>19} "
                f"{cell('wire_bytes', _fmt_bytes):>19}  {ga}->{gb}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=REPO_ROOT,
                    help="directory holding BENCH_r*.json (default: repo)")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as JSON")
    ap.add_argument("--round", type=int, default=None,
                    help="render this round's blobs (default: latest)")
    ap.add_argument("--runtime", default=None, metavar="PATH",
                    help="runprof session dump (.json/.jsonl) or a "
                         "directory of them — renders the measured "
                         "runtime sections next to the AOT roofline")
    ap.add_argument("--tuning", default=None, metavar="DIR",
                    help="autotuner decisions dir (tuning_<seam>.json "
                         "from python -m deeplearning4j_tpu.tune) — "
                         "renders candidates, roofline position, and "
                         "pruned-by-dominance reasons")
    args = ap.parse_args(argv)
    rounds = load_profile_rounds(args.dir)
    try:
        report = build_report(rounds, round_id=args.round)
    except ValueError as exc:
        print(str(exc), file=sys.stderr)
        return 2
    sessions = None
    if args.runtime is not None:
        try:
            sessions = load_runtime_sessions(args.runtime)
        except OSError as exc:
            print(f"cannot read runtime sessions: {exc}", file=sys.stderr)
            return 2
        report["runtime_sessions"] = sessions
    decisions = None
    if args.tuning is not None:
        decisions = load_tuning_decisions(args.tuning)
        report["tuning_decisions"] = decisions
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print(render_text(report))
        if sessions is not None:
            print(render_runtime_text(sessions))
        if decisions is not None:
            print(render_tuning_text(decisions))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
