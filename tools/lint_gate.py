"""graftlint CI/tier-1 gate.

Run standalone::

    python tools/lint_gate.py                 # human output, exit 1 on findings
    python tools/lint_gate.py --json          # machine output
    python tools/lint_gate.py --update-baseline   # regenerate the allowlist
    python tools/lint_gate.py deeplearning4j_tpu/models/word2vec.py

or as the installed ``graftlint`` console script ([project.scripts]).
tests/test_graftlint_repo.py calls :func:`run_gate` directly, so the
tier-1 suite and this CLI can never disagree about what "clean" means.

Baseline workflow: a deliberate exception gets an entry in
``tools/graftlint_baseline.json`` with a one-line ``why`` (or an inline
``# graftlint: allow[rule] why`` on the offending line). A fixed finding
leaves a *stale* entry behind; the gate fails on stale entries until
``--update-baseline`` prunes them, so the allowlist only ever shrinks
by being honest about it.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

if __package__ in (None, ""):  # executed as a script: repo root onto path
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.graftlint import (  # noqa: E402
    apply_baseline,
    lint_paths,
    load_baseline,
    match_entry,
    prune_baseline,
    write_baseline,
)
from tools.graftlint.baseline import FIXME_WHY  # noqa: E402

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE_PATH = os.path.join(REPO_ROOT, "tools", "graftlint_baseline.json")

# the repo gate's scan set: the package, the tooling, and the bench
# drivers. tests/ is exercised through golden fixtures instead — test code
# legitimately does host-sync things the rules exist to forbid elsewhere.
DEFAULT_TARGETS = (
    "deeplearning4j_tpu",
    "tools",
    "bench.py",
    "scaling_bench.py",
    "accuracy_gates.py",
)


def run_gate(paths=None, baseline_path: str = BASELINE_PATH,
             use_baseline: bool = True, rule_ids=None):
    """(non-baselined findings, stale baseline entries, all findings).
    ``rule_ids`` restricts the run to those rules (triage mode: stale
    entries for the non-run rules are not reported)."""
    findings = lint_paths(paths or DEFAULT_TARGETS, REPO_ROOT,
                          rule_ids=rule_ids)
    if not use_baseline:
        return findings, [], findings
    entries = load_baseline(baseline_path)
    if rule_ids is not None:
        entries = [e for e in entries if e["rule"] in set(rule_ids)]
    fresh, used, stale = apply_baseline(findings, entries)
    fixme = [e for e in used if e["why"].startswith("FIXME")]
    if fixme:  # an unjustified allowlist entry is itself a finding
        from tools.graftlint.engine import Finding

        fresh = list(fresh) + [
            Finding("unjustified-baseline", e["path"], 0,
                    f"baseline entry for [{e['rule']}] has no real why",
                    "edit tools/graftlint_baseline.json: replace the FIXME "
                    "with a one-line justification", e["snippet"])
            for e in fixme]
    return fresh, stale, findings


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint",
        description="JAX-aware static analysis gate (see tools/graftlint/)")
    ap.add_argument("paths", nargs="*",
                    help=f"files/dirs to lint (default: {DEFAULT_TARGETS})")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as a JSON object")
    ap.add_argument("--baseline", default=BASELINE_PATH,
                    help="baseline/allowlist path")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report raw findings, ignoring the baseline")
    ap.add_argument("--rule", action="append", dest="rules", metavar="ID",
                    help="run only this rule (repeatable) — the triage "
                         "filter for working one rule's findings; stale "
                         "entries for other rules are not reported")
    ap.add_argument("--update-baseline", action="store_true",
                    help="regenerate the baseline from current findings "
                         "(carries forward existing whys; new entries get "
                         f"'{FIXME_WHY}')")
    args = ap.parse_args(argv)

    paths = args.paths or None
    if args.rules:
        from tools.graftlint import RULES

        unknown = [r for r in args.rules if r not in RULES]
        if unknown:
            ap.error(f"unknown rule(s): {', '.join(unknown)} "
                     f"(known: {', '.join(sorted(RULES))})")
    if args.update_baseline:
        if args.rules:
            ap.error("--update-baseline regenerates the FULL baseline; "
                     "it cannot be combined with --rule")
        from tools.graftlint import RULES

        findings = lint_paths(paths or DEFAULT_TARGETS, REPO_ROOT)
        old = load_baseline(args.baseline)
        # dead entries (file gone / rule unregistered) can never match a
        # finding again — drop them loudly instead of carrying them
        old, pruned = prune_baseline(old, REPO_ROOT, set(RULES))
        entries = write_baseline(args.baseline, findings, old)
        for e in pruned:
            print(f"pruned: [{e['rule']}] {e['path']}: "
                  f"{e['pruned_because']}")
        n_fixme = sum(1 for e in entries if e["why"].startswith("FIXME"))
        print(f"baseline: {len(entries)} entries written to {args.baseline}"
              + (f" ({len(pruned)} dead entr(ies) pruned)" if pruned else "")
              + (f" ({n_fixme} need a why — gate fails until justified)"
                 if n_fixme else ""))
        return 0

    fresh, stale, all_findings = run_gate(
        paths, args.baseline, use_baseline=not args.no_baseline,
        rule_ids=args.rules)
    exit_code = 1 if (fresh or stale) else 0
    if args.as_json:
        # per-finding baseline status so CI can annotate a diff without
        # re-deriving the matching: fresh findings fail the gate,
        # baselined ones carry the entry's why
        entries = ([] if args.no_baseline
                   else load_baseline(args.baseline))
        if args.rules:
            entries = [e for e in entries
                       if e["rule"] in set(args.rules)]
        baselined = []
        for f in all_findings:
            hit = match_entry(entries, f)
            if hit is not None:
                baselined.append(dict(f.to_dict(),
                                      baseline_why=hit["why"]))
        print(json.dumps({
            "findings": [f.to_dict() for f in fresh],
            "baselined_findings": baselined,
            "stale_baseline_entries": stale,
            "total_findings_including_baselined": len(all_findings),
            "exit_code": exit_code,
        }, indent=1))
    else:
        for f in fresh:
            print(f.render())
        for e in stale:
            print(f"STALE baseline entry (code was fixed — run "
                  f"--update-baseline to prune): [{e['rule']}] {e['path']}: "
                  f"{e['snippet']}")
        n_base = len(all_findings) - len(
            [f for f in fresh if f.rule != "unjustified-baseline"])
        print(f"graftlint: {len(fresh)} finding(s), {n_base} baselined, "
              f"{len(stale)} stale baseline entr(ies)")
    return exit_code


if __name__ == "__main__":
    sys.exit(main())
