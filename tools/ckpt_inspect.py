#!/usr/bin/env python
"""Inspect sharded checkpoints (scaleout/ckpt): manifest, checksums, diff.

Usage:
    python tools/ckpt_inspect.py CKPT             # manifest summary
    python tools/ckpt_inspect.py CKPT --verify    # re-read + CRC every chunk
    python tools/ckpt_inspect.py A --diff B       # structural + value diff
    ... --json                                    # machine output

``CKPT`` is either a checkpoint root (the latest COMMITTED step is picked;
manifest-less interrupted saves are ignored, exactly as ``latest_step``
does for a resume) or a specific ``step_*`` directory. Exit codes: 0 ok,
1 verification failed / checkpoints differ, 2 usage or missing input.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from deeplearning4j_tpu.scaleout.ckpt.manifest import (  # noqa: E402
    has_manifest,
    list_part_manifests,
    read_manifest,
)
from deeplearning4j_tpu.scaleout.ckpt.reshard import (  # noqa: E402
    _ChunkStore,
    assemble_region,
    latest_step_dir,
    verify_checksums,
)


def resolve_step_dir(path: str) -> str:
    """A root (pick latest committed) or a step dir (must be committed)."""
    if has_manifest(path):
        return path
    step_dir = latest_step_dir(path)
    if step_dir is None:
        parts = list_part_manifests(path)
        hint = (f" ({len(parts)} part manifest(s) present — a multi-host "
                "save whose coordinator never merged)") if parts else ""
        raise FileNotFoundError(
            f"{path}: no committed checkpoint (a directory without a "
            f"MANIFEST.json is an interrupted save, not a checkpoint){hint}")
    return step_dir


def _leaf_bytes(entry) -> int:
    n = 1
    for d in entry.shape:
        n *= int(d)
    try:
        return n * np.dtype(entry.dtype).itemsize
    except TypeError:  # extension dtypes (bfloat16): 2 bytes
        return n * 2


def optimizer_summary(manifest) -> dict | None:
    """Optimizer-state block for manifests that carry one (ISSUE 13: the
    ``['opt']`` subtree — canonical moment trees + step count saved next
    to the params): leaf/byte counts per moment tree plus the save-time
    sharding specs, so an operator can see at a glance whether a
    checkpoint restores moments and how they were laid out. ``None``
    when the checkpoint has no optimizer state (a plain-SGD save renders
    exactly as before)."""
    opt = [e for e in manifest.leaves if e.path.startswith("['opt']")]
    if not opt:
        return None
    moments = sorted({e.path.split("']")[1][2:] for e in opt
                      if e.path.count("[") > 1})
    specs = sorted({json.dumps(e.spec) for e in opt}, key=str)
    out = {
        "leaves": len(opt),
        "bytes": sum(_leaf_bytes(e) for e in opt),
        "moments": [m for m in moments if m not in ("count",)],
        "shardings": [json.loads(s) for s in specs],
    }
    count = next((e for e in opt if e.path == "['opt']['count']"), None)
    if count is not None:
        out["has_step_count"] = True
    return out


def summarize(step_dir: str) -> dict:
    m = read_manifest(step_dir)
    out = {
        "dir": step_dir,
        "format": m.format,
        "step": m.step,
        "mesh": m.mesh,
        "meta_keys": sorted((m.meta or {}).keys()),
        "leaves": len(m.leaves),
        "chunks": sum(len(e.chunks) for e in m.leaves),
        "files": len(m.files),
        "bytes": m.total_bytes,
    }
    opt = optimizer_summary(m)
    if opt is not None:
        out["optimizer_state"] = opt
    return out


def format_summary(step_dir: str) -> str:
    m = read_manifest(step_dir)
    s = summarize(step_dir)
    lines = [f"checkpoint {step_dir}",
             f"  format {s['format']}  step {s['step']}  "
             f"mesh {s['mesh']}",
             f"  {s['leaves']} leaves, {s['chunks']} chunks, "
             f"{s['files']} shard files, {s['bytes'] / 1e6:.2f} MB",
             f"  meta: {', '.join(s['meta_keys']) or '(none)'}"]
    opt = s.get("optimizer_state")
    if opt:
        lines.append(
            f"  optimizer state: {opt['leaves']} leaves "
            f"({', '.join(opt['moments'])}"
            f"{' + step count' if opt.get('has_step_count') else ''}), "
            f"{opt['bytes'] / 1e6:.2f} MB, "
            f"shardings {opt['shardings']}")
    for entry in m.leaves:
        spec = "" if entry.spec is None else f"  spec={entry.spec}"
        lines.append(f"  {entry.path}  {list(entry.shape)} {entry.dtype}"
                     f"  x{len(entry.chunks)} chunk(s){spec}")
    return "\n".join(lines)


def diff_checkpoints(dir_a: str, dir_b: str) -> dict:
    """Structural diff (leaves present, shape/dtype) plus max|a-b| for
    leaves both checkpoints carry — a host-side tool, so full-leaf
    assembly here is fine."""
    ma, mb = read_manifest(dir_a), read_manifest(dir_b)
    paths_a = {e.path: e for e in ma.leaves}
    paths_b = {e.path: e for e in mb.leaves}
    only_a = sorted(set(paths_a) - set(paths_b))
    only_b = sorted(set(paths_b) - set(paths_a))
    changed = []
    max_abs_diff = 0.0
    with _ChunkStore(dir_a) as sa, _ChunkStore(dir_b) as sb:
        for path in sorted(set(paths_a) & set(paths_b)):
            ea, eb = paths_a[path], paths_b[path]
            if ea.shape != eb.shape or ea.dtype != eb.dtype:
                changed.append({"path": path,
                                "a": [list(ea.shape), ea.dtype],
                                "b": [list(eb.shape), eb.dtype]})
                continue
            va = assemble_region(ea, sa, None, np.dtype(ea.dtype))
            vb = assemble_region(eb, sb, None, np.dtype(eb.dtype))
            d = float(np.max(np.abs(np.asarray(va, np.float64)
                                    - np.asarray(vb, np.float64)))) \
                if va.size else 0.0
            max_abs_diff = max(max_abs_diff, d)
            if d > 0.0:
                changed.append({"path": path, "max_abs_diff": d})
    return {
        "a": {"dir": dir_a, "step": ma.step},
        "b": {"dir": dir_b, "step": mb.step},
        "only_in_a": only_a,
        "only_in_b": only_b,
        "changed": changed,
        "max_abs_diff": max_abs_diff,
        "identical": not (only_a or only_b or changed),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("ckpt", help="checkpoint root or step_* directory")
    ap.add_argument("--verify", action="store_true",
                    help="re-read every chunk and check CRC32s")
    ap.add_argument("--diff", metavar="OTHER",
                    help="compare against another checkpoint root/step dir")
    ap.add_argument("--json", action="store_true",
                    help="machine output instead of the table")
    args = ap.parse_args(argv)
    try:
        step_dir = resolve_step_dir(args.ckpt)
    except (FileNotFoundError, ValueError) as e:
        print(str(e), file=sys.stderr)
        return 2

    if args.diff:
        try:
            other = resolve_step_dir(args.diff)
        except (FileNotFoundError, ValueError) as e:
            print(str(e), file=sys.stderr)
            return 2
        result = diff_checkpoints(step_dir, other)
        if args.json:
            print(json.dumps(result, indent=1))
        elif result["identical"]:
            print(f"identical: {step_dir} == {other}")
        else:
            print(f"diff {step_dir} vs {other}:")
            for path in result["only_in_a"]:
                print(f"  only in A: {path}")
            for path in result["only_in_b"]:
                print(f"  only in B: {path}")
            for c in result["changed"]:
                print(f"  changed: {c}")
        return 0 if result["identical"] else 1

    if args.verify:
        problems = verify_checksums(step_dir)
        payload = {"dir": step_dir, "ok": not problems, "problems": problems}
        if args.json:
            print(json.dumps(payload, indent=1))
        elif problems:
            print(f"CORRUPT checkpoint {step_dir}:")
            for p in problems:
                print(f"  {p}")
        else:
            print(f"ok: every chunk of {step_dir} matches its manifest CRC")
        return 0 if not problems else 1

    if args.json:
        print(json.dumps(summarize(step_dir), indent=1))
    else:
        print(format_summary(step_dir))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
