#!/usr/bin/env python
"""Render a telemetry JSONL step log into a throughput/grad-norm summary.

Usage:
    python tools/telemetry_report.py PATH/steps.jsonl [--json]

Reads the step-event log a TrainTelemetry session (or
MetricsIterationListener) wrote and prints an aligned summary table:
step count, wall-clock p50/p95/mean, mean tokens/s, loss and grad-norm
first→last, and the mean per-expert router load. ``--json`` emits the raw
summary dict instead (CI-friendly).

The aggregation itself lives in telemetry/step_log.summarize_step_log so
bench.py's lm_composed stage and this report can never disagree.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning4j_tpu.telemetry.step_log import (  # noqa: E402
    read_step_log,
    summarize_step_log,
)


def format_report(summary: dict, path: str) -> str:
    rows = [("steps", str(summary.get("steps", 0)))]
    wall = summary.get("wall_ms")
    if wall:
        # p99 (ISSUE 12): older summaries may predate it — render "-"
        rows.append(("wall ms (p50 / p95 / p99 / mean)",
                     f"{wall['p50']} / {wall['p95']} / "
                     f"{wall.get('p99', '-')} / {wall['mean']}"))
    if "tokens_per_sec_mean" in summary:
        rows.append(("tokens/s (mean)", str(summary["tokens_per_sec_mean"])))
    # moment_norm_m/v + lamb_trust_ratio: the ISSUE 13 optimizer-health
    # block — rendered only when the run carried an in-graph optimizer
    # (silent-when-absent pinned both ways in tests/test_updaters.py)
    for key in ("loss", "score", "grad_norm", "param_norm", "update_ratio",
                "moment_norm_m", "moment_norm_v", "lamb_trust_ratio"):
        if key in summary:
            s = summary[key]
            rows.append((f"{key} (first -> last)",
                         f"{s['first']} -> {s['last']}"))
    if "router_load_mean" in summary:
        load = summary["router_load_mean"]
        rows.append(("router load (mean/expert)",
                     " ".join(f"e{i}={v}" for i, v in enumerate(load))))
    # numerical faults SHOUT (ISSUE 8): step_log preserves NaN/Inf as repr
    # strings so the JSONL stays parseable; a report that silently dropped
    # them would hide exactly the steps worth investigating
    bad = summary.get("nonfinite")
    if bad:
        rows.append(("!! NONFINITE values", " ".join(
            f"{k}x{n}" for k, n in sorted(bad.items()))))
    for key in ("skipped_steps", "clipped_steps"):
        if summary.get(key):
            rows.append((f"!! guard {key}", str(summary[key])))
    width = max(len(r[0]) for r in rows)
    lines = [f"telemetry report — {path}", "-" * (width + 24)]
    lines += [f"{name:<{width}}  {value}" for name, value in rows]
    # lock telemetry (ISSUE 11): one row per watched lock when the run
    # carried utils.lockwatch metrics; silent otherwise
    watch = summary.get("lockwatch")
    if watch:
        lines += ["", "lockwatch (per watched lock)",
                  f"{'lock':<24} {'acquires':>9} {'contended':>9} "
                  f"{'hold p.max ms':>13} {'wait max ms':>11}"]
        names = sorted({k[len("lockwatch_"):-len("_acquires")]
                        for k in watch if k.endswith("_acquires")})
        for name in names:
            get = lambda stat: watch.get(f"lockwatch_{name}_{stat}", 0)  # noqa: E731
            lines.append(
                f"{name:<24} {get('acquires'):>9.0f} "
                f"{get('contended'):>9.0f} {get('hold_ms_max'):>13.3f} "
                f"{get('wait_ms_max'):>11.3f}")
        for flag in ("lockwatch_cycles", "lockwatch_watchdog_dumps"):
            if watch.get(flag):
                lines.append(f"!! {flag}: {watch[flag]:.0f}")
    # network telemetry (ISSUE 18): one row per watched endpoint when the
    # run carried utils.netwatch metrics; silent otherwise
    net = summary.get("netwatch")
    if net:
        lines += ["", "netwatch (per watched endpoint)",
                  f"{'endpoint':<28} {'ops':>7} {'timeouts':>8} "
                  f"{'reconnects':>10} {'retries':>7} {'wait max ms':>11}"]
        eps = sorted({k[len("netwatch_"):-len("_ops")]
                      for k in net if k.endswith("_ops")})
        for ep in eps:
            get = lambda stat: net.get(f"netwatch_{ep}_{stat}", 0)  # noqa: E731
            lines.append(
                f"{ep:<28} {get('ops'):>7.0f} {get('timeouts'):>8.0f} "
                f"{get('reconnects'):>10.0f} {get('retries'):>7.0f} "
                f"{get('wait_ms_max'):>11.3f}")
        if net.get("netwatch_stall_dumps"):
            lines.append(
                f"!! netwatch_stall_dumps: {net['netwatch_stall_dumps']:.0f}")
    # serve / federation registry metrics (ISSUE 12): one row per metric
    # when the run carried serve_* / federation_* keys
    # (registry.flat_record via the subsystem metrics_record()s); silent
    # otherwise — both directions pinned by the ISSUE 12 meta-test, so a
    # new metric under either prefix can never ship unrendered
    # + alerts/history (ISSUE 15): the watchtower blocks, same contract
    # + runprof (ISSUE 17): the runtime profiler's gauges, same contract
    for block_key, title in (("serve", "serve metrics (registry)"),
                             ("federation",
                              "federation metrics (registry)"),
                             ("alerts", "alert metrics (registry)"),
                             ("history", "history metrics (registry)"),
                             ("runprof", "runprof metrics (registry)")):
        block = summary.get(block_key)
        if block:
            bw = max(len(k) for k in block)
            lines += ["", title]
            lines += [f"  {k:<{bw}}  {block[k]:g}"
                      for k in sorted(block)]
    if bad:
        lines.append(
            f"WARNING: {sum(bad.values())} non-finite metric value(s) in "
            "this log — see the nonfinite row; replay bundles (if the "
            "watchdog was armed) hold the faulting steps")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("log", help="JSONL step log path")
    ap.add_argument("--json", action="store_true",
                    help="emit the summary as JSON instead of a table")
    args = ap.parse_args(argv)
    if not os.path.isfile(args.log):
        print(f"no such step log: {args.log}", file=sys.stderr)
        return 2
    try:
        records = read_step_log(args.log)
    except ValueError as exc:
        # truncated (writer killed mid-line) or corrupt log: a clear
        # message naming the bad line, not a traceback
        print(str(exc), file=sys.stderr)
        return 3
    if not records:
        print(f"step log {args.log} is empty (no step records)",
              file=sys.stderr)
        return 3
    summary = summarize_step_log(records)
    if args.json:
        print(json.dumps(summary, indent=1))
    else:
        print(format_report(summary, args.log))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
