import time, statistics
import jax, jax.numpy as jnp

N = 4096

def bench(dtype, precision, steps):
    a = jax.random.normal(jax.random.PRNGKey(0), (N, N)).astype(dtype)
    b = jax.random.normal(jax.random.PRNGKey(1), (N, N)).astype(dtype)

    @jax.jit
    def prog(a, b):
        def body(carry, _):
            c = jnp.dot(carry, b, precision=precision)
            c = c / jnp.float32(64.0).astype(c.dtype)
            return c, ()
        out, _ = jax.lax.scan(body, a, None, length=steps)
        return jnp.sum(out.astype(jnp.float32))

    float(prog(a, b))  # compile+warm
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        float(prog(a, b))
        times.append(time.perf_counter() - t0)
    t = statistics.median(times)
    flops = 2 * N**3 * steps
    return flops / t / 1e12, t

for dtype, prec, label, steps in [
    (jnp.bfloat16, jax.lax.Precision.DEFAULT, "bf16_default", 4096),
    (jnp.float32, jax.lax.Precision.DEFAULT, "fp32_default", 4096),
    (jnp.float32, jax.lax.Precision.HIGH, "fp32_high", 2048),
    (jnp.float32, jax.lax.Precision.HIGHEST, "fp32_highest", 512),
]:
    tf, t = bench(dtype, prec, steps)
    print(f"{label}: {tf:.1f} TFLOP/s (run {t:.2f}s)")

# Measured 2026-07-30 on the driver's TPU v5 lite chip (axon tunnel):
#   bf16_default: 185.7 TFLOP/s   (94% of the 197 TF/s spec peak)
#   fp32_default: 153.5 TFLOP/s   (same single-bf16-pass MXU path; the gap
#                                  is fp32 operand HBM traffic)
#   fp32_high:     59.5 TFLOP/s   (bf16x3 passes)
#   fp32_highest:  29.7 TFLOP/s   (bf16x6 passes ~ true fp32 accuracy)
