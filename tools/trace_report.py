#!/usr/bin/env python
"""Merge per-process trace span files into one round timeline.

Usage:
    python tools/trace_report.py TRACE_DIR [--json] [--chrome OUT.json]

Reads every ``spans_*.jsonl`` the telemetry.trace tracers wrote under
``TRACE_DIR`` (master + workers of an elastic run) plus any
``flightrec_*.json`` flight-recorder dumps, pairs begin/end records into
spans (an unmatched begin — a process that died mid-span — becomes an
*open* span), and renders:

- the merged **round timeline**: per elastic round, duration, who
  contributed, and the **barrier-wait attribution** — which worker the
  round waited on and for how long after the first contribution arrived
  (from the master barrier span's ``contribution`` events, falling back
  to worker ``worker.publish`` span end times when the master file is
  missing);
- partial rounds reconstructed from open spans (a kill -9 run shows the
  round the victim died in, with the spans it never closed);
- ``--chrome``: a Chrome trace-event JSON export (load in
  ``chrome://tracing`` / Perfetto) with one row per process;

and, when the trace carries serving spans (ISSUE 12 — the decode
engine's ``serve.request`` trees and ``engine.step`` scheduler spans):

- the **per-request latency-attribution table**: queue_wait / prefill /
  decode / scheduler-gap milliseconds per request (the components sum to
  the request latency by construction — gap is the time a request sat
  admitted but outside its own dispatches), retire reason, weight
  version; requests whose process died mid-flight (kill -9) appear as
  ``open`` rows reconstructed from their eager begin records;
- a **slot-occupancy Gantt** in the Chrome export: per-slot rows
  (``serve.prefill``/``serve.decode`` spans land on ``tid = slot``) plus
  a ``slot_occupancy`` counter track from the ``engine.step`` spans.

The aggregation is importable (``load_trace_dir`` / ``build_timeline`` /
``serve_attribution`` / ``chrome_trace``) so bench.py's traced stages and
the fault tests use the exact same reconstruction this CLI prints.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _merge_begin(spans: Dict[str, Dict], rec: Dict) -> None:
    sp = spans.setdefault(rec["span_id"], {})
    sp.update({
        "span_id": rec["span_id"], "trace_id": rec.get("trace_id"),
        "parent_id": rec.get("parent_id"), "name": rec.get("name"),
        "process": rec.get("process"), "start": rec.get("ts"),
        "attrs": {**rec.get("attrs", {}), **sp.get("attrs", {})},
    })
    sp.setdefault("status", "open")
    sp.setdefault("events", [])


def _merge_end(spans: Dict[str, Dict], rec: Dict) -> None:
    sp = spans.setdefault(rec["span_id"], {})
    sp.update({
        "span_id": rec["span_id"],
        "trace_id": rec.get("trace_id", sp.get("trace_id")),
        "name": rec.get("name", sp.get("name")),
        "process": rec.get("process", sp.get("process")),
        "end": rec.get("ts"), "dur_ms": rec.get("dur_ms"),
        "status": rec.get("status", "ok"), "error": rec.get("error"),
        "attrs": {**sp.get("attrs", {}), **rec.get("attrs", {})},
        "events": rec.get("events", sp.get("events", [])),
    })
    if sp.get("start") is None and rec.get("dur_ms") is not None:
        sp["start"] = rec["ts"] - rec["dur_ms"] / 1000.0


def load_trace_dir(trace_dir: str) -> Dict[str, Dict]:
    """All spans under ``trace_dir`` keyed by span_id. Tolerant of a
    truncated trailing line (a process killed mid-write) — everything
    parseable is kept, the torn tail is skipped."""
    spans: Dict[str, Dict] = {}
    for path in sorted(glob.glob(os.path.join(trace_dir, "spans_*.jsonl"))):
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail from a killed process
                if rec.get("ev") == "B":
                    _merge_begin(spans, rec)
                elif rec.get("ev") == "E":
                    _merge_end(spans, rec)
    # flight dumps can carry spans whose jsonl never made it (e.g. a sink
    # on a dead NFS mount) — merge, never overwrite fresher jsonl data
    for path in sorted(glob.glob(os.path.join(trace_dir, "flightrec_*.json"))):
        try:
            with open(path) as fh:
                dump = json.load(fh)
        except (OSError, json.JSONDecodeError):
            continue
        for rec in dump.get("recent", []):
            if rec.get("span_id") not in spans:
                _merge_end(spans, rec)
        for sp in dump.get("open", []):
            if sp.get("span_id") not in spans:
                spans[sp["span_id"]] = {**sp, "status": "open",
                                        "events": sp.get("events", [])}
    return spans


def find_trace(spans: Dict[str, Dict], trace_id: str) -> Dict[str, Dict]:
    """The spans of ONE trace, keyed by span_id (ISSUE 15: how an alert
    exemplar's trace id resolves to real spans — a firing
    serve_latency_slo_burn carries the offending request trace ids, and
    this lookup turns each into its serve.request tree)."""
    want = str(trace_id).lower()
    return {sid: sp for sid, sp in spans.items()
            if str(sp.get("trace_id", "")).lower() == want}


def render_trace_text(trace_id: str, trace_spans: Dict[str, Dict]) -> str:
    """One trace's spans as an indented start-ordered tree."""
    lines = [f"trace {trace_id} — {len(trace_spans)} span(s)"]
    children: Dict = {}
    for sid, sp in trace_spans.items():
        children.setdefault(sp.get("parent_id"), []).append(sid)

    def emit(sid: str, depth: int) -> None:
        sp = trace_spans[sid]
        dur = (f"{sp['dur_ms']:.2f}ms" if sp.get("dur_ms") is not None
               else "open")
        lines.append(f"{'  ' * depth}{sp.get('name')} "
                     f"[{sp.get('process')}] {dur} {sp.get('status')}")
        for kid in sorted(children.get(sid, []),
                          key=lambda k: trace_spans[k].get("start", 0.0)):
            emit(kid, depth + 1)

    roots = [sid for sid, sp in trace_spans.items()
             if sp.get("parent_id") not in trace_spans]
    for sid in sorted(roots,
                      key=lambda k: trace_spans[k].get("start", 0.0)):
        emit(sid, 1)
    return "\n".join(lines)


def _arrivals(round_info: Dict) -> List[Dict]:
    """Per-worker contribution arrival times for one round, preferring the
    master barrier span's events (one clock — the master's) and falling
    back to worker publish span ends."""
    by_worker: Dict[str, float] = {}
    barrier = round_info.get("barrier")
    if barrier:
        for ev in barrier.get("events", []):
            if ev.get("name") == "contribution" and ev.get("worker"):
                by_worker.setdefault(str(ev["worker"]), float(ev["ts"]))
    for sp in round_info.get("publishes", []):
        ts = sp.get("end") or sp.get("start")
        w = str(sp.get("attrs", {}).get("worker", sp.get("process")))
        if ts is not None:
            by_worker.setdefault(w, float(ts))
    return [{"worker": w, "ts": ts}
            for w, ts in sorted(by_worker.items(), key=lambda kv: kv[1])]


def build_timeline(spans: Dict[str, Dict]) -> Dict:
    """Group spans into elastic rounds with barrier-wait attribution."""
    rounds: Dict[int, Dict] = {}

    def rnd_of(sp) -> Optional[int]:
        r = sp.get("attrs", {}).get("round")
        return int(r) if r is not None else None

    for sp in spans.values():
        r, name = rnd_of(sp), sp.get("name")
        if r is None:
            continue
        info = rounds.setdefault(r, {"publishes": [], "worker_rounds": []})
        if name == "elastic.round":
            info["master"] = sp
        elif name == "elastic.barrier":
            info["barrier"] = sp
        elif name == "worker.publish":
            info["publishes"].append(sp)
        elif name == "worker.round":
            info["worker_rounds"].append(sp)

    out_rounds = []
    for r in sorted(rounds):
        info = rounds[r]
        master = info.get("master")
        committed = master is not None and master.get("end") is not None
        arrivals = _arrivals(info)
        if committed and not arrivals and not info["worker_rounds"] \
                and "barrier" not in info:
            # the final published version whose round was never collected
            # (the run ended there) — not a committed round, not a crash
            status = "uncollected"
        else:
            status = "committed" if committed else "partial"
        row: Dict = {
            "round": r,
            "status": status,
            "contributors": arrivals,
            "workers_seen": sorted({
                str(sp.get("attrs", {}).get("worker", sp.get("process")))
                for sp in info["worker_rounds"] + info["publishes"]}),
            "open_spans": sorted({
                f"{sp.get('process')}:{sp.get('name')}"
                for group in (info["worker_rounds"], info["publishes"])
                for sp in group if sp.get("status") == "open"}
                | ({f"{master.get('process')}:{master.get('name')}"}
                   if master is not None and not committed else set())),
        }
        if master is not None:
            row["start"] = master.get("start")
            if committed:
                row["dur_ms"] = master.get("dur_ms")
        if arrivals:
            first, last = arrivals[0], arrivals[-1]
            row["straggler"] = last["worker"]
            row["straggler_wait_ms"] = round(
                (last["ts"] - first["ts"]) * 1000.0, 3)
            for a in arrivals:
                a["waited_ms"] = round((last["ts"] - a["ts"]) * 1000.0, 3)
        out_rounds.append(row)

    processes = sorted({sp.get("process") for sp in spans.values()
                        if sp.get("process")})
    n_open = sum(1 for sp in spans.values() if sp.get("status") == "open")
    errors = [{"process": sp.get("process"), "name": sp.get("name"),
               "error": sp.get("error")}
              for sp in spans.values() if sp.get("status") == "error"]
    return {"processes": processes, "n_spans": len(spans),
            "n_open": n_open, "errors": errors, "rounds": out_rounds}


def serve_attribution(spans: Dict[str, Dict]) -> List[Dict]:
    """Per-request latency attribution from ``serve.request`` trees
    (ISSUE 12). Completed requests carry the exact attribution the engine
    stamped at retire (queue_wait + prefill + decode + gap ≡ latency);
    requests cut short by a dead process surface as ``status: "open"``
    rows with whatever their children's begin/end records pin down."""
    children: Dict[str, List[Dict]] = {}
    for sp in spans.values():
        pid = sp.get("parent_id")
        if pid:
            children.setdefault(pid, []).append(sp)

    def child_dur(req_span: Dict, name: str):
        for c in children.get(req_span["span_id"], []):
            if c.get("name") == name:
                return c.get("dur_ms"), c
        return None, None

    rows: List[Dict] = []
    for sp in spans.values():
        if sp.get("name") != "serve.request":
            continue
        attrs = sp.get("attrs", {})
        is_open = sp.get("end") is None
        queue_ms = attrs.get("queue_wait_ms")
        prefill_ms = attrs.get("prefill_ms")
        decode_ms = attrs.get("decode_ms")
        if queue_ms is None:
            queue_ms = child_dur(sp, "serve.queue_wait")[0]
        if prefill_ms is None:
            prefill_ms = child_dur(sp, "serve.prefill")[0]
        dspan = None
        if decode_ms is None:
            dms, dspan = child_dur(sp, "serve.decode")
            decode_ms = (dspan.get("attrs", {}).get("decode_ms")
                         if dspan is not None else None) or dms
        else:
            dspan = child_dur(sp, "serve.decode")[1]
        total_ms = attrs.get("latency_ms", sp.get("dur_ms"))
        gap_ms = attrs.get("gap_ms")
        if gap_ms is None and None not in (total_ms, queue_ms, prefill_ms,
                                           decode_ms):
            gap_ms = round(total_ms - queue_ms - prefill_ms - decode_ms, 3)
        # ISSUE 16: prefill splits into the cached-skip (prefix pages
        # seeded from the page table) and the suffix actually computed —
        # preferring the retire attrs, falling back to the prefill child
        # span's own attribution for open/killed requests
        pspan = child_dur(sp, "serve.prefill")[1]
        pattrs = pspan.get("attrs", {}) if pspan is not None else {}
        cached_ms = attrs.get("prefill_cached_ms",
                              pattrs.get("cached_ms"))
        suffix_ms = attrs.get("prefill_suffix_ms",
                              pattrs.get("suffix_ms"))
        cached_tokens = attrs.get("cached_tokens",
                                  pattrs.get("cached_tokens"))
        # speculative verify rounds ride the decode span as "verify"
        # events tagged with their accepted-token counts
        verifies = [ev for ev in (dspan.get("events", [])
                                  if dspan is not None else [])
                    if ev.get("name") == "verify"]
        rows.append({
            "rid": attrs.get("rid"),
            "trace_id": sp.get("trace_id"),
            "process": sp.get("process"),
            "status": "open" if is_open else sp.get("status", "ok"),
            "start": sp.get("start"),
            "queue_wait_ms": queue_ms,
            "prefill_ms": prefill_ms,
            "prefill_cached_ms": cached_ms,
            "prefill_suffix_ms": suffix_ms,
            "cached_tokens": cached_tokens,
            "decode_ms": decode_ms,
            "gap_ms": gap_ms,
            "total_ms": total_ms,
            "tokens": attrs.get("tokens"),
            "verify_steps": len(verifies),
            "spec_accepted_tokens": sum(
                int(ev.get("accepted", 0)) for ev in verifies),
            "finish_reason": attrs.get("finish_reason"),
            "weight_version": attrs.get("weight_version"),
        })
    rows.sort(key=lambda r: (r.get("start") or 0.0,
                             r.get("rid") if r.get("rid") is not None
                             else -1))
    return rows


def render_serve_text(rows: List[Dict]) -> str:
    """The per-request attribution table (appended to the CLI output when
    the trace carries serving spans)."""
    def fmt(v, w):
        return f"{v:>{w}.2f}" if isinstance(v, (int, float)) else f"{'-':>{w}}"

    hdr = (f"{'rid':>5}  {'status':<7}  {'queue':>8}  {'prefill':>8}  "
           f"{'cached':>8}  {'decode':>8}  {'gap':>8}  {'total':>8}  "
           f"{'tok':>4}  {'acc':>4}  {'reason':<14}  weights")
    lines = ["", f"serve requests — latency attribution (ms), "
             f"{len(rows)} request(s), "
             f"{sum(1 for r in rows if r['status'] == 'open')} open "
             f"(cached = prefix-cache skip inside prefill; acc = "
             f"speculative tokens accepted)",
             hdr, "-" * len(hdr)]
    for r in rows:
        rid = r["rid"] if r["rid"] is not None else "?"
        tok = r["tokens"] if r["tokens"] is not None else "-"
        acc = (r["spec_accepted_tokens"] if r.get("verify_steps")
               else "-")
        lines.append(
            f"{rid:>5}  {r['status']:<7}  {fmt(r['queue_wait_ms'], 8)}  "
            f"{fmt(r['prefill_ms'], 8)}  "
            f"{fmt(r.get('prefill_cached_ms'), 8)}  "
            f"{fmt(r['decode_ms'], 8)}  "
            f"{fmt(r['gap_ms'], 8)}  {fmt(r['total_ms'], 8)}  {tok:>4}  "
            f"{acc:>4}  "
            f"{str(r['finish_reason'] or '-'):<14}  "
            f"{r['weight_version'] or '-'}")
    return "\n".join(lines)


def chrome_trace(spans: Dict[str, Dict]) -> Dict:
    """Chrome trace-event JSON (the ``chrome://tracing`` / Perfetto
    format): one "X" complete event per span in µs, one row per process,
    open spans extended to the latest timestamp seen and flagged.

    Serving traces get a slot-occupancy Gantt (ISSUE 12): spans carrying
    a ``slot`` attribute (``serve.prefill``/``serve.decode``) land on
    ``tid = 1 + slot`` — one named row per cache slot, so the per-slot
    residency of the continuous-batching scheduler reads directly off
    the timeline — and every ``engine.step`` span contributes a
    ``slot_occupancy`` counter sample (ph "C")."""
    processes = sorted({sp.get("process") or "?" for sp in spans.values()})
    pid_of = {p: i for i, p in enumerate(processes)}
    latest = max((sp.get("end") or sp.get("start") or 0.0
                  for sp in spans.values()), default=0.0)
    events: List[Dict] = [
        {"name": "process_name", "ph": "M", "pid": pid_of[p], "tid": 0,
         "args": {"name": p}}
        for p in processes
    ]
    slot_rows = set()  # (pid, tid) pairs needing a thread_name meta event
    for sp in sorted(spans.values(), key=lambda s: s.get("start") or 0.0):
        start = sp.get("start")
        if start is None:
            continue
        is_open = sp.get("end") is None
        end = sp.get("end") if not is_open else latest
        args = dict(sp.get("attrs", {}))
        args.update({"span_id": sp.get("span_id"),
                     "trace_id": sp.get("trace_id"),
                     "status": sp.get("status")})
        if is_open:
            args["open"] = True
        if sp.get("error"):
            args["error"] = sp["error"]
        pid = pid_of[sp.get("process") or "?"]
        tid = 0
        slot = sp.get("attrs", {}).get("slot")
        if isinstance(slot, int) and slot >= 0:
            tid = 1 + slot
            slot_rows.add((pid, tid))
        events.append({
            "name": sp.get("name") or "?", "ph": "X",
            "ts": round(start * 1e6, 1),
            "dur": round(max(0.0, (end - start)) * 1e6, 1),
            "pid": pid, "tid": tid,
            "args": args,
        })
        if sp.get("name") == "engine.step" and "occupancy" in args:
            events.append({
                "name": "slot_occupancy", "ph": "C",
                "ts": round(start * 1e6, 1), "pid": pid, "tid": 0,
                "args": {"occupancy": args["occupancy"]},
            })
    for pid, tid in sorted(slot_rows):
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": tid, "args": {"name": f"slot {tid - 1}"}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def render_text(timeline: Dict, trace_dir: str) -> str:
    lines = [f"trace report — {trace_dir}",
             f"processes: {', '.join(timeline['processes'])} "
             f"({timeline['n_spans']} spans, {timeline['n_open']} open)"]
    if timeline["errors"]:
        lines.append("errors:")
        lines += [f"  {e['process']}:{e['name']}  {e['error']}"
                  for e in timeline["errors"]]
    hdr = (f"{'round':>5}  {'status':<9}  {'dur_ms':>9}  "
           f"{'contrib':<24}  {'waited on':<12}  {'wait_ms':>8}")
    lines += ["", hdr, "-" * len(hdr)]
    for row in timeline["rounds"]:
        contrib = ",".join(a["worker"] for a in row["contributors"]) or "-"
        dur = (f"{row['dur_ms']:.1f}" if row.get("dur_ms") is not None
               else "-")
        lines.append(
            f"{row['round']:>5}  {row['status']:<9}  {dur:>9}  "
            f"{contrib:<24}  {row.get('straggler', '-'):<12}  "
            f"{row.get('straggler_wait_ms', 0.0):>8}")
        if row["open_spans"]:
            # a committed round can still carry a dead worker's unclosed
            # spans (kill -9 mid-round, survivors committed without it)
            lines.append(f"{'':>5}  open: {', '.join(row['open_spans'])}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace_dir", help="directory of spans_*.jsonl files")
    ap.add_argument("--json", action="store_true",
                    help="emit the merged timeline as JSON")
    ap.add_argument("--chrome", metavar="OUT",
                    help="also write a Chrome trace-event JSON export")
    ap.add_argument("--trace-id", metavar="ID",
                    help="render only the spans of ONE trace (the id an "
                         "alert exemplar / /api/alerts carries); exits 1 "
                         "when the trace has no spans here")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.trace_dir):
        print(f"no such trace dir: {args.trace_dir}", file=sys.stderr)
        return 2
    spans = load_trace_dir(args.trace_dir)
    if not spans:
        print(f"no span records under {args.trace_dir} "
              "(expected spans_*.jsonl / flightrec_*.json)", file=sys.stderr)
        return 2
    if args.trace_id:
        trace_spans = find_trace(spans, args.trace_id)
        if not trace_spans:
            print(f"no spans for trace id {args.trace_id} under "
                  f"{args.trace_dir}", file=sys.stderr)
            return 1
        if args.json:
            print(json.dumps({"trace_id": args.trace_id,
                              "spans": trace_spans}, indent=1))
        else:
            print(render_trace_text(args.trace_id, trace_spans))
        return 0
    timeline = build_timeline(spans)
    serve_rows = serve_attribution(spans)
    if args.chrome:
        with open(args.chrome, "w") as fh:
            json.dump(chrome_trace(spans), fh)
        print(f"chrome trace written: {args.chrome}", file=sys.stderr)
    if args.json:
        if serve_rows:
            timeline = dict(timeline, serve_requests=serve_rows)
        print(json.dumps(timeline, indent=1))
    else:
        out = render_text(timeline, args.trace_dir)
        if serve_rows:
            out += "\n" + render_serve_text(serve_rows)
        print(out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
