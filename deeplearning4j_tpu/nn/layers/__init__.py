"""Layer implementations as pure functions over (conf, params, input).

Replaces the reference's stateful Layer classes + LayerFactory dispatch
(ref: nn/layers/, nn/layers/factory/LayerFactories.java). ``forward`` is the
single activate entry point; training uses jax.grad over composed forwards
instead of the reference's hand-written backwardGradient chains.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax

from deeplearning4j_tpu.nn.api import LayerType
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import (
    attention,
    autoencoder,
    convolution,
    dense,
    lstm,
    output,
    rbm,
    recursive_autoencoder,
    subsampling,
)

_FORWARD = {
    LayerType.DENSE: dense.forward,
    LayerType.OUTPUT: output.forward,
    LayerType.RBM: rbm.forward,
    LayerType.AUTOENCODER: autoencoder.forward,
    LayerType.RECURSIVE_AUTOENCODER: recursive_autoencoder.forward,
    LayerType.CONVOLUTION: convolution.forward,
    LayerType.SUBSAMPLING: subsampling.forward,
    LayerType.LSTM: lstm.forward,
    LayerType.ATTENTION: attention.forward,
}


_TAKES_DROP_CONNECT = {LayerType.DENSE, LayerType.OUTPUT}


def forward(
    conf: NeuralNetConfiguration,
    params: Dict[str, jax.Array],
    x: jax.Array,
    *,
    train: bool = False,
    key: Optional[jax.Array] = None,
    drop_connect: bool = False,
) -> jax.Array:
    """Layer.activate (ref: nn/api/Layer.java:37)."""
    fn = _FORWARD[conf.layer_type]
    if conf.layer_type in _TAKES_DROP_CONNECT:
        return fn(conf, params, x, train=train, key=key, drop_connect=drop_connect)
    return fn(conf, params, x, train=train, key=key)
