"""Multi-head self-attention block (beyond-reference long-context layer).

The reference is pre-transformer (2015) and has no attention anywhere
(SURVEY.md §2.5); this layer is the long-context counterpart to the scan
LSTM and follows the same head contract (ref: nn/layers/recurrent/LSTM.java
decoder + LSTMParamInitializer — the layer owns a decoder projection
producing per-timestep logits, so it can be a sequence head under
MultiLayerNetwork exactly like the LSTM).

Block: pre-LayerNorm multi-head self-attention (causal by conf) with a
residual connection, then the decoder projection n_in → n_out. All matmuls
are (batch·time, d)-shaped MXU work; the attention core is the same dense
einsum used by parallel/ring_attention.reference_attention, so the
sequence-parallel path (``forward_ring``) computes the IDENTICAL function
with the time axis sharded over a mesh axis and K/V rotating on ICI.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.params import DECODER_BIAS_KEY, DECODER_WEIGHT_KEY

Array = jax.Array

LN_GAIN_KEY = "ln_g"
LN_BIAS_KEY = "ln_b"
Q_KEY, K_KEY, V_KEY, OUT_KEY = "wq", "wk", "wv", "wo"


def _layernorm(x: Array, g: Array, b: Array) -> Array:
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + 1e-5) * g + b


def _split_heads(x: Array, n_heads: int) -> Array:
    """(B, T, D) → (B, H, T, D/H)."""
    b, t, d = x.shape
    return x.reshape(b, t, n_heads, d // n_heads).transpose(0, 2, 1, 3)


def _merge_heads(x: Array) -> Array:
    """(B, H, T, Hd) → (B, T, D)."""
    b, h, t, hd = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b, t, h * hd)


def attend_block(conf: NeuralNetConfiguration, params: Dict[str, Array],
                 x: Array, attn_core) -> Array:
    """Pre-LN MHA + residual; ``attn_core(q, k, v) -> out`` supplies the
    attention math ((B,H,T,Hd) in and out) so the dense and ring/Ulysses
    paths share every projection."""
    xn = _layernorm(x, params[LN_GAIN_KEY], params[LN_BIAS_KEY])
    h = conf.n_heads
    q = _split_heads(xn @ params[Q_KEY], h)
    k = _split_heads(xn @ params[K_KEY], h)
    v = _split_heads(xn @ params[V_KEY], h)
    return x + _merge_heads(attn_core(q, k, v)) @ params[OUT_KEY]


def _forward(conf: NeuralNetConfiguration, params: Dict[str, Array],
             x: Array, attn_core) -> Array:
    """Shared 2-D lift + block + decoder head for every attention path."""
    if x.ndim == 2:
        x = x[None]
    hs = attend_block(conf, params, x, attn_core)
    return hs @ params[DECODER_WEIGHT_KEY] + params[DECODER_BIAS_KEY]


def _dense_core(conf):
    # ops/flash_attention dispatches: portable blockwise scan at long
    # block-aligned T (measured faster than the pallas kernel on v5e),
    # materializing einsum at short T — the identical function, so the
    # layer is O(T)-memory at real sequence lengths without any conf change
    from deeplearning4j_tpu.ops.flash_attention import attention_core

    return lambda q, k, v: attention_core(q, k, v, causal=conf.causal)


def hidden_sequence(conf: NeuralNetConfiguration, params: Dict[str, Array],
                    x: Array) -> Array:
    """The block output before the decoder: (batch, time, n_in)."""
    if x.ndim == 2:
        x = x[None]
    return attend_block(conf, params, x, _dense_core(conf))


def forward(
    conf: NeuralNetConfiguration,
    params: Dict[str, Array],
    x: Array,
    *,
    train: bool = False,
    key: Optional[Array] = None,
) -> Array:
    """Per-timestep logits: (batch, time, n_out)."""
    return _forward(conf, params, x, _dense_core(conf))


def forward_ring(conf: NeuralNetConfiguration, params: Dict[str, Array],
                 x: Array, mesh: Mesh, axis: str) -> Array:
    """The identical block with the SEQUENCE axis sharded over ``axis`` —
    attention runs as ring attention (K/V blocks rotating via ppermute,
    online-softmax accumulation, parallel/ring_attention.py) so per-device
    memory is O(T/P). x: (batch, time, n_in) with time divisible by the
    axis size; validated against ``forward`` in tests."""
    from deeplearning4j_tpu.parallel.ring_attention import ring_attention

    return _forward(
        conf, params, x,
        lambda q, k, v: ring_attention(q, k, v, mesh, axis,
                                       causal=conf.causal),
    )
