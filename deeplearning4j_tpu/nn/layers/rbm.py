"""Restricted Boltzmann Machine with CD-k contrastive divergence.

Parity with ref: nn/layers/feedforward/rbm/RBM.java — propUp/propDown
(:318,:351), unit-type sampling (BINARY/GAUSSIAN/RECTIFIED/SOFTMAX hidden,
BINARY/GAUSSIAN/LINEAR/SOFTMAX visible, :217-:310), Gibbs chain gibbhVh
(:266), CD-k gradient (:111-191).

TPU-first: the Gibbs chain is a ``lax.scan`` with explicitly threaded PRNG
keys (the reference mutates a shared RNG in place); the CD gradient is the
standard positive-minus-negative sufficient statistics, batched on the MXU.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.api import HiddenUnit, VisibleUnit
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.params import BIAS_KEY, VISIBLE_BIAS_KEY, WEIGHT_KEY

Array = jax.Array
Params = Dict[str, Array]


def prop_up(conf: NeuralNetConfiguration, params: Params, v: Array) -> Array:
    """Hidden mean given visible (ref: RBM.java:318 propUp)."""
    pre = v @ params[WEIGHT_KEY] + params[BIAS_KEY]
    h = conf.hidden_unit
    if h == HiddenUnit.RECTIFIED:
        return jnp.maximum(pre, 0.0)
    if h == HiddenUnit.BINARY:
        return jax.nn.sigmoid(pre)
    if h == HiddenUnit.SOFTMAX:
        return jax.nn.softmax(pre, axis=-1)
    if h == HiddenUnit.GAUSSIAN:
        return pre
    raise ValueError(f"Unhandled hidden unit {h}")


def prop_down(conf: NeuralNetConfiguration, params: Params, h: Array) -> Array:
    """Visible mean given hidden (ref: RBM.java:351 propDown)."""
    pre = h @ params[WEIGHT_KEY].T + params[VISIBLE_BIAS_KEY]
    v = conf.visible_unit
    if v == VisibleUnit.BINARY:
        return jax.nn.sigmoid(pre)
    if v == VisibleUnit.SOFTMAX:
        return jax.nn.softmax(pre, axis=-1)
    if v in (VisibleUnit.GAUSSIAN, VisibleUnit.LINEAR):
        return pre
    raise ValueError(f"Unhandled visible unit {v}")


def sample_hidden_given_visible(
    conf: NeuralNetConfiguration, params: Params, v: Array, key: Array
) -> Tuple[Array, Array]:
    """(mean, sample) (ref: RBM.java:217)."""
    mean = prop_up(conf, params, v)
    h = conf.hidden_unit
    if h == HiddenUnit.BINARY:
        sample = jax.random.bernoulli(key, mean).astype(mean.dtype)
    elif h == HiddenUnit.GAUSSIAN:
        sample = mean + jax.random.normal(key, mean.shape, mean.dtype)
    elif h == HiddenUnit.RECTIFIED:
        # noisy ReLU: mean + N(0,1)*sqrt(sigmoid(mean)), clipped at 0
        noise = jax.random.normal(key, mean.shape, mean.dtype)
        sample = jnp.maximum(mean + noise * jnp.sqrt(jax.nn.sigmoid(mean)), 0.0)
    elif h == HiddenUnit.SOFTMAX:
        sample = mean
    else:
        raise ValueError(f"Unhandled hidden unit {h}")
    return mean, sample


def sample_visible_given_hidden(
    conf: NeuralNetConfiguration, params: Params, h: Array, key: Array
) -> Tuple[Array, Array]:
    """(mean, sample) (ref: RBM.java sampleVisibleGivenHidden)."""
    mean = prop_down(conf, params, h)
    v = conf.visible_unit
    if v == VisibleUnit.BINARY:
        sample = jax.random.bernoulli(key, mean).astype(mean.dtype)
    elif v in (VisibleUnit.GAUSSIAN, VisibleUnit.LINEAR):
        sample = mean + jax.random.normal(key, mean.shape, mean.dtype)
    elif v == VisibleUnit.SOFTMAX:
        sample = mean
    else:
        raise ValueError(f"Unhandled visible unit {v}")
    return mean, sample


def contrastive_divergence(
    conf: NeuralNetConfiguration, params: Params, v0: Array, key: Array
) -> Dict[str, Array]:
    """CD-k gradient (to be *descended*): negative-phase minus positive-phase
    statistics, ÷ batch. (ref: RBM.java:111-191 gradient().)"""
    k0, kscan = jax.random.split(key)
    h0_mean, h0_sample = sample_hidden_given_visible(conf, params, v0, k0)

    def gibbs_step(carry, step_key):
        h_sample = carry
        kv, kh = jax.random.split(step_key)
        _, v_sample = sample_visible_given_hidden(conf, params, h_sample, kv)
        h_mean, h_sample = sample_hidden_given_visible(conf, params, v_sample, kh)
        return h_sample, (v_sample, h_mean)

    keys = jax.random.split(kscan, max(conf.k, 1))
    _, (v_chain, h_chain) = jax.lax.scan(gibbs_step, h0_sample, keys)
    vk, hk_mean = v_chain[-1], h_chain[-1]

    n = v0.shape[0]
    w_grad = (vk.T @ hk_mean - v0.T @ h0_mean) / n
    hb_grad = jnp.mean(hk_mean - h0_mean, axis=0)
    vb_grad = jnp.mean(vk - v0, axis=0)
    if conf.apply_sparsity and conf.sparsity > 0:
        # push hidden biases toward sparse activations (ref:
        # BasePretrainNetwork.applySparsity on the hidden-bias gradient)
        hb_grad = hb_grad + conf.sparsity * jnp.mean(h0_mean, axis=0)
    return {WEIGHT_KEY: w_grad, BIAS_KEY: hb_grad, VISIBLE_BIAS_KEY: vb_grad}


def free_energy(conf: NeuralNetConfiguration, params: Params, v: Array) -> Array:
    """Mean free energy; used as the RBM score (lower = better fit)."""
    pre = v @ params[WEIGHT_KEY] + params[BIAS_KEY]
    vbias_term = v @ params[VISIBLE_BIAS_KEY]
    hidden_term = jnp.sum(jax.nn.softplus(pre), axis=-1)
    return jnp.mean(-hidden_term - vbias_term)


def reconstruction_error(conf: NeuralNetConfiguration, params: Params, v: Array) -> Array:
    """Cross-entropy between input and its one-step reconstruction — the
    score the reference reports during pretraining."""
    recon = prop_down(conf, params, prop_up(conf, params, v))
    eps = 1e-7
    p = jnp.clip(recon, eps, 1 - eps)
    if conf.visible_unit == VisibleUnit.BINARY:
        return -jnp.mean(jnp.sum(v * jnp.log(p) + (1 - v) * jnp.log(1 - p), axis=-1))
    return jnp.mean(jnp.sum((v - recon) ** 2, axis=-1))


def forward(
    conf: NeuralNetConfiguration,
    params: Params,
    x: Array,
    *,
    train: bool = False,
    key: Optional[Array] = None,
) -> Array:
    return prop_up(conf, params, x)
