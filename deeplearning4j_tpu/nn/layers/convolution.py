"""Convolution layer.

The reference loops ``Nd4j.getConvolution().convn(input, filter, VALID)`` per
feature map (ref: nn/layers/convolution/ConvolutionLayer.java:115-128). Here
the whole layer runs as ONE im2col matmul on the MXU: patches are gathered by
stacking KH*KW static slices of the input and contracted against the filter
bank with an einsum. External layout stays NCHW / OIHW (ref parameter
conventions, ``nn/params.py``), VALID padding to match the reference.

im2col rather than ``lax.conv_general_dilated`` is deliberate: forward conv
compiles fine everywhere, but the *weight-gradient* convolution XLA derives
from a conv op wedges the axon TPU compiler (>150 s for a single LeNet-sized
layer, measured round 3 — the round-2 bench timeout). Slice+einsum
differentiates into pads and matmuls only, compiling in ~1 s and keeping both
passes on the MXU. The extra patch buffer is B*C*KH*KW*H'*W' — ~20 MB at
LeNet scale, negligible next to HBM.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.params import CONV_BIAS_KEY, CONV_WEIGHT_KEY
from deeplearning4j_tpu.ops.activations import activation


def im2col_conv(x: jax.Array, w: jax.Array) -> jax.Array:
    """VALID stride-1 conv: x (B,C,H,W) * w (O,C,KH,KW) -> (B,O,H',W')."""
    o, c, kh, kw = w.shape
    h_out = x.shape[2] - kh + 1
    w_out = x.shape[3] - kw + 1
    cols = jnp.stack(
        [
            x[:, :, i : i + h_out, j : j + w_out]
            for i in range(kh)
            for j in range(kw)
        ],
        axis=2,
    )  # (B, C, KH*KW, H', W')
    return jnp.einsum("bckhw,ock->bohw", cols, w.reshape(o, c, kh * kw))


def forward(
    conf: NeuralNetConfiguration,
    params: Dict[str, jax.Array],
    x: jax.Array,
    *,
    train: bool = False,
    key: Optional[jax.Array] = None,
) -> jax.Array:
    w = params[CONV_WEIGHT_KEY]
    b = params[CONV_BIAS_KEY]
    # the weights set the compute dtype: under a bf16 policy the conv runs on
    # the bf16 MXU path (the MXU still accumulates in f32 internally)
    out = im2col_conv(x.astype(w.dtype), w)
    out = out + b[None, :, None, None]
    return activation(conf.activation_function)(out)
