"""Convolution layer.

The reference loops ``Nd4j.getConvolution().convn(input, filter, VALID)`` per
feature map (ref: nn/layers/convolution/ConvolutionLayer.java:115-128). Here
the whole layer runs as ONE conv on the MXU — ``lax.conv_general_dilated``
for wide contractions, im2col slice+einsum for narrow ones — NCHW / OIHW
layout (ref parameter conventions, ``nn/params.py``), VALID padding to match
the reference.

History: rounds 2-4 used im2col everywhere because the weight-gradient
convolution XLA derives from ``conv_general_dilated`` wedged the axon TPU
compiler (>150 s for one LeNet-sized layer, measured round 3). Round 5
re-measured (VERDICT r04 next-step #3): at WIDE shapes the wedge is gone
(conv_wide grad convs compile in ~4 s) and the conv emitter beats im2col
by 4.4x on the HBM-bound first conv_wide layer — im2col materialized a
B*C*KH*KW*H'*W' patch buffer (~80 MB/pass at conv_wide's 32ch 32x32 input)
in forward AND both backward passes, while the conv emitter streams patches
through VMEM. Measured per-layer train-step MFU (B=64, bf16, grads wrt both
w and x): conv1 32->128ch 0.12 -> 0.52, conv2 128->128ch 0.49 -> 0.72;
end-to-end conv_wide stage 2.12x. At NARROW shapes the slow compile is
still real (12-16 s per LeNet grad conv, >300 s for the bench stage), so
``conv2d`` gates on contraction width — see ``_EMITTER_MIN_CONTRACTION``.
``im2col_conv`` stays as the narrow-shape path and the parity oracle.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.params import CONV_BIAS_KEY, CONV_WEIGHT_KEY
from deeplearning4j_tpu.ops.activations import activation

# A/B switch for bench attribution (None = shape-gated auto, see conv2d;
# True forces the conv emitter, False forces the legacy im2col formulation)
_use_conv_emitter: "bool | None" = None

# auto gate: the conv emitter wins when the im2col contraction (C*KH*KW)
# is wide enough to make the patch buffer HBM traffic dominate (measured
# 4.4x at conv_wide's 800-wide conv1); below it im2col compiles in ~1 s
# while the axon conv emitter's grad convolutions take 12-16 s per layer
# at LeNet shapes (>300 s for the whole bench stage) for compute that is
# model-bound either way (LeNet 0.0116 MFU documented r04)
_EMITTER_MIN_CONTRACTION = 512


def set_conv_emitter(enabled: "bool | None") -> None:
    global _use_conv_emitter
    _use_conv_emitter = enabled


def im2col_conv(x: jax.Array, w: jax.Array) -> jax.Array:
    """VALID stride-1 conv via im2col: x (B,C,H,W) * w (O,C,KH,KW) ->
    (B,O,H',W'). Legacy core (see module docstring) — differentiates into
    pads and matmuls only; parity oracle for conv2d."""
    o, c, kh, kw = w.shape
    h_out = x.shape[2] - kh + 1
    w_out = x.shape[3] - kw + 1
    cols = jnp.stack(
        [
            x[:, :, i : i + h_out, j : j + w_out]
            for i in range(kh)
            for j in range(kw)
        ],
        axis=2,
    )  # (B, C, KH*KW, H', W')
    return jnp.einsum("bckhw,ock->bohw", cols, w.reshape(o, c, kh * kw))


def conv2d(x: jax.Array, w: jax.Array) -> jax.Array:
    """VALID stride-1 conv: x (B,C,H,W) * w (O,C,KH,KW) -> (B,O,H',W')."""
    o, c, kh, kw = w.shape
    use_emitter = c * kh * kw >= _EMITTER_MIN_CONTRACTION
    if _use_conv_emitter is not None:
        use_emitter = _use_conv_emitter
    if not use_emitter:
        return im2col_conv(x, w)
    return lax.conv_general_dilated(
        x, w, (1, 1), "VALID", dimension_numbers=("NCHW", "OIHW", "NCHW"))


def forward(
    conf: NeuralNetConfiguration,
    params: Dict[str, jax.Array],
    x: jax.Array,
    *,
    train: bool = False,
    key: Optional[jax.Array] = None,
) -> jax.Array:
    w = params[CONV_WEIGHT_KEY]
    b = params[CONV_BIAS_KEY]
    # the weights set the compute dtype: under a bf16 policy the conv runs on
    # the bf16 MXU path (the MXU still accumulates in f32 internally)
    out = conv2d(x.astype(w.dtype), w)
    out = out + b[None, :, None, None]
    return activation(conf.activation_function)(out)
