"""Convolution layer.

The reference loops ``Nd4j.getConvolution().convn(input, filter, VALID)`` per
feature map (ref: nn/layers/convolution/ConvolutionLayer.java:115-128). Here a
single batched ``lax.conv_general_dilated`` maps the whole layer onto the MXU
(XLA lowers it to im2col+matmul or direct conv as it sees fit). Layout NCHW,
filters OIHW, VALID padding to match the reference.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.params import CONV_BIAS_KEY, CONV_WEIGHT_KEY
from deeplearning4j_tpu.ops.activations import activation


def forward(
    conf: NeuralNetConfiguration,
    params: Dict[str, jax.Array],
    x: jax.Array,
    *,
    train: bool = False,
    key: Optional[jax.Array] = None,
) -> jax.Array:
    w = params[CONV_WEIGHT_KEY]
    b = params[CONV_BIAS_KEY]
    # the weights set the compute dtype: under a bf16 policy the conv runs on
    # the bf16 MXU path (the MXU still accumulates in f32 internally)
    out = lax.conv_general_dilated(
        x.astype(w.dtype),
        w,
        window_strides=(1, 1),
        padding="VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )
    out = out + b[None, :, None, None]
    return activation(conf.activation_function)(out)
