"""Denoising AutoEncoder.

Parity with ref: nn/layers/feedforward/autoencoder/AutoEncoder.java:64-96 —
encode = act(x·W + b), decode = act(h·Wᵀ + vb) (tied weights), corrupted input
via binomial masking at conf.corruption_level. The pretrain objective is the
configured loss (default RECONSTRUCTION_CROSSENTROPY) differentiated by
jax.grad instead of the reference's hand-derived gradient.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.params import BIAS_KEY, VISIBLE_BIAS_KEY, WEIGHT_KEY
from deeplearning4j_tpu.ops.activations import activation
from deeplearning4j_tpu.ops.losses import loss


def get_corrupted_input(key: jax.Array, x: jax.Array, corruption_level: float):
    """Masking noise: zero each input element w.p. corruption_level
    (ref: AutoEncoder.java getCorruptedInput)."""
    keep = jax.random.bernoulli(key, 1.0 - corruption_level, x.shape)
    return x * keep.astype(x.dtype)


def encode(conf: NeuralNetConfiguration, params: Dict[str, jax.Array], x: jax.Array):
    act = activation(conf.activation_function)
    return act(x @ params[WEIGHT_KEY] + params[BIAS_KEY])


def decode(conf: NeuralNetConfiguration, params: Dict[str, jax.Array], h: jax.Array):
    act = activation(conf.activation_function)
    return act(h @ params[WEIGHT_KEY].T + params[VISIBLE_BIAS_KEY])


def forward(
    conf: NeuralNetConfiguration,
    params: Dict[str, jax.Array],
    x: jax.Array,
    *,
    train: bool = False,
    key: Optional[jax.Array] = None,
) -> jax.Array:
    return encode(conf, params, x)


def pretrain_loss(
    conf: NeuralNetConfiguration,
    params: Dict[str, jax.Array],
    x: jax.Array,
    key: jax.Array,
) -> jax.Array:
    corrupted = get_corrupted_input(key, x, conf.corruption_level)
    h = encode(conf, params, corrupted)
    recon = decode(conf, params, h)
    total = loss(conf.loss_function, x, recon)
    if conf.apply_sparsity and conf.sparsity > 0:
        # activation-sparsity penalty (ref: BasePretrainNetwork applySparsity;
        # realized here as an L1 penalty on mean hidden activation)
        total = total + conf.sparsity * jnp.mean(jnp.abs(h))
    return total
