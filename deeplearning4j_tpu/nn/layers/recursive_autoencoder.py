"""Recursive AutoEncoder.

Parity with ref nn/layers/feedforward/recursive/RecursiveAutoEncoder.java
(148 LoC): rows of the input are folded left-to-right — at each step the
running parent vector is concatenated with the next row, encoded with
c = f(W·[parent; xᵢ] + b), decoded back with the transposed weights, and the
reconstruction errors accumulate into the pretrain loss.

TPU-first: the fold is a single ``lax.scan`` over the row axis (the reference
loops rows in Java, re-entering ND4J per step); jax.grad differentiates the
whole chain instead of the reference's hand-derived combined gradient.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.params import BIAS_KEY, VISIBLE_BIAS_KEY, WEIGHT_KEY
from deeplearning4j_tpu.ops.activations import activation

Array = jax.Array


def _fold(conf: NeuralNetConfiguration, params: Dict[str, Array], x: Array):
    """Scan the rows; returns (final parent (H,), per-step losses (N-1,)).

    W: (in + hidden, hidden) combines [xᵢ; parent] → hidden; decode uses Wᵀ.
    The first parent is x₀ projected through the x-block of W.
    """
    act = activation(conf.activation_function)
    w, b = params[WEIGHT_KEY], params[BIAS_KEY]
    vb = params[VISIBLE_BIAS_KEY]
    n_in = x.shape[1]

    parent0 = act(x[0] @ w[:n_in] + b)

    def step(parent, xi):
        joint = jnp.concatenate([xi, parent])            # (in + hidden,)
        c = act(joint @ w + b)                           # (hidden,)
        recon = act(c @ w.T + vb)                        # (in + hidden,)
        loss = ((recon - joint) ** 2).sum()
        return c, loss

    parent, losses = jax.lax.scan(step, parent0, x[1:])
    return parent, losses


def pretrain_loss(conf: NeuralNetConfiguration, params: Dict[str, Array],
                  x: Array, key: Array) -> Array:
    """Mean reconstruction error of the fold (ref scores the summed
    reconstruction error across combine steps)."""
    _, losses = _fold(conf, params, x)
    return losses.mean() if losses.shape[0] else jnp.float32(0.0)


def forward(conf: NeuralNetConfiguration, params: Dict[str, Array],
            x: Array, *, train: bool = False, key=None) -> Array:
    """Feed-forward view: encode each row independently through the x-block
    (so the layer composes in a stack like the reference, which reuses the
    encoded activations downstream)."""
    act = activation(conf.activation_function)
    n_in = x.shape[1]
    return act(x @ params[WEIGHT_KEY][:n_in] + params[BIAS_KEY])


def encode_sequence(conf: NeuralNetConfiguration, params: Dict[str, Array],
                    x: Array) -> Array:
    """Final parent vector of the whole sequence (the tree-root embedding)."""
    parent, _ = _fold(conf, params, jnp.asarray(x))
    return parent
