"""Input pre-processors between layers.

Parity with ref: nn/conf/preprocessor/ — reshape, zero-mean, unit-variance,
binomial sampling — plus the conv↔feed-forward reshapers the LeNet stack
needs. Registered by string name so MultiLayerConfiguration JSON round-trips.
"""

from __future__ import annotations

import math
from typing import Callable, Dict

import jax
import jax.numpy as jnp

Array = jax.Array

_REGISTRY: Dict[str, Callable[[Array], Array]] = {}


def register(name: str):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn

    return deco


@register("zero_mean")
def zero_mean(x: Array) -> Array:
    return x - jnp.mean(x, axis=0, keepdims=True)


@register("zero_mean_unit_variance")
def zero_mean_unit_variance(x: Array) -> Array:
    mu = jnp.mean(x, axis=0, keepdims=True)
    sd = jnp.std(x, axis=0, keepdims=True)
    return (x - mu) / (sd + 1e-6)


@register("unit_variance")
def unit_variance(x: Array) -> Array:
    return x / (jnp.std(x, axis=0, keepdims=True) + 1e-6)


@register("ff_to_conv")
def ff_to_conv(x: Array) -> Array:
    """(batch, d) → (batch, 1, s, s) assuming square single-channel images."""
    side = int(math.isqrt(x.shape[-1]))
    return x.reshape(x.shape[0], 1, side, side)


@register("conv_to_ff")
def conv_to_ff(x: Array) -> Array:
    """(batch, c, h, w) → (batch, c*h*w)."""
    return x.reshape(x.shape[0], -1)


def preprocessor(name: str) -> Callable[[Array], Array]:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise ValueError(f"Unknown preprocessor '{name}'. Known: {sorted(_REGISTRY)}") from None
