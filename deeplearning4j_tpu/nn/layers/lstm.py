"""LSTM layer (Karpathy-style fused-gate char-LSTM).

Parity with ref: nn/layers/recurrent/LSTM.java:54-160 — a single recurrent
matrix maps [1 | x_t | h_{t-1}] to the fused i,f,o,g gate buffer ("iFog"),
cell update c_t = f⊙c_{t-1} + i⊙g, h_t = o⊙tanh(c_t), then a decoder
projection to the output.

TPU-first: the reference's manual Java loop over time slices (and its
hand-written BPTT at LSTM.java backward()) becomes one ``lax.scan`` whose
gradient is derived by jax.grad — XLA unrolls/pipelines the scan and keeps the
(batch, 4*hidden) gate matmuls on the MXU. Input layout: (batch, time, n_in).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.ops.pallas_kernels import lstm_gates
from deeplearning4j_tpu.nn.params import (
    DECODER_BIAS_KEY,
    DECODER_WEIGHT_KEY,
    RECURRENT_WEIGHT_KEY,
)

Array = jax.Array


def hidden_sequence(
    conf: NeuralNetConfiguration, params: Dict[str, Array], x: Array
) -> Array:
    """Run the recurrence; returns h for every timestep: (batch, time, hidden)."""
    if x.ndim == 2:  # single sequence (time, n_in) → add batch axis
        x = x[None]
    w = params[RECURRENT_WEIGHT_KEY]
    batch = x.shape[0]
    hidden = conf.n_out
    ones = jnp.ones((batch, 1), x.dtype)

    def step(carry, x_t):
        h_prev, c_prev = carry
        h_in = jnp.concatenate([ones, x_t, h_prev], axis=-1)
        gates = h_in @ w
        # fused i/f/o/g cell kernel (pallas on TPU, lax elsewhere)
        c, h = lstm_gates(gates, c_prev)
        return (h, c), h

    zeros = jnp.zeros((batch, hidden), x.dtype)
    xs = jnp.swapaxes(x, 0, 1)  # (time, batch, n_in) for scan
    _, hs = jax.lax.scan(step, (zeros, zeros), xs)
    return jnp.swapaxes(hs, 0, 1)


def forward(
    conf: NeuralNetConfiguration,
    params: Dict[str, Array],
    x: Array,
    *,
    train: bool = False,
    key: Optional[Array] = None,
) -> Array:
    """Decoded output per timestep (ref: LSTM.activate decoder projection)."""
    hs = hidden_sequence(conf, params, x)
    return hs @ params[DECODER_WEIGHT_KEY] + params[DECODER_BIAS_KEY]
