"""Output (classification/regression) layer.

Parity with ref: nn/layers/OutputLayer.java — softmax/sigmoid head whose
gradient is the label-error outer product (OutputLayer.java:98-117). Here the
loss is differentiated by jax.grad; for the softmax+MCXENT / sigmoid+XENT
pairs the fused log-softmax path is used so XLA folds it into the matmul.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers.dense import apply_dropout, pre_output
from deeplearning4j_tpu.ops.activations import activation
from deeplearning4j_tpu.ops.losses import (
    FUSABLE,
    finalize_loss,
    per_example_loss,
    per_example_loss_from_logits,
)


def forward(
    conf: NeuralNetConfiguration,
    params: Dict[str, jax.Array],
    x: jax.Array,
    *,
    train: bool = False,
    key: Optional[jax.Array] = None,
    drop_connect: bool = False,
) -> jax.Array:
    kdrop = kdc = None
    if key is not None:
        kdrop, kdc = jax.random.split(key)
    x = apply_dropout(x, conf.dropout, train, kdrop)
    pre = pre_output(conf, params, x, train=train, key=kdc, drop_connect=drop_connect)
    return activation(conf.activation_function)(pre)


def output_loss(
    conf: NeuralNetConfiguration,
    params: Dict[str, jax.Array],
    x: jax.Array,
    labels: jax.Array,
    *,
    train: bool = False,
    key: Optional[jax.Array] = None,
    drop_connect: bool = False,
) -> jax.Array:
    """Scalar training loss for the head (ref: OutputLayer.score())."""
    per = output_per_example_loss(conf, params, x, labels, train=train,
                                  key=key, drop_connect=drop_connect)
    return finalize_loss(conf.loss_function, jnp.mean(per))


def output_per_example_loss(
    conf: NeuralNetConfiguration,
    params: Dict[str, jax.Array],
    x: jax.Array,
    labels: jax.Array,
    *,
    train: bool = False,
    key: Optional[jax.Array] = None,
    drop_connect: bool = False,
) -> jax.Array:
    """Per-example pre-reduction losses, shape (batch,).

    Scalar loss = ops.losses.finalize_loss(conf.loss_function, weighted mean);
    keeping rows separate lets data-parallel callers mask padded rows and
    normalize across shards exactly.
    """
    kdrop = kdc = None
    if key is not None:
        kdrop, kdc = jax.random.split(key)
    x = apply_dropout(x, conf.dropout, train, kdrop)
    logits = pre_output(conf, params, x, train=train, key=kdc, drop_connect=drop_connect)
    logits = logits.astype(jnp.float32)
    labels = labels.astype(jnp.float32)
    if (conf.activation_function, conf.loss_function) in FUSABLE:
        return per_example_loss_from_logits(conf.loss_function, labels, logits)
    out = activation(conf.activation_function)(logits)
    return per_example_loss(conf.loss_function, labels, out)
