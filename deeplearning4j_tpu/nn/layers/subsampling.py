"""Subsampling (pooling) layer.

Parity with ref: nn/layers/convolution/subsampling/SubsamplingLayer.java:114-155
— downsampling by conf.stride with MAX/SUM/AVG/NONE pooling
(ConvolutionType, ref: ConvolutionLayer.ConvolutionType). Implemented with
``lax.reduce_window`` so XLA fuses it; the reference's hand-written rot+FULL-conv
backward is replaced by autodiff.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.api import ConvolutionType
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration


def forward(
    conf: NeuralNetConfiguration,
    params: Dict[str, jax.Array],
    x: jax.Array,
    *,
    train: bool = False,
    key: Optional[jax.Array] = None,
) -> jax.Array:
    if conf.convolution_type == ConvolutionType.NONE:
        return x
    sh, sw = conf.stride[-2], conf.stride[-1]
    window = (1, 1, sh, sw)
    strides = (1, 1, sh, sw)
    if conf.convolution_type == ConvolutionType.MAX:
        return lax.reduce_window(x, -jnp.inf, lax.max, window, strides, "VALID")
    summed = lax.reduce_window(x, 0.0, lax.add, window, strides, "VALID")
    if conf.convolution_type == ConvolutionType.SUM:
        return summed
    if conf.convolution_type == ConvolutionType.AVG:
        return summed / float(sh * sw)
    raise ValueError(f"Unhandled pooling type {conf.convolution_type}")
