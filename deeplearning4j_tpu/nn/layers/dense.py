"""Dense (fully-connected) layer.

Parity with the reference's BaseLayer: preOutput = x·W + b
(ref: nn/layers/BaseLayer.java:272-281), activation via the registry
(ref: BaseLayer.java:294), inverted-dropout masking during training
(ref: BaseLayer.java:333 applyDropOutIfNecessary).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.params import BIAS_KEY, WEIGHT_KEY
from deeplearning4j_tpu.ops.activations import activation
from deeplearning4j_tpu.ops.pallas_kernels import (
    _FUSABLE,
    fused_dense,
    use_fused_dense,
)


_DROP_CONNECT_KEEP = 0.5  # ref BaseLayer drop-connect keeps weights w.p. 0.5


def pre_output(
    conf: NeuralNetConfiguration,
    params: Dict[str, jax.Array],
    x: jax.Array,
    *,
    train: bool = False,
    key: Optional[jax.Array] = None,
    drop_connect: bool = False,
):
    w = params[WEIGHT_KEY]
    if drop_connect and train and key is not None:
        # inverted drop-connect on the weight matrix (ref: BaseLayer.preOutput
        # conf.isUseDropConnect branch)
        mask = jax.random.bernoulli(key, _DROP_CONNECT_KEEP, w.shape)
        w = jnp.where(mask, w / _DROP_CONNECT_KEEP, 0.0)
    return x @ w + params[BIAS_KEY]


def apply_dropout(x: jax.Array, rate: float, train: bool, key: Optional[jax.Array]):
    if not train or rate <= 0.0 or key is None:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0)


def forward(
    conf: NeuralNetConfiguration,
    params: Dict[str, jax.Array],
    x: jax.Array,
    *,
    train: bool = False,
    key: Optional[jax.Array] = None,
    drop_connect: bool = False,
) -> jax.Array:
    kdrop = kdc = None
    if key is not None:
        kdrop, kdc = jax.random.split(key)
    x = apply_dropout(x, conf.dropout, train, kdrop)
    # fused matmul+bias+activation kernel when enabled (see
    # pallas_kernels.use_fused_dense for the sharding rationale); the masked
    # (drop-connect) pre_output variant keeps the unfused route
    if (x.ndim == 2  # the fused kernel + its VJP are (batch, features) only
            and not (drop_connect and train)
            and conf.activation_function in _FUSABLE
            and use_fused_dense()):
        return fused_dense(x, params[WEIGHT_KEY], params[BIAS_KEY],
                           conf.activation_function)
    pre = pre_output(conf, params, x, train=train, key=kdc, drop_connect=drop_connect)
    return activation(conf.activation_function)(pre)
