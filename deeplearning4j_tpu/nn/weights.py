"""Weight initialization schemes.

Parity with the reference's ``WeightInit`` enum and ``WeightInitUtil`` switch
(ref: nn/weights/WeightInit.java:25-38, nn/weights/WeightInitUtil.java:78-100):

- NORMALIZED: U(0,1) - 0.5, divided by fan-in
- UNIFORM:    U(-1/fanIn, 1/fanIn)
- VI:         U(-r, r) with r = sqrt(6)/sqrt(sum(shape)+1)
- SIZE:       U(-s, s) with s = sqrt(6/(fanIn+fanOut))
- DISTRIBUTION: sample from a configured distribution
- ZERO:       zeros
"""

from __future__ import annotations

import enum
import math
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


class WeightInit(str, enum.Enum):
    DISTRIBUTION = "DISTRIBUTION"
    NORMALIZED = "NORMALIZED"
    SIZE = "SIZE"
    UNIFORM = "UNIFORM"
    VI = "VI"
    ZERO = "ZERO"

    @classmethod
    def coerce(cls, v) -> "WeightInit":
        return v if isinstance(v, cls) else cls(str(v).upper())


# A configured distribution is ("normal", mean, std) or ("uniform", lo, hi) —
# the serializable analogue of the reference's nn/conf/distribution classes.
Distribution = Tuple[str, float, float]


def sample_distribution(key: jax.Array, dist: Distribution, shape: Sequence[int]):
    kind, a, b = dist
    if kind == "normal":
        return a + b * jax.random.normal(key, shape)
    if kind == "uniform":
        return jax.random.uniform(key, shape, minval=a, maxval=b)
    raise ValueError(f"Unknown distribution kind '{kind}'")


def init_weights(
    key: jax.Array,
    shape: Sequence[int],
    scheme: "WeightInit | str",
    dist: Optional[Distribution] = None,
    dtype=jnp.float32,
) -> jax.Array:
    scheme = WeightInit.coerce(scheme)
    shape = tuple(int(s) for s in shape)
    fan_in = shape[0]
    if scheme == WeightInit.ZERO:
        return jnp.zeros(shape, dtype)
    if scheme == WeightInit.NORMALIZED:
        u = jax.random.uniform(key, shape, dtype)
        return (u - 0.5) / fan_in
    if scheme == WeightInit.UNIFORM:
        a = 1.0 / fan_in
        return jax.random.uniform(key, shape, dtype, minval=-a, maxval=a)
    if scheme == WeightInit.VI:
        r = math.sqrt(6.0) / math.sqrt(sum(shape) + 1.0)
        return jax.random.uniform(key, shape, dtype, minval=-r, maxval=r)
    if scheme == WeightInit.SIZE:
        fan_out = shape[1] if len(shape) > 1 else shape[0]
        s = math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, minval=-s, maxval=s)
    if scheme == WeightInit.DISTRIBUTION:
        if dist is None:
            dist = ("normal", 0.0, 0.01)
        return sample_distribution(key, dist, shape).astype(dtype)
    raise ValueError(f"Unhandled weight init {scheme}")
