"""Gradient container.

Parity with ref: nn/gradient/DefaultGradient.java — an ordered map of
variable name → array. In JAX a gradient is just a pytree matching the params
pytree, so this is a thin dict alias plus flattening helpers used by the
flat-param-vector API (ref: MultiLayerNetwork.java:744-835 pack/unPack).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array
# params for one layer: {"W": ..., "b": ...}; for a network: tuple of those
LayerParams = Dict[str, Array]
NetParams = Tuple[LayerParams, ...]


def flatten_params(params) -> Array:
    """Pack a params pytree into one flat vector (ref: params()/pack)."""
    leaves = jax.tree_util.tree_leaves(params)
    if not leaves:
        return jnp.zeros((0,))
    return jnp.concatenate([jnp.ravel(leaf) for leaf in leaves])


def unflatten_params(template, flat: Array):
    """Unpack a flat vector into the shape of `template` (ref: setParams/unPack)."""
    leaves, treedef = jax.tree_util.tree_flatten(template)
    expected = sum(leaf.size for leaf in leaves)
    if flat.ndim != 1 or flat.shape[0] != expected:
        raise ValueError(
            f"Parameter vector of shape {flat.shape} does not match the "
            f"network's {expected} parameters"
        )
    out: List[Array] = []
    offset = 0
    for leaf in leaves:
        n = leaf.size
        out.append(jnp.reshape(flat[offset : offset + n], leaf.shape).astype(leaf.dtype))
        offset += n
    return jax.tree_util.tree_unflatten(treedef, out)


def num_params(params) -> int:
    return sum(leaf.size for leaf in jax.tree_util.tree_leaves(params))
