"""Core API enums and protocols.

Parity with the reference's ``nn/api`` package:
- ``Model``/``Layer``/``Classifier`` contracts (ref: nn/api/Model.java:36,
  nn/api/Layer.java:37) — realized here as the stateful facade
  ``MultiLayerNetwork`` over pure JAX functions.
- ``OptimizationAlgorithm`` enum (ref: nn/api/OptimizationAlgorithm.java).
- ``LayerType`` replaces the reference's layer-class + LayerFactory dispatch
  (ref: nn/layers/factory/LayerFactories.java).
"""

from __future__ import annotations

import enum


class OptimizationAlgorithm(str, enum.Enum):
    GRADIENT_DESCENT = "GRADIENT_DESCENT"
    CONJUGATE_GRADIENT = "CONJUGATE_GRADIENT"
    HESSIAN_FREE = "HESSIAN_FREE"
    LBFGS = "LBFGS"
    ITERATION_GRADIENT_DESCENT = "ITERATION_GRADIENT_DESCENT"

    @classmethod
    def coerce(cls, v) -> "OptimizationAlgorithm":
        return v if isinstance(v, cls) else cls(str(v))


class LayerType(str, enum.Enum):
    """Which layer implementation a NeuralNetConfiguration instantiates."""

    DENSE = "DENSE"
    OUTPUT = "OUTPUT"
    RBM = "RBM"
    AUTOENCODER = "AUTOENCODER"
    RECURSIVE_AUTOENCODER = "RECURSIVE_AUTOENCODER"
    CONVOLUTION = "CONVOLUTION"
    SUBSAMPLING = "SUBSAMPLING"
    LSTM = "LSTM"
    ATTENTION = "ATTENTION"

    @classmethod
    def coerce(cls, v) -> "LayerType":
        return v if isinstance(v, cls) else cls(str(v).upper())


class VisibleUnit(str, enum.Enum):
    """RBM visible unit types (ref: nn/layers/feedforward/rbm/RBM.java)."""

    BINARY = "BINARY"
    GAUSSIAN = "GAUSSIAN"
    SOFTMAX = "SOFTMAX"
    LINEAR = "LINEAR"

    @classmethod
    def coerce(cls, v) -> "VisibleUnit":
        return v if isinstance(v, cls) else cls(str(v).upper())


class HiddenUnit(str, enum.Enum):
    """RBM hidden unit types (ref: RBM.java:217 sampleHiddenGivenVisible)."""

    BINARY = "BINARY"
    GAUSSIAN = "GAUSSIAN"
    SOFTMAX = "SOFTMAX"
    RECTIFIED = "RECTIFIED"

    @classmethod
    def coerce(cls, v) -> "HiddenUnit":
        return v if isinstance(v, cls) else cls(str(v).upper())


class ConvolutionType(str, enum.Enum):
    """Subsampling pooling type (ref: ConvolutionLayer.ConvolutionType)."""

    MAX = "MAX"
    SUM = "SUM"
    AVG = "AVG"
    NONE = "NONE"

    @classmethod
    def coerce(cls, v) -> "ConvolutionType":
        return v if isinstance(v, cls) else cls(str(v).upper())
