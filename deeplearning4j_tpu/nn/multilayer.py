"""MultiLayerNetwork — the network container.

API parity with ref: nn/multilayer/MultiLayerNetwork.java:63 —
init/pretrain/finetune/fit/feedForward/output/predict/score/params/setParams/
merge/clone, plus JSON conf round-trip and save/load of (conf JSON + flat
param vector), matching the reference checkpoint format
(MultiLayerNetwork(String conf, INDArray params) ctor at :99).

Internally everything is the pure-functional core in nn/functional.py; this
class only owns state (params pytree, updater state, RNG keys) and the
host-side training loops.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import DataSetIterator, ListDataSetIterator
from deeplearning4j_tpu.nn import functional as F
from deeplearning4j_tpu.nn.api import LayerType, OptimizationAlgorithm
from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration, NeuralNetConfiguration
from deeplearning4j_tpu.nn.gradient import flatten_params, num_params, unflatten_params
from deeplearning4j_tpu.nn.layers import autoencoder as ae_ops
from deeplearning4j_tpu.nn.layers import recursive_autoencoder as rae_ops
from deeplearning4j_tpu.nn.layers import output as output_ops
from deeplearning4j_tpu.nn.layers import rbm as rbm_ops
from deeplearning4j_tpu.ops.rng import KeySequence
from deeplearning4j_tpu.optimize.solver import Solver

DataLike = Union[DataSet, DataSetIterator]


def _as_iterator(data, labels=None, batch_size: Optional[int] = None) -> DataSetIterator:
    if isinstance(data, DataSetIterator):
        return data
    if isinstance(data, DataSet):
        ds = data
    else:
        ds = DataSet(np.asarray(data), None if labels is None else np.asarray(labels))
    return ListDataSetIterator(ds, batch_size or ds.num_examples())


class MultiLayerNetwork:
    def __init__(self, conf: MultiLayerConfiguration, params=None):
        if isinstance(conf, str):
            conf = MultiLayerConfiguration.from_json(conf)
        self.conf = conf
        self._params = params
        self._train_state = None
        self._train_step = None
        self._iteration = 0
        self._keys = KeySequence(conf.conf(0).seed if conf.n_layers else 123)
        self.listeners: List = []

    # ---- lifecycle ----
    def init(self) -> "MultiLayerNetwork":
        """Build params from confs (ref: MultiLayerNetwork.init :330-422)."""
        if self._params is None:
            self._params = F.init_params(self.conf, self._keys.next())
        return self

    @property
    def params_tree(self):
        if self._params is None:
            self.init()
        return self._params

    def set_listeners(self, listeners: Sequence) -> None:
        self.listeners = list(listeners)

    # ---- flat parameter vector API (ref: params/setParams :744-835) ----
    def params(self) -> jax.Array:
        return flatten_params(self.params_tree)

    def set_params(self, flat) -> None:
        self._params = unflatten_params(self.params_tree, jnp.asarray(flat))

    def num_params(self) -> int:
        return num_params(self.params_tree)

    # ---- inference ----
    def feed_forward(self, x) -> List[jax.Array]:
        return F.feed_forward(self.conf, self.params_tree, jnp.asarray(x))

    def output(self, x) -> jax.Array:
        return F.output(self.conf, self.params_tree, jnp.asarray(x))

    def predict(self, x) -> np.ndarray:
        """Argmax class per example (ref: MultiLayerNetwork.predict :1094)."""
        return np.asarray(jnp.argmax(self.output(x), axis=-1))

    def label_probabilities(self, x) -> jax.Array:
        return self.output(x)

    def score(self, data: DataLike, labels=None) -> float:
        if data is None:
            raise ValueError("score() requires a DataSet/iterator (features+labels)")
        it = _as_iterator(data, labels)
        total, n = 0.0, 0
        for batch in it:
            b = batch.num_examples()
            total += float(
                F.score(self.conf, self.params_tree, jnp.asarray(batch.features),
                        jnp.asarray(batch.labels))
            ) * b
            n += b
        return total / max(n, 1)

    # ---- training ----
    def fit(self, data: DataLike, labels=None, batch_size: Optional[int] = None) -> None:
        """pretrain → finetune → backprop (ref: MultiLayerNetwork.fit :936-956)."""
        from deeplearning4j_tpu.optimize.listeners import close_listeners

        it = _as_iterator(data, labels, batch_size)
        try:
            if self.conf.pretrain:
                self.pretrain(it)
                it.reset()
                self.finetune(it)
            if self.conf.backward:
                it.reset()
                for batch in it:
                    self._do_backward(batch.features, batch.labels)
        finally:
            # crash-safe: an exception mid-fit must not leave a profiler
            # listener's trace window armed (close() is idempotent)
            close_listeners(self.listeners)

    def _ensure_train_step(self):
        if self._train_step is None:
            self._train_step = F.make_train_step(self.conf)
        if self._train_state is None:
            self._train_state = F.init_train_state(self.conf, self.params_tree)

    def _do_backward(self, features, labels) -> None:
        """numIterations fused train steps on one batch
        (ref: MultiLayerNetwork.doBackWard :959-1010)."""
        if labels is None:
            raise ValueError("No labels found (supervised fit requires labels)")
        self._ensure_train_step()
        x = jnp.asarray(features)
        y = jnp.asarray(labels)
        n_iters = self.conf.conf(0).num_iterations
        params, state = self.params_tree, self._train_state
        for i in range(n_iters):
            params, state, score = self._train_step(
                params, state, jnp.asarray(self._iteration), x, y, self._keys.next()
            )
            self._iteration += 1
            if self.listeners:
                from deeplearning4j_tpu.optimize.listeners import (
                    dispatch_listeners,
                )

                # refresh the facade BEFORE dispatch (reference assignment,
                # no host sync) so state-capturing listeners — the ckpt
                # subsystem's CheckpointIterationListener — snapshot the
                # current iteration's params/updater state
                self._params, self._train_state = params, state
                dispatch_listeners(self.listeners, self, self._iteration,
                                   float(score))
        self._params, self._train_state = params, state

    def fit_epochs(self, data: DataLike, num_epochs: int = 1, labels=None,
                   batch_size: Optional[int] = None) -> None:
        """Epoch-style supervised training (one fused step per batch) — the
        TPU-idiomatic loop most benchmarks use; numIterations-per-batch
        semantics remain available via fit()."""
        from deeplearning4j_tpu.optimize.listeners import (
            close_listeners,
            dispatch_listeners,
        )

        self._ensure_train_step()
        it = _as_iterator(data, labels, batch_size)
        params, state = self.params_tree, self._train_state
        try:
            for _ in range(num_epochs):
                it.reset()
                for batch in it:
                    params, state, score = self._train_step(
                        params, state, jnp.asarray(self._iteration),
                        jnp.asarray(batch.features), jnp.asarray(batch.labels),
                        self._keys.next(),
                    )
                    self._iteration += 1
                    if self.listeners:
                        # fresh refs before dispatch: see _do_backward
                        self._params, self._train_state = params, state
                        dispatch_listeners(self.listeners, self,
                                           self._iteration, float(score))
        finally:
            close_listeners(self.listeners)
        self._params, self._train_state = params, state

    def pretrain(self, data: DataLike, labels=None) -> None:
        """Greedy layerwise unsupervised pretraining
        (ref: MultiLayerNetwork.pretrain :150-191)."""
        it = _as_iterator(data, labels)
        params = list(self.params_tree)
        for i in range(self.conf.n_layers):
            conf_i = self.conf.conf(i)
            if conf_i.layer_type not in (
                LayerType.RBM, LayerType.AUTOENCODER, LayerType.RECURSIVE_AUTOENCODER
            ):
                continue
            it.reset()
            for batch in it:
                x = jnp.asarray(batch.features)
                frozen = tuple(params)
                layer_input = F.hidden_activation(self.conf, frozen, x, i)
                params[i] = self._pretrain_layer(conf_i, params[i], layer_input)
        self._params = tuple(params)

    def _pretrain_layer(self, conf: NeuralNetConfiguration, layer_params, x):
        if conf.layer_type == LayerType.RBM:
            def score_fn(p, key):
                return rbm_ops.reconstruction_error(conf, p, x)

            def grad_fn(p, key):
                return rbm_ops.contrastive_divergence(conf, p, x, key)

            solver = Solver(conf, score_fn, grad_fn=grad_fn, listeners=self.listeners,
                            num_iterations=conf.num_iterations)
            # CD gradients don't come from the score surface; line-search
            # algorithms are meaningless here → force iteration GD, matching
            # how the reference's RBM is in practice trained via its own
            # gradient() (ref: RBM.java:391-419 fit → contrastiveDivergence).
            return solver.optimize(
                layer_params, self._keys.next(),
                algo=OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT,
            )
        if conf.layer_type in (LayerType.AUTOENCODER, LayerType.RECURSIVE_AUTOENCODER):
            # fresh corruption mask each iteration for the denoising AE (ref
            # corrupts per gradient call, AutoEncoder.java getCorruptedInput)
            ops = (ae_ops if conf.layer_type == LayerType.AUTOENCODER
                   else rae_ops)

            def score_fn(p, key):
                return ops.pretrain_loss(conf, p, x, key)

            solver = Solver(conf, score_fn, listeners=self.listeners,
                            num_iterations=conf.num_iterations)
            return solver.optimize(layer_params, self._keys.next())
        return layer_params

    def finetune(self, data: DataLike, labels=None) -> None:
        """Train the OUTPUT head on top-of-stack activations
        (ref: MultiLayerNetwork.finetune :1033-1084)."""
        it = _as_iterator(data, labels)
        head_idx = self.conf.n_layers - 1
        head_conf = self.conf.conf(head_idx)
        if head_conf.layer_type != LayerType.OUTPUT:
            return
        params = list(self.params_tree)
        for batch in it:
            x = jnp.asarray(batch.features)
            y = jnp.asarray(batch.labels)
            frozen = tuple(params)
            top = F.hidden_activation(self.conf, frozen, x, head_idx)

            def score_fn(p, key):
                return output_ops.output_loss(head_conf, p, top, y)

            solver = Solver(head_conf, score_fn, listeners=self.listeners,
                            num_iterations=head_conf.num_iterations)
            params[head_idx] = solver.optimize(params[head_idx], self._keys.next())
        self._params = tuple(params)

    # ---- distributed parity ----
    def merge(self, other: "MultiLayerNetwork", batch_size: int) -> None:
        """Parameter-averaging hook (ref: MultiLayerNetwork.merge :1358,
        BaseLayer.merge :354: params += other.params / batchSize)."""
        if other.conf.n_layers != self.conf.n_layers:
            raise ValueError("Unable to merge networks that are not of equal length")
        self._params = jax.tree_util.tree_map(
            lambda p, o: p + o / batch_size, self.params_tree, other.params_tree
        )

    def clone(self) -> "MultiLayerNetwork":
        return MultiLayerNetwork(self.conf, params=self.params_tree)

    # ---- persistence (conf JSON + flat params, ref ctor :99) ----
    def save(self, path: str) -> None:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        np.savez(
            path if path.endswith(".npz") else path + ".npz",
            params=np.asarray(self.params()),
            conf=np.frombuffer(self.conf.to_json().encode(), dtype=np.uint8),
        )

    @classmethod
    def load(cls, path: str) -> "MultiLayerNetwork":
        if not path.endswith(".npz") and os.path.exists(path + ".npz"):
            path = path + ".npz"
        with np.load(path) as z:
            conf = MultiLayerConfiguration.from_json(bytes(z["conf"]).decode())
            net = cls(conf)
            net.init()
            net.set_params(z["params"])
        return net

    # ---- JSON conf parity helpers ----
    def to_json(self) -> str:
        return self.conf.to_json()

    @classmethod
    def from_json(cls, s: str) -> "MultiLayerNetwork":
        return cls(MultiLayerConfiguration.from_json(s))
