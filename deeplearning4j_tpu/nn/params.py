"""Named-parameter initialization per layer type.

Parity with ref: nn/params/ — DefaultParamInitializer (W, b),
PretrainParamInitializer (+vb), ConvolutionParamInitializer
(convweights, convbias), LSTMParamInitializer (recurrentweights,
decoderweights, decoderbias). Same parameter keys so flat param vectors and
checkpoints line up with the reference's ordering conventions.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.api import LayerType
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.weights import init_weights

# canonical parameter keys (ref: nn/params/*.java)
WEIGHT_KEY = "W"
BIAS_KEY = "b"
VISIBLE_BIAS_KEY = "vb"
CONV_WEIGHT_KEY = "convweights"
CONV_BIAS_KEY = "convbias"
RECURRENT_WEIGHT_KEY = "recurrentweights"
DECODER_WEIGHT_KEY = "decoderweights"
DECODER_BIAS_KEY = "decoderbias"


def _dense_params(key, conf: NeuralNetConfiguration) -> Dict[str, jax.Array]:
    wkey, _ = jax.random.split(key)
    return {
        WEIGHT_KEY: init_weights(wkey, (conf.n_in, conf.n_out), conf.weight_init, conf.dist),
        BIAS_KEY: jnp.zeros((conf.n_out,)),
    }


def _pretrain_params(key, conf: NeuralNetConfiguration) -> Dict[str, jax.Array]:
    p = _dense_params(key, conf)
    p[VISIBLE_BIAS_KEY] = jnp.zeros((conf.n_in,))
    return p


def _recursive_ae_params(key, conf: NeuralNetConfiguration) -> Dict[str, jax.Array]:
    # combine matrix maps [x_i; parent] (n_in + n_out) -> n_out, decoded with
    # the transpose (ref: RecursiveAutoEncoder.java / RecursiveParamInitializer)
    in_dim = conf.n_in + conf.n_out
    wkey, _ = jax.random.split(key)
    return {
        WEIGHT_KEY: init_weights(wkey, (in_dim, conf.n_out), conf.weight_init, conf.dist),
        BIAS_KEY: jnp.zeros((conf.n_out,)),
        VISIBLE_BIAS_KEY: jnp.zeros((in_dim,)),
    }


def _conv_params(key, conf: NeuralNetConfiguration) -> Dict[str, jax.Array]:
    # OIHW filters: (out_channels, in_channels, kh, kw). The reference stores
    # per-feature-map filters of shape filterSize and loops convn over maps
    # (ref: ConvolutionLayer.java:115-128); one batched lax.conv here.
    kh, kw = conf.filter_size[-2], conf.filter_size[-1]
    shape = (conf.n_out, conf.n_in, kh, kw)
    wkey, _ = jax.random.split(key)
    return {
        CONV_WEIGHT_KEY: init_weights(wkey, shape, conf.weight_init, conf.dist),
        CONV_BIAS_KEY: jnp.zeros((conf.n_out,)),
    }


def _lstm_params(key, conf: NeuralNetConfiguration) -> Dict[str, jax.Array]:
    # Karpathy-style fused-gate LSTM (ref: nn/layers/recurrent/LSTM.java:54-160,
    # nn/params/LSTMParamInitializer.java:39-41): one recurrent matrix maps
    # [1, x_t, h_{t-1}] -> 4*hidden (i,f,o,g fused), plus a decoder to n_out.
    hidden = conf.n_out
    in_dim = 1 + conf.n_in + hidden
    k1, k2, _ = jax.random.split(key, 3)
    return {
        RECURRENT_WEIGHT_KEY: init_weights(k1, (in_dim, 4 * hidden), conf.weight_init, conf.dist),
        DECODER_WEIGHT_KEY: init_weights(k2, (hidden, conf.n_out), conf.weight_init, conf.dist),
        DECODER_BIAS_KEY: jnp.zeros((conf.n_out,)),
    }


def _attention_params(key, conf: NeuralNetConfiguration) -> Dict[str, jax.Array]:
    # Pre-LN multi-head self-attention block + decoder (beyond-reference —
    # the 2015 codebase is pre-transformer; head contract mirrors the LSTM's
    # decoder, nn/params/LSTMParamInitializer.java:39-41).
    d = conf.n_in
    if conf.n_heads < 1 or d % conf.n_heads != 0:
        raise ValueError(
            f"attention n_in ({d}) must be divisible by n_heads "
            f"({conf.n_heads}); n_heads must be >= 1"
        )
    kq, kk, kv, ko, kd = jax.random.split(key, 5)
    dd = (d, d)
    return {
        "ln_g": jnp.ones((d,)),
        "ln_b": jnp.zeros((d,)),
        "wq": init_weights(kq, dd, conf.weight_init, conf.dist),
        "wk": init_weights(kk, dd, conf.weight_init, conf.dist),
        "wv": init_weights(kv, dd, conf.weight_init, conf.dist),
        "wo": init_weights(ko, dd, conf.weight_init, conf.dist),
        DECODER_WEIGHT_KEY: init_weights(kd, (d, conf.n_out), conf.weight_init, conf.dist),
        DECODER_BIAS_KEY: jnp.zeros((conf.n_out,)),
    }


def init_layer_params(key: jax.Array, conf: NeuralNetConfiguration) -> Dict[str, jax.Array]:
    """conf → named params; dispatch replaces ref LayerFactories.getFactory."""
    t = conf.layer_type
    if t in (LayerType.DENSE, LayerType.OUTPUT):
        return _dense_params(key, conf)
    if t in (LayerType.RBM, LayerType.AUTOENCODER):
        return _pretrain_params(key, conf)
    if t == LayerType.RECURSIVE_AUTOENCODER:
        return _recursive_ae_params(key, conf)
    if t == LayerType.CONVOLUTION:
        return _conv_params(key, conf)
    if t == LayerType.SUBSAMPLING:
        return {}  # pooling has no params (ref: SubsampleParamInitializer)
    if t == LayerType.LSTM:
        return _lstm_params(key, conf)
    if t == LayerType.ATTENTION:
        return _attention_params(key, conf)
    raise ValueError(f"No param initializer for layer type {t}")
