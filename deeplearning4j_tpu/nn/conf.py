"""Network configuration with JSON round-trip.

Parity with the reference's ``NeuralNetConfiguration`` (field set at ref:
nn/conf/NeuralNetConfiguration.java:53-121, fluent Builder at :854-1065,
Jackson mapper at :840-851) and ``MultiLayerConfiguration``
(ref: nn/conf/MultiLayerConfiguration.java:36-50, toJson/fromJson at :166-191).

TPU-first design notes:
- configs are frozen, hashable dataclasses → they can be closed over by / passed
  as static arguments to ``jax.jit`` without retracing hazards;
- the mutable Jackson object graph becomes plain data; layer classes are named
  by the ``LayerType`` enum instead of the reference's LayerFactory dispatch;
- the per-layer mutable RNG becomes a single integer ``seed``.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Mapping, Optional, Tuple

from deeplearning4j_tpu.nn.api import (
    ConvolutionType,
    HiddenUnit,
    LayerType,
    OptimizationAlgorithm,
    VisibleUnit,
)
from deeplearning4j_tpu.nn.weights import WeightInit
from deeplearning4j_tpu.ops.losses import LossFunction


def _freeze_schedule(sched) -> Tuple[Tuple[int, float], ...]:
    """Normalise {iteration: value} schedules to sorted tuples (hashable)."""
    if sched is None:
        return ()
    if isinstance(sched, Mapping):
        return tuple(sorted((int(k), float(v)) for k, v in sched.items()))
    return tuple((int(k), float(v)) for k, v in sched)


@dataclasses.dataclass(frozen=True)
class NeuralNetConfiguration:
    """Per-layer hyperparameter configuration (one per layer in a network)."""

    # architecture
    layer_type: LayerType = LayerType.DENSE
    n_in: int = 0
    n_out: int = 0
    activation_function: str = "sigmoid"
    # optimisation
    lr: float = 1e-1
    use_ada_grad: bool = True
    momentum: float = 0.5
    momentum_after: Tuple[Tuple[int, float], ...] = ()
    reset_ada_grad_iterations: int = -1
    num_iterations: int = 1000
    num_line_search_iterations: int = 5
    optimization_algo: OptimizationAlgorithm = OptimizationAlgorithm.GRADIENT_DESCENT
    minimize: bool = True
    step_function: str = "default"
    # regularisation
    l1: float = 0.0
    l2: float = 0.0
    use_regularization: bool = False
    dropout: float = 0.0
    constrain_gradient_to_unit_norm: bool = False
    sparsity: float = 0.0
    apply_sparsity: bool = False
    # loss / init
    loss_function: LossFunction = LossFunction.RECONSTRUCTION_CROSSENTROPY
    weight_init: WeightInit = WeightInit.VI
    dist: Optional[Tuple[str, float, float]] = None
    seed: int = 123
    # pretraining (RBM / AutoEncoder)
    corruption_level: float = 0.3
    k: int = 1
    visible_unit: VisibleUnit = VisibleUnit.BINARY
    hidden_unit: HiddenUnit = HiddenUnit.BINARY
    # convolutional
    filter_size: Tuple[int, ...] = (2, 2)
    stride: Tuple[int, ...] = (2, 2)
    feature_map_size: Tuple[int, ...] = (9, 9)
    convolution_type: ConvolutionType = ConvolutionType.MAX
    # attention (beyond-reference long-context layer)
    n_heads: int = 1
    causal: bool = True
    # batching
    batch_size: int = 10

    def __post_init__(self):
        # Coerce loosely-typed JSON values into enums/tuples so fromJson and
        # hand-built configs behave identically.
        object.__setattr__(self, "layer_type", LayerType.coerce(self.layer_type))
        object.__setattr__(
            self, "optimization_algo", OptimizationAlgorithm.coerce(self.optimization_algo)
        )
        object.__setattr__(self, "loss_function", LossFunction.coerce(self.loss_function))
        object.__setattr__(self, "weight_init", WeightInit.coerce(self.weight_init))
        object.__setattr__(self, "visible_unit", VisibleUnit.coerce(self.visible_unit))
        object.__setattr__(self, "hidden_unit", HiddenUnit.coerce(self.hidden_unit))
        object.__setattr__(
            self, "convolution_type", ConvolutionType.coerce(self.convolution_type)
        )
        object.__setattr__(self, "momentum_after", _freeze_schedule(self.momentum_after))
        for f in ("filter_size", "stride", "feature_map_size"):
            object.__setattr__(self, f, tuple(int(x) for x in getattr(self, f)))
        # fail at conf time, not first trace: a typo'd activation or step
        # function should raise here with the list of known names
        from deeplearning4j_tpu.ops.activations import activation as _act
        _act(self.activation_function)
        from deeplearning4j_tpu.optimize.stepfunctions import step_function as _sf
        _sf(self.step_function)
        if self.dist is not None:
            k, a, b = self.dist
            object.__setattr__(self, "dist", (str(k), float(a), float(b)))

    # ---- momentum schedule ----
    def momentum_at(self, iteration: int) -> float:
        """Momentum honouring the momentumAfter schedule (ref:
        GradientAdjustment.java:85-92, which uses only the first entry)."""
        m = self.momentum
        for it, val in self.momentum_after:
            if iteration >= it:
                m = val
        return m

    # ---- serialization ----
    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        for key, val in list(d.items()):
            if isinstance(val, tuple):
                d[key] = list(val)
        d["momentum_after"] = [[i, v] for i, v in self.momentum_after]
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "NeuralNetConfiguration":
        known = {f.name for f in dataclasses.fields(cls)}
        kwargs = {k: v for k, v in d.items() if k in known}
        if kwargs.get("dist") is not None:
            kwargs["dist"] = tuple(kwargs["dist"])
        return cls(**kwargs)

    @classmethod
    def from_json(cls, s: str) -> "NeuralNetConfiguration":
        return cls.from_dict(json.loads(s))

    # ---- fluent builder (API parity with ref Builder at :854-1065) ----
    class Builder:
        def __init__(self):
            self._kw: Dict[str, Any] = {}

        def __getattr__(self, name):
            def setter(value):
                self._kw[name] = value
                return self

            return setter

        def layer(self, layer_type):
            self._kw["layer_type"] = layer_type
            return self

        def list(self, n_layers: int) -> "ListBuilder":
            return ListBuilder(NeuralNetConfiguration(**self._kw), n_layers)

        def build(self) -> "NeuralNetConfiguration":
            return NeuralNetConfiguration(**self._kw)


@dataclasses.dataclass(frozen=True)
class MultiLayerConfiguration:
    """Whole-network configuration: ordered per-layer confs + global flags.

    Parity with ref: nn/conf/MultiLayerConfiguration.java:36-50 (confs,
    hiddenLayerSizes, pretrain/backward flags, input preprocessors).
    Preprocessors are named by string key per layer index; see
    nn/layers/preprocessor.py for the registry (ref: nn/conf/preprocessor/).
    """

    confs: Tuple[NeuralNetConfiguration, ...] = ()
    hidden_layer_sizes: Tuple[int, ...] = ()
    pretrain: bool = True
    backward: bool = False
    use_drop_connect: bool = False
    input_preprocessors: Tuple[Tuple[int, str], ...] = ()

    def __post_init__(self):
        object.__setattr__(self, "confs", tuple(self.confs))
        object.__setattr__(
            self, "hidden_layer_sizes", tuple(int(x) for x in self.hidden_layer_sizes)
        )
        object.__setattr__(
            self,
            "input_preprocessors",
            tuple(sorted((int(i), str(p)) for i, p in self.input_preprocessors)),
        )

    def conf(self, i: int) -> NeuralNetConfiguration:
        return self.confs[i]

    @property
    def n_layers(self) -> int:
        return len(self.confs)

    def preprocessor_for(self, i: int) -> Optional[str]:
        for idx, name in self.input_preprocessors:
            if idx == i:
                return name
        return None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "confs": [c.to_dict() for c in self.confs],
            "hidden_layer_sizes": list(self.hidden_layer_sizes),
            "pretrain": self.pretrain,
            "backward": self.backward,
            "use_drop_connect": self.use_drop_connect,
            "input_preprocessors": [[i, p] for i, p in self.input_preprocessors],
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "MultiLayerConfiguration":
        return cls(
            confs=tuple(NeuralNetConfiguration.from_dict(c) for c in d.get("confs", ())),
            hidden_layer_sizes=tuple(d.get("hidden_layer_sizes", ())),
            pretrain=bool(d.get("pretrain", True)),
            backward=bool(d.get("backward", False)),
            use_drop_connect=bool(d.get("use_drop_connect", False)),
            input_preprocessors=tuple(
                (int(i), str(p)) for i, p in d.get("input_preprocessors", ())
            ),
        )

    @classmethod
    def from_json(cls, s: str) -> "MultiLayerConfiguration":
        return cls.from_dict(json.loads(s))


class ListBuilder:
    """Builder for MultiLayerConfiguration via per-layer overrides.

    Parity with the reference's ``NeuralNetConfiguration.ListBuilder`` +
    ``ConfOverride`` mechanism (ref: nn/conf/NeuralNetConfiguration.java,
    nn/conf/override/ConfOverride.java): start from a base conf replicated
    across layers, then override individual layers.
    """

    def __init__(self, base: NeuralNetConfiguration, n_layers: int):
        self._base = base
        self._n = n_layers
        self._overrides: Dict[int, Dict[str, Any]] = {}
        self._hidden_sizes: Tuple[int, ...] = ()
        self._pretrain = True
        self._backward = False
        self._use_drop_connect = False
        self._preprocessors: Dict[int, str] = {}

    def hidden_layer_sizes(self, *sizes: int) -> "ListBuilder":
        self._hidden_sizes = tuple(sizes)
        return self

    def override(self, layer: int, **kwargs) -> "ListBuilder":
        self._overrides.setdefault(layer, {}).update(kwargs)
        return self

    def pretrain(self, flag: bool) -> "ListBuilder":
        self._pretrain = flag
        return self

    def backward(self, flag: bool) -> "ListBuilder":
        self._backward = flag
        return self

    def use_drop_connect(self, flag: bool) -> "ListBuilder":
        self._use_drop_connect = flag
        return self

    def input_preprocessor(self, layer: int, name: str) -> "ListBuilder":
        self._preprocessors[layer] = name
        return self

    def build(self) -> MultiLayerConfiguration:
        confs = []
        for i in range(self._n):
            kw = dataclasses.asdict(self._base)
            # asdict loses enum identity; re-coercion happens in __post_init__
            kw.update(self._overrides.get(i, {}))
            confs.append(NeuralNetConfiguration(**kw))
        return MultiLayerConfiguration(
            confs=tuple(confs),
            hidden_layer_sizes=self._hidden_sizes,
            pretrain=self._pretrain,
            backward=self._backward,
            use_drop_connect=self._use_drop_connect,
            input_preprocessors=tuple(self._preprocessors.items()),
        )
