"""Parse trees for recursive models (RNTN, recursive autoencoder).

Parity with ref rntn/Tree usage and nn/layers/feedforward/recursive/Tree.java
(485 LoC): children/label/value accessors, leaves, pre-order traversal, plus
an s-expression parser for Stanford-sentiment-style strings like
``(3 (2 good) (3 (2 great) (2 movie)))``.

TPU-first addition: ``linearize`` flattens a binary tree into arrays of merge
steps (left, right, out indices) so a whole tree evaluates as one
``lax.scan`` over a node buffer instead of per-node Python recursion.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np


@dataclass
class Tree:
    label: Optional[int] = None  # gold class (e.g. sentiment 0..4)
    word: Optional[str] = None  # set on leaves
    children: List["Tree"] = field(default_factory=list)

    def is_leaf(self) -> bool:
        return not self.children

    def leaves(self) -> List["Tree"]:
        if self.is_leaf():
            return [self]
        out: List[Tree] = []
        for c in self.children:
            out.extend(c.leaves())
        return out

    def preorder(self) -> List["Tree"]:
        out = [self]
        for c in self.children:
            out.extend(c.preorder())
        return out

    def depth(self) -> int:
        if self.is_leaf():
            return 0
        return 1 + max(c.depth() for c in self.children)

    def num_nodes(self) -> int:
        return len(self.preorder())

    def yield_words(self) -> List[str]:
        return [leaf.word for leaf in self.leaves()]

    @staticmethod
    def parse(s: str) -> "Tree":
        """Parse an s-expression: ``(label child child)`` | ``(label word)``."""
        tokens = s.replace("(", " ( ").replace(")", " ) ").split()
        pos = [0]

        def read() -> Tree:
            assert tokens[pos[0]] == "(", f"expected '(' at {pos[0]}"
            pos[0] += 1
            label = tokens[pos[0]]
            pos[0] += 1
            node = Tree(label=int(label) if label.lstrip("-").isdigit() else None)
            if tokens[pos[0]] == "(":
                while tokens[pos[0]] == "(":
                    node.children.append(read())
            else:
                node.word = tokens[pos[0]]
                pos[0] += 1
            assert tokens[pos[0]] == ")", f"expected ')' at {pos[0]}"
            pos[0] += 1
            return node

        tree = read()
        assert pos[0] == len(tokens), "trailing tokens"
        return tree

    def binarize(self) -> "Tree":
        """Left-branching binarization of n-ary nodes (merge steps need
        exactly two children)."""
        if self.is_leaf():
            return Tree(label=self.label, word=self.word)
        kids = [c.binarize() for c in self.children]
        if len(kids) == 1:
            # collapse unary chains, keep the top label
            only = kids[0]
            return Tree(label=self.label, word=only.word,
                        children=list(only.children))
        node = kids[0]
        # fabricated intermediate nodes carry NO gold label — only the real
        # top node keeps self.label (labeling invented spans would train the
        # model on supervision no annotator provided)
        for k in kids[1:-1]:
            node = Tree(label=None, children=[node, k])
        return Tree(label=self.label, children=[node, kids[-1]])


def linearize(tree: Tree, word_index, unk_index: int = 0
              ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Flatten a binarized tree into scan-ready arrays.

    Returns (leaf_ids, merges, labels):
    - leaf_ids: (L,) vocab index per leaf (slots 0..L-1 of the node buffer)
    - merges: (M,3) [left_slot, right_slot, out_slot] in bottom-up order;
      out slots are L..L+M-1
    - labels: (L+M,) gold label per buffer slot (-1 where unlabeled)
    """
    leaves: List[int] = []
    merges: List[Tuple[int, int, int]] = []

    def slot_of(node: Tree) -> int:
        if node.is_leaf():
            idx = word_index(node.word) if callable(word_index) else \
                word_index.get(node.word, unk_index)
            if idx is None or idx < 0:
                idx = unk_index
            leaves.append(idx)
            return len(leaves) - 1
        assert len(node.children) == 2, "linearize requires a binarized tree"
        l = slot_of(node.children[0])
        r = slot_of(node.children[1])
        out = -(len(merges) + 1)  # placeholder, patched below
        merges.append((l, r, out))
        return out

    slot_of(tree)
    n_leaves = len(leaves)
    # patch merge output slots (and child refs to merge outputs) to be
    # offset past the leaves; labels were appended leaf-interleaved, so
    # rebuild them in slot order
    fixed = []
    for l, r, out in merges:
        fix = lambda s: n_leaves + (-s - 1) if s < 0 else s
        fixed.append((fix(l), fix(r), fix(out)))
    # walk again assigning labels in slot order (same DFS as slot_of)
    slot_labels = np.full(n_leaves + len(merges), -1, np.int32)
    li = 0
    mi = 0

    def assign(node: Tree) -> int:
        nonlocal li, mi
        if node.is_leaf():
            s = li
            li += 1
            slot_labels[s] = node.label if node.label is not None else -1
            return s
        assign(node.children[0])
        assign(node.children[1])
        s = n_leaves + mi
        mi += 1
        slot_labels[s] = node.label if node.label is not None else -1
        return s

    assign(tree)
    return (np.asarray(leaves, np.int32),
            np.asarray(fixed, np.int32).reshape(-1, 3),
            slot_labels)
