"""Pure functional network core.

These are the functions ``jit``/``grad``/``pjit`` actually trace. The stateful
``MultiLayerNetwork`` facade (multilayer.py) wraps them, mirroring how the
reference's mutable MultiLayerNetwork sits over per-layer math
(ref: nn/multilayer/MultiLayerNetwork.java:495-525 feedForward, :959-1010
doBackWard). Backprop is jax.grad of the composed loss instead of the
reference's hand-chained ``backwardGradient`` calls.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import layers as layer_ops
from deeplearning4j_tpu.nn.api import LayerType
from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration
from deeplearning4j_tpu.nn.layers import output as output_layer
from deeplearning4j_tpu.nn.layers.preprocessor import preprocessor
from deeplearning4j_tpu.nn.params import init_layer_params
from deeplearning4j_tpu.optimize.updater import apply_updater, init_updater_state

Array = jax.Array
NetParams = Tuple[dict, ...]


def init_params(conf: MultiLayerConfiguration, key: Array) -> NetParams:
    keys = jax.random.split(key, max(conf.n_layers, 1))
    return tuple(
        init_layer_params(keys[i], conf.conf(i)) for i in range(conf.n_layers)
    )


def _maybe_preprocess(conf: MultiLayerConfiguration, i: int, x: Array) -> Array:
    name = conf.preprocessor_for(i)
    return preprocessor(name)(x) if name else x


def feed_forward(
    conf: MultiLayerConfiguration,
    params: NetParams,
    x: Array,
    *,
    train: bool = False,
    key: Optional[Array] = None,
) -> List[Array]:
    """Activations per layer, input first (ref: MultiLayerNetwork.java:495-525)."""
    acts = [x]
    keys = (
        jax.random.split(key, conf.n_layers) if key is not None else [None] * conf.n_layers
    )
    for i in range(conf.n_layers):
        x = _maybe_preprocess(conf, i, x)
        x = layer_ops.forward(conf.conf(i), params[i], x, train=train, key=keys[i],
                              drop_connect=conf.use_drop_connect)
        acts.append(x)
    return acts


def output(conf: MultiLayerConfiguration, params: NetParams, x: Array) -> Array:
    """Final network output (ref: MultiLayerNetwork.output :1184)."""
    return feed_forward(conf, params, x)[-1]


def hidden_activation(
    conf: MultiLayerConfiguration, params: NetParams, x: Array, upto: int,
    *, train: bool = False, key: Optional[Array] = None,
) -> Array:
    """Forward through layers [0, upto) — pretraining input for layer `upto`
    (ref: MultiLayerNetwork.activationFromPrevLayer :479)."""
    keys = jax.random.split(key, max(upto, 1)) if key is not None else [None] * max(upto, 1)
    for i in range(upto):
        x = _maybe_preprocess(conf, i, x)
        x = layer_ops.forward(conf.conf(i), params[i], x, train=train, key=keys[i])
    return x


def network_loss(
    conf: MultiLayerConfiguration,
    params: NetParams,
    x: Array,
    labels: Array,
    *,
    train: bool = False,
    key: Optional[Array] = None,
) -> Array:
    """Loss through the whole stack; the head uses the fused-logits path."""
    from deeplearning4j_tpu.ops.losses import finalize_loss

    per = network_per_example_loss(conf, params, x, labels, train=train, key=key)
    head = conf.conf(conf.n_layers - 1)
    return finalize_loss(head.loss_function, jnp.mean(per))


def network_per_example_loss(
    conf: MultiLayerConfiguration,
    params: NetParams,
    x: Array,
    labels: Array,
    *,
    train: bool = False,
    key: Optional[Array] = None,
) -> Array:
    """Per-example pre-reduction losses, shape (batch,).

    The scalar ``network_loss`` equals
    ``ops.losses.finalize_loss(head.loss_function, mean(per_example))``;
    data-parallel callers weight rows (padding masks) and normalize the mean
    across shards with a psum so uneven batches stay unbiased.

    Head layers:
    - OUTPUT: fused-logits classifier head. 3-D labels (batch, time, classes)
      are scored per timestep and averaged over time.
    - LSTM: the layer's own decoder projection provides per-timestep logits
      (ref: nn/layers/recurrent/LSTM.java:54-160 trains through its decoder
      with per-timestep softmax); labels are (batch, time, vocab).
    """
    from deeplearning4j_tpu.ops.losses import (
        LossFunction,
        per_example_loss,
        per_example_loss_from_logits,
    )

    n = conf.n_layers
    keys = jax.random.split(key, n) if key is not None else [None] * n
    for i in range(n - 1):
        x = _maybe_preprocess(conf, i, x)
        x = layer_ops.forward(conf.conf(i), params[i], x, train=train, key=keys[i],
                              drop_connect=conf.use_drop_connect)
    x = _maybe_preprocess(conf, n - 1, x)
    head = conf.conf(n - 1)
    if head.layer_type == LayerType.OUTPUT:
        per = output_layer.output_per_example_loss(
            head, params[n - 1], x, labels, train=train,
            key=keys[n - 1], drop_connect=conf.use_drop_connect)
    elif head.layer_type in (LayerType.LSTM, LayerType.ATTENTION):
        # sequence heads own a decoder producing per-timestep logits
        logits = layer_ops.forward(head, params[n - 1], x, train=train,
                                   key=keys[n - 1]).astype(jnp.float32)
        labels = labels.astype(jnp.float32)
        ce_family = (LossFunction.MCXENT, LossFunction.NEGATIVELOGLIKELIHOOD,
                     LossFunction.XENT, LossFunction.RECONSTRUCTION_CROSSENTROPY)
        if LossFunction.coerce(head.loss_function) in ce_family:
            per = per_example_loss_from_logits(head.loss_function, labels, logits)
        else:
            per = per_example_loss(head.loss_function, labels, logits)
    else:
        raise ValueError("network_per_example_loss requires an OUTPUT, "
                         "LSTM, or ATTENTION head layer")
    if per.ndim > 1:  # sequence head: average the per-timestep losses
        per = jnp.mean(per, axis=tuple(range(1, per.ndim)))
    return per


def make_train_step(conf: MultiLayerConfiguration, donate: bool = False,
                    policy=None):
    """Build the jitted full-network training step.

    step(params, updater_states, iteration, x, labels, key)
      -> (new_params, new_states, score)

    One fused XLA program: forward, backward (jax.grad), per-layer updater —
    the TPU equivalent of doBackWard's per-iteration body
    (ref: MultiLayerNetwork.java:976-1002).

    ``donate=True`` donates the params/state buffers to XLA (in-place update,
    halves HBM traffic for the update) — only safe when the caller owns the
    arrays exclusively, i.e. nothing else (facade fields, clones, listeners)
    still references them. MultiLayerNetwork keeps False; the data-parallel
    trainer and benches, which own their loop state, opt in.

    ``policy`` (ops.dtypes.Policy) enables mixed precision: params/activations
    are cast to ``policy.compute_dtype`` (e.g. bfloat16 for the MXU) inside
    the step; master params, updater state, and the loss stay float32.
    """

    step = _raw_train_step(conf, policy)
    donate_argnums = (0, 1) if donate else ()
    return jax.jit(step, donate_argnums=donate_argnums)


def _raw_train_step(conf: MultiLayerConfiguration, policy=None):
    """Unjitted step body shared by make_train_step / make_train_epoch."""

    def step(params, states, iteration, x, labels, key):
        kdrop, _ = jax.random.split(key)

        def loss_fn(ps):
            if policy is not None:
                ps = jax.tree_util.tree_map(
                    lambda a: a.astype(policy.compute_dtype), ps
                )
                xin = x.astype(policy.compute_dtype)
            else:
                xin = x
            return network_loss(conf, ps, xin, labels, train=True, key=kdrop)

        score, grads = jax.value_and_grad(loss_fn)(params)
        new_params = []
        new_states = []
        for i in range(conf.n_layers):
            upd, st = apply_updater(conf.conf(i), iteration, grads[i], params[i], states[i])
            new_params.append(
                jax.tree_util.tree_map(lambda p, u: p - u, params[i], upd)
            )
            new_states.append(st)
        return tuple(new_params), tuple(new_states), score

    return step


def make_train_epoch(conf: MultiLayerConfiguration, n_steps: int,
                     donate: bool = True, policy=None):
    """Device-resident training loop: ``lax.scan`` over ``n_steps`` batches
    inside ONE jitted program.

    epoch(params, states, iteration0, xs, ys, key)
      -> (new_params, new_states, scores)

    xs: (n_steps, batch, features), ys: (n_steps, batch, classes). Keeps the
    loop on the TPU — one dispatch per epoch chunk instead of per step, which
    matters when host→device dispatch latency rivals step compute (small
    models, remote-tunnel setups). The per-step RNG key is folded from the
    step index, matching make_train_step semantics.
    """
    step = _raw_train_step(conf, policy)

    def epoch(params, states, iteration0, xs, ys, key):
        def body(carry, inp):
            params, states = carry
            i, x, y = inp
            sub = jax.random.fold_in(key, i)
            params, states, score = step(params, states, iteration0 + i, x, y, sub)
            return (params, states), score

        idx = jnp.arange(n_steps)
        (params, states), scores = jax.lax.scan(body, (params, states),
                                                (idx, xs, ys))
        return params, states, scores

    donate_argnums = (0, 1) if donate else ()
    return jax.jit(epoch, donate_argnums=donate_argnums)


def init_train_state(conf: MultiLayerConfiguration, params: NetParams):
    return tuple(init_updater_state(params[i]) for i in range(conf.n_layers))


def score(
    conf: MultiLayerConfiguration, params: NetParams, x: Array, labels: Array
) -> Array:
    return network_loss(conf, params, x, labels, train=False)
