"""Cluster provisioning: TPU-pod analogue of the reference's EC2 tooling.

Parity with ref: aws/ec2/provision/ — Ec2BoxCreator (creates worker VMs),
HostProvisioner (SSH upload + run per host), ClusterSetup (provision all
hosts then launch master/workers), DistributedDeepLearningTrainer (CLI
entry). The AWS SDK/JSch calls become:

- TpuPodCreator — builds the `gcloud compute tpus tpu-vm` command lines a
  TPU pod needs (create/delete/describe). Commands are GENERATED and
  returned; execution goes through a pluggable runner so tests (and
  zero-egress environments) assert the exact commands without any cloud
  call — the same reason the reference isolates provisioning behind
  interfaces it mocks in tests.
- HostProvisioner — per-host upload-and-run over a command runner
  (production: subprocess `gcloud ... ssh/scp`; tests: recording fake).
- ClusterSetup — provisions every worker host in parallel and emits the
  multihost launch commands (coordinator address/rank env wiring matches
  parallel/multihost.py initialize()).
"""

from __future__ import annotations

import shlex
import subprocess
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

# runner: takes argv, returns (exit_code, stdout). Injectable for tests.
CommandRunner = Callable[[List[str]], "tuple[int, str]"]


def subprocess_runner(argv: List[str]) -> "tuple[int, str]":
    proc = subprocess.run(argv, capture_output=True, text=True)
    return proc.returncode, proc.stdout + proc.stderr


@dataclass
class TpuPodSpec:
    """What to provision (ref: Ec2BoxCreator fields — ami/size/securityGroup
    become their TPU equivalents)."""

    name: str = "dl4j-tpu"
    accelerator_type: str = "v5litepod-8"
    zone: str = "us-central1-a"
    project: Optional[str] = None
    runtime_version: str = "tpu-ubuntu2204-base"
    num_hosts: int = 1  # v5litepod-8 = 1 host; a v5litepod-256 = 32 hosts
    labels: Dict[str, str] = field(default_factory=dict)


class TpuPodCreator:
    """Generates + optionally executes pod lifecycle commands
    (ref: Ec2BoxCreator.create/blowupBoxes)."""

    def __init__(self, spec: TpuPodSpec,
                 runner: CommandRunner = subprocess_runner):
        self.spec = spec
        self.runner = runner

    def _base(self) -> List[str]:
        cmd = ["gcloud", "compute", "tpus", "tpu-vm"]
        return cmd

    def _common_flags(self) -> List[str]:
        flags = [f"--zone={self.spec.zone}"]
        if self.spec.project:
            flags.append(f"--project={self.spec.project}")
        return flags

    def create_command(self) -> List[str]:
        cmd = self._base() + ["create", self.spec.name] + self._common_flags()
        cmd += [f"--accelerator-type={self.spec.accelerator_type}",
                f"--version={self.spec.runtime_version}"]
        if self.spec.labels:
            kv = ",".join(f"{k}={v}" for k, v in sorted(self.spec.labels.items()))
            cmd.append(f"--labels={kv}")
        return cmd

    def delete_command(self) -> List[str]:
        return self._base() + ["delete", self.spec.name, "--quiet"] + self._common_flags()

    def describe_command(self) -> List[str]:
        return self._base() + ["describe", self.spec.name] + self._common_flags()

    def create(self) -> "tuple[int, str]":
        return self.runner(self.create_command())

    def destroy(self) -> "tuple[int, str]":
        return self.runner(self.delete_command())


class HostProvisioner:
    """Upload + run on one pod host (ref: HostProvisioner.uploadAndRun /
    runRemoteCommand / uploadForDeployment, minus the JSch key plumbing —
    gcloud owns auth)."""

    def __init__(self, pod: str, worker: int = 0, zone: str = "us-central1-a",
                 project: Optional[str] = None,
                 runner: CommandRunner = subprocess_runner):
        self.pod = pod
        self.worker = worker
        self.zone = zone
        self.project = project
        self.runner = runner

    def _flags(self) -> List[str]:
        flags = [f"--zone={self.zone}", f"--worker={self.worker}"]
        if self.project:
            flags.append(f"--project={self.project}")
        return flags

    def run_remote_command(self, remote_command: str) -> "tuple[int, str]":
        argv = (["gcloud", "compute", "tpus", "tpu-vm", "ssh", self.pod]
                + self._flags() + [f"--command={remote_command}"])
        return self.runner(argv)

    def upload_for_deployment(self, src: str, dest: str) -> "tuple[int, str]":
        argv = (["gcloud", "compute", "tpus", "tpu-vm", "scp", src,
                 f"{self.pod}:{dest}"] + self._flags())
        return self.runner(argv)

    def upload_and_run(self, script: str, root_dir: str = "~") -> "tuple[int, str]":
        code, out = self.upload_for_deployment(script, root_dir)
        if code != 0:
            return code, out
        base = script.rsplit("/", 1)[-1]
        # leave a leading ~ unquoted so the remote shell tilde-expands it
        if root_dir == "~" or root_dir.startswith("~/"):
            cd = "~" + shlex.quote(root_dir[1:]) if len(root_dir) > 1 else "~"
        else:
            cd = shlex.quote(root_dir)
        return self.run_remote_command(f"cd {cd} && bash {shlex.quote(base)}")


class ClusterSetup:
    """Provision every host then emit/launch the multihost training command
    (ref: ClusterSetup.exec — provisions master then workers in parallel via
    ActorSystem futures; here a thread pool)."""

    def __init__(self, spec: TpuPodSpec, train_argv: Sequence[str],
                 coordinator_port: int = 8476,
                 runner: CommandRunner = subprocess_runner):
        self.spec = spec
        self.train_argv = list(train_argv)
        self.coordinator_port = coordinator_port
        self.runner = runner

    def launch_command(self, process_id: int, coordinator_host: str) -> str:
        """Per-host training launch wiring the env parallel/multihost.py
        initialize() reads."""
        env = (f"DL4J_COORDINATOR={coordinator_host}:{self.coordinator_port} "
               f"DL4J_NUM_PROCESSES={self.spec.num_hosts} "
               f"DL4J_PROCESS_ID={process_id}")
        return env + " " + " ".join(shlex.quote(a) for a in self.train_argv)

    def provision_hosts(self, setup_script: str,
                        max_parallel: int = 8) -> List["tuple[int, str]"]:
        provs = [
            HostProvisioner(self.spec.name, worker=i, zone=self.spec.zone,
                            project=self.spec.project, runner=self.runner)
            for i in range(self.spec.num_hosts)
        ]
        with ThreadPoolExecutor(max_workers=max_parallel) as ex:
            return list(ex.map(lambda p: p.upload_and_run(setup_script), provs))

    def exec(self, setup_script: str, coordinator_host: str = "localhost"
             ) -> List["tuple[int, str]"]:
        """Provision all hosts, then start training on each
        (ref: ClusterSetup.exec). If ANY host fails provisioning, no launch
        is attempted — a partial multihost job would hang the
        DL4J_NUM_PROCESSES rendezvous on the healthy hosts."""
        results = self.provision_hosts(setup_script)
        failed = [i for i, (code, _) in enumerate(results) if code != 0]
        if failed:
            raise RuntimeError(
                f"provisioning failed on hosts {failed}; aborting launch. "
                f"Outputs: {[results[i][1][-500:] for i in failed]}")
        launches = []
        for i in range(self.spec.num_hosts):
            prov = HostProvisioner(self.spec.name, worker=i,
                                   zone=self.spec.zone,
                                   project=self.spec.project,
                                   runner=self.runner)
            launches.append(
                prov.run_remote_command(self.launch_command(i, coordinator_host)))
        return results + launches
