"""StateTracker — the cluster-state API.

Parity with ref: scaleout/api/statetracker/StateTracker.java (workers, jobs,
updates, replication flags, counters, current best params, done/earlyStop)
and its Hazelcast implementation BaseHazelCastStateTracker.java:78-100.

The in-memory implementation is thread-safe (the reference's tests run the
whole cluster in one JVM against embedded Hazelcast; same play here — one
process, many threads, shared tracker).
"""

from __future__ import annotations

import threading

from deeplearning4j_tpu.utils.lockwatch import make_rlock
from collections import defaultdict
from typing import Any, Dict, List, Optional

from deeplearning4j_tpu.scaleout.job import Job


class StateTracker:
    """Abstract API (ref: StateTracker.java)."""

    # workers
    def add_worker(self, worker_id: str) -> None: raise NotImplementedError
    def remove_worker(self, worker_id: str) -> None: raise NotImplementedError
    def workers(self) -> List[str]: raise NotImplementedError
    # jobs
    def add_job(self, job: Job) -> None: raise NotImplementedError
    def job_for(self, worker_id: str) -> Optional[Job]: raise NotImplementedError
    def clear_job(self, worker_id: str) -> None: raise NotImplementedError
    # updates
    def add_update(self, worker_id: str, job: Job) -> None: raise NotImplementedError
    def updates(self) -> Dict[str, Job]: raise NotImplementedError
    def clear_updates(self, expected: Optional[Dict[str, Job]] = None) -> None:
        """Clear updates. With ``expected`` (a prior updates() snapshot),
        remove ONLY entries still identical to the snapshot — an update a
        worker published after the snapshot survives for the next
        aggregation round (barrier-free Hogwild would otherwise lose it)."""
        raise NotImplementedError
    # current (averaged) result
    def set_current(self, result: Any) -> None: raise NotImplementedError
    def get_current(self) -> Any: raise NotImplementedError
    # replication
    def add_replicate(self, worker_id: str) -> None: raise NotImplementedError
    def needs_replicate(self, worker_id: str) -> bool: raise NotImplementedError
    def done_replicating(self, worker_id: str) -> None: raise NotImplementedError
    # generic KV blobs (ISSUE 12: the Hazelcast-map shape — last-write-wins
    # per key; telemetry federation pushes per-process registry snapshots
    # through these, exactly how the elastic membership rides the counters)
    def put_kv(self, key: str, value: Any) -> None: raise NotImplementedError
    def get_kv(self, key: str, default: Any = None) -> Any: raise NotImplementedError
    def kv_snapshot(self, prefix: str = "") -> Dict[str, Any]: raise NotImplementedError
    # counters / lifecycle
    def increment(self, key: str, by: float = 1.0) -> None: raise NotImplementedError
    def count(self, key: str) -> float: raise NotImplementedError
    def finish(self) -> None: raise NotImplementedError
    def is_done(self) -> bool: raise NotImplementedError
    def has_pending_jobs(self) -> bool: raise NotImplementedError
    # early stopping / best model (ref: tracker earlyStop/bestLoss) — the
    # runner calls these unconditionally, so they are part of the contract
    def set_best_loss(self, loss: float) -> None: raise NotImplementedError
    def best_loss(self) -> float: raise NotImplementedError
    def early_stop(self) -> None: raise NotImplementedError
    def is_early_stop(self) -> bool: raise NotImplementedError


class InMemoryStateTracker(StateTracker):
    """Thread-safe single-process tracker (the embedded-Hazelcast analogue).

    ``metrics_registry`` (a telemetry.MetricsRegistry) mirrors every
    ``increment`` into a registry counter of the same key, so scaleout
    workers' job_ms_total / jobs_done / rounds.* counters surface on the
    same Prometheus endpoint as the training metrics (dotted keys are
    sanitized at render time)."""

    def __init__(self, metrics_registry=None):
        self._registry = metrics_registry
        self._lock = make_rlock("tracker.state")  # lockwatch seam
        self._workers: List[str] = []
        self._jobs: Dict[str, Job] = {}
        self._updates: Dict[str, Job] = {}
        self._current: Any = None
        self._kv: Dict[str, Any] = {}
        self._replicate: set = set()
        self._counters: Dict[str, float] = defaultdict(float)
        self._done = False
        self._early_stop = False
        self._best_loss = float("inf")

    # ---- workers ----
    def add_worker(self, worker_id: str) -> None:
        with self._lock:
            if worker_id not in self._workers:
                self._workers.append(worker_id)

    def remove_worker(self, worker_id: str) -> None:
        with self._lock:
            if worker_id in self._workers:
                self._workers.remove(worker_id)

    def workers(self) -> List[str]:
        with self._lock:
            return list(self._workers)

    # ---- jobs ----
    def add_job(self, job: Job) -> None:
        with self._lock:
            self._jobs[job.worker_id] = job

    def job_for(self, worker_id: str) -> Optional[Job]:
        with self._lock:
            return self._jobs.get(worker_id)

    def clear_job(self, worker_id: str) -> None:
        with self._lock:
            self._jobs.pop(worker_id, None)

    def has_pending_jobs(self) -> bool:
        with self._lock:
            return bool(self._jobs)

    # ---- updates ----
    def add_update(self, worker_id: str, job: Job) -> None:
        with self._lock:
            self._updates[worker_id] = job

    def updates(self) -> Dict[str, Job]:
        with self._lock:
            return dict(self._updates)

    def clear_updates(self, expected: Optional[Dict[str, Job]] = None) -> None:
        with self._lock:
            if expected is None:
                self._updates.clear()
                return
            for worker_id, job in expected.items():
                if self._updates.get(worker_id) is job:
                    del self._updates[worker_id]

    # ---- current result ----
    def set_current(self, result: Any) -> None:
        with self._lock:
            self._current = result

    def get_current(self) -> Any:
        with self._lock:
            return self._current

    # ---- replication ----
    def add_replicate(self, worker_id: str) -> None:
        with self._lock:
            self._replicate.add(worker_id)

    def needs_replicate(self, worker_id: str) -> bool:
        with self._lock:
            return worker_id in self._replicate

    def done_replicating(self, worker_id: str) -> None:
        with self._lock:
            self._replicate.discard(worker_id)

    # ---- generic KV blobs (ISSUE 12) ----
    def put_kv(self, key: str, value: Any) -> None:
        with self._lock:
            self._kv[str(key)] = value

    def get_kv(self, key: str, default: Any = None) -> Any:
        with self._lock:
            return self._kv.get(str(key), default)

    def kv_snapshot(self, prefix: str = "") -> Dict[str, Any]:
        """All KV entries under ``prefix`` in one read — the federation
        aggregator pays one RPC per collect, not one per process."""
        with self._lock:
            return {k: v for k, v in self._kv.items()
                    if k.startswith(prefix)}

    # ---- counters / lifecycle ----
    def increment(self, key: str, by: float = 1.0) -> None:
        with self._lock:
            self._counters[key] += by
        if self._registry is not None and by >= 0:
            self._registry.counter(key).inc(by)

    def count(self, key: str) -> float:
        with self._lock:
            return self._counters[key]

    def counters_snapshot(self, prefix: str = "") -> Dict[str, float]:
        """All counters under ``prefix`` in one read — a remote poller
        (elastic workers watching ``elastic.*``) pays one RPC instead of
        one per key."""
        with self._lock:
            return {k: v for k, v in self._counters.items()
                    if k.startswith(prefix)}

    def finish(self) -> None:
        with self._lock:
            self._done = True

    def is_done(self) -> bool:
        with self._lock:
            return self._done

    # ---- early stopping / best model (ref: tracker earlyStop/bestLoss) ----
    def set_best_loss(self, loss: float) -> None:
        with self._lock:
            self._best_loss = loss

    def best_loss(self) -> float:
        with self._lock:
            return self._best_loss

    def early_stop(self) -> None:
        with self._lock:
            self._early_stop = True

    def is_early_stop(self) -> bool:
        with self._lock:
            return self._early_stop
