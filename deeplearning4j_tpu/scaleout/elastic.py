"""Elastic multi-host parameter-averaging training (ISSUE 6 / ROADMAP 3).

SparkNet (arXiv:1511.06051) trains by running every worker's local SGD for a
fixed window and averaging parameters infrequently; DeepSpark
(arXiv:1602.08191) relaxes the barrier with a bounded-staleness knob. That
sync model is exactly what makes elastic membership cheap: the only state a
worker uniquely owns is its *unsynced* delta, so a crashed worker costs one
round's local progress — never the run.

Topology: ``ElasticMaster`` embeds a ``StateTrackerServer`` (control plane:
membership, heartbeats, round counters) and shares a ``BlobStore`` (data
plane: parameter trees) with K ``ElasticWorker`` OS processes, each running
its own single-host JAX runtime and a jitted mesh train step over a
deterministic per-worker data stream.

Round protocol (global *versions* ``g = 0, 1, …``; version 0 is the initial
params, version ``g`` averages round ``g-1``'s contributions):

- worker: adopt the freshest committed global version, run ``sync_every``
  local steps, publish its params as the round-``r`` contribution, advance.
  With ``max_staleness = 0`` this is bulk-synchronous (wait for version
  ``r+1`` before round ``r+1``); with ``s > 0`` the worker keeps training on
  its local chain up to ``s`` rounds ahead of the last committed version
  (DeepSpark), adopting the freshest global whenever one is available.
- master: a round commits when every *live* worker admitted at-or-before it
  has contributed; heartbeat-stale workers are deregistered mid-barrier, so
  a kill -9 turns into a shrunk survivor set, not a hung barrier. The
  commit averages all contributions received for the round (weighted by
  local step count), publishes the new version, bumps ``elastic.version``.

Membership: a worker that registers mid-run (rejoin or replacement) pulls
the current version's params + step and is admitted from the current round
(``admit.<wid>`` counter) — earlier barriers never wait for it.
``min_workers`` picks degrade-vs-halt: the run continues on any survivor
set of at least ``min_workers``, and raises ``ElasticTrainingError`` below
that.

Numerical faults (ISSUE 8): averaging is exactly how one poisoned worker
would contaminate every survivor, so the master gates every contribution
on ``guardrails.tree_all_finite`` BEFORE it can reach ``average_trees`` —
a NaN/Inf tree quarantines its worker through the bury path (excluded
from the barrier and from all future averaging; ``workers_quarantined``
counter, ``nonfinite`` barrier event + flight dump). Worker-side,
``SyntheticRegressionModel(guard=True)`` runs the guarded SGD update so a
poisoned batch is skipped in-graph and never reaches a publish at all.

Persistence: the master checkpoints the averaged params through
``scaleout.ckpt`` (optionally via ``AsyncCheckpointer`` so snapshots stay
off the training/aggregation thread) and ``resume()`` restarts from the
latest committed version.

Tracing (ISSUE 7): with a process tracer configured (``telemetry.trace``;
the worker CLI's ``--trace-dir``, or ``ElasticMaster(trace_dir=...)``),
the round protocol is spanned end to end — master ``elastic.round`` /
``elastic.barrier`` (contribution arrivals as events) / ``elastic.average``,
worker ``worker.round`` → ``worker.steps`` / ``worker.publish`` /
``worker.sync_wait`` — and the master's round-span context rides every
published global blob's meta, so worker spans parent under the master
round that collects them: one trace tree across K+1 processes. Both sides
dump the flight recorder on ``ElasticTrainingError`` and checkpoint it at
round boundaries, so even a kill -9 leaves the previous boundary's dump
plus begin-records for the spans that were open when the process died.
"""

from __future__ import annotations

import argparse
import contextlib
import importlib
import io
import json
import logging
import threading
import time
import uuid
from typing import Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.scaleout.blobstore import BlobStore, open_store
from deeplearning4j_tpu.scaleout.remote_tracker import (
    StateTrackerClient,
    StateTrackerServer,
    TrackerUnavailable,
)
from deeplearning4j_tpu.telemetry import trace as _trace

log = logging.getLogger(__name__)

VERSION_KEY = "elastic.version"


class ElasticTrainingError(RuntimeError):
    """The run can no longer make progress (survivor set below
    ``min_workers``, or a round barrier timed out with no contributions)."""


# --------------------------------------------------------------- trees ----

def tree_to_bytes(tree, meta: Optional[Dict] = None) -> bytes:
    """Serialize a pytree of array leaves (+ JSON-able meta) to npz bytes.
    Leaves are keyed by their ``keystr`` path, so any process holding the
    same tree *structure* can deserialize without sharing code objects —
    the data-plane twin of the tracker's pickle frames."""
    import jax

    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    payload = {f"leaf_{i}": np.asarray(leaf) for i, (_, leaf) in
               enumerate(leaves)}
    payload["__paths__"] = np.frombuffer(json.dumps(
        [jax.tree_util.keystr(p) for p, _ in leaves]).encode(), np.uint8)
    payload["__meta__"] = np.frombuffer(
        json.dumps(meta or {}).encode(), np.uint8)
    buf = io.BytesIO()
    np.savez(buf, **payload)
    return buf.getvalue()


def tree_from_bytes(data: bytes, template) -> Tuple[object, Dict]:
    """Rebuild ``(tree, meta)`` from ``tree_to_bytes`` output into the
    structure of ``template``. Strict: the saved paths must be exactly the
    template's paths, in order — a structure mismatch is a loud error, not
    a silently misassigned parameter."""
    import jax

    with np.load(io.BytesIO(data)) as z:
        paths = json.loads(bytes(z["__paths__"]).decode())
        meta = json.loads(bytes(z["__meta__"]).decode())
        leaves = [np.asarray(z[f"leaf_{i}"]) for i in range(len(paths))]
    t_leaves, treedef = jax.tree_util.tree_flatten_with_path(template)
    want = [jax.tree_util.keystr(p) for p, _ in t_leaves]
    if want != paths:
        raise ValueError(
            f"elastic tree structure mismatch: payload has {paths}, "
            f"template expects {want}")
    return jax.tree_util.tree_unflatten(treedef, leaves), meta


def average_trees(trees: List, weights: List[float]):
    """Weighted parameter average, deterministic: float64 accumulation in a
    fixed caller-supplied order, cast back to each leaf's dtype. Both the
    master and the in-process parity reference (``simulate_elastic``) go
    through this exact function, so 'matches within tolerance' is limited
    by training math, not by averaging-order noise."""
    import jax

    if not trees:
        raise ValueError("cannot average zero contributions")
    total = float(sum(weights))
    flats = [jax.tree_util.tree_flatten(t) for t in trees]
    treedef = flats[0][1]
    n_leaves = len(flats[0][0])
    out = []
    for i in range(n_leaves):
        acc = np.zeros_like(np.asarray(flats[0][0][i], np.float64))
        for (leaves, _), w in zip(flats, weights):
            acc += np.asarray(leaves[i], np.float64) * (w / total)
        out.append(acc.astype(np.asarray(flats[0][0][i]).dtype))
    return jax.tree_util.tree_unflatten(treedef, out)


# --------------------------------------------------------------- model ----

class ElasticModel:
    """What a worker trains. ``run_steps`` owns the jit/mesh/data details;
    the framework only moves host trees around it."""

    def init_params(self):
        raise NotImplementedError

    def run_steps(self, params, start_step: int, n_steps: int,
                  worker_seed: int):
        """Advance ``params`` by ``n_steps`` local steps whose data stream
        is a pure function of ``(worker_seed, step_index)`` — so a
        survivor's trajectory is identical whether or not other workers
        exist. Returns ``(params, last_loss: float)``."""
        raise NotImplementedError


class SyntheticRegressionModel(ElasticModel):
    """Teacher-student MLP regression with a jitted data-parallel mesh
    step — the reference workload for elastic tests and the SparkNet
    sync-period bench. Deterministic end to end: params from a fixed init
    key, batches from ``fold_in(data_key, worker_seed, step)``.

    Guardrails (ISSUE 8): ``guard=True`` swaps in the guarded SGD update
    (optimize/guardrails.py — skip-on-nonfinite, optional ``clip_norm``);
    skips are counted on ``self.skipped_steps``. Fault injection for the
    elastic NaN matrix: ``nan_at_step`` poisons the batch of that global
    step index with a NaN (restricted to ``nan_worker_seed`` when set) —
    a pure function of (worker_seed, step), so ``simulate_elastic`` with
    the same knobs is still an exact oracle.

    Profiling (ISSUE 9): ``profile=True`` wraps the jitted mesh step in
    ``telemetry.xprofile.ProfiledStep`` — after the first ``run_steps``
    the compile-time :class:`StepProfile` (cost/memory analysis + the
    grad all-reduce inventory of the data-parallel mesh) is exposed as
    ``model.step_profile``.

    Optimizer (ISSUE 13): ``optimizer=`` ("adam" | "lamb" | ... or an
    ``optimize.updaters.OptimizerConfig``) swaps SGD for the in-graph
    stateful updater; moments persist on the instance across
    ``run_steps`` calls (local optimizer state under parameter
    averaging) and — with ``update_sharding="sharded"`` — live
    dp-partitioned over the model's own data mesh, composing with
    ``guard=True`` (a skipped step carries the moments bitwise)."""

    def __init__(self, d_in: int = 8, d_hidden: int = 16, batch: int = 32,
                 lr: float = 0.05, seed: int = 0, mesh_devices: int = 2,
                 guard: bool = False, clip_norm: Optional[float] = None,
                 nan_at_step: Optional[int] = None,
                 nan_worker_seed: Optional[int] = None,
                 profile: bool = False, optimizer=None, runprof=None):
        self.d_in, self.d_hidden = int(d_in), int(d_hidden)
        self.batch, self.lr, self.seed = int(batch), float(lr), int(seed)
        self.mesh_devices = int(mesh_devices)
        self.guard = bool(guard)
        self.clip_norm = clip_norm
        self.nan_at_step = nan_at_step
        self.nan_worker_seed = nan_worker_seed
        self.profile = profile
        # ISSUE 13: the optimizer= seam (name string or OptimizerConfig).
        # Moments live on the model instance and persist across run_steps
        # calls — the standard local-optimizer-state regime of a
        # parameter-averaging cluster (contributions carry params only);
        # a pure function of the deterministic batch stream, so
        # simulate_elastic stays an exact oracle when every worker uses
        # the same knobs.
        self.optimizer = optimizer
        # ISSUE 17: the runprof= seam — phase-timed worker steps feeding
        # the runprof_* gauges (None = env-knob default; False = off)
        self.runprof = runprof
        self.skipped_steps = 0
        self._step = None
        self._mesh = None
        self._opt_state = None

    def init_params(self):
        import jax
        import jax.numpy as jnp

        k1, k2 = jax.random.split(jax.random.PRNGKey(self.seed))
        scale = 1.0 / np.sqrt(self.d_in)
        return {
            "w1": jax.random.normal(k1, (self.d_in, self.d_hidden),
                                    jnp.float32) * scale,
            "b1": jnp.zeros((self.d_hidden,), jnp.float32),
            "w2": jax.random.normal(k2, (self.d_hidden, 1),
                                    jnp.float32) * scale,
        }

    def _teacher(self):
        import jax

        k = jax.random.PRNGKey(self.seed + 1000)
        return jax.random.normal(k, (self.d_in, 1))

    @staticmethod
    def _loss_of(p, x, y):
        import jax.numpy as jnp

        h = jnp.tanh(x @ p["w1"] + p["b1"])
        return jnp.mean((h @ p["w2"] - y) ** 2)

    def _guard_config(self):
        if not self.guard:
            return None
        from deeplearning4j_tpu.optimize.guardrails import GuardConfig

        return GuardConfig(clip_norm=self.clip_norm)

    def _opt_config(self):
        from deeplearning4j_tpu.optimize.updaters import OptimizerConfig

        cfg = OptimizerConfig.coerce(self.optimizer)
        return cfg.resolved() if cfg is not None else None

    def _build(self):
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        n = max(1, min(self.mesh_devices, len(jax.devices())))
        n = max(d for d in range(1, n + 1) if self.batch % d == 0)
        self._mesh = Mesh(np.array(jax.devices()[:n]), ("data",))
        self._batch_sharding = NamedSharding(self._mesh, P("data"))
        self._rep_sharding = NamedSharding(self._mesh, P())
        lr = self.lr
        loss_of = self._loss_of
        guard_cfg = self._guard_config()
        opt_cfg = self._opt_config()

        if opt_cfg is not None:
            from deeplearning4j_tpu.optimize.updaters import (
                ZeroSharding,
                guarded_opt_update,
                init_opt_state,
                opt_update,
            )

            zero = (ZeroSharding(self._mesh, "data")
                    if opt_cfg.sharded else None)
            if self._opt_state is None:
                self._opt_state = init_opt_state(opt_cfg,
                                                 self.init_params(), zero)

            if guard_cfg is None:
                def step(params, opt_state, x, y):
                    loss, grads = jax.value_and_grad(loss_of)(params, x, y)
                    new, state = opt_update(opt_cfg, params, grads,
                                            opt_state, lr, zero=zero)
                    return new, state, loss
            else:
                def step(params, opt_state, x, y):
                    loss, grads = jax.value_and_grad(loss_of)(params, x, y)
                    new, state, gm = guarded_opt_update(
                        params, grads, opt_state, loss, lr, opt_cfg,
                        guard_cfg, zero=zero)
                    return new, state, loss, gm["nonfinite"]

            from deeplearning4j_tpu.telemetry.runprof import maybe_runprof
            from deeplearning4j_tpu.telemetry.xprofile import maybe_profiled

            self._step = maybe_runprof(maybe_profiled(
                jax.jit(step, donate_argnums=(0, 1)), self.profile,
                "elastic_worker"), self.runprof, "elastic_worker")
            return

        if guard_cfg is None:
            def step(params, x, y):
                loss, grads = jax.value_and_grad(loss_of)(params, x, y)
                new = jax.tree_util.tree_map(lambda p, g: p - lr * g,
                                             params, grads)
                return new, loss
        else:
            from deeplearning4j_tpu.optimize.guardrails import (
                guarded_sgd_update,
            )

            def step(params, x, y):
                loss, grads = jax.value_and_grad(loss_of)(params, x, y)
                new, gm = guarded_sgd_update(params, grads, loss, lr,
                                             guard_cfg)
                return new, loss, gm["nonfinite"]

        from deeplearning4j_tpu.telemetry.runprof import maybe_runprof
        from deeplearning4j_tpu.telemetry.xprofile import maybe_profiled

        self._step = maybe_runprof(
            maybe_profiled(jax.jit(step, donate_argnums=(0,)),
                           self.profile, "elastic_worker"),
            self.runprof, "elastic_worker")

    @property
    def step_profile(self):
        """The compile-time StepProfile once a profiled step has run
        (None before the first ``run_steps`` or without ``profile=True``)."""
        return getattr(self._step, "step_profile", None)

    def _batch_for(self, worker_seed: int, step_index: int):
        import jax

        k = jax.random.fold_in(
            jax.random.fold_in(jax.random.PRNGKey(self.seed + 7),
                               int(worker_seed)), int(step_index))
        x = jax.random.normal(k, (self.batch, self.d_in))
        y = x @ self._teacher()
        x = np.asarray(x)
        if (self.nan_at_step is not None
                and int(step_index) == int(self.nan_at_step)
                and (self.nan_worker_seed is None
                     or int(worker_seed) == int(self.nan_worker_seed))):
            # deterministic fault injection: poison ONE element of this
            # step's batch — still a pure function of (worker_seed, step),
            # so the simulate_elastic oracle reproduces it exactly
            x = x.copy()
            x[0, 0] = np.nan
        return x, np.asarray(y)

    def eval_loss(self, params, n_batches: int = 8,
                  eval_seed: int = 10_007) -> float:
        """Deterministic held-out MSE — the metric the SparkNet
        sync-period A/B compares across ``sync_every`` settings."""
        import jax
        import jax.numpy as jnp

        p = jax.tree_util.tree_map(jnp.asarray, params)
        total = 0.0
        for i in range(int(n_batches)):
            x, y = self._batch_for(eval_seed, i)
            h = jnp.tanh(jnp.asarray(x) @ p["w1"] + p["b1"])
            total += float(jnp.mean((h @ p["w2"] - jnp.asarray(y)) ** 2))
        return total / n_batches

    def run_steps(self, params, start_step: int, n_steps: int,
                  worker_seed: int):
        import jax

        if self._step is None:
            self._build()
        leaves = jax.tree_util.tree_leaves(params)
        if leaves and all(isinstance(l, jax.Array) for l in leaves):
            # live fast path (ISSUE 14): params already on devices (the
            # carried tree between rounds, or an in-process adoption)
            # respec through the in-graph redistribution plans instead of
            # a host round-trip; noop when already replicated here
            from deeplearning4j_tpu.scaleout.ckpt.redistribution import (
                redistribute_tree,
            )

            params = redistribute_tree(
                params, jax.tree_util.tree_map(
                    lambda _: self._rep_sharding, params))
        else:
            # host path: blobstore-adopted trees arrive as numpy
            params = jax.device_put(
                jax.tree_util.tree_map(np.asarray, params),
                self._rep_sharding)
        has_opt = self._opt_state is not None
        loss = None
        nonfinite_flags = []  # device scalars; ONE fetch after the loop
        for i in range(int(n_steps)):
            x, y = self._batch_for(worker_seed, start_step + i)
            xs = jax.device_put(x, self._batch_sharding)
            ys = jax.device_put(y, self._batch_sharding)
            if has_opt:
                out = self._step(params, self._opt_state, xs, ys)
                params, self._opt_state = out[0], out[1]
                loss = out[2]
                if self.guard:
                    nonfinite_flags.append(out[3])
            else:
                out = self._step(params, xs, ys)
                if self.guard:
                    params, loss, nf = out
                    nonfinite_flags.append(nf)
                else:
                    params, loss = out
        host = jax.tree_util.tree_map(np.asarray, jax.device_get(params))
        if nonfinite_flags:
            skipped = int(sum(
                float(v) for v in jax.device_get(nonfinite_flags)))
            self.skipped_steps += skipped
            if skipped:
                # the live wiring for the nonfinite_step_rate alert rule
                # (ISSUE 15): guarded skips surface in the process
                # registry a worker-side watchtower samples — same name
                # the DivergenceWatchdog uses on the trainer paths
                from deeplearning4j_tpu.telemetry.registry import (
                    default_registry,
                )

                default_registry().counter(
                    "guard_skipped_steps_total").inc(skipped)
        return host, (float(loss) if loss is not None else float("nan"))


def synthetic_regression_model(**kwargs) -> SyntheticRegressionModel:
    """CLI factory (``--model deeplearning4j_tpu.scaleout.elastic:
    synthetic_regression_model``)."""
    return SyntheticRegressionModel(**kwargs)


def synthetic_replay(**kwargs):
    """``tools/step_replay.py`` factory for SyntheticRegressionModel replay
    bundles (``--factory deeplearning4j_tpu.scaleout.elastic:
    synthetic_replay``): re-executes the faulting step's loss + grad from
    a payload of ``{"params": ..., "batch": {"x", "y"}}`` using the exact
    training loss — deterministic, so the non-finite result reproduces."""
    import jax
    import jax.numpy as jnp

    model = SyntheticRegressionModel(**kwargs)

    def run(payload: Dict) -> Dict:
        from deeplearning4j_tpu.telemetry.metrics import global_norm

        p = jax.tree_util.tree_map(jnp.asarray, payload["params"])
        x = jnp.asarray(payload["batch"]["x"])
        y = jnp.asarray(payload["batch"]["y"])
        loss, grads = jax.value_and_grad(model._loss_of)(p, x, y)
        return {"loss": float(loss), "grad_norm": float(global_norm(grads))}

    return run


# ---------------------------------------------------------- blob layout ----

def _global_key(version: int) -> str:
    return f"elastic/global/round_{int(version):06d}.npz"


def _contrib_key(rnd: int, worker_id: str) -> str:
    return f"elastic/contrib/round_{int(rnd):06d}/{worker_id}.npz"


# --------------------------------------------------------------- worker ----

class ElasticWorker:
    """One elastic training process: register → adopt global params →
    ``sync_every`` local jitted steps → publish contribution → repeat.

    Transport robustness: every tracker interaction goes through the
    hardened ``StateTrackerClient`` (timeouts + idempotent retries), and a
    ``TrackerUnavailable`` in the main loop is absorbed as a stall —
    reconnect, re-register (idempotent), continue — so a master restart or
    a flaky link degrades throughput instead of killing the worker."""

    def __init__(self, address: str, blob_uri: str, model: ElasticModel,
                 worker_id: Optional[str] = None, sync_every: int = 4,
                 max_staleness: int = 0, worker_seed: Optional[int] = None,
                 poll_s: float = 0.02, heartbeat_s: float = 0.25,
                 round_timeout_s: float = 60.0,
                 request_timeout_s: float = 5.0,
                 crash_at_round: Optional[int] = None,
                 crash_after_steps: int = 1):
        self.address = address
        self.blob: BlobStore = open_store(blob_uri)
        self.model = model
        self.worker_id = worker_id or f"ew-{uuid.uuid4().hex[:8]}"
        self.sync_every = max(1, int(sync_every))
        self.max_staleness = max(0, int(max_staleness))
        self.worker_seed = (int(worker_seed) if worker_seed is not None
                            else abs(hash(self.worker_id)) % (1 << 31))
        self.poll_s = poll_s
        self.heartbeat_s = heartbeat_s
        self.round_timeout_s = round_timeout_s
        self.request_timeout_s = request_timeout_s
        # fault injection (tests): hard-exit (os._exit, no cleanup) after
        # ``crash_after_steps`` LOCAL steps of round ``crash_at_round`` —
        # mid-round, before that round's contribution is published
        self.crash_at_round = crash_at_round
        self.crash_after_steps = max(0, int(crash_after_steps))
        self.tracker: Optional[StateTrackerClient] = None
        self.round = 0          # next round this worker will contribute to
        self.local_step = 0
        # trace context of the master round span that published the last
        # adopted global version — the parent for this worker's round spans
        self._master_ctx: Optional[Dict] = None

    # -- tracker plumbing --
    def _connect(self) -> StateTrackerClient:
        return StateTrackerClient(self.address, timeout=10.0,
                                  request_timeout_s=self.request_timeout_s)

    def _register(self) -> None:
        t = self.tracker
        t.add_worker(self.worker_id)
        t.increment(f"hb.{self.worker_id}")

    def _heartbeat_loop(self, stop: threading.Event) -> None:
        # separate connection: the main loop's RPCs (and its stalls) must
        # never delay the liveness signal the master watches
        try:
            hb = self._connect()
        except (ConnectionError, OSError) as exc:
            # from here the worker runs with NO liveness signal: the
            # master WILL evict it at the stale deadline. Say so.
            log.warning("worker %s heartbeat connect failed: %r; running "
                        "without a liveness signal", self.worker_id, exc)
            return
        try:
            while not stop.is_set():
                hb.increment(f"hb.{self.worker_id}")
                stop.wait(self.heartbeat_s)
        except (ConnectionError, OSError) as exc:
            # TrackerUnavailable included; master will see us stale
            log.warning("worker %s heartbeat loop died: %r",
                        self.worker_id, exc)
            return
        finally:
            hb.close()

    # -- protocol steps --
    def _committed_version(self) -> int:
        return int(self.tracker.count(VERSION_KEY))

    def _adopt(self, version: int, template):
        data = self.blob.try_get(_global_key(version))
        if data is None:
            return None
        tree, meta = tree_from_bytes(data, template)
        return tree, meta

    def _wait_version_at_least(self, version: int, deadline: float) -> int:
        while True:
            if self.tracker.is_done():
                return -1
            v = self._committed_version()
            if v >= version:
                return v
            if time.monotonic() > deadline:
                raise ElasticTrainingError(
                    f"worker {self.worker_id}: global version {version} not "
                    f"committed within {self.round_timeout_s}s (stuck at {v})")
            time.sleep(self.poll_s)

    def _publish(self, rnd: int, params, loss: float) -> None:
        self.blob.put(_contrib_key(rnd, self.worker_id), tree_to_bytes(
            params, {"round": rnd, "worker": self.worker_id,
                     "n_steps": self.sync_every, "loss": loss}))
        # signal AFTER the atomic blob publish: a counter without a blob
        # can never be observed
        self.tracker.increment(f"contrib.{rnd}.{self.worker_id}")

    def run(self) -> Dict:
        """Train until the master finishes. Returns a summary dict
        (final round/step — what the rejoin test asserts on)."""
        self.tracker = self._connect()
        template = self.model.init_params()
        stop = threading.Event()
        hb = threading.Thread(target=self._heartbeat_loop, args=(stop,),
                              daemon=True)
        tracer = _trace.get_tracer()
        try:
            # join at the CURRENT version: pull averaged params + step and
            # get admitted from this round — the rejoin path and the cold
            # start are the same code
            with _trace.maybe_span("worker.join",
                                   attrs={"worker": self.worker_id}):
                v = self._committed_version()
                adopted = None
                deadline = time.monotonic() + self.round_timeout_s
                while adopted is None:
                    adopted = self._adopt(v, template)
                    if adopted is None:
                        if time.monotonic() > deadline:
                            raise ElasticTrainingError(
                                f"worker {self.worker_id}: no global params "
                                f"blob for version {v}")
                        time.sleep(self.poll_s)
                params, meta = adopted
                self._master_ctx = meta.get("trace")
                self.round = v
                self.local_step = int(meta.get("step", v * self.sync_every))
                if v > 0:
                    self.tracker.increment("elastic.joined")
                self.tracker.increment(f"admit.{self.worker_id}", float(v))
                self._register()
            hb.start()
            if tracer is not None:
                # write-ahead dump: a round-0 kill -9 still leaves this
                tracer.flight_checkpoint(extra={"event": "registered",
                                                "worker": self.worker_id,
                                                "round": self.round})
            params = self._run_rounds(params, template)
            return {"worker_id": self.worker_id, "round": self.round,
                    "step": self.local_step}
        except BaseException as exc:
            if tracer is not None:
                tracer.dump(type(exc).__name__, error=exc,
                            extra={"worker": self.worker_id,
                                   "round": self.round,
                                   "step": self.local_step})
            raise
        finally:
            stop.set()
            if hb.ident is not None:  # started: wait for its last RPC so
                hb.join(timeout=10)   # teardown never races hb.close()
            if self.tracker is not None:
                self.tracker.close()

    def _run_rounds(self, params, template):
        last_ok = time.monotonic()
        while True:
            # re-read per round: tracing can be enabled/disabled mid-run
            # (late configure, or the bench's round-alternating A/B)
            tracer = _trace.get_tracer()
            try:
                if self.tracker.is_done():
                    return params
                # adopt the freshest committed version we haven't seen;
                # jump forward if the cluster moved on without us
                v = self._committed_version()
                if v >= self.round:
                    adopted = self._adopt(v, template)
                    if adopted is not None:
                        params, meta = adopted
                        self._master_ctx = meta.get("trace")
                        self.round = v
                        self.local_step = int(
                            meta.get("step", v * self.sync_every))
                rnd = self.round
                # the round span parents under the master round span that
                # published the adopted version (its ctx rode the blob
                # meta) — the cross-process link in the merged trace. A
                # crash inside the ``with`` skips __exit__, leaving the
                # begin-record on disk as an OPEN span for trace_report.
                round_cm = (tracer.span(
                                "worker.round",
                                parent=self._master_ctx or False,
                                attrs={"round": rnd,
                                       "worker": self.worker_id,
                                       "start_step": self.local_step})
                            if tracer is not None
                            else contextlib.nullcontext())
                with round_cm:
                    if self.crash_at_round is not None and \
                            rnd >= self.crash_at_round:
                        import os as _os

                        with _trace.maybe_span(
                                "worker.steps",
                                attrs={"round": rnd,
                                       "n_steps": self.crash_after_steps}):
                            params, _ = self.model.run_steps(
                                params, self.local_step,
                                self.crash_after_steps, self.worker_seed)
                        _os._exit(23)  # kill -9 analogue: mid-round, unsynced
                    with _trace.maybe_span(
                            "worker.steps",
                            attrs={"round": rnd,
                                   "start_step": self.local_step,
                                   "n_steps": self.sync_every}) as ssp:
                        params, loss = self.model.run_steps(
                            params, self.local_step, self.sync_every,
                            self.worker_seed)
                        if ssp is not None:
                            ssp.set_attr("loss", float(loss))
                    self.local_step += self.sync_every
                    with _trace.maybe_span(
                            "worker.publish",
                            attrs={"round": rnd, "worker": self.worker_id}):
                        self._publish(rnd, params, loss)
                    self.round = rnd + 1
                    # DeepSpark staleness window: block only once our lead
                    # over the committed version exceeds max_staleness
                    with _trace.maybe_span(
                            "worker.sync_wait",
                            attrs={"round": rnd,
                                   "wait_for_version":
                                       self.round - self.max_staleness}):
                        got = self._wait_version_at_least(
                            self.round - self.max_staleness,
                            time.monotonic() + self.round_timeout_s)
                if tracer is not None:
                    tracer.flight_checkpoint(
                        extra={"worker": self.worker_id, "round": self.round,
                               "step": self.local_step})
                if got < 0:
                    return params
                last_ok = time.monotonic()
            except TrackerUnavailable:
                # master restart / dropped link: stall, reconnect,
                # re-register (idempotent), carry on from local state —
                # bounded by round_timeout_s so a dead master is
                # eventually a loud failure, not a silent spin
                if time.monotonic() - last_ok > self.round_timeout_s:
                    raise
                time.sleep(self.poll_s * 5)
                try:
                    self.tracker.close()
                    self.tracker = self._connect()
                    self._register()
                except (ConnectionError, OSError):
                    continue


# --------------------------------------------------------------- master ----

class ElasticMaster:
    """The elastic counterpart of ``distributed_runner.DistributedMaster``:
    embeds the tracker server, owns the blob store, commits averaging
    rounds over whatever survivor set is alive, and checkpoints the
    averaged params. ``train(rounds)`` returns the final averaged tree."""

    def __init__(self, model: ElasticModel, blob_uri: str,
                 server: Optional[StateTrackerServer] = None,
                 initial_params=None, start_version: int = 0,
                 sync_every: int = 4, min_workers: int = 1,
                 worker_timeout_s: float = 5.0,
                 register_timeout_s: float = 60.0,
                 round_timeout_s: float = 120.0, tick_s: float = 0.01,
                 checkpointer=None, checkpoint_every: int = 0,
                 registry=None, trace_dir: Optional[str] = None,
                 quarantine_nonfinite: bool = True,
                 watch: bool = False, watch_dir: Optional[str] = None):
        from deeplearning4j_tpu.telemetry.registry import default_registry

        # tracing: adopt the process tracer if one is configured; a
        # trace_dir here is the convenience path that configures one
        # (process name "master") including crash hooks
        self.tracer = _trace.get_tracer()
        if trace_dir is not None and self.tracer is None:
            self.tracer = _trace.configure("master", trace_dir)
        self._run_span = None
        self._round_span = None
        self.server = server or StateTrackerServer()
        self.tracker = self.server.tracker  # embedded: zero-IPC master side
        self.blob_uri = blob_uri
        self.blob = open_store(blob_uri)
        self.model = model
        self.sync_every = max(1, int(sync_every))
        self.min_workers = max(1, int(min_workers))
        self.worker_timeout_s = worker_timeout_s
        self.register_timeout_s = register_timeout_s
        self.round_timeout_s = round_timeout_s
        self.tick_s = tick_s
        self.checkpointer = checkpointer
        self.checkpoint_every = int(checkpoint_every)
        self.registry = registry if registry is not None else default_registry()
        self.version = int(start_version)
        self._params = (initial_params if initial_params is not None
                        else self.model.init_params())
        self._params = _host_tree(self._params)
        self._template = self.model.init_params()
        self._hb_seen: Dict[str, tuple] = {}
        self._admit: Dict[str, int] = {}
        # numerical quarantine (ISSUE 8): a contribution with any
        # non-finite leaf is excluded from the average and its worker is
        # buried (removed from the round barrier) — sticky for the run, so
        # averaging can NEVER ingest a poisoned delta
        self.quarantine_nonfinite = bool(quarantine_nonfinite)
        self._quarantined: set = set()
        # watchtower (ISSUE 15): history sampler + alert engine over THIS
        # master's registry, publishing verdicts into the embedded
        # tracker's KV — workers / routers / a UiServer aggregator read
        # the cluster alert view over the same TCP plane the membership
        # rides. The default pack's worker_divergence / heartbeat-stale /
        # reconnect-storm rules all key off metrics this class emits.
        self.watchtower = None
        if watch:
            from deeplearning4j_tpu.telemetry.alerts import arm_watchtower

            self.watchtower = arm_watchtower(
                registry=self.registry, tracker=self.tracker,
                process="master", out_dir=watch_dir)
        self._publish_version(self.version, self._params)

    # -- plumbing --
    @property
    def address(self) -> str:
        return self.server.address

    def _publish_version(self, version: int, params) -> None:
        meta = {"version": version, "step": version * self.sync_every}
        if self.tracer is not None:
            if self._run_span is None:
                self._run_span = self.tracer.start_span(
                    "elastic.train", parent=False,
                    attrs={"sync_every": self.sync_every,
                           "min_workers": self.min_workers})
            # the span for round ``version`` opens when version ``version``
            # is published (workers adopt it and train round ``version``
            # against it) and closes when version+1 commits; its context
            # rides the blob meta so worker round spans parent under it
            if self._round_span is not None:
                self._round_span.end()
            self._round_span = self.tracer.start_span(
                "elastic.round", parent=self._run_span,
                attrs={"round": version})
            meta["trace"] = self._round_span.context()
        self.blob.put(_global_key(version), tree_to_bytes(params, meta))
        # the counter IS the committed-version number; a resume can jump it
        # by more than one
        behind = version - self.tracker.count(VERSION_KEY)
        if behind > 0:
            self.tracker.increment(VERSION_KEY, float(behind))
        self.registry.gauge("elastic_version").set(float(version))

    def _live_workers(self) -> List[str]:
        return list(self.tracker.workers())

    def _dead_workers(self) -> List[str]:
        now = time.monotonic()
        dead = []
        for wid in self._live_workers():
            count = self.tracker.count(f"hb.{wid}")
            seen = self._hb_seen.get(wid)
            if seen is None or seen[0] != count:
                self._hb_seen[wid] = (count, now)
                # heartbeat-timestamp gauge (ISSUE 15): the absence-rule
                # convention — a *_unix gauge per worker that the
                # worker_heartbeat_stale rule checks for staleness
                self.registry.gauge("elastic_worker_heartbeat_unix",
                                    {"worker": wid}).set(time.time())
            elif now - seen[1] > self.worker_timeout_s:
                dead.append(wid)
        return dead

    def _bury(self, wid: str) -> None:
        self.tracker.remove_worker(wid)
        self._hb_seen.pop(wid, None)
        # retire the heartbeat series (non-positive sentinel): a BURIED
        # worker is handled — the staleness alert must stop firing for it
        self.registry.gauge("elastic_worker_heartbeat_unix",
                            {"worker": wid}).set(-1.0)
        self.tracker.increment("workers_failed")
        self.registry.counter("elastic_workers_failed_total").inc()
        log.warning("elastic worker %s heartbeat stale >%ss: deregistered; "
                    "continuing on the survivor set", wid,
                    self.worker_timeout_s)

    def _admit_round(self, wid: str) -> int:
        if wid not in self._admit:
            self._admit[wid] = int(self.tracker.count(f"admit.{wid}"))
            if self._admit[wid] > 0:
                self.registry.counter("elastic_workers_joined_total").inc()
        return self._admit[wid]

    def _contributions(self, rnd: int) -> Dict[str, tuple]:
        """(tree, n_steps) per worker that has a committed contribution
        blob for ``rnd`` — includes workers that died after publishing
        (their synced work is kept; only unsynced deltas are lost), but
        never a quarantined worker's (its numerical trust is gone for the
        run; see ``_quarantine``)."""
        out: Dict[str, tuple] = {}
        signals = self.tracker.counters_snapshot(f"contrib.{rnd}.")
        template = self._template
        for key, val in signals.items():
            if val <= 0:
                continue
            wid = key[len(f"contrib.{rnd}."):]
            if wid in self._quarantined:
                continue
            data = self.blob.try_get(_contrib_key(rnd, wid))
            if data is None:
                continue  # signal raced the (atomic) blob publish; re-poll
            tree, meta = tree_from_bytes(data, template)
            out[wid] = (tree, float(meta.get("n_steps", self.sync_every)))
        return out

    def _quarantine(self, wid: str, rnd: int, barrier_sp=None) -> None:
        """The bury path for NUMERICAL faults: a worker whose round-``rnd``
        contribution carries NaN/Inf is removed from membership (so the
        barrier stops waiting for it) and excluded from every future
        round's averaging — one poisoned delta must never contaminate the
        survivors. Sticky for the run: replace the worker process to
        rejoin. Recorded as the ``nonfinite`` barrier event + a flight
        dump, the forensic trail the fault-matrix test pins."""
        from deeplearning4j_tpu.optimize.guardrails import nonfinite_report

        self._quarantined.add(wid)
        self.tracker.remove_worker(wid)
        self._hb_seen.pop(wid, None)
        self.registry.gauge("elastic_worker_heartbeat_unix",
                            {"worker": wid}).set(-1.0)
        self.tracker.increment("workers_quarantined")
        self.registry.counter("elastic_workers_quarantined_total").inc()
        log.error("elastic worker %s published a NON-FINITE contribution "
                  "for round %s: quarantined (excluded from averaging and "
                  "the round barrier for the rest of the run)", wid, rnd)
        if barrier_sp is not None:
            barrier_sp.add_event("nonfinite", worker=wid, round=rnd)
        if self.tracer is not None:
            data = self.blob.try_get(_contrib_key(rnd, wid))
            report = []
            if data is not None:
                tree, _meta = tree_from_bytes(data, self._template)
                report = [e for e in nonfinite_report(tree)
                          if e.get("nonfinite")]
            self.tracer.dump("nonfinite",
                             extra={"worker": wid, "round": int(rnd),
                                    "poisoned_leaves": report})

    # -- lifecycle --
    def wait_for_workers(self, n: Optional[int] = None) -> None:
        need = n if n is not None else self.min_workers
        deadline = time.monotonic() + self.register_timeout_s
        while len(self._live_workers()) < need:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"{len(self._live_workers())}/{need} elastic workers "
                    f"registered within {self.register_timeout_s}s")
            time.sleep(0.05)

    def _maybe_checkpoint(self, version: int) -> None:
        if (self.checkpointer is None or self.checkpoint_every <= 0
                or version % self.checkpoint_every):
            return
        ck_cm = (self.tracer.span("elastic.checkpoint",
                                  parent=self._round_span,
                                  attrs={"version": version})
                 if self.tracer is not None else contextlib.nullcontext())
        with ck_cm:
            self.checkpointer.save(
                version, {"params": self._params},
                meta={"elastic_version": version,
                      "elastic_step": version * self.sync_every,
                      "sync_every": self.sync_every})

    def train(self, rounds: int, finish: bool = True):
        """Commit ``rounds`` averaging rounds (versions ``start+1 ..
        start+rounds``); returns the final averaged host tree.
        ``finish=False`` keeps the cluster alive (workers park at the
        staleness gate) so a later ``train`` call can continue the run —
        the rejoin tests use the gap to admit replacements
        deterministically."""
        ok = False
        try:
            target = self.version + int(rounds)
            while self.version < target:
                rnd = self.version  # collecting round ``rnd`` contributions
                contribs = self._barrier(rnd)
                wids = sorted(contribs)  # deterministic averaging order
                avg_sp = (self.tracer.start_span(
                              "elastic.average", parent=self._round_span,
                              attrs={"round": rnd, "n_contrib": len(wids)})
                          if self.tracer is not None else None)
                self._params = average_trees(
                    [contribs[w][0] for w in wids],
                    [contribs[w][1] for w in wids])
                if avg_sp is not None:
                    avg_sp.end()
                self.version += 1
                self._publish_version(self.version, self._params)
                self.registry.counter("elastic_rounds_total").inc()
                self.tracker.increment("rounds_completed")
                self._maybe_checkpoint(self.version)
                if self.tracer is not None:
                    # write-ahead dump at the commit boundary: a later
                    # master kill leaves at least this round's forensics
                    self.tracer.flight_checkpoint(
                        extra={"version": self.version,
                               "contributors": wids})
            ok = True
            return self._params
        except ElasticTrainingError as exc:
            if self.tracer is not None:
                self.tracer.dump("ElasticTrainingError", error=exc,
                                 extra={"version": self.version})
            raise
        finally:
            if finish or not ok:  # a failed run always releases the
                self.tracker.finish()  # workers' poll loops

    def _barrier(self, rnd: int) -> Dict[str, tuple]:
        """Collect round ``rnd`` until every live worker admitted
        at-or-before it has contributed (burying heartbeat-stale workers
        along the way). Traced as ``elastic.barrier`` with a
        ``contribution``/``buried`` event per arrival/death — the raw
        material for trace_report's who-did-the-round-wait-on table."""
        deadline = time.monotonic() + self.round_timeout_s
        barrier_sp = (self.tracer.start_span(
                          "elastic.barrier", parent=self._round_span,
                          attrs={"round": rnd})
                      if self.tracer is not None else None)
        seen: set = set()
        try:
            while True:
                for wid in self._dead_workers():
                    self._bury(wid)
                    if barrier_sp is not None:
                        barrier_sp.add_event("buried", worker=wid)
                live = self._live_workers()
                self.registry.gauge("elastic_live_workers").set(
                    float(len(live)))
                if len(live) < self.min_workers:
                    raise ElasticTrainingError(
                        f"survivor set {live} below min_workers="
                        f"{self.min_workers} at round {rnd} — halting "
                        "(raise min_workers tolerance or add workers)")
                contribs = self._contributions(rnd)
                if self.quarantine_nonfinite:
                    from deeplearning4j_tpu.optimize.guardrails import (
                        tree_all_finite,
                    )

                    for w in sorted(contribs):
                        if not tree_all_finite(contribs[w][0]):
                            contribs.pop(w)
                            self._quarantine(w, rnd, barrier_sp)
                    live = self._live_workers()  # quarantine shrank the set
                    if len(live) < self.min_workers:
                        raise ElasticTrainingError(
                            f"survivor set {live} below min_workers="
                            f"{self.min_workers} after quarantine at round "
                            f"{rnd} — halting")
                if barrier_sp is not None:
                    for w in sorted(contribs):
                        if w not in seen:
                            seen.add(w)
                            barrier_sp.add_event("contribution", worker=w)
                required = [w for w in live
                            if self._admit_round(w) <= rnd]
                if required and all(w in contribs for w in required):
                    if barrier_sp is not None:
                        barrier_sp.set_attr("contributors", sorted(contribs))
                        barrier_sp.set_attr("required", sorted(required))
                        barrier_sp.end()
                    return contribs
                if time.monotonic() > deadline:
                    raise ElasticTrainingError(
                        f"round {rnd} barrier timed out after "
                        f"{self.round_timeout_s}s: live={live} "
                        f"contributed={sorted(contribs)}")
                time.sleep(self.tick_s)
        except BaseException as exc:
            if barrier_sp is not None:
                barrier_sp.end(error=exc)
            raise

    def resume(self) -> Optional[int]:
        """Adopt the latest committed checkpoint (params + version); call
        before ``train``. Returns the resumed version or None."""
        if self.checkpointer is None:
            return None
        step = self.checkpointer.latest_step()
        if step is None:
            return None
        template = {"params": self.model.init_params()}
        state, version, meta = self.checkpointer.restore(template, step=step)
        self._params = _host_tree(state["params"])
        self.version = int(meta.get("elastic_version", version))
        self._publish_version(self.version, self._params)
        return self.version

    def params(self):
        return self._params

    def shutdown(self) -> None:
        if self.watchtower is not None:
            self.watchtower.tick()  # final verdict lands even mid-interval
            self.watchtower.stop()
            self.watchtower = None
        if self.checkpointer is not None and hasattr(self.checkpointer,
                                                     "flush"):
            self.checkpointer.flush()
        if self.tracer is not None:
            if self._round_span is not None:
                self._round_span.end()
                self._round_span = None
            if self._run_span is not None:
                self._run_span.end()
                self._run_span = None
        self.server.shutdown()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()


def _host_tree(tree):
    import jax

    return jax.tree_util.tree_map(np.asarray, jax.device_get(tree))


# ----------------------------------------------------- parity reference ----

def simulate_elastic(model: ElasticModel, worker_seeds: List[int],
                     sync_every: int, rounds: int,
                     schedule: Optional[Dict[int, List[int]]] = None):
    """In-process reference of the round protocol: same adoption, same
    local-step indexing, same ``average_trees`` math — the oracle the
    multi-process fault tests compare against. ``schedule`` optionally maps
    round → the subset of worker indices contributing that round (models a
    mid-run kill or rejoin); default: everyone, every round. Returns
    ``(final_params, per_round_losses)``."""
    global_params = _host_tree(model.init_params())
    losses: List[float] = []
    for rnd in range(int(rounds)):
        present = (schedule.get(rnd) if schedule is not None else None)
        idxs = list(range(len(worker_seeds))) if present is None else present
        if not idxs:
            raise ElasticTrainingError(f"simulated round {rnd} has no "
                                       "contributors")
        trees, weights, rl = [], [], []
        for i in idxs:
            p, loss = model.run_steps(global_params, rnd * sync_every,
                                      sync_every, worker_seeds[i])
            trees.append(_host_tree(p))
            weights.append(float(sync_every))
            rl.append(loss)
        global_params = average_trees(trees, weights)
        losses.append(float(np.mean(rl)))
    return global_params, losses


# ------------------------------------------------------------------ CLI ----

def _resolve_model(spec: str, kwargs: dict) -> ElasticModel:
    """"pkg.module:factory" → factory(**kwargs) -> ElasticModel."""
    module_name, _, attr = spec.partition(":")
    factory = getattr(importlib.import_module(module_name), attr)
    return factory(**kwargs)


def worker_main(argv=None) -> None:
    """CLI worker entry: ``python -m deeplearning4j_tpu.scaleout.elastic
    --connect HOST:PORT --blob URI --model pkg.mod:factory [...]`` — the
    elastic analogue of ``distributed_runner.worker_main``."""
    p = argparse.ArgumentParser(description="elastic training worker")
    p.add_argument("--connect", required=True, help="master tracker host:port")
    p.add_argument("--blob", required=True, help="shared blob store URI")
    p.add_argument("--model", required=True,
                   help="pkg.module:factory for the ElasticModel")
    p.add_argument("--kwargs-json", default="{}",
                   help="JSON kwargs for the model factory")
    p.add_argument("--worker-id", default=None)
    p.add_argument("--worker-seed", type=int, default=None)
    p.add_argument("--sync-every", type=int, default=4)
    p.add_argument("--max-staleness", type=int, default=0)
    p.add_argument("--round-timeout-s", type=float, default=60.0)
    p.add_argument("--crash-at-round", type=int, default=None,
                   help="fault injection: os._exit mid-round at round N")
    p.add_argument("--crash-after-steps", type=int, default=1,
                   help="local steps to run inside the crashing round")
    p.add_argument("--trace-dir", default=None,
                   help="write per-process span JSONL + flight-recorder "
                        "dumps under this directory (ISSUE 7)")
    p.add_argument("--watch-dir", default=None,
                   help="arm the watchtower (ISSUE 15): sample this "
                        "process's registry into a history spill, "
                        "evaluate the default alert pack, publish "
                        "verdicts to the master's tracker KV, and write "
                        "history/alert JSONL under this directory")
    args = p.parse_args(argv)
    model = _resolve_model(args.model, json.loads(args.kwargs_json))
    worker = ElasticWorker(
        args.connect, args.blob, model, worker_id=args.worker_id,
        sync_every=args.sync_every, max_staleness=args.max_staleness,
        worker_seed=args.worker_seed, round_timeout_s=args.round_timeout_s,
        crash_at_round=args.crash_at_round,
        crash_after_steps=args.crash_after_steps)
    if args.trace_dir:
        _trace.configure(worker.worker_id, args.trace_dir)
    tower = None
    if args.watch_dir:
        from deeplearning4j_tpu.telemetry.alerts import arm_watchtower

        # its own tracker connection: alert publishes must never ride
        # (or stall behind) the training loop's RPC slot
        tower = arm_watchtower(process=worker.worker_id,
                               tracker_address=args.connect,
                               out_dir=args.watch_dir)
    try:
        summary = worker.run()
    finally:
        if tower is not None:
            tower.tick()  # the final verdict lands even mid-interval
            tower.stop()
    print("ELASTIC_WORKER_DONE " + json.dumps(summary), flush=True)


if __name__ == "__main__":
    worker_main()
