"""Cross-process StateTracker: TCP server + client with the same contract.

Parity with ref: the reference's tracker is a Hazelcast data grid usable
embedded-or-client across machines
(scaleout/statetracker/hazelcast/BaseHazelCastStateTracker.java:78-100 —
the constructor takes "master"/"worker" and either boots the grid or
connects to it; cluster boot actor/runner/DeepLearning4jDistributed.java:
207-260). Here the master EMBEDS ``StateTrackerServer`` (which wraps a
thread-safe in-process tracker) and workers connect a
``StateTrackerClient`` — the identical ``StateTracker`` API on both sides,
so every control-plane component (work routers, aggregators, early
stopping, the runners) runs unchanged across process boundaries.

Wire protocol: length-prefixed pickle frames carrying (method, args,
kwargs[, trace_ctx]) → (ok, result-or-exception). The optional 4th
element is a telemetry.trace span context ({"trace_id", "span_id"}) —
present only when the calling thread is inside a traced operation — and
the server, when a process tracer is configured, records a
``tracker.serve`` span parented under it, so a worker's RPC and the
master's handling of it land in ONE distributed trace (ISSUE 7). A
3-tuple frame stays valid: tracing off ⇒ the PR 6 wire format, byte for
byte. Pickle matches the payloads (Jobs
holding numpy param arrays / DataSets) and the reference's posture
(Hazelcast serialized arbitrary Java objects the same way); the listener
binds to 127.0.0.1 by default and the boundary is trusted-cluster only —
exactly the reference's deployment model, not an internet-facing API.

Cross-process ``clear_updates(expected)``: the in-memory tracker keys the
"only clear what I aggregated" rule on object IDENTITY, which cannot cross
pickling. The server versions every update; ``updates()`` on the client
remembers each snapshot's versions and ``clear_updates`` sends them, so
the compare-and-delete happens server-side with the same no-lost-update
guarantee (a newer unseen snapshot is never deleted unaggregated).

Transport fault model (ISSUE 6): every client socket carries a connect AND
a per-request timeout — a hung or restarting master turns into a bounded
stall, never a thread blocked forever in ``recv``. Idempotent calls (reads,
and the last-write-wins / compare-and-delete writes) are retried on a fresh
connection with bounded jittered backoff; non-idempotent calls
(``increment``, blind ``clear_updates``) fail fast, because a retry after a
lost response could double-apply. Every transport failure surfaces as
``TrackerUnavailable`` (a ``ConnectionError`` subclass, so existing
handlers keep working) rather than a bare socket error, and reconnects /
retries / failures land in the telemetry registry
(``tracker_reconnects_total`` / ``tracker_retries_total`` /
``tracker_failures_total``).
"""

from __future__ import annotations

import pickle
import random
import socket
import socketserver
import struct
import threading
import time
from typing import Any, Dict, Optional

from deeplearning4j_tpu.scaleout.job import Job
from deeplearning4j_tpu.scaleout.statetracker import (
    InMemoryStateTracker,
    StateTracker,
)
from deeplearning4j_tpu.telemetry import trace as _trace
from deeplearning4j_tpu.utils import netwatch
from deeplearning4j_tpu.utils.lockwatch import make_lock

_HDR = struct.Struct(">I")
_MAX_FRAME = 1 << 30


class TrackerUnavailable(ConnectionError):
    """The tracker could not be reached (connect/request timeout, broken
    frame, or retry budget exhausted). Subclasses ``ConnectionError`` so
    pre-existing ``except (ConnectionError, OSError)`` handlers — worker
    heartbeat loops, poll loops — keep treating it as a transport fault."""


# Calls safe to re-issue after an ambiguous failure (the request may or may
# not have been applied before the connection broke): pure reads, and writes
# that are last-write-wins per key or compare-and-delete. ``increment`` and
# the blind ``clear_updates`` are excluded — replaying either can
# double-apply (double-count / drop an update that landed in between).
# High-frequency poll methods whose per-call spans would be pure noise
# (a version-wait loop issues dozens per round at poll_s cadence). Their
# aggregate cost is exactly the enclosing span's duration (worker.sync_wait
# etc), so skipping the per-poll spans loses nothing the timeline needs.
_UNTRACED_POLLS = frozenset({"count", "is_done"})

_IDEMPOTENT = frozenset({
    "add_worker", "remove_worker", "workers",
    "add_job", "job_for", "clear_job", "has_pending_jobs",
    "add_update", "updates_versioned", "clear_updates_versioned",
    "set_current", "get_current",
    "put_kv", "get_kv", "kv_snapshot",
    "add_replicate", "needs_replicate", "done_replicating",
    "count", "counters_snapshot", "finish", "is_done",
    "set_best_loss", "best_loss", "early_stop", "is_early_stop",
})

# Every RPC method must be classified one way or the other: a new method
# in neither set is a retry-policy decision nobody made, and both the
# ``nonidempotent-retry`` lint and ``_call_locked`` reject it.
_NONIDEMPOTENT = frozenset({"increment", "clear_updates"})


def _send_frame(sock: socket.socket, obj: Any) -> None:
    data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_HDR.pack(len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("tracker connection closed")
        buf += chunk
    return buf


def _recv_frame(sock: socket.socket) -> Any:
    (n,) = _HDR.unpack(_recv_exact(sock, _HDR.size))
    if n > _MAX_FRAME:
        raise ConnectionError(f"oversized tracker frame ({n} bytes)")
    return pickle.loads(_recv_exact(sock, n))


class _VersionedTracker(InMemoryStateTracker):
    """Server-side tracker: updates carry monotone versions so the
    clear-if-unchanged rule survives serialization."""

    def __init__(self):
        super().__init__()
        self._update_versions: Dict[str, int] = {}
        self._version_counter = 0

    def add_update(self, worker_id: str, job: Job) -> None:
        with self._lock:
            self._updates[worker_id] = job
            self._version_counter += 1
            self._update_versions[worker_id] = self._version_counter

    def updates_versioned(self):
        with self._lock:
            return dict(self._updates), dict(self._update_versions)

    def clear_updates_versioned(self, expected_versions: Dict[str, int]):
        with self._lock:
            for worker_id, version in expected_versions.items():
                if self._update_versions.get(worker_id) == version:
                    del self._updates[worker_id]
                    del self._update_versions[worker_id]

    def clear_updates(self, expected=None) -> None:
        # embedded-side callers still get identity semantics; keep the
        # version map consistent with whatever survives
        with self._lock:
            super().clear_updates(expected)
            self._update_versions = {
                w: v for w, v in self._update_versions.items()
                if w in self._updates
            }


class StateTrackerServer:
    """Embeds a versioned tracker and serves it over TCP (the "master"
    Hazelcast member). ``tracker`` is the embedded handle — the master-side
    code uses it directly with zero IPC."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 handler_timeout_s: float = 300.0):
        self.tracker = _VersionedTracker()
        self.handler_timeout_s = handler_timeout_s
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                # A dead client must not pin this handler thread forever
                # (the PR 10 deflake documented exactly this class):
                # bound every recv, generously enough that an idle but
                # alive client at the repo's poll cadences never trips
                # it. ``socket.timeout`` is an OSError, so expiry takes
                # the same client-went-away exit below.
                self.request = netwatch.wrap_socket(
                    self.request, "tracker.server.handler")
                self.request.settimeout(outer.handler_timeout_s)
                try:
                    while True:
                        frame = _recv_frame(self.request)
                        method, args, kwargs = frame[:3]
                        ctx = frame[3] if len(frame) > 3 else None
                        tracer = _trace.get_tracer()
                        sp = (tracer.start_span(
                                  "tracker.serve", parent=ctx,
                                  attrs={"method": method})
                              if tracer is not None and ctx else None)
                        try:
                            fn = getattr(outer.tracker, method)
                            _send_frame(self.request,
                                        (True, fn(*args, **kwargs)))
                            if sp is not None:
                                sp.end()
                        except Exception as e:  # surfaced client-side
                            if sp is not None:
                                sp.end(error=e)
                            _send_frame(self.request, (False, e))
                # graftlint: allow[swallowed-thread-exception] a transport fault here IS the handler's normal exit: the client disconnected (or idled past handler_timeout_s) and its state stays in the grid
                except (ConnectionError, EOFError, OSError):
                    return

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="state-tracker-server")
        self._thread.start()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def shutdown(self) -> None:
        self._server.shutdown()  # stops serve_forever; established handler
        self._server.server_close()  # sockets drain on their own threads
        self._thread.join(timeout=10)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()


class StateTrackerClient(StateTracker):
    """The "worker" Hazelcast client: every StateTracker method is one RPC
    to the master's server. Thread-safe (one socket, request lock).

    ``timeout`` bounds the TCP connect; ``request_timeout_s`` bounds every
    request/response round trip (a hung master is a ``TrackerUnavailable``
    after that many seconds, not a forever-blocked worker thread).
    Idempotent calls are retried up to ``retries`` times on a fresh
    connection with jittered exponential backoff; a broken frame mid-stream
    (master restart, dropped proxy) triggers the same reconnect path."""

    def __init__(self, address: str, timeout: float = 30.0,
                 request_timeout_s: float = 10.0, retries: int = 3,
                 backoff_s: float = 0.05, max_backoff_s: float = 1.0,
                 registry=None):
        host, _, port = address.rpartition(":")
        self._addr = (host, int(port))
        self._connect_timeout = timeout
        self._request_timeout_s = request_timeout_s
        self._retries = max(0, int(retries))
        self._backoff_s = backoff_s
        self._max_backoff_s = max_backoff_s
        if registry is None:
            from deeplearning4j_tpu.telemetry.registry import default_registry

            registry = default_registry()
        self._registry = registry
        self._lock = make_lock("tracker.client")  # lockwatch seam
        self._sock: Optional[socket.socket] = None
        # version bookkeeping for clear_updates(expected) — see module doc
        self._snapshot_versions: Dict[int, Dict[str, int]] = {}
        self._connect()  # fail fast on a bad address, like the old client

    # ---- transport ----
    def _connect(self) -> None:
        sock = socket.create_connection(self._addr,
                                        timeout=self._connect_timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.settimeout(self._request_timeout_s)
        self._sock = netwatch.wrap_socket(sock, "tracker.client")

    def _drop_socket(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _roundtrip(self, method: str, args, kwargs, span=None):
        if self._sock is None:
            self._connect()
            self._registry.counter("tracker_reconnects_total").inc()
            netwatch.record_reconnect("tracker.client")
            if span is not None:
                span.add_event("reconnect")
        if span is not None:
            frame = (method, args, kwargs, span.context())
        else:
            frame = (method, args, kwargs)
        _send_frame(self._sock, frame)
        return _recv_frame(self._sock)

    def _call(self, method: str, *args, **kwargs):
        """One RPC with the retry policy (see ``_call_locked``). When the
        calling thread is inside a traced span, the RPC gets its own
        ``tracker.rpc`` span (retries/reconnects as span events) and the
        span context rides the frame to the server — a thread with no open
        span (heartbeat loops, bare polls) stays on the untraced 3-tuple
        path, so tracing never floods the sink with liveness chatter."""
        tracer = _trace.get_tracer()
        if (tracer is not None and method not in _UNTRACED_POLLS
                and tracer.current_span() is not None):
            with tracer.span("tracker.rpc",
                             attrs={"method": method}) as sp:
                return self._call_locked(method, args, kwargs, sp)
        return self._call_locked(method, args, kwargs, None)

    def _call_locked(self, method: str, args, kwargs, span):
        """Any transport-layer failure — timeout, reset, short/garbled
        frame — closes the socket; idempotent methods then retry on a
        fresh connection, everything else surfaces ``TrackerUnavailable``
        immediately (see ``_IDEMPOTENT``)."""
        if method not in _IDEMPOTENT and method not in _NONIDEMPOTENT:
            raise ValueError(
                f"tracker RPC {method!r} has no idempotency classification; "
                "add it to _IDEMPOTENT or _NONIDEMPOTENT (this decides its "
                "retry policy — see the nonidempotent-retry lint)")
        attempts = (self._retries + 1) if method in _IDEMPOTENT else 1
        last_exc: Optional[BaseException] = None
        with self._lock:
            for attempt in range(attempts):
                if attempt:
                    self._registry.counter("tracker_retries_total").inc()
                    netwatch.record_retry("tracker.client")
                    if span is not None:
                        span.add_event("retry", attempt=attempt,
                                       error=repr(last_exc))
                    delay = min(self._max_backoff_s,
                                self._backoff_s * (2 ** (attempt - 1)))
                    # graftlint: allow[blocking-under-lock] deliberate: the request lock IS the retry slot — releasing it mid-backoff would interleave another thread's frames onto the resyncing socket
                    time.sleep(delay * (0.5 + random.random() / 2))
                try:
                    ok, result = self._roundtrip(method, args, kwargs, span)
                except (ConnectionError, socket.timeout, OSError, EOFError,
                        struct.error, pickle.UnpicklingError) as exc:
                    last_exc = exc
                    self._drop_socket()  # broken frame ⇒ resync via reconnect
                    continue
                if not ok:
                    raise result  # server-side exception, transport is fine
                return result
        self._registry.counter("tracker_failures_total").inc()
        raise TrackerUnavailable(
            f"tracker at {self._addr[0]}:{self._addr[1]} unavailable after "
            f"{attempts} attempt(s) calling {method!r}: {last_exc!r}"
        ) from last_exc

    def close(self) -> None:
        with self._lock:
            self._drop_socket()

    # ---- workers ----
    def add_worker(self, worker_id):
        return self._call("add_worker", worker_id)

    def remove_worker(self, worker_id):
        return self._call("remove_worker", worker_id)

    def workers(self):
        return self._call("workers")

    # ---- jobs ----
    def add_job(self, job):
        return self._call("add_job", job)

    def job_for(self, worker_id):
        return self._call("job_for", worker_id)

    def clear_job(self, worker_id):
        return self._call("clear_job", worker_id)

    def has_pending_jobs(self):
        return self._call("has_pending_jobs")

    # ---- updates (versioned across the wire) ----
    def add_update(self, worker_id, job):
        return self._call("add_update", worker_id, job)

    def updates(self):
        jobs, versions = self._call("updates_versioned")
        self._snapshot_versions[id(jobs)] = versions
        # bound the cache: keep only the most recent few snapshots
        if len(self._snapshot_versions) > 8:
            oldest = next(iter(self._snapshot_versions))
            del self._snapshot_versions[oldest]
        return jobs

    def clear_updates(self, expected: Optional[Dict[str, Job]] = None):
        if expected is None:
            return self._call("clear_updates")
        versions = self._snapshot_versions.pop(id(expected), None)
        if versions is None:
            # not one of our snapshots (caller-built dict): conservative —
            # clearing blind could drop an unseen newer update, so no-op
            return None
        return self._call(
            "clear_updates_versioned",
            {w: versions[w] for w in expected if w in versions})

    # ---- current result ----
    def set_current(self, result):
        return self._call("set_current", result)

    def get_current(self):
        return self._call("get_current")

    # ---- generic KV blobs (ISSUE 12; last-write-wins per key, so the
    # writes are retry-safe idempotent like set_current) ----
    def put_kv(self, key, value):
        return self._call("put_kv", key, value)

    def get_kv(self, key, default=None):
        return self._call("get_kv", key, default)

    def kv_snapshot(self, prefix: str = ""):
        return self._call("kv_snapshot", prefix)

    # ---- replication ----
    def add_replicate(self, worker_id):
        return self._call("add_replicate", worker_id)

    def needs_replicate(self, worker_id):
        return self._call("needs_replicate", worker_id)

    def done_replicating(self, worker_id):
        return self._call("done_replicating", worker_id)

    # ---- counters / lifecycle ----
    def increment(self, key, by: float = 1.0):
        return self._call("increment", key, by)

    def count(self, key):
        return self._call("count", key)

    def counters_snapshot(self, prefix: str = ""):
        return self._call("counters_snapshot", prefix)

    def finish(self):
        return self._call("finish")

    def is_done(self):
        return self._call("is_done")

    # ---- early stopping / best model ----
    def set_best_loss(self, loss):
        return self._call("set_best_loss", loss)

    def best_loss(self):
        return self._call("best_loss")

    def early_stop(self):
        return self._call("early_stop")

    def is_early_stop(self):
        return self._call("is_early_stop")
