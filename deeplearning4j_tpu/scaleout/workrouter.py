"""Work routers — sync vs async aggregation policy.

Parity with ref: scaleout/api/workrouter/BaseWorkRouter.java:47-62 (update():
aggregate saved updates → setCurrent → mark replicates) and the Akka routers:
IterativeReduceWorkRouter (send work only when every worker has reported —
synchronous parameter averaging) and HogWildWorkRouter (always send — async).
"""

from __future__ import annotations

from deeplearning4j_tpu.scaleout.aggregator import JobAggregator
from deeplearning4j_tpu.scaleout.statetracker import StateTracker


class WorkRouter:
    #: True when workers run barrier-free and the master aggregates on its
    #: own cadence (ref: HogWildWorkRouter — MasterActor's heartbeat calls
    #: sendWork() every tick regardless of worker progress).
    asynchronous = False

    def __init__(self, tracker: StateTracker, aggregator: JobAggregator):
        self.tracker = tracker
        self.aggregator = aggregator

    def send_work(self) -> bool:
        """Whether the master may hand out the next round of jobs."""
        raise NotImplementedError

    def update(self, updates=None) -> None:
        """Aggregate worker updates into the tracker's current params and
        flag every worker for replication (ref: BaseWorkRouter.update).

        ``updates``: an existing tracker.updates() snapshot to aggregate
        (so a caller inspecting the round — e.g. early stopping — and the
        aggregation see the SAME jobs); taken fresh when omitted.

        Only the snapshotted updates are cleared: an update published
        between updates() and clear_updates() stays for the next round.
        Note the tracker keeps ONE slot per worker holding its latest FULL
        param snapshot (ref: LocalFileUpdateSaver keyed by worker id) — a
        newer snapshot from the same worker supersedes an un-aggregated
        older one (it embeds that training), and the identity check here
        guarantees a newer-unseen snapshot is never deleted unaggregated."""
        if updates is None:
            updates = self.tracker.updates()
        for job in updates.values():
            self.aggregator.accumulate(job)
        result = self.aggregator.aggregate()
        if result is not None:
            self.tracker.set_current(result)
        for worker_id in self.tracker.workers():
            self.tracker.add_replicate(worker_id)
        self.tracker.clear_updates(updates)
        if hasattr(self.aggregator, "reset"):
            self.aggregator.reset()


class IterativeReduceWorkRouter(WorkRouter):
    """Synchronous: wait for all workers (ref: IterativeReduceWorkRouter.java)."""

    def send_work(self) -> bool:
        workers = self.tracker.workers()
        return bool(workers) and len(self.tracker.updates()) >= len(workers)


class HogWildWorkRouter(WorkRouter):
    """Asynchronous: always route (ref: HogWildWorkRouter.java). With
    ``asynchronous=True`` the runner drops its per-round barrier entirely —
    workers pull/perform/publish continuously at their own pace (ref:
    WorkerActor.java:168-206) while the master aggregates whatever updates
    exist on each heartbeat."""

    asynchronous = True

    def send_work(self) -> bool:
        return True
