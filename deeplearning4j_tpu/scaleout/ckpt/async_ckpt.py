"""Background (off-the-training-thread) snapshots.

A blocking ``Checkpointer.save`` fetches every shard to host and fsyncs the
files on the training thread — at save steps the step time spikes by the
full serialize+IO cost. ``AsyncCheckpointer`` moves that cost to a
dedicated writer thread:

- ``save(step, state)`` starts a non-blocking device→host copy for every
  addressable shard (``copy_to_host_async``), enqueues the snapshot, and
  returns immediately; the writer thread materializes the (by then mostly
  landed) host bytes and runs the ordinary manifest-committed save.
- The queue is double-buffered (``max_pending=2``): one snapshot draining,
  one on deck. A third save while both buffers are full blocks — creating
  checkpoints faster than the disk drains them should apply backpressure,
  not grow memory without bound — and bumps ``<prefix>_async_backpressure``.
- A failed background save can't be raised on the caller's stack, so it is
  counted (``<prefix>_async_failures_total``), logged, kept as
  ``last_error``, and re-raised at the next ``flush()``/``close()`` — a
  run that checks its flush can never silently lose every snapshot.

Caveat (same as any async snapshot scheme): the caller must not donate the
saved arrays to the next step before the device→host copy completes. Pass
a non-donating step's output, a host tree, or ``flush()`` first. The
elastic master snapshots host-averaged numpy trees, which are trivially
safe.

Atomicity is inherited: the writer thread calls the same
manifest-commit-last path, so a crash (or process exit with the daemon
writer mid-save) leaves an invisible, GC-able directory — never a
half-checkpoint a resume could pick up.
"""

from __future__ import annotations

import contextlib
import logging
import queue
import threading
from typing import Dict, Optional

from deeplearning4j_tpu.telemetry import trace as _trace
from deeplearning4j_tpu.utils.lockwatch import make_lock

log = logging.getLogger(__name__)

_SENTINEL = object()


def _start_host_copies(state) -> None:
    """Kick off non-blocking device→host transfers for every leaf that
    supports it, so the writer thread's ``np.asarray`` finds the bytes
    already on host instead of synchronizing the device then."""
    import jax

    for leaf in jax.tree_util.tree_leaves(state):
        copy = getattr(leaf, "copy_to_host_async", None)
        if copy is not None:
            try:
                copy()
            except Exception:  # non-committed/donated arrays: let the
                pass           # writer thread surface the real error


class AsyncCheckpointer:
    """A ``Checkpointer`` facade whose saves run on a background writer
    thread. Restore/latest/gc and friends delegate to the wrapped
    checkpointer (flushing pending saves first where staleness would
    surprise: a restore right after a save must see that save)."""

    def __init__(self, checkpointer, max_pending: int = 2):
        self._ck = checkpointer
        self.registry = checkpointer.registry
        self.prefix = checkpointer.prefix
        self._queue: "queue.Queue" = queue.Queue(maxsize=max(1, max_pending))
        self._error_lock = make_lock("ckpt.async.error")
        self.last_error: Optional[BaseException] = None
        self._thread = threading.Thread(target=self._writer_loop,
                                        daemon=True, name="ckpt-writer")
        self._thread.start()

    # ---------------------------------------------------------- writes ----
    def save(self, step: int, state, meta: Optional[Dict] = None,
             mesh=None) -> None:
        """Enqueue a snapshot and return (no step-dir yet — the commit
        happens on the writer thread). Blocks only when both snapshot
        buffers are full."""
        reg, p = self.registry, self.prefix
        _start_host_copies(state)
        # the enqueuer's span context rides the queue item so the writer
        # thread's ``ckpt.async_write`` span parents under the training-
        # side operation that requested the snapshot (cross-thread link)
        item = (int(step), state, dict(meta or {}), mesh,
                _trace.current_trace_context())
        if self._queue.full():
            reg.counter(f"{p}_async_backpressure").inc()
        self._queue.put(item)
        reg.gauge(f"{p}_async_pending").set(float(self._queue.qsize()))

    save_async = save

    def maybe_save(self, step, state_fn, save_every, meta=None, mesh=None):
        if save_every <= 0 or step <= 0 or step % save_every:
            return None
        return self.save(step, state_fn(), meta=meta, mesh=mesh)

    def _writer_loop(self) -> None:
        reg, p = self.registry, self.prefix
        while True:
            item = self._queue.get()
            if item is _SENTINEL:
                self._queue.task_done()
                return
            step, state, meta, mesh, ctx = item
            tracer = _trace.get_tracer()
            try:
                write_cm = (tracer.span("ckpt.async_write",
                                        parent=ctx or False,
                                        attrs={"step": step})
                            if tracer is not None
                            else contextlib.nullcontext())
                with write_cm:
                    self._ck.save(step, state, meta=meta, mesh=mesh)
                reg.counter(f"{p}_async_saves_total").inc()
            except BaseException as exc:  # surfaced at flush()/close()
                with self._error_lock:
                    self.last_error = exc
                reg.counter(f"{p}_async_failures_total").inc()
                log.exception("background checkpoint save for step %s "
                              "failed", step)
            finally:
                self._queue.task_done()
                reg.gauge(f"{p}_async_pending").set(
                    float(self._queue.qsize()))

    # ----------------------------------------------------------- sync ----
    def flush(self) -> None:
        """Block until every enqueued save has committed; re-raise the
        first background failure since the last flush."""
        self._queue.join()
        with self._error_lock:
            exc, self.last_error = self.last_error, None
        if exc is not None:
            raise exc

    def close(self) -> None:
        self.flush()
        self._queue.put(_SENTINEL)
        self._thread.join(timeout=30)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------- delegates ----
    def restore(self, template, shardings=None, step=None):
        self.flush()  # a restore must see the saves issued before it
        return self._ck.restore(template, shardings=shardings, step=step)

    def restore_net(self, step=None):
        self.flush()
        return self._ck.restore_net(step=step)

    def latest_step(self):
        self.flush()
        return self._ck.latest_step()

    def step_dirs(self):
        return self._ck.step_dirs()

    def mark_last_good(self, step: int) -> None:
        # the tag must never name a step whose (async) save is still in
        # flight — flush so the marker always points at a committed dir
        self.flush()
        self._ck.mark_last_good(step)

    def last_good_step(self):
        return self._ck.last_good_step()

    def gc(self) -> None:
        self._ck.gc()

    @property
    def root(self) -> str:
        return self._ck.root
