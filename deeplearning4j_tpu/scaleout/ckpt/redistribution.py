"""In-graph array redistribution plans (ISSUE 14; arXiv:2112.01075).

``reshard.restore_sharded`` assembles every target shard ON THE HOST from
saved chunks (``jax.make_array_from_callback``) — the right tool when the
source is a directory of npz files, and the only tool the repo had even
when the source arrays were already sitting on devices (elastic rejoin
param adoption, a serving engine cold-starting from a live trainer's
tree). This module is the device-resident fast path: a redistribution is
planned as an EXPLICIT program of collective steps and executed inside one
jitted identity, so the bytes move over ICI instead of bouncing through
host memory.

Plan model (``plan_redistribution(src_spec, dst_spec, mesh)``): a
same-mesh respec decomposes into at most three canonical steps, each one
sharding transition that XLA's SPMD partitioner lowers to the matching
collective —

    ``all_gather``   drop the mesh axes ``src`` shards over that ``dst``
                     does not (per-device data grows g×; lowered to
                     all-gather, ring wire (g−1)/g·B per gathered axis
                     group)
    ``all_to_all``   relocate axes that shard DIFFERENT tensor dims in
                     ``src`` vs ``dst`` (per-device bytes constant;
                     lowered to all-to-all, wire (g−1)/g·B)
    ``slice``        add the mesh axes ``dst`` shards over that ``src``
                     did not (pure local dynamic-slice, zero wire bytes)

applied in that order (gather → move → slice), skipping the ones that are
identities. Cross-mesh transitions over the SAME device set collapse to a
single step: ``ppermute`` when the per-dim shard structure is unchanged
(pure device-order permutation, wire ≤ B) else ``all_to_all`` (GSPMD
chooses the minimal collective program for the respec). A transition whose
device sets differ (single-device ↔ mesh) is a ``rebind`` — executed as a
runtime device-to-device transfer (``jax.device_put``), still never a host
assembly.

``apply_plan`` executes a plan as ONE jitted identity whose intermediate
``with_sharding_constraint``s materialize each step; the compiled module's
collective inventory (telemetry/xprofile.py) therefore shows exactly the
planned ops — pinned in tests/test_redistribution.py. ``redistribute`` /
``redistribute_tree`` are the leaf/pytree entry points the live-resharding
callers use (``scaleout.elastic`` param adoption,
``serve.DecodeEngine.from_live_params``); parity vs the host-callback
restore path is ≤1e-6 (bit-exact in practice) across the existing
cross-mesh matrix (dp×ep ↔ dp×sp×ep ↔ dp×pp ↔ single-device).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from functools import partial
from typing import List, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "PlanStep",
    "RedistributionPlan",
    "apply_plan",
    "plan_redistribution",
    "redistribute",
    "redistribute_tree",
]


def _norm_spec(spec, ndim: int) -> Tuple[Tuple[str, ...], ...]:
    """A PartitionSpec (or tuple) → per-dim tuples of mesh-axis names,
    padded with replicated dims to ``ndim``."""
    entries = tuple(spec) if spec is not None else ()
    out: List[Tuple[str, ...]] = []
    for e in entries:
        if e is None:
            out.append(())
        elif isinstance(e, str):
            out.append((e,))
        else:
            out.append(tuple(e))
    while len(out) < ndim:
        out.append(())
    if len(out) > ndim:
        raise ValueError(
            f"spec {spec} names {len(out)} dims but the array has {ndim}")
    return tuple(out)


def _axis_dims(norm) -> dict:
    """{mesh axis name: tensor dim it shards} of a normalized spec."""
    out = {}
    for dim, axes in enumerate(norm):
        for a in axes:
            if a in out:
                raise ValueError(f"axis {a!r} appears twice in spec {norm}")
            out[a] = dim
    return out


def _to_partition_spec(norm) -> P:
    entries = []
    for axes in norm:
        if not axes:
            entries.append(None)
        elif len(axes) == 1:
            entries.append(axes[0])
        else:
            entries.append(tuple(axes))
    return P(*entries)


@dataclass(frozen=True)
class PlanStep:
    """One collective step: after executing it the array carries ``spec``
    (a normalized per-dim tuple; None for the ``rebind`` runtime step)."""

    kind: str  # all_gather | all_to_all | slice | ppermute | rebind | noop
    spec: Optional[tuple]
    note: str = ""

    def partition_spec(self) -> P:
        if self.spec is None:
            raise ValueError(f"{self.kind} step has no partition spec")
        return _to_partition_spec(self.spec)


@dataclass
class RedistributionPlan:
    """The explicit collective program moving an array from ``src_spec``
    to ``dst_spec`` on ``mesh``. ``kinds()`` is the introspection handle
    tests and reports use."""

    mesh: Mesh
    src_spec: tuple
    dst_spec: tuple
    steps: List[PlanStep] = field(default_factory=list)

    def kinds(self) -> List[str]:
        return [s.kind for s in self.steps]


def plan_redistribution(src_spec, dst_spec, mesh: Mesh,
                        ndim: Optional[int] = None) -> RedistributionPlan:
    """Derive the explicit same-mesh collective program from ``src_spec``
    to ``dst_spec`` (PartitionSpecs on ``mesh``): gather the axes ``dst``
    drops, all-to-all the axes that change tensor dim, slice in the axes
    ``dst`` adds — each step one sharding transition, at most three steps,
    empty for ``src == dst``. ``ndim`` bounds the per-dim normalization
    (default: as many dims as the longer spec names)."""
    if ndim is None:
        ndim = max(len(tuple(src_spec) if src_spec else ()),
                   len(tuple(dst_spec) if dst_spec else ()))
    src = _norm_spec(src_spec, ndim)
    dst = _norm_spec(dst_spec, ndim)
    for a in set(_axis_dims(src)) | set(_axis_dims(dst)):
        if a not in mesh.axis_names:
            raise ValueError(
                f"spec axis {a!r} is not on the mesh {mesh.axis_names}")
    plan = RedistributionPlan(mesh=mesh, src_spec=src, dst_spec=dst)
    if src == dst:
        return plan
    src_dims, dst_dims = _axis_dims(src), _axis_dims(dst)
    removed = {a for a in src_dims if a not in dst_dims}
    moved = {a for a in src_dims
             if a in dst_dims and dst_dims[a] != src_dims[a]}

    cur = src
    if removed:
        nxt = tuple(tuple(a for a in axes if a not in removed)
                    for axes in cur)
        if nxt != cur:
            plan.steps.append(PlanStep(
                "all_gather", nxt,
                note=f"gather axes {sorted(removed)} (dst drops them)"))
            cur = nxt
    if moved:
        kept = set(_axis_dims(cur))
        nxt = tuple(tuple(a for a in axes if a in kept) for axes in dst)
        if nxt != cur:
            plan.steps.append(PlanStep(
                "all_to_all", nxt,
                note=f"relocate axes {sorted(moved)} to their dst dims"))
            cur = nxt
    if cur != dst:
        plan.steps.append(PlanStep(
            "slice", dst, note="shard in the axes dst adds (local slice)"))
    return plan


def _same_device_set(a, b) -> bool:
    return ({d.id for d in a.device_set}
            == {d.id for d in b.device_set})


def _shard_structure(sharding: NamedSharding, ndim: int):
    """Per-dim shard counts — equal structures across meshes means a
    respec is a pure device-order permutation (the ppermute case)."""
    norm = _norm_spec(sharding.spec, ndim)
    return tuple(math.prod(sharding.mesh.shape[a] for a in axes)
                 for axes in norm)


def plan_cross_mesh(src: NamedSharding, dst: NamedSharding,
                    ndim: int) -> RedistributionPlan:
    """One-step plan for a respec across two meshes over the SAME device
    set: ``ppermute`` when the per-dim shard structure is unchanged (only
    the device order differs), else ``all_to_all`` (GSPMD lowers the
    minimal collective program for the transition)."""
    plan = RedistributionPlan(
        mesh=dst.mesh,
        src_spec=_norm_spec(src.spec, ndim),
        dst_spec=_norm_spec(dst.spec, ndim))
    if _shard_structure(src, ndim) == _shard_structure(dst, ndim):
        kind, note = "ppermute", ("device-order permutation — same per-dim "
                                  "shard structure on a different mesh")
    else:
        kind, note = "all_to_all", ("cross-mesh respec — GSPMD lowers the "
                                    "minimal collective program")
    plan.steps.append(PlanStep(kind, plan.dst_spec, note=note))
    return plan


def apply_plan(plan: RedistributionPlan, arr, donate: bool = False,
               dst_sharding: Optional[NamedSharding] = None):
    """Execute a plan as ONE jitted identity: every intermediate step is a
    ``with_sharding_constraint`` and the final step the ``out_shardings``,
    so the compiled program contains exactly the planned collectives and
    the bytes never leave the devices. ``donate`` donates the source
    buffers (safe when the caller rebinds, e.g. live adoption);
    ``dst_sharding`` overrides the plan-reconstructed target (callers
    that hold the exact NamedSharding object pass it through so the
    result compares equal to it)."""
    mesh = plan.mesh
    if dst_sharding is None:
        dst_sharding = NamedSharding(mesh, _to_partition_spec(plan.dst_spec))
    if not plan.steps:
        return arr  # src == dst: nothing to move
    mids = [NamedSharding(mesh, s.partition_spec())
            for s in plan.steps[:-1]]

    @partial(jax.jit, out_shardings=dst_sharding,
             donate_argnums=(0,) if donate else ())
    def run(v):
        for sh in mids:
            v = jax.lax.with_sharding_constraint(v, sh)
        return v

    return run(arr)


def redistribute(arr, dst_sharding, donate: bool = False):
    """Move one array to ``dst_sharding`` without a host round-trip:

    - already there → returned as-is;
    - same mesh → the explicit ``plan_redistribution`` program, jitted;
    - different mesh, same device set → the one-step cross-mesh plan;
    - different device set (single-device ↔ mesh, host-uncommitted
      inputs) → runtime ``rebind`` via ``jax.device_put`` (a managed
      device-to-device/broadcast transfer — still no host assembly of
      sharded state).
    """
    src = getattr(arr, "sharding", None)
    if src == dst_sharding:
        return arr
    if (isinstance(src, NamedSharding)
            and isinstance(dst_sharding, NamedSharding)
            and _same_device_set(src, dst_sharding)):
        ndim = len(arr.shape)
        if src.mesh.shape == dst_sharding.mesh.shape \
                and src.mesh.axis_names == dst_sharding.mesh.axis_names \
                and [d.id for d in src.mesh.devices.flat] \
                == [d.id for d in dst_sharding.mesh.devices.flat]:
            plan = plan_redistribution(src.spec, dst_sharding.spec,
                                       dst_sharding.mesh, ndim=ndim)
        else:
            plan = plan_cross_mesh(src, dst_sharding, ndim)
        return apply_plan(plan, arr, donate=donate,
                          dst_sharding=dst_sharding)
    return jax.device_put(arr, dst_sharding)


def redistribute_tree(tree, dst_shardings, donate: bool = False):
    """Pytree twin of ``redistribute``: ``dst_shardings`` mirrors ``tree``
    (None entries leave the leaf untouched — flattened with None-as-leaf,
    the same convention as ``reshard.restore_sharded``). The
    live-resharding fast path of elastic rejoin adoption and the serving
    cold start — the host-callback ``reshard.restore_sharded`` remains the
    disk path."""
    t_leaves, treedef = jax.tree_util.tree_flatten(tree)
    s_leaves = jax.tree_util.tree_flatten(
        dst_shardings, is_leaf=lambda x: x is None)[0]
    if len(s_leaves) != len(t_leaves):
        raise ValueError(
            f"dst_shardings has {len(s_leaves)} leaves, tree has "
            f"{len(t_leaves)}")
    out = [leaf if sh is None else redistribute(leaf, sh, donate=donate)
           for leaf, sh in zip(t_leaves, s_leaves)]
    return jax.tree_util.tree_unflatten(treedef, out)
