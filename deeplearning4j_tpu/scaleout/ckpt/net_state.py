"""Full MultiLayerNetwork training state as a checkpointable pytree.

One capture/restore pair shared by every net-level persistence path — the
``CheckpointIterationListener``, the legacy single-file
``scaleout/checkpoint.py`` wrapper, and direct ``Checkpointer`` use — so
what "complete training state" means (per-layer params, per-layer updater
state, host RNG stream position, iteration counter, conf) is defined in
exactly one place.

Typed PRNG keys are stored as their raw key data plus an ``rng_impl`` meta
string (key arrays are extension dtypes no serializer understands); raw
uint32 keys pass through as-is.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import numpy as np


def _key_is_typed(key) -> bool:
    return jax.dtypes.issubdtype(key.dtype, jax.dtypes.prng_key)


def capture_net_state(net, iteration: Optional[int] = None
                      ) -> Tuple[Dict, Dict]:
    """(state pytree, meta dict) for a MultiLayerNetwork.

    The tree carries params, updater state (when initialized), and the RNG
    stream position; meta carries the conf JSON, the iteration counter, and
    the RNG key impl for typed keys.
    """
    tree: Dict = {"params": net.params_tree}
    state = getattr(net, "_train_state", None)
    if state is not None:
        tree["state"] = state
    meta: Dict = {"conf": net.conf.to_json()}
    it = iteration if iteration is not None else getattr(net, "_iteration", 0)
    meta["iteration"] = int(it)
    keys = getattr(net, "_keys", None)
    if keys is not None:
        key = keys._key
        if _key_is_typed(key):
            tree["rng"] = np.asarray(jax.random.key_data(key))
            meta["rng_impl"] = str(jax.random.key_impl(key))
        else:
            tree["rng"] = np.asarray(key)
    return tree, meta


def net_state_template(net) -> Dict:
    """The template pytree a ``restore_sharded`` of a net checkpoint needs —
    same structure ``capture_net_state`` produces for this net."""
    tree, _meta = capture_net_state(net)
    return tree


def restore_net_state(net, tree: Dict, meta: Dict):
    """Install a captured state tree into ``net`` (in place; returns net)."""
    net._params = tuple(tree["params"])
    if "state" in tree:
        net._train_state = tuple(tree["state"])
    net._iteration = int(meta.get("iteration", 0))
    if "rng" in tree and getattr(net, "_keys", None) is not None:
        raw = jax.numpy.asarray(np.asarray(tree["rng"]),
                                dtype=jax.numpy.uint32)
        impl = meta.get("rng_impl")
        if impl:
            net._keys._key = jax.random.wrap_key_data(raw, impl=impl)
        else:
            net._keys._key = raw
    return net


def rebuild_net(tree: Dict, meta: Dict):
    """Reconstruct a fresh MultiLayerNetwork from a captured checkpoint
    (conf JSON in meta) — the resume path when no live net exists."""
    from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = MultiLayerConfiguration.from_json(meta["conf"])
    net = MultiLayerNetwork(conf).init()
    # make sure the updater-state template exists when the tree carries one
    if "state" in tree:
        net._ensure_train_step()
    return restore_net_state(net, tree, meta)
