"""Checkpoint manifest: the commit record of a sharded snapshot.

A checkpoint directory holds per-shard data files plus ONE ``MANIFEST.json``
written last via unique-tmp + ``os.replace``. The manifest is the atomicity
boundary: readers (``reshard.latest_step``, ``restore_sharded``, the
``ckpt_inspect`` CLI) treat a directory without a committed manifest as
nonexistent, so a writer killed mid-save can never be resumed from.

Schema (``format`` = ``dl4j-tpu-ckpt-v1``)::

    {"format": ..., "step": int,
     "mesh": {"axis_names": [...], "shape": [...]} | null,
     "meta": {...},                      # caller metadata (conf JSON, rng impl)
     "leaves": [{"path": "['params']['blocks']['wq']",
                 "shape": [...], "dtype": "float32",
                 "spec": [null, "expert"] | null,   # save-time PartitionSpec
                 "chunks": [{"file": "shard_00000.npz", "key": <path>,
                             "start": [...], "shape": [...],
                             "crc32": int}]}]}

``spec`` is informational (the save-time layout); restore never needs it —
chunk offsets alone determine how any *target* slice is covered.
"""

from __future__ import annotations

import dataclasses
import json
import os
import uuid
from typing import Dict, List, Optional, Sequence, Tuple

FORMAT = "dl4j-tpu-ckpt-v1"
MANIFEST_NAME = "MANIFEST.json"
_STEP_PREFIX = "step_"


@dataclasses.dataclass(frozen=True)
class Chunk:
    """One saved slice of one leaf: where it lives and what it covers."""

    file: str
    key: str
    start: Tuple[int, ...]
    shape: Tuple[int, ...]
    crc32: int

    def to_dict(self) -> Dict:
        return {"file": self.file, "key": self.key,
                "start": list(self.start), "shape": list(self.shape),
                "crc32": self.crc32}

    @classmethod
    def from_dict(cls, d: Dict) -> "Chunk":
        return cls(file=d["file"], key=d["key"], start=tuple(d["start"]),
                   shape=tuple(d["shape"]), crc32=int(d["crc32"]))


@dataclasses.dataclass(frozen=True)
class LeafEntry:
    path: str
    shape: Tuple[int, ...]
    dtype: str
    spec: Optional[List]
    chunks: Tuple[Chunk, ...]

    def to_dict(self) -> Dict:
        return {"path": self.path, "shape": list(self.shape),
                "dtype": self.dtype, "spec": self.spec,
                "chunks": [c.to_dict() for c in self.chunks]}

    @classmethod
    def from_dict(cls, d: Dict) -> "LeafEntry":
        return cls(path=d["path"], shape=tuple(d["shape"]), dtype=d["dtype"],
                   spec=d.get("spec"),
                   chunks=tuple(Chunk.from_dict(c) for c in d["chunks"]))


@dataclasses.dataclass(frozen=True)
class Manifest:
    step: int
    leaves: Tuple[LeafEntry, ...]
    mesh: Optional[Dict] = None
    meta: Optional[Dict] = None
    format: str = FORMAT

    def leaf(self, path: str) -> Optional[LeafEntry]:
        for entry in self.leaves:
            if entry.path == path:
                return entry
        return None

    @property
    def files(self) -> List[str]:
        seen: List[str] = []
        for entry in self.leaves:
            for chunk in entry.chunks:
                if chunk.file not in seen:
                    seen.append(chunk.file)
        return seen

    @property
    def total_bytes(self) -> int:
        import numpy as np

        total = 0
        for entry in self.leaves:
            itemsize = np.dtype(entry.dtype).itemsize
            for chunk in entry.chunks:
                n = 1
                for dim in chunk.shape:
                    n *= dim
                total += n * itemsize
        return total

    def to_json(self) -> str:
        return json.dumps({
            "format": self.format,
            "step": self.step,
            "mesh": self.mesh,
            "meta": self.meta or {},
            "leaves": [entry.to_dict() for entry in self.leaves],
        }, indent=1)

    @classmethod
    def from_json(cls, text: str) -> "Manifest":
        d = json.loads(text)
        if d.get("format") != FORMAT:
            raise ValueError(
                f"unsupported checkpoint format {d.get('format')!r} "
                f"(expected {FORMAT!r})")
        return cls(step=int(d["step"]),
                   leaves=tuple(LeafEntry.from_dict(e) for e in d["leaves"]),
                   mesh=d.get("mesh"), meta=d.get("meta") or {})


def step_dir_name(step: int) -> str:
    return f"{_STEP_PREFIX}{int(step):010d}"


def parse_step(dirname: str) -> Optional[int]:
    base = os.path.basename(dirname.rstrip("/"))
    if not base.startswith(_STEP_PREFIX):
        return None
    try:
        return int(base[len(_STEP_PREFIX):])
    except ValueError:
        return None


def manifest_path(step_dir: str) -> str:
    return os.path.join(step_dir, MANIFEST_NAME)


def has_manifest(step_dir: str) -> bool:
    return os.path.isfile(manifest_path(step_dir))


def write_manifest(step_dir: str, manifest: Manifest) -> str:
    """Commit the manifest atomically: unique tmp (pid+uuid, so concurrent
    savers can never collide on the tmp name) then ``os.replace``. This is
    the LAST write of a save — the rename is the commit point."""
    final = manifest_path(step_dir)
    tmp = f"{final}.tmp-{os.getpid()}-{uuid.uuid4().hex}"
    try:
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(manifest.to_json())
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return final


def read_manifest(step_dir: str) -> Manifest:
    path = manifest_path(step_dir)
    if not os.path.isfile(path):
        raise FileNotFoundError(
            f"no committed manifest in {step_dir} — an interrupted save is "
            "not a checkpoint")
    with open(path, "r", encoding="utf-8") as f:
        return Manifest.from_json(f.read())


# ------------------------------------------------- multi-host part files ----
# A multi-host save writes one PART manifest per process ("MANIFEST.part-
# <process>.json", atomic tmp+replace so a reader never sees a torn part),
# each listing every leaf but only the chunks that process owns. The
# coordinator merges the parts into the single committed MANIFEST.json —
# part files are working state, never a commit record: a directory with
# parts but no manifest is still an interrupted save.

_PART_PREFIX = "MANIFEST.part-"


def part_manifest_path(step_dir: str, process_index: int) -> str:
    return os.path.join(step_dir, f"{_PART_PREFIX}{int(process_index):05d}.json")


def write_part_manifest(step_dir: str, process_index: int, step: int,
                        entries: Sequence[LeafEntry]) -> str:
    final = part_manifest_path(step_dir, process_index)
    tmp = f"{final}.tmp-{os.getpid()}-{uuid.uuid4().hex}"
    payload = {"format": FORMAT, "process": int(process_index),
               "step": int(step),
               "leaves": [e.to_dict() for e in entries]}
    try:
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return final


def list_part_manifests(step_dir: str) -> List[Tuple[int, str]]:
    """(process_index, path) for every part file present, ascending."""
    out: List[Tuple[int, str]] = []
    if not os.path.isdir(step_dir):
        return out
    for name in sorted(os.listdir(step_dir)):
        if not (name.startswith(_PART_PREFIX) and name.endswith(".json")):
            continue
        try:
            idx = int(name[len(_PART_PREFIX):-len(".json")])
        except ValueError:
            continue
        out.append((idx, os.path.join(step_dir, name)))
    return out


def read_part_manifest(path: str) -> Tuple[int, int, Tuple[LeafEntry, ...]]:
    """→ (process_index, step, leaf entries)."""
    with open(path, "r", encoding="utf-8") as f:
        d = json.load(f)
    if d.get("format") != FORMAT:
        raise ValueError(f"unsupported part-manifest format "
                         f"{d.get('format')!r} in {path}")
    return (int(d["process"]), int(d["step"]),
            tuple(LeafEntry.from_dict(e) for e in d["leaves"]))


def committed_steps(root: str) -> List[Tuple[int, str]]:
    """(step, step_dir) for every COMMITTED checkpoint under root,
    ascending by step. Manifest-less (interrupted) directories are
    invisible here by design."""
    out: List[Tuple[int, str]] = []
    if not os.path.isdir(root):
        return out
    for name in sorted(os.listdir(root)):
        step = parse_step(name)
        step_dir = os.path.join(root, name)
        if step is None or not os.path.isdir(step_dir):
            continue
        if has_manifest(step_dir):
            out.append((step, step_dir))
    return sorted(out)


def uncommitted_dirs(root: str) -> List[Tuple[Optional[int], str]]:
    """step-shaped directories WITHOUT a manifest (interrupted saves)."""
    out: List[Tuple[Optional[int], str]] = []
    if not os.path.isdir(root):
        return out
    for name in sorted(os.listdir(root)):
        step = parse_step(name)
        step_dir = os.path.join(root, name)
        if step is None or not os.path.isdir(step_dir):
            continue
        if not has_manifest(step_dir):
            out.append((step, step_dir))
    return out


def serialize_spec(spec: Optional[Sequence]) -> Optional[List]:
    """PartitionSpec entries → JSON (None | str | [str, ...] per dim)."""
    if spec is None:
        return None
    out: List = []
    for part in spec:
        if part is None:
            out.append(None)
        elif isinstance(part, (tuple, list)):
            out.append([str(p) for p in part])
        else:
            out.append(str(part))
    return out
