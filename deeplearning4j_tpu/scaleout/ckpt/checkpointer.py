"""Training integration: periodic saves, retention, resume, telemetry.

``Checkpointer`` owns one checkpoint root: ``save()`` writes a sharded
snapshot (manifest-committed, see sharded_io), bumps the telemetry
registry (save duration / bytes / shard count through the PR 2 metrics
layer), and applies retention — keep the newest ``keep_last`` committed
steps, delete older ones, and sweep interrupted (manifest-less) save
directories once a same-or-newer step has committed.

``CheckpointIterationListener`` rides the existing exception-safe listener
chain (optimize/listeners.dispatch_listeners): every ``save_every``
iterations it captures the model's full training state and saves it — a
listener crash is logged and skipped by the chain, never killing the run.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import threading
import time
from typing import Callable, Dict, Optional, Tuple

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.scaleout.ckpt import manifest as mf
from deeplearning4j_tpu.scaleout.ckpt import net_state as ns
from deeplearning4j_tpu.scaleout.ckpt.reshard import (
    latest_step_dir,
    restore_sharded,
    verify_checksums,
)
from deeplearning4j_tpu.scaleout.ckpt.sharded_io import save_sharded
from deeplearning4j_tpu.telemetry import trace as _trace

log = logging.getLogger(__name__)


def replicated_shardings(template, mesh):
    """A shardings pytree placing every leaf replicated on ``mesh`` — the
    restore layout for DP-replicated params/updater state."""
    rep = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda _: rep, template)


class Checkpointer:
    """Sharded checkpoint store with retention and telemetry.

    save(step, state[, meta, mesh])   → committed step dir
    restore(template[, shardings])    → (state, step, meta)
    latest_step() / step_dirs()       → what a resume would load
    """

    def __init__(self, root: str, keep_last: int = 3, registry=None,
                 prefix: str = "ckpt", verify_on_restore: bool = False):
        from deeplearning4j_tpu.telemetry.registry import default_registry

        self.root = str(root)
        self.keep_last = max(1, int(keep_last))
        self.registry = registry if registry is not None else default_registry()
        self.prefix = prefix
        self.verify_on_restore = verify_on_restore
        # retention-race pin: the step a reader most recently resolved
        # (latest_step()/restore()) is never GC'd, even if newer saves
        # (possibly from a background writer thread) push it past
        # keep_last mid-restore
        self._pin_lock = threading.Lock()
        self._last_resolved_step: Optional[int] = None

    # ------------------------------------------------------- last_good ----
    def _last_good_path(self) -> str:
        return os.path.join(self.root, "LAST_GOOD.json")

    def mark_last_good(self, step: int) -> None:
        """Tag ``step`` as the divergence watchdog's rollback target
        (optimize/guardrails.DivergenceWatchdog.note_checkpoint). The tag
        is a marker file next to the step dirs (atomic tmp+replace), so it
        survives the process and is visible to every reader of the root;
        ``gc()`` never collects the tagged step."""
        os.makedirs(self.root, exist_ok=True)
        tmp = f"{self._last_good_path()}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump({"step": int(step), "ts": time.time()}, fh)
        os.replace(tmp, self._last_good_path())
        self.registry.gauge(f"{self.prefix}_last_good_step").set(float(step))

    def last_good_step(self) -> Optional[int]:
        """The tagged rollback target, or None when none was ever tagged
        (rollback then falls back to the latest committed step)."""
        try:
            with open(self._last_good_path()) as fh:
                return int(json.load(fh)["step"])
        except (OSError, ValueError, KeyError, TypeError):
            return None

    # ------------------------------------------------------------- save ----
    def save(self, step: int, state, meta: Optional[Dict] = None,
             mesh=None) -> str:
        reg, p = self.registry, self.prefix
        with _trace.maybe_span("ckpt.save",
                               attrs={"step": int(step)}) as sp:
            t0 = time.perf_counter()
            step_dir = save_sharded(self.root, step, state, meta=meta,
                                    mesh=mesh)
            # graftlint: allow[untimed-dispatch] save_sharded fetches every shard via np.asarray (host-synchronous IO); nothing is left enqueued when the clock stops
            save_ms = (time.perf_counter() - t0) * 1000.0
            manifest = mf.read_manifest(step_dir)
            n_chunks = sum(len(e.chunks) for e in manifest.leaves)
            reg.counter(f"{p}_saves_total").inc()
            reg.counter(f"{p}_bytes_total").inc(float(manifest.total_bytes))
            reg.histogram(f"{p}_save_ms").observe(save_ms)
            reg.gauge(f"{p}_last_step").set(float(step))
            reg.gauge(f"{p}_last_bytes").set(float(manifest.total_bytes))
            reg.gauge(f"{p}_last_shards").set(float(n_chunks))
            if sp is not None:
                sp.set_attr("bytes", int(manifest.total_bytes))
                sp.set_attr("chunks", int(n_chunks))
            self.gc()
            return step_dir

    def maybe_save(self, step: int, state_fn: Callable[[], object],
                   save_every: int, meta: Optional[Dict] = None,
                   mesh=None) -> Optional[str]:
        """Save iff ``step`` lands on the cadence (and is > 0)."""
        if save_every <= 0 or step <= 0 or step % save_every:
            return None
        return self.save(step, state_fn(), meta=meta, mesh=mesh)

    # ------------------------------------------------- multi-host save ----
    def save_process(self, step: int, state,
                     process_index: Optional[int] = None) -> str:
        """Every host's half of a multi-host save: write only this
        process's addressable chunks + an atomic part manifest. Commit
        happens on the coordinator via ``merge_save``; until then the step
        is invisible to every reader."""
        from deeplearning4j_tpu.scaleout.ckpt.sharded_io import (
            save_process_shards,
        )

        return save_process_shards(self.root, step, state,
                                   process_index=process_index)

    def merge_save(self, step: int, n_processes: int,
                   meta: Optional[Dict] = None, mesh=None, state=None,
                   timeout_s: float = 120.0) -> str:
        """Coordinator-only: the manifest merge barrier (waits for all
        ``n_processes`` part manifests, validates coverage, commits LAST)
        plus the same telemetry + retention a single-host ``save`` gets."""
        from deeplearning4j_tpu.scaleout.ckpt.sharded_io import (
            merge_process_manifests,
        )

        reg, p = self.registry, self.prefix
        with _trace.maybe_span("ckpt.merge_save",
                               attrs={"step": int(step),
                                      "n_processes": int(n_processes)}):
            t0 = time.perf_counter()
            step_dir = merge_process_manifests(
                self.root, step, n_processes, meta=meta, mesh=mesh,
                state=state, timeout_s=timeout_s)
            # graftlint: allow[untimed-dispatch] merge is pure host IO (part-manifest JSON + rename); nothing device-side is in flight
            merge_ms = (time.perf_counter() - t0) * 1000.0
            manifest = mf.read_manifest(step_dir)
            reg.counter(f"{p}_saves_total").inc()
            reg.counter(f"{p}_bytes_total").inc(float(manifest.total_bytes))
            reg.histogram(f"{p}_save_ms").observe(merge_ms)
            reg.gauge(f"{p}_last_step").set(float(step))
            self.gc()
            return step_dir

    # ---------------------------------------------------------- restore ----
    def latest_step(self) -> Optional[int]:
        from deeplearning4j_tpu.scaleout.ckpt.reshard import latest_step

        step = latest_step(self.root)
        if step is not None:
            self._pin(step)
        return step

    def _pin(self, step: int) -> None:
        with self._pin_lock:
            self._last_resolved_step = int(step)

    def step_dirs(self):
        return mf.committed_steps(self.root)

    def _dir_for(self, step: Optional[int]) -> str:
        if step is None:
            step_dir = latest_step_dir(self.root)
            if step_dir is None:
                raise FileNotFoundError(
                    f"no committed checkpoint under {self.root}")
            resolved = mf.parse_step(step_dir)
            if resolved is not None:
                self._pin(resolved)
            return step_dir
        import os

        step_dir = os.path.join(self.root, mf.step_dir_name(step))
        if not mf.has_manifest(step_dir):
            raise FileNotFoundError(
                f"step {step} has no committed checkpoint under {self.root}")
        self._pin(int(step))
        return step_dir

    def restore(self, template, shardings=None,
                step: Optional[int] = None) -> Tuple[object, int, Dict]:
        """Load the latest (or a specific) committed step into the template
        structure, resharded onto the target ``shardings``. Returns
        ``(state, step, meta)``."""
        reg, p = self.registry, self.prefix
        with _trace.maybe_span("ckpt.restore") as sp:
            step_dir = self._dir_for(step)
            if self.verify_on_restore:
                problems = verify_checksums(step_dir)
                if problems:
                    raise ValueError(
                        f"checkpoint {step_dir} failed checksum "
                        "verification: " + "; ".join(problems))
            t0 = time.perf_counter()
            state, manifest = restore_sharded(step_dir, template, shardings)
            # graftlint: allow[untimed-dispatch] restore assembles host chunks synchronously (np.load + copies); device placement is fenced by callers
            restore_ms = (time.perf_counter() - t0) * 1000.0
            reg.histogram(f"{p}_restore_ms").observe(restore_ms)
            reg.counter(f"{p}_restores_total").inc()
            if sp is not None:
                sp.set_attr("step", int(manifest.step))
            return state, manifest.step, dict(manifest.meta or {})

    def restore_net(self, step: Optional[int] = None):
        """Rebuild a MultiLayerNetwork from a net-state checkpoint (one
        saved by ``CheckpointIterationListener`` or ``save_net``):
        returns ``(net, iteration)``."""
        from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        step_dir = self._dir_for(step)
        manifest = mf.read_manifest(step_dir)
        meta = dict(manifest.meta or {})
        net = MultiLayerNetwork(
            MultiLayerConfiguration.from_json(meta["conf"])).init()
        if any(e.path.startswith("['state']") for e in manifest.leaves):
            net._ensure_train_step()
        template = ns.net_state_template(net)
        state, _step, meta = self.restore(template, step=manifest.step)
        ns.restore_net_state(net, state, meta)
        return net, net._iteration

    def save_net(self, net, iteration: Optional[int] = None) -> str:
        tree, meta = ns.capture_net_state(net, iteration=iteration)
        return self.save(meta["iteration"], tree, meta=meta)

    # --------------------------------------------------------- retention ----
    def gc(self) -> None:
        """Retention sweep: keep the newest ``keep_last`` committed steps;
        delete older committed ones, and delete interrupted (manifest-less)
        directories that a same-or-newer committed step has superseded —
        a crashed save can never shadow or outlive real checkpoints.

        Never deletes the step a reader most recently resolved via
        ``latest_step()``/``restore()``: a background save pushing that
        step out of the retention window mid-restore (the retention race)
        would otherwise yank the files out from under the reader. Never
        deletes the step tagged ``last_good`` either (the watchdog's
        rollback target — retention pressure must not destroy the only
        known-healthy snapshot; extends the PR 6 retention-race fix)."""
        committed = mf.committed_steps(self.root)
        if not committed:
            return
        newest = committed[-1][0]
        with self._pin_lock:
            pinned = self._last_resolved_step
        last_good = self.last_good_step()
        for step, step_dir in committed[:-self.keep_last]:
            if pinned is not None and step == pinned:
                continue
            if last_good is not None and step == last_good:
                continue
            shutil.rmtree(step_dir, ignore_errors=True)
        for step, step_dir in mf.uncommitted_dirs(self.root):
            if step is not None and step <= newest:
                shutil.rmtree(step_dir, ignore_errors=True)


class CheckpointIterationListener:
    """Periodic checkpointing through the exception-safe listener chain.

    ``state_fn(model, iteration) -> (tree, meta)`` defaults to
    ``capture_net_state`` — the full params + updater + RNG + iteration
    snapshot. The listener chain logs-and-skips a raising listener
    (dispatch_listeners), so an unwritable disk degrades a run to
    checkpoint-less instead of killing it; retention/atomicity guarantee a
    partial save is never visible.
    """

    def __init__(self, checkpointer: Checkpointer, save_every: int = 10,
                 state_fn: Optional[Callable] = None, mesh=None):
        self.checkpointer = checkpointer
        self.save_every = max(1, int(save_every))
        self.state_fn = state_fn
        self.mesh = mesh
        self.saved_steps = []

    def __call__(self, model, iteration: int, score: float) -> None:
        if iteration <= 0 or iteration % self.save_every:
            return
        if self.state_fn is not None:
            tree, meta = self.state_fn(model, iteration)
        else:
            tree, meta = ns.capture_net_state(model, iteration=iteration)
        meta = dict(meta)
        meta.setdefault("score", float(score))
        self.checkpointer.save(iteration, tree, meta=meta, mesh=self.mesh)
        self.saved_steps.append(iteration)
