"""Per-shard checkpoint writer.

Each leaf of the state pytree is partitioned into its UNIQUE shards (the
distinct index rectangles of its save-time sharding — replicas are
deduplicated, so a fully replicated leaf writes exactly once), and every
chunk is assigned to the lowest-id device that holds it. One npz file per
owning device (``shard_00000.npz`` …) keeps the file count bounded by the
mesh size while letting a future multi-host writer emit only its
addressable shards. The manifest — tree paths, global shapes, dtypes,
save-time sharding specs, mesh topology, step, per-chunk CRC32s — commits
LAST via atomic rename (see manifest.py); data files written before a crash
are invisible garbage, collected once a later save commits.
"""

from __future__ import annotations

import os
import zlib
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

import time

from deeplearning4j_tpu.scaleout.ckpt.manifest import (
    Chunk,
    LeafEntry,
    Manifest,
    list_part_manifests,
    part_manifest_path,
    read_part_manifest,
    serialize_spec,
    step_dir_name,
    write_manifest,
    write_part_manifest,
)


def _shard_file_name(device_ord: int) -> str:
    return f"shard_{device_ord:05d}.npz"


def _normalize_index(index, shape) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
    """A shard's index (tuple of slices) → (start, chunk_shape), concrete."""
    starts: List[int] = []
    sizes: List[int] = []
    for sl, dim in zip(index, shape):
        start, stop, stride = sl.indices(dim)
        if stride != 1:
            raise ValueError(f"strided shard index {sl} is not supported")
        starts.append(start)
        sizes.append(stop - start)
    return tuple(starts), tuple(sizes)


def _leaf_spec(leaf) -> Optional[List]:
    sharding = getattr(leaf, "sharding", None)
    spec = getattr(sharding, "spec", None)
    if spec is None:
        return None
    return serialize_spec(tuple(spec))


def _leaf_chunks(leaf) -> List[Tuple[int, Tuple[int, ...], np.ndarray]]:
    """(owner device ordinal, start offsets, host chunk) per UNIQUE shard.

    Replicated copies collapse onto the lowest device id holding the
    rectangle; a host numpy array (or any unsharded leaf) is one chunk
    owned by device 0.
    """
    shards = getattr(leaf, "addressable_shards", None)
    if shards is None:
        arr = np.asarray(leaf)
        return [(0, (0,) * arr.ndim, arr)]
    by_start: Dict[Tuple[int, ...], Tuple[int, object]] = {}
    for shard in shards:
        start, _sizes = _normalize_index(shard.index, leaf.shape)
        dev = int(getattr(shard.device, "id", 0))
        prev = by_start.get(start)
        if prev is None or dev < prev[0]:
            by_start[start] = (dev, shard)
    out = []
    for start in sorted(by_start):
        dev, shard = by_start[start]
        out.append((dev, start, np.asarray(shard.data)))
    return out


def _leaf_chunks_for_process(leaf, process_index: int):
    """The multi-host ownership rule: dedup every rectangle across the
    GLOBAL shard list onto its lowest-device-id holder, then keep only the
    rectangles whose owner lives on ``process_index`` — so K processes
    writing concurrently produce disjoint chunk sets whose union is exactly
    the one ``save_sharded`` would have written. Returns
    ``(owned chunks like _leaf_chunks, n_global_unique)``."""
    shards = getattr(leaf, "global_shards", None)
    if shards is None:
        shards = getattr(leaf, "addressable_shards", None)
    if shards is None:
        # host array: the coordinator owns it
        if process_index == 0:
            arr = np.asarray(leaf)
            return [(0, (0,) * arr.ndim, arr)], 1
        return [], 1
    by_start: Dict[Tuple[int, ...], Tuple[int, int, object]] = {}
    for shard in shards:
        start, _sizes = _normalize_index(shard.index, leaf.shape)
        dev = int(getattr(shard.device, "id", 0))
        proc = int(getattr(shard.device, "process_index", 0))
        prev = by_start.get(start)
        if prev is None or dev < prev[0]:
            by_start[start] = (dev, proc, shard)
    owned = []
    for start in sorted(by_start):
        dev, proc, shard = by_start[start]
        if proc != process_index:
            continue
        if getattr(shard, "data", None) is None:  # pragma: no cover - guard
            raise ValueError(
                f"process {process_index} owns chunk at {start} but its "
                "data is not addressable here — ownership filter bug")
        owned.append((dev, start, np.asarray(shard.data)))
    return owned, len(by_start)


def _mesh_topology(state, mesh=None) -> Optional[Dict]:
    """Axis names/sizes recorded for the manifest — informational: restore
    works from chunk offsets alone, on any target mesh."""
    if mesh is not None:
        return {"axis_names": [str(a) for a in mesh.axis_names],
                "shape": [int(mesh.shape[a]) for a in mesh.axis_names]}
    for leaf in jax.tree_util.tree_leaves(state):
        leaf_mesh = getattr(getattr(leaf, "sharding", None), "mesh", None)
        if leaf_mesh is not None:
            return {"axis_names": [str(a) for a in leaf_mesh.axis_names],
                    "shape": [int(leaf_mesh.shape[a])
                              for a in leaf_mesh.axis_names]}
    return None


def save_sharded(root: str, step: int, state, meta: Optional[Dict] = None,
                 mesh=None) -> str:
    """Write ``state`` (a pytree) as the sharded checkpoint for ``step``
    under ``root``; returns the committed step directory.

    Writes every device's unique slices into per-shard npz files, then
    commits the manifest atomically. Until the manifest rename lands the
    directory does not exist as far as any reader is concerned.
    """
    step_dir = os.path.join(root, step_dir_name(step))
    os.makedirs(step_dir, exist_ok=True)

    per_file: Dict[str, Dict[str, np.ndarray]] = {}
    entries: List[LeafEntry] = []
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(state)[0]
    for path, leaf in leaves_with_paths:
        key = jax.tree_util.keystr(path)
        chunks: List[Chunk] = []
        global_shape = tuple(int(d) for d in np.shape(leaf))
        dtype = None
        for dev, start, arr in _leaf_chunks(leaf):
            arr = np.ascontiguousarray(arr)
            dtype = arr.dtype
            fname = _shard_file_name(dev)
            per_file.setdefault(fname, {})[key] = arr
            chunks.append(Chunk(file=fname, key=key, start=start,
                                shape=tuple(int(d) for d in arr.shape),
                                crc32=zlib.crc32(arr.tobytes())))
        entries.append(LeafEntry(path=key, shape=global_shape,
                                 dtype=str(dtype), spec=_leaf_spec(leaf),
                                 chunks=tuple(chunks)))

    for fname, payload in sorted(per_file.items()):
        with open(os.path.join(step_dir, fname), "wb") as f:
            np.savez(f, **payload)
            f.flush()
            os.fsync(f.fileno())

    manifest = Manifest(step=int(step), leaves=tuple(entries),
                        mesh=_mesh_topology(state, mesh), meta=dict(meta or {}))
    write_manifest(step_dir, manifest)
    return step_dir


# ----------------------------------------------------- multi-host writer ----

def save_process_shards(root: str, step: int, state,
                        process_index: Optional[int] = None) -> str:
    """One host's half of a multi-host save: write ONLY the chunks this
    process's devices own (lowest-global-device-id dedup, so replicas
    write once cluster-wide) plus an atomic part manifest listing every
    leaf with this process's chunks. Nothing here is a commit — the
    directory stays invisible to ``latest_step`` until the coordinator's
    ``merge_process_manifests`` lands the real manifest LAST."""
    if process_index is None:
        process_index = int(getattr(jax, "process_index", lambda: 0)())
    step_dir = os.path.join(root, step_dir_name(step))
    os.makedirs(step_dir, exist_ok=True)

    per_file: Dict[str, Dict[str, np.ndarray]] = {}
    entries: List[LeafEntry] = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        key = jax.tree_util.keystr(path)
        global_shape = tuple(int(d) for d in np.shape(leaf))
        dtype = str(np.asarray(
            leaf.addressable_shards[0].data
            if getattr(leaf, "addressable_shards", None) else leaf).dtype)
        chunks: List[Chunk] = []
        owned, _total = _leaf_chunks_for_process(leaf, process_index)
        for dev, start, arr in owned:
            arr = np.ascontiguousarray(arr)
            fname = _shard_file_name(dev)
            per_file.setdefault(fname, {})[key] = arr
            chunks.append(Chunk(file=fname, key=key, start=start,
                                shape=tuple(int(d) for d in arr.shape),
                                crc32=zlib.crc32(arr.tobytes())))
        entries.append(LeafEntry(path=key, shape=global_shape, dtype=dtype,
                                 spec=_leaf_spec(leaf), chunks=tuple(chunks)))

    for fname, payload in sorted(per_file.items()):
        with open(os.path.join(step_dir, fname), "wb") as f:
            np.savez(f, **payload)
            f.flush()
            os.fsync(f.fileno())

    write_part_manifest(step_dir, process_index, step, entries)
    return step_dir


def merge_process_manifests(root: str, step: int, n_processes: int,
                            meta: Optional[Dict] = None, mesh=None,
                            state=None, timeout_s: float = 120.0,
                            poll_s: float = 0.05) -> str:
    """The coordinator's merge barrier: wait for all ``n_processes`` part
    manifests, union their chunk lists per leaf, validate that the union
    exactly covers every leaf's global shape, THEN commit the single
    manifest atomically and remove the parts. A coordinator killed at any
    point before the final rename leaves no committed manifest — readers
    still resume from the previous step and retention sweeps the debris."""
    step_dir = os.path.join(root, step_dir_name(step))
    deadline = time.monotonic() + timeout_s
    while True:
        parts = list_part_manifests(step_dir)
        if len(parts) >= int(n_processes):
            break
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"manifest merge barrier: {len(parts)}/{n_processes} part "
                f"manifests present in {step_dir} after {timeout_s}s "
                f"(have processes {[i for i, _ in parts]})")
        time.sleep(poll_s)

    merged: Dict[str, LeafEntry] = {}
    order: List[str] = []
    for proc_idx, path in parts:
        got_idx, got_step, entries = read_part_manifest(path)
        if got_step != int(step):
            raise ValueError(
                f"part manifest {path} is for step {got_step}, merging "
                f"step {step}")
        for entry in entries:
            prev = merged.get(entry.path)
            if prev is None:
                merged[entry.path] = entry
                order.append(entry.path)
                continue
            if (prev.shape != entry.shape or prev.dtype != entry.dtype):
                raise ValueError(
                    f"part manifests disagree on leaf {entry.path}: "
                    f"{prev.shape}/{prev.dtype} vs "
                    f"{entry.shape}/{entry.dtype}")
            merged[entry.path] = LeafEntry(
                path=prev.path, shape=prev.shape, dtype=prev.dtype,
                spec=prev.spec if prev.spec is not None else entry.spec,
                chunks=prev.chunks + entry.chunks)

    # coverage check BEFORE commit: disjoint-by-construction chunks must
    # tile each leaf exactly — a missing host's chunks fail here, loudly
    for path in order:
        entry = merged[path]
        want = 1
        for dim in entry.shape:
            want *= dim
        got = 0
        for chunk in entry.chunks:
            vol = 1
            for dim in chunk.shape:
                vol *= dim
            got += vol
        if got != want:
            raise ValueError(
                f"merge barrier: leaf {path} chunks cover {got} of {want} "
                f"elements — a host's shards are missing; refusing to "
                "commit a hole-y checkpoint")

    manifest = Manifest(step=int(step),
                        leaves=tuple(merged[p] for p in order),
                        mesh=_mesh_topology(state, mesh) if state is not None
                        or mesh is not None else None,
                        meta=dict(meta or {}))
    write_manifest(step_dir, manifest)
    for proc_idx, _path in parts:
        part = part_manifest_path(step_dir, proc_idx)
        if os.path.exists(part):
            os.unlink(part)
    return step_dir
