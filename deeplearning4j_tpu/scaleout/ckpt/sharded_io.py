"""Per-shard checkpoint writer.

Each leaf of the state pytree is partitioned into its UNIQUE shards (the
distinct index rectangles of its save-time sharding — replicas are
deduplicated, so a fully replicated leaf writes exactly once), and every
chunk is assigned to the lowest-id device that holds it. One npz file per
owning device (``shard_00000.npz`` …) keeps the file count bounded by the
mesh size while letting a future multi-host writer emit only its
addressable shards. The manifest — tree paths, global shapes, dtypes,
save-time sharding specs, mesh topology, step, per-chunk CRC32s — commits
LAST via atomic rename (see manifest.py); data files written before a crash
are invisible garbage, collected once a later save commits.
"""

from __future__ import annotations

import os
import zlib
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from deeplearning4j_tpu.scaleout.ckpt.manifest import (
    Chunk,
    LeafEntry,
    Manifest,
    serialize_spec,
    step_dir_name,
    write_manifest,
)


def _shard_file_name(device_ord: int) -> str:
    return f"shard_{device_ord:05d}.npz"


def _normalize_index(index, shape) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
    """A shard's index (tuple of slices) → (start, chunk_shape), concrete."""
    starts: List[int] = []
    sizes: List[int] = []
    for sl, dim in zip(index, shape):
        start, stop, stride = sl.indices(dim)
        if stride != 1:
            raise ValueError(f"strided shard index {sl} is not supported")
        starts.append(start)
        sizes.append(stop - start)
    return tuple(starts), tuple(sizes)


def _leaf_spec(leaf) -> Optional[List]:
    sharding = getattr(leaf, "sharding", None)
    spec = getattr(sharding, "spec", None)
    if spec is None:
        return None
    return serialize_spec(tuple(spec))


def _leaf_chunks(leaf) -> List[Tuple[int, Tuple[int, ...], np.ndarray]]:
    """(owner device ordinal, start offsets, host chunk) per UNIQUE shard.

    Replicated copies collapse onto the lowest device id holding the
    rectangle; a host numpy array (or any unsharded leaf) is one chunk
    owned by device 0.
    """
    shards = getattr(leaf, "addressable_shards", None)
    if shards is None:
        arr = np.asarray(leaf)
        return [(0, (0,) * arr.ndim, arr)]
    by_start: Dict[Tuple[int, ...], Tuple[int, object]] = {}
    for shard in shards:
        start, _sizes = _normalize_index(shard.index, leaf.shape)
        dev = int(getattr(shard.device, "id", 0))
        prev = by_start.get(start)
        if prev is None or dev < prev[0]:
            by_start[start] = (dev, shard)
    out = []
    for start in sorted(by_start):
        dev, shard = by_start[start]
        out.append((dev, start, np.asarray(shard.data)))
    return out


def _mesh_topology(state, mesh=None) -> Optional[Dict]:
    """Axis names/sizes recorded for the manifest — informational: restore
    works from chunk offsets alone, on any target mesh."""
    if mesh is not None:
        return {"axis_names": [str(a) for a in mesh.axis_names],
                "shape": [int(mesh.shape[a]) for a in mesh.axis_names]}
    for leaf in jax.tree_util.tree_leaves(state):
        leaf_mesh = getattr(getattr(leaf, "sharding", None), "mesh", None)
        if leaf_mesh is not None:
            return {"axis_names": [str(a) for a in leaf_mesh.axis_names],
                    "shape": [int(leaf_mesh.shape[a])
                              for a in leaf_mesh.axis_names]}
    return None


def save_sharded(root: str, step: int, state, meta: Optional[Dict] = None,
                 mesh=None) -> str:
    """Write ``state`` (a pytree) as the sharded checkpoint for ``step``
    under ``root``; returns the committed step directory.

    Writes every device's unique slices into per-shard npz files, then
    commits the manifest atomically. Until the manifest rename lands the
    directory does not exist as far as any reader is concerned.
    """
    step_dir = os.path.join(root, step_dir_name(step))
    os.makedirs(step_dir, exist_ok=True)

    per_file: Dict[str, Dict[str, np.ndarray]] = {}
    entries: List[LeafEntry] = []
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(state)[0]
    for path, leaf in leaves_with_paths:
        key = jax.tree_util.keystr(path)
        chunks: List[Chunk] = []
        global_shape = tuple(int(d) for d in np.shape(leaf))
        dtype = None
        for dev, start, arr in _leaf_chunks(leaf):
            arr = np.ascontiguousarray(arr)
            dtype = arr.dtype
            fname = _shard_file_name(dev)
            per_file.setdefault(fname, {})[key] = arr
            chunks.append(Chunk(file=fname, key=key, start=start,
                                shape=tuple(int(d) for d in arr.shape),
                                crc32=zlib.crc32(arr.tobytes())))
        entries.append(LeafEntry(path=key, shape=global_shape,
                                 dtype=str(dtype), spec=_leaf_spec(leaf),
                                 chunks=tuple(chunks)))

    for fname, payload in sorted(per_file.items()):
        with open(os.path.join(step_dir, fname), "wb") as f:
            np.savez(f, **payload)
            f.flush()
            os.fsync(f.fileno())

    manifest = Manifest(step=int(step), leaves=tuple(entries),
                        mesh=_mesh_topology(state, mesh), meta=dict(meta or {}))
    write_manifest(step_dir, manifest)
    return step_dir
