"""Resharding checkpoint loader.

Restores a saved pytree into the CURRENT mesh even when it differs from the
save-time mesh (dp×sp×ep ↔ dp×pp ↔ single-device). For every leaf the
target sharding decides which index rectangle each device needs, and that
rectangle is assembled from the covering saved chunks via the manifest
offsets (``jax.make_array_from_callback``) — the full global array is never
materialized on one host unless the caller asks for an unsharded restore
(``shardings=None`` for that leaf).

Grouped-expert resharding rides the same mechanism: the flagship keeps the
GLOBAL expert layout G-invariant ((L, E, ...) leaves, device d owning the
contiguous G-expert slab — models/transformer_lm.lm_param_shardings), so a
G=4 save (chunks 4 experts wide) restores onto a G=1 mesh by SPLITTING
inside each chunk, and a G=1 save restores onto a wider grouping by
MERGING adjacent per-expert chunks — both are ordinary rectangle
intersections here, pinned end to end in
tests/test_ckpt_resume.py::test_grouped_expert_cross_g_resume.

Strictness (no silent corruption): a missing leaf, a shape mismatch, a
lossy dtype narrowing, or an uncovered target region all raise — nothing is
broadcast, truncated, or ``astype``-narrowed on the way in.

Live twin (ISSUE 14): when the SOURCE is not a directory but a tree of
arrays already resident on devices (elastic rejoin adoption, serve
cold start from a live trainer), ``ckpt.redistribution`` reshards it as
an explicit in-graph collective program instead of this module's host
assembly — same strictness, parity ≤1e-6, zero host round-trip. Disk
restores stay here.
"""

from __future__ import annotations

import os
import re
import zlib
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from deeplearning4j_tpu.scaleout.ckpt.manifest import (
    LeafEntry,
    Manifest,
    committed_steps,
    read_manifest,
)


class CorruptShardError(ValueError):
    """A chunk's bytes don't match the manifest CRC. The message names the
    shard file, leaf path, and chunk index so an operator knows exactly
    which file to re-copy (or which step to abandon)."""


def latest_step(root: str) -> Optional[int]:
    """Highest COMMITTED step under root; interrupted (manifest-less)
    directories are ignored."""
    steps = committed_steps(root)
    return steps[-1][0] if steps else None


def latest_step_dir(root: str) -> Optional[str]:
    steps = committed_steps(root)
    return steps[-1][1] if steps else None


def check_compatible(saved_shape: Tuple[int, ...], saved_dtype: str,
                     template_leaf, path: str) -> np.dtype:
    """Strict template check: exact shape, and dtype either identical or a
    SAFE (lossless) cast to the template dtype. Returns the target dtype.

    float64→float32, int64→int32 etc. are data-losing narrows and raise;
    the legacy loader's silent ``astype`` let those corruptions surface as
    late training divergence instead of a load-time error.
    """
    t_shape = tuple(int(d) for d in np.shape(template_leaf))
    if tuple(saved_shape) != t_shape:
        raise ValueError(
            f"checkpoint leaf {path} has shape {tuple(saved_shape)} but the "
            f"template expects {t_shape} — refusing to broadcast/truncate")
    src = np.dtype(saved_dtype)
    dst = np.dtype(getattr(template_leaf, "dtype", np.asarray(template_leaf).dtype))
    if src == dst:
        return dst
    try:
        safe = np.can_cast(src, dst, casting="safe")
    except TypeError:  # extension dtypes (bfloat16) outside can_cast's table
        safe = False
    if not safe:
        raise TypeError(
            f"checkpoint leaf {path} was saved as {src} but the template is "
            f"{dst} — a lossy dtype narrowing; restore into a matching-dtype "
            "template instead")
    return dst


class _ChunkStore:
    """Lazy per-file npz handles so a restore only reads the members the
    target shards actually cover. With ``verify_crc`` every chunk is
    CRC-checked once, on first read — silent disk corruption becomes a
    load-time ``CorruptShardError``, not late training divergence."""

    def __init__(self, step_dir: str, verify_crc: bool = False):
        self.step_dir = step_dir
        self.verify_crc = verify_crc
        self._files: Dict[str, object] = {}
        self._crc_ok: set = set()

    def get(self, fname: str, key: str) -> np.ndarray:
        z = self._files.get(fname)
        if z is None:
            z = np.load(os.path.join(self.step_dir, fname))
            self._files[fname] = z
        return z[key]

    def get_checked(self, entry: "LeafEntry", chunk_index: int) -> np.ndarray:
        """Read one chunk of ``entry``, verifying its CRC on first touch."""
        chunk = entry.chunks[chunk_index]
        data = self.get(chunk.file, chunk.key)
        if self.verify_crc and (chunk.file, chunk.key) not in self._crc_ok:
            crc = zlib.crc32(np.ascontiguousarray(data).tobytes())
            if crc != chunk.crc32:
                raise CorruptShardError(
                    f"checkpoint shard {chunk.file} is corrupt: leaf "
                    f"{entry.path} chunk {chunk_index} (of "
                    f"{len(entry.chunks)}, start {tuple(chunk.start)}) "
                    f"read crc32 {crc} != manifest {chunk.crc32} — re-copy "
                    f"the shard file or restore a different step")
            self._crc_ok.add((chunk.file, chunk.key))
        return data

    def close(self) -> None:
        for z in self._files.values():
            z.close()
        self._files = {}

    def __enter__(self) -> "_ChunkStore":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _region_of(index, shape) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
    """An Index (tuple of slices, possibly open) → (starts, sizes)."""
    if index is None:
        return (0,) * len(shape), tuple(shape)
    starts, sizes = [], []
    for sl, dim in zip(index, shape):
        start, stop, stride = sl.indices(dim)
        if stride != 1:
            raise ValueError(f"strided restore index {sl} is not supported")
        starts.append(start)
        sizes.append(stop - start)
    return tuple(starts), tuple(sizes)


def assemble_region(entry: LeafEntry, store: _ChunkStore, index,
                    dtype: np.dtype) -> np.ndarray:
    """Build the requested index rectangle of one leaf from the covering
    saved chunks. Chunks from a sharding partition the global space, so
    overlap volumes must sum to the region volume — anything less means a
    corrupt/incomplete checkpoint and raises."""
    starts, sizes = _region_of(index, entry.shape)
    # same-layout fast path: when one saved chunk IS the requested region
    # (same-mesh resume, the common case), hand its array back without the
    # empty-alloc + copy — the resharding assembly below is only paid when
    # the chunking actually changed (e.g. a cross-G expert regroup)
    for i, chunk in enumerate(entry.chunks):
        if tuple(chunk.start) == starts and tuple(chunk.shape) == tuple(sizes):
            return np.asarray(store.get_checked(entry, i), dtype=dtype)
    out = np.empty(sizes, dtype=dtype)
    covered = 0
    for i, chunk in enumerate(entry.chunks):
        lo = [max(s, cs) for s, cs in zip(starts, chunk.start)]
        hi = [min(s + n, cs + cn)
              for s, n, cs, cn in zip(starts, sizes, chunk.start, chunk.shape)]
        if any(l >= h for l, h in zip(lo, hi)):
            continue
        data = store.get_checked(entry, i)
        src = tuple(slice(l - cs, h - cs)
                    for l, h, cs in zip(lo, hi, chunk.start))
        dst = tuple(slice(l - s, h - s) for l, h, s in zip(lo, hi, starts))
        out[dst] = np.asarray(data[src], dtype=dtype)
        vol = 1
        for l, h in zip(lo, hi):
            vol *= h - l
        covered += vol
    if covered != out.size:
        raise ValueError(
            f"checkpoint leaf {entry.path}: saved chunks cover {covered} of "
            f"{out.size} elements of the requested region — incomplete "
            "checkpoint")
    return out


def restore_sharded(step_dir: str, template, shardings=None,
                    verify_crc: bool = True):
    """Restore the pytree saved in ``step_dir`` into the structure of
    ``template``. Returns ``(state, manifest)``.

    ``shardings``: a pytree matching ``template`` of per-leaf target
    ``jax.sharding.Sharding`` (or None entries). A leaf with a sharding is
    built shard-by-shard via ``jax.make_array_from_callback`` — each device
    assembles only ITS rectangle from the covering saved chunks, whatever
    mesh the save ran on. A leaf without one is assembled whole and placed
    as an ordinary (uncommitted) ``jnp`` array.

    Strict by construction: missing leaves, shape mismatches, and lossy
    dtype narrowing raise (see ``check_compatible``); every chunk actually
    read is CRC-verified (``verify_crc=False`` opts out) so a corrupt
    shard fails the restore with a ``CorruptShardError`` naming the file.
    """
    import jax.numpy as jnp

    manifest = read_manifest(step_dir)
    by_path = {entry.path: entry for entry in manifest.leaves}
    t_leaves, treedef = jax.tree_util.tree_flatten_with_path(template)
    if shardings is None:
        s_leaves = [None] * len(t_leaves)
    else:
        s_leaves = jax.tree_util.tree_flatten(
            shardings, is_leaf=lambda x: x is None)[0]
        if len(s_leaves) != len(t_leaves):
            raise ValueError(
                f"shardings pytree has {len(s_leaves)} leaves, template has "
                f"{len(t_leaves)}")
    new_leaves = []
    with _ChunkStore(step_dir, verify_crc=verify_crc) as store:
        for (path, t_leaf), sharding in zip(t_leaves, s_leaves):
            key = jax.tree_util.keystr(path)
            entry = by_path.get(key)
            if entry is None:
                raise KeyError(f"checkpoint is missing leaf {key}")
            dtype = check_compatible(entry.shape, entry.dtype, t_leaf, key)
            if sharding is None:
                new_leaves.append(
                    jnp.asarray(assemble_region(entry, store, None, dtype)))
            else:
                new_leaves.append(jax.make_array_from_callback(
                    tuple(entry.shape), sharding,
                    lambda idx, e=entry, d=dtype: assemble_region(
                        e, store, idx, d)))
    return jax.tree_util.tree_unflatten(
        treedef, new_leaves), manifest


_KEYSTR_SEG = re.compile(r"\['([^']*)'\]")


def template_from_manifest(manifest: Manifest):
    """Rebuild a zeroed template pytree from the manifest alone (leaf
    paths + global shapes + dtypes) — template-free restore for consumers
    that don't hold the training-time structure, e.g. the serving engine
    loading an LM checkpoint (``serve.DecodeEngine.from_checkpoint``).
    Supports string-keyed nested dicts, the layout every checkpoint in
    this tree uses; anything else raises rather than guessing."""
    tree: Dict = {}
    for entry in manifest.leaves:
        keys = _KEYSTR_SEG.findall(entry.path)
        if "".join(f"['{k}']" for k in keys) != entry.path or not keys:
            raise ValueError(
                f"manifest leaf path {entry.path!r} is not a string-keyed "
                "dict path — template-free restore supports dict pytrees "
                "only; restore with an explicit template instead")
        try:
            dtype = np.dtype(entry.dtype)
        except TypeError:
            import ml_dtypes  # extension dtypes (bfloat16) ship with jax

            dtype = np.dtype(getattr(ml_dtypes, entry.dtype))
        node = tree
        for k in keys[:-1]:
            node = node.setdefault(k, {})
        node[keys[-1]] = np.zeros(tuple(entry.shape), dtype)
    return tree


def verify_checksums(step_dir: str) -> List[str]:
    """Re-read every chunk and compare CRC32 against the manifest. Returns
    a list of human-readable mismatch descriptions (empty = intact)."""
    manifest = read_manifest(step_dir)
    problems: List[str] = []
    with _ChunkStore(step_dir) as store:
        for entry in manifest.leaves:
            for i, chunk in enumerate(entry.chunks):
                where = (f"{entry.path} chunk {i} [{chunk.file}, "
                         f"start {tuple(chunk.start)}]")
                try:
                    data = np.ascontiguousarray(
                        store.get(chunk.file, chunk.key))
                except Exception as e:  # missing file/member counts as corrupt
                    problems.append(f"{where}: unreadable ({e})")
                    continue
                crc = zlib.crc32(data.tobytes())
                if crc != chunk.crc32:
                    problems.append(
                        f"{where}: crc32 {crc} != manifest {chunk.crc32}")
                if tuple(data.shape) != chunk.shape:
                    problems.append(
                        f"{where}: stored shape {tuple(data.shape)} != "
                        f"manifest {chunk.shape}")
    return problems


def _manifest_or_none(step_dir: str) -> Optional[Manifest]:
    try:
        return read_manifest(step_dir)
    except (FileNotFoundError, ValueError):
        return None
