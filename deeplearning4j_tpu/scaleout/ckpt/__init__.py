"""Sharded, atomic, resumable checkpoints for the composed training paths.

The legacy ``scaleout/checkpoint.py`` is a single-controller npz writer: it
gathers every leaf to one host, so the dp×sp×ep / dp×pp flagship runs could
not snapshot without materializing global state. This package is the
subsystem that replaces it underneath (the legacy API stays as a thin
single-file wrapper for single-device nets):

- ``sharded_io.save_sharded`` — each device's slice of a sharded pytree is
  written as its own chunk into per-shard npz files; a JSON ``MANIFEST``
  (tree paths, global shapes, dtypes, sharding specs, mesh topology, step,
  per-chunk CRCs) commits LAST via atomic rename — a checkpoint without a
  committed manifest is invisible to every reader.
- ``reshard.restore_sharded`` — restores into the *current* mesh even when
  it differs from the save-time mesh (dp×sp×ep ↔ dp×pp ↔ single-device):
  each target shard is assembled from the covering saved chunks via the
  manifest offsets (``jax.make_array_from_callback``), never the full
  global array on one host.
- ``redistribution.plan_redistribution`` / ``apply_plan`` /
  ``redistribute_tree`` (ISSUE 14) — the LIVE twin of the resharding
  loader: when the source arrays are already on devices (elastic rejoin
  adoption, a serving engine cold-starting from a trainer's tree), the
  respec runs as an explicit in-graph collective program
  (slice/all_gather/all_to_all/ppermute steps, arXiv:2112.01075) inside
  one jitted identity — no host round-trip. Disk restores keep the host
  path above.
- ``checkpointer.Checkpointer`` / ``CheckpointIterationListener`` — the
  training integration: save-every-N through the exception-safe listener
  chain, retention GC, ``latest()``/``restore()`` resume entry points, and
  telemetry counters (save duration/bytes/shards) in the PR 2 registry.
- ``net_state`` — capture/restore of the full MultiLayerNetwork training
  state (params + updater state + RNG stream position + iteration), shared
  by the listener and the legacy wrapper.

Sharding the persisted optimizer/param state mirrors the weight-update
sharding argument of arXiv:2004.13336; periodic fault-tolerant snapshots
are the DeepSpark-style (arXiv:1602.08191) recovery mechanism.
"""

from deeplearning4j_tpu.scaleout.ckpt.manifest import (  # noqa: F401
    MANIFEST_NAME,
    Manifest,
    read_manifest,
    step_dir_name,
)
from deeplearning4j_tpu.scaleout.ckpt.sharded_io import (  # noqa: F401
    merge_process_manifests,
    save_process_shards,
    save_sharded,
)
from deeplearning4j_tpu.scaleout.ckpt.reshard import (  # noqa: F401
    CorruptShardError,
    latest_step,
    latest_step_dir,
    restore_sharded,
    verify_checksums,
)
from deeplearning4j_tpu.scaleout.ckpt.redistribution import (  # noqa: F401
    RedistributionPlan,
    apply_plan,
    plan_redistribution,
    redistribute,
    redistribute_tree,
)
from deeplearning4j_tpu.scaleout.ckpt.checkpointer import (  # noqa: F401
    Checkpointer,
    CheckpointIterationListener,
    replicated_shardings,
)
from deeplearning4j_tpu.scaleout.ckpt.async_ckpt import (  # noqa: F401
    AsyncCheckpointer,
)
from deeplearning4j_tpu.scaleout.ckpt.net_state import (  # noqa: F401
    capture_net_state,
    restore_net_state,
)
