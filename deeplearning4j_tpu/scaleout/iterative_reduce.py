"""IterativeReduce superstep runtime (YARN-runtime parity).

Parity with ref hadoop-yarn cdh4 module: the ComputableMaster /
ComputableWorker SPI (iterativereduce/runtime/Computable{Master,Worker}.java),
the superstep loop of ApplicationWorkerService.run (:203-280 — compute →
send update → barrier → receive master update), and the in-process IRUnit
simulator (iterativereduce/irunit/IRUnitDriver.java) that runs one master +
N workers in a single process over file splits.

TPU-first notes: the control plane is threads + a barrier in one process
(the reference's Avro-RPC master↔worker exchange is host-side Java
serialization; here workers already share an address space). The DEFAULT
model implementations run their per-worker fit on the device; cross-worker
averaging of flat param vectors happens host-side exactly like the
reference's Master.compute — the in-graph psum path lives in
parallel/trainer.py and is the preferred fast path.
"""

from __future__ import annotations

import threading
from typing import Any, Generic, List, Optional, Sequence, TypeVar

import numpy as np

T = TypeVar("T")


class ComputableMaster(Generic[T]):
    """ref: ComputableMaster.java — compute() merges worker updates."""

    def compute(self, worker_updates: Sequence[T], master_update: Optional[T]) -> T:
        raise NotImplementedError

    def complete(self) -> None:
        """Called once after the final superstep (ref writes final state)."""


class ComputableWorker(Generic[T]):
    """ref: ComputableWorker.java — compute() one batch, update() receives
    the master's merged state."""

    def compute(self) -> Optional[T]:
        """One superstep of local work; None signals this worker is done."""
        raise NotImplementedError

    def update(self, master_update: T) -> None:
        raise NotImplementedError


class IterativeReduceRunner(Generic[T]):
    """In-process superstep driver (ref IRUnitDriver): all workers compute,
    barrier, master merges, update fan-out — until every worker reports done
    or max_supersteps is hit."""

    def __init__(self, master: ComputableMaster[T],
                 workers: Sequence[ComputableWorker[T]],
                 max_supersteps: int = 1000):
        if not workers:
            raise ValueError("need at least one worker")
        self.master = master
        self.workers = list(workers)
        self.max_supersteps = max_supersteps
        self.supersteps_run = 0
        self.master_update: Optional[T] = None

    def run(self) -> Optional[T]:
        n = len(self.workers)
        for _ in range(self.max_supersteps):
            updates: List[Optional[T]] = [None] * n
            errors: List[BaseException] = []

            def work(idx: int) -> None:
                try:
                    updates[idx] = self.workers[idx].compute()
                except BaseException as e:  # surfaced after the join barrier
                    errors.append(e)

            threads = [threading.Thread(target=work, args=(i,))
                       for i in range(n)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()  # ══ superstep barrier (ref waiting() poll loop)
            if errors:
                # ref: AM tallies worker errors and aborts nonzero
                # (ApplicationMasterService.java:163-189)
                raise errors[0]
            live = [u for u in updates if u is not None]
            if not live:
                break
            self.supersteps_run += 1
            self.master_update = self.master.compute(live, self.master_update)
            for w in self.workers:
                w.update(self.master_update)
        self.master.complete()
        return self.master_update


# ------------------------- default MultiLayerNetwork master/worker impls ----

class ParameterAveragingMaster(ComputableMaster[np.ndarray]):
    """ref impl/multilayer/Master.java: average flat param vectors."""

    def compute(self, worker_updates, master_update=None) -> np.ndarray:
        return np.mean([np.asarray(u) for u in worker_updates], axis=0)


class NetworkWorker(ComputableWorker[np.ndarray]):
    """ref impl/multilayer/WorkerNode.java: fit one local batch per
    superstep, emit the resulting flat params; absorb averaged params."""

    def __init__(self, conf, features: np.ndarray, labels: np.ndarray,
                 supersteps: int = 1):
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        self.net = MultiLayerNetwork(conf).init()
        self.features = features
        self.labels = labels
        self.remaining = supersteps

    def compute(self) -> Optional[np.ndarray]:
        if self.remaining <= 0:
            return None
        self.remaining -= 1
        self.net.fit(self.features, self.labels)
        return np.asarray(self.net.params())

    def update(self, master_update: np.ndarray) -> None:
        self.net.set_params(master_update)


def run_iterative_reduce(conf, features: np.ndarray, labels: np.ndarray,
                         n_workers: int = 2, supersteps: int = 3):
    """Convenience IRUnit-style entry: split data row-wise over workers
    (ref TextInputFormat splits), run the superstep loop, return a network
    holding the final averaged params."""
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    splits_x = np.array_split(features, n_workers)
    splits_y = np.array_split(labels, n_workers)
    workers = [
        NetworkWorker(conf, sx, sy, supersteps=supersteps)
        for sx, sy in zip(splits_x, splits_y)
    ]
    runner = IterativeReduceRunner(ParameterAveragingMaster(), workers)
    final = runner.run()
    net = MultiLayerNetwork(conf).init()
    if final is not None:
        net.set_params(final)
    return net, runner
