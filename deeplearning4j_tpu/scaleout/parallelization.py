"""Local thread-pool map (ref: parallel/Parallelization.java:35-130)."""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterable, List, Optional, TypeVar

T = TypeVar("T")
R = TypeVar("R")


def iterate(items: Iterable[T], fn: Callable[[T], R],
            num_threads: Optional[int] = None) -> List[R]:
    """Apply fn to every item on a thread pool (ref: Parallelization.iterateInParallel)."""
    with ThreadPoolExecutor(max_workers=num_threads) as pool:
        return list(pool.map(fn, items))


def run_in_parallel(tasks: Iterable[Callable[[], R]],
                    num_threads: Optional[int] = None) -> List[R]:
    with ThreadPoolExecutor(max_workers=num_threads) as pool:
        futures = [pool.submit(t) for t in tasks]
        return [f.result() for f in futures]
