"""Multi-process master/worker training over the remote StateTracker.

Parity with ref: actor/runner/DeepLearning4jDistributed.java boots a
master actor + worker actors on separate JVMs joined through the Hazelcast
tracker; here ``DistributedMaster`` embeds ``StateTrackerServer`` and each
``DistributedWorker`` (separate OS process, see ``worker_main``) connects a
``StateTrackerClient``. The round protocol, routers, aggregators and
early-stopping policy are the SAME objects the in-process
LocalDistributedRunner uses — the tracker is the only seam, exactly the
reference's design (MasterActor.java:106-142, WorkerActor.java:168-206).

Fault model (ref posture: MasterActor clears dead workers' jobs on its
heartbeat): every worker runs a daemon heartbeat thread bumping the
``hb.<worker-id>`` counter; the master requeues the jobs of any worker
whose heartbeat goes stale for ``worker_timeout_s`` and deregisters it —
a worker process crash (or kill -9) costs its in-flight job one reroute,
never the run.
"""

from __future__ import annotations

import argparse
import importlib
import json
import logging
import threading
import time
import uuid
from collections import deque
from typing import Dict, Optional

from deeplearning4j_tpu.scaleout.aggregator import ParameterAveragingAggregator
from deeplearning4j_tpu.scaleout.job import JobIterator
from deeplearning4j_tpu.scaleout.model_saver import ModelSaver
from deeplearning4j_tpu.scaleout.perform import WorkerPerformer
from deeplearning4j_tpu.scaleout.remote_tracker import (
    StateTrackerClient,
    StateTrackerServer,
)
from deeplearning4j_tpu.scaleout.runner import EarlyStopping
from deeplearning4j_tpu.scaleout.workrouter import (
    IterativeReduceWorkRouter,
    WorkRouter,
)

log = logging.getLogger(__name__)


class DistributedWorker:
    """Worker-process loop: register → poll job → perform → publish.

    (ref: WorkerActor heartbeat pull/perform/publish, minus Akka.)"""

    def __init__(self, address: str, performer: WorkerPerformer,
                 worker_id: Optional[str] = None, poll_s: float = 0.02,
                 heartbeat_s: float = 0.25):
        self.address = address
        self.tracker = StateTrackerClient(address)
        self.worker_id = worker_id or f"worker-{uuid.uuid4().hex[:8]}"
        self.performer = performer
        self.poll_s = poll_s
        self.heartbeat_s = heartbeat_s

    def _heartbeat_loop(self, stop: threading.Event) -> None:
        # separate client connection: the main loop holds the RPC lock for
        # the whole perform() round-trip, and a stalled heartbeat is
        # exactly what the master interprets as death
        hb = StateTrackerClient(self.address)
        try:
            while not stop.is_set():
                hb.increment(f"hb.{self.worker_id}")
                stop.wait(self.heartbeat_s)
        except (ConnectionError, OSError) as exc:
            # master gone; main loop will notice too — but a silent dead
            # heartbeat is indistinguishable from a healthy idle one
            log.warning("worker %s heartbeat loop died: %r",
                        self.worker_id, exc)
            return
        finally:
            hb.close()

    def run(self) -> None:
        t = self.tracker
        t.add_worker(self.worker_id)
        stop = threading.Event()
        hb_thread = threading.Thread(target=self._heartbeat_loop,
                                     args=(stop,), daemon=True)
        hb_thread.start()
        try:
            while not (t.is_done() or t.is_early_stop()):
                if t.needs_replicate(self.worker_id):
                    current = t.get_current()
                    if current is not None:
                        self.performer.update(current)
                    t.done_replicating(self.worker_id)
                job = t.job_for(self.worker_id)
                if job is None:
                    time.sleep(self.poll_s)
                    continue
                t0 = time.perf_counter()
                self.performer.perform(job)
                t.increment("job_ms_total",  # graftlint: allow[untimed-dispatch] heartbeat counter, not a bench: perform() ends in the performer's own score fetch
                            (time.perf_counter() - t0) * 1000.0)
                t.add_update(self.worker_id, job)
                t.clear_job(self.worker_id)
                t.increment("jobs_done")
                t.increment(f"rounds.{self.worker_id}")
        finally:
            stop.set()
            hb_thread.join(timeout=10)  # deterministic shutdown: the loop
            self.tracker.close()        # wakes from stop.wait immediately


class DistributedMaster:
    """Master-process loop around an embedded StateTrackerServer: feeds
    jobs, aggregates per the router's policy, recovers worker failures,
    enforces early stopping. ``train()`` returns the aggregated params."""

    def __init__(
        self,
        job_iterator: JobIterator,
        router: Optional[WorkRouter] = None,
        server: Optional[StateTrackerServer] = None,
        min_workers: int = 1,
        max_rounds: int = 10_000,
        worker_timeout_s: float = 15.0,
        register_timeout_s: float = 60.0,
        model_saver: Optional[ModelSaver] = None,
        early_stopping: Optional[EarlyStopping] = None,
        tick_s: float = 0.02,
    ):
        self.server = server or StateTrackerServer()
        self.tracker = self.server.tracker  # embedded: zero-IPC master side
        self.router = router or IterativeReduceWorkRouter(
            self.tracker, ParameterAveragingAggregator())
        self.job_iterator = job_iterator
        self.min_workers = min_workers
        self.max_rounds = max_rounds
        self.worker_timeout_s = worker_timeout_s
        self.register_timeout_s = register_timeout_s
        self.model_saver = model_saver
        self.early_stopping = early_stopping
        self.tick_s = tick_s
        self._requeued: deque = deque()
        self._jobs_left = 0
        self._hb_seen: Dict[str, tuple] = {}  # wid -> (count, wallclock)
        self._no_improve = 0
        self._es_scores: Dict[str, float] = {}

    @property
    def address(self) -> str:
        return self.server.address

    # ---- fault detection ----
    def _dead_workers(self) -> list:
        now = time.monotonic()
        dead = []
        for wid in self.tracker.workers():
            count = self.tracker.count(f"hb.{wid}")
            seen = self._hb_seen.get(wid)
            if seen is None or seen[0] != count:
                self._hb_seen[wid] = (count, now)
            elif now - seen[1] > self.worker_timeout_s:
                dead.append(wid)
        return dead

    def _bury(self, wid: str) -> None:
        job = self.tracker.job_for(wid)
        if job is not None:
            self._requeued.append(job)
            self.tracker.clear_job(wid)
        self.tracker.remove_worker(wid)
        self._hb_seen.pop(wid, None)
        self._es_scores.pop(wid, None)
        self.tracker.increment("workers_failed")
        log.warning("worker %s heartbeat stale >%ss: job requeued, "
                    "deregistered", wid, self.worker_timeout_s)

    # ---- early stopping (same policy as LocalDistributedRunner) ----
    def _check_early_stopping(self, snapshot) -> None:
        if self.early_stopping is None:
            return
        for wid, job in snapshot.items():
            if job.score is not None:
                self._es_scores[wid] = float(job.score)
        live = set(self.tracker.workers())
        if not live or not live.issubset(self._es_scores.keys()):
            return  # full-coverage rule: every live worker must have scored
        mean = sum(self._es_scores[w] for w in live) / len(live)
        self._es_scores = {}
        best = self.tracker.best_loss()
        if mean < best - self.early_stopping.min_delta:
            self.tracker.set_best_loss(mean)
            self._no_improve = 0
        else:
            self._no_improve += 1
            if self._no_improve >= self.early_stopping.patience:
                self.tracker.early_stop()

    # ---- job feeding ----
    def _feed_idle_workers(self) -> None:
        for wid in self.tracker.workers():
            if self.tracker.job_for(wid) is not None:
                continue
            if self._requeued:
                job = self._requeued.popleft()
                job.worker_id = wid
            elif self._jobs_left > 0 and self.job_iterator.has_next():
                self._jobs_left -= 1
                job = self.job_iterator.next(wid)
            else:
                continue
            self.tracker.add_job(job)

    def _wait_for_workers(self) -> None:
        deadline = time.monotonic() + self.register_timeout_s
        while len(self.tracker.workers()) < self.min_workers:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"{len(self.tracker.workers())}/{self.min_workers} "
                    f"workers registered within {self.register_timeout_s}s")
            time.sleep(0.05)

    def train(self):
        self._wait_for_workers()
        self._jobs_left = self.max_rounds * max(
            len(self.tracker.workers()), 1)
        last_save = 0.0
        try:
            while not self.tracker.is_early_stop():
                for wid in self._dead_workers():
                    self._bury(wid)
                if not self.tracker.workers():
                    raise RuntimeError("all workers failed")
                self._feed_idle_workers()
                snapshot = self.tracker.updates()
                if snapshot and self.router.send_work():
                    self._check_early_stopping(snapshot)
                    self.router.update(snapshot)
                    self.tracker.increment("aggregations")
                    now = time.monotonic()
                    if (self.model_saver is not None
                            and now - last_save >= 1.0):
                        current = self.tracker.get_current()
                        if current is not None:
                            self.model_saver.save(current)
                            last_save = now
                drained = (not self._requeued
                           and (self._jobs_left <= 0
                                or not self.job_iterator.has_next()))
                if drained and not self.tracker.has_pending_jobs():
                    # workers publish BEFORE clearing their job, so with
                    # nothing pending no further update can ever arrive —
                    # a sync router's barrier can no longer be met and
                    # waiting on updates() would livelock; the straggler
                    # flush below aggregates whatever remains
                    break
                time.sleep(self.tick_s)
            # stragglers published after the last aggregation
            if self.tracker.updates():
                self.router.update()
                self.tracker.increment("aggregations")
            if self.model_saver is not None:
                current = self.tracker.get_current()
                if current is not None:
                    self.model_saver.save(current)
        finally:
            self.tracker.finish()  # releases every worker's poll loop
        return self.tracker.get_current()

    def shutdown(self) -> None:
        self.server.shutdown()


def _resolve_performer(spec: str, kwargs: dict) -> WorkerPerformer:
    """"pkg.module:callable" → callable(**kwargs) -> WorkerPerformer."""
    module_name, _, attr = spec.partition(":")
    factory = getattr(importlib.import_module(module_name), attr)
    return factory(**kwargs)


def worker_main(argv=None) -> None:
    """CLI worker entry: ``python -m
    deeplearning4j_tpu.scaleout.distributed_runner --connect HOST:PORT
    --performer pkg.mod:factory [--kwargs-json '{...}'] [--worker-id ID]``
    (the analogue of launching the reference's WorkerNode JVM)."""
    p = argparse.ArgumentParser(description="distributed training worker")
    p.add_argument("--connect", required=True, help="master tracker host:port")
    p.add_argument("--performer", required=True,
                   help="pkg.module:factory for the WorkerPerformer")
    p.add_argument("--kwargs-json", default="{}",
                   help="JSON kwargs for the performer factory")
    p.add_argument("--worker-id", default=None)
    args = p.parse_args(argv)
    performer = _resolve_performer(args.performer,
                                   json.loads(args.kwargs_json))
    DistributedWorker(args.connect, performer,
                      worker_id=args.worker_id).run()


if __name__ == "__main__":
    worker_main()
