"""Local distributed runtime — master/worker training in one process.

Parity with ref: actor/runner/DeepLearning4jDistributed.java + the
MasterActor/WorkerActor heartbeat protocol (MasterActor.java:106-142,
WorkerActor.java:168-206), replacing Akka actors + Hazelcast with a thread
pool + InMemoryStateTracker — exactly how the reference's own tests run the
cluster (testsupport/BaseTestDistributed.java: everything in one JVM).

Round protocol per heartbeat:
  master: if router.send_work(): aggregate updates (router.update), feed next
          jobs from the JobIterator
  worker: if tracker.needs_replicate(id): pull current params
          (performer.update); take job; performer.perform(job);
          tracker.add_update(id, job)

On TPU silicon prefer parallel/trainer.py (in-graph collectives). This runner
is the control-plane-parity path and also the host-level orchestration for
multi-process setups.
"""

from __future__ import annotations

import logging
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor, wait
from typing import Optional

from deeplearning4j_tpu.scaleout.aggregator import ParameterAveragingAggregator
from deeplearning4j_tpu.scaleout.job import JobIterator
from deeplearning4j_tpu.scaleout.model_saver import ModelSaver
from deeplearning4j_tpu.scaleout.perform import WorkerPerformer
from deeplearning4j_tpu.scaleout.statetracker import InMemoryStateTracker
from deeplearning4j_tpu.scaleout.workrouter import IterativeReduceWorkRouter, WorkRouter

log = logging.getLogger(__name__)


class LocalDistributedRunner:
    def __init__(
        self,
        performer_factory,
        job_iterator: JobIterator,
        num_workers: int = 4,
        router: Optional[WorkRouter] = None,
        tracker: Optional[InMemoryStateTracker] = None,
        model_saver: Optional[ModelSaver] = None,
        max_rounds: int = 10_000,
        fault_tolerant: bool = False,
    ):
        """performer_factory() -> WorkerPerformer (one per worker, mirroring
        WorkerPerformerFactory, ref: scaleout/perform/WorkerPerformerFactory)."""
        self.tracker = tracker or InMemoryStateTracker()
        self.router = router or IterativeReduceWorkRouter(
            self.tracker, ParameterAveragingAggregator()
        )
        self.performers = {
            f"worker-{i}": performer_factory() for i in range(num_workers)
        }
        self.job_iterator = job_iterator
        self.model_saver = model_saver
        self.max_rounds = max_rounds
        self.fault_tolerant = fault_tolerant
        self._requeued: deque = deque()  # jobs orphaned by failed workers
        for worker_id in self.performers:
            self.tracker.add_worker(worker_id)

    def _worker_round(self, worker_id: str) -> None:
        performer: WorkerPerformer = self.performers[worker_id]
        if self.tracker.needs_replicate(worker_id):
            current = self.tracker.get_current()
            if current is not None:
                performer.update(current)
            self.tracker.done_replicating(worker_id)
        job = self.tracker.job_for(worker_id)
        if job is None:
            return
        t0 = time.perf_counter()
        performer.perform(job)
        # per-job timing counter (ref: WorkerActor heartbeat ms logging,
        # WorkerActor.java:198-202 / YARN WorkerNode StopWatch)
        self.tracker.increment("job_ms_total",
                               (time.perf_counter() - t0) * 1000.0)
        self.tracker.add_update(worker_id, job)
        self.tracker.clear_job(worker_id)
        self.tracker.increment("jobs_done")

    def _handle_worker_failure(self, worker_id: str, exc: BaseException) -> None:
        """Dead-worker recovery (ref: MasterActor stale-job GC + tracker
        recentlyCleared re-route, MasterActor.java:115-142): the worker is
        deregistered and its in-flight job requeued for a surviving worker."""
        log.warning("worker %s failed: %s — rerouting its job", worker_id, exc)
        job = self.tracker.job_for(worker_id)
        self.tracker.clear_job(worker_id)
        self.tracker.remove_worker(worker_id)
        self.performers.pop(worker_id, None)
        self.tracker.increment("worker_failures")
        if job is not None:
            # queue for reassignment: assigning directly could clobber a
            # survivor's own in-flight job slot
            self._requeued.append(job)

    def train(self):
        """Run rounds until the JobIterator is exhausted; returns the final
        averaged flat param vector (tracker current)."""
        workers = list(self.performers)
        with ThreadPoolExecutor(max_workers=len(workers)) as pool:
            rounds = 0
            while rounds < self.max_rounds:
                rounds += 1
                # master: feed one job per IDLE worker — orphaned jobs from
                # failed workers first, then fresh ones from the iterator
                fed = False
                for worker_id in workers:
                    if self.tracker.job_for(worker_id) is not None:
                        continue
                    if self._requeued:
                        job = self._requeued.popleft()
                        job.worker_id = worker_id
                        self.tracker.add_job(job)
                        fed = True
                    elif self.job_iterator.has_next():
                        self.tracker.add_job(self.job_iterator.next(worker_id))
                        fed = True
                if (not fed and not self.tracker.has_pending_jobs()
                        and not self._requeued):
                    break
                # workers: one heartbeat each (parallel)
                futures = {w: pool.submit(self._worker_round, w)
                           for w in workers}
                wait(futures.values())
                for w, f in futures.items():
                    exc = f.exception()
                    if exc is None:
                        continue
                    if not self.fault_tolerant:
                        raise exc
                    self._handle_worker_failure(w, exc)
                    workers = list(self.performers)
                    if not workers:
                        raise RuntimeError(
                            "all workers failed"
                        ) from exc
                # master: aggregate when router policy allows
                if self.router.send_work():
                    self.router.update()
                    self.tracker.increment("aggregations")
                    if self.model_saver is not None:
                        current = self.tracker.get_current()
                        if current is not None:
                            self.model_saver.save(current)
            # final aggregation of any straggler updates
            if self.tracker.updates():
                self.router.update()
        self.tracker.finish()
        return self.tracker.get_current()
