"""Local distributed runtime — master/worker training in one process.

Parity with ref: actor/runner/DeepLearning4jDistributed.java + the
MasterActor/WorkerActor heartbeat protocol (MasterActor.java:106-142,
WorkerActor.java:168-206), replacing Akka actors + Hazelcast with a thread
pool + InMemoryStateTracker — exactly how the reference's own tests run the
cluster (testsupport/BaseTestDistributed.java: everything in one JVM).

Round protocol per heartbeat:
  master: if router.send_work(): aggregate updates (router.update), feed next
          jobs from the JobIterator
  worker: if tracker.needs_replicate(id): pull current params
          (performer.update); take job; performer.perform(job);
          tracker.add_update(id, job)

On TPU silicon prefer parallel/trainer.py (in-graph collectives). This runner
is the control-plane-parity path and also the host-level orchestration for
multi-process setups.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor, wait
from typing import Optional

from deeplearning4j_tpu.scaleout.aggregator import ParameterAveragingAggregator
from deeplearning4j_tpu.scaleout.job import JobIterator
from deeplearning4j_tpu.scaleout.model_saver import ModelSaver
from deeplearning4j_tpu.scaleout.perform import WorkerPerformer
from deeplearning4j_tpu.scaleout.statetracker import InMemoryStateTracker
from deeplearning4j_tpu.scaleout.workrouter import IterativeReduceWorkRouter, WorkRouter

log = logging.getLogger(__name__)


class EarlyStopping:
    """Master-side early-stopping policy: stop distributing work after
    ``patience`` aggregation rounds whose mean reported job loss fails to
    improve the tracker's best loss by ``min_delta``.

    The reference exposes earlyStop/bestLoss flags on the StateTracker
    (StateTracker.java / BaseHazelCastStateTracker) but ships no policy
    that trips them; here the master enforces them — and any external
    caller can still trip ``tracker.early_stop()`` directly, which both
    runner paths honor."""

    def __init__(self, patience: int = 3, min_delta: float = 0.0):
        if patience < 1:
            raise ValueError("patience must be >= 1")
        self.patience = patience
        self.min_delta = min_delta


class LocalDistributedRunner:
    def __init__(
        self,
        performer_factory,
        job_iterator: JobIterator,
        num_workers: int = 4,
        router: Optional[WorkRouter] = None,
        tracker: Optional[InMemoryStateTracker] = None,
        model_saver: Optional[ModelSaver] = None,
        max_rounds: int = 10_000,
        fault_tolerant: bool = False,
        heartbeat_s: float = 0.05,
        async_timeout_s: Optional[float] = None,
        early_stopping: Optional[EarlyStopping] = None,
    ):
        """performer_factory() -> WorkerPerformer (one per worker, mirroring
        WorkerPerformerFactory, ref: scaleout/perform/WorkerPerformerFactory)."""
        self.tracker = tracker or InMemoryStateTracker()
        self.router = router or IterativeReduceWorkRouter(
            self.tracker, ParameterAveragingAggregator()
        )
        self.performers = {
            f"worker-{i}": performer_factory() for i in range(num_workers)
        }
        self.job_iterator = job_iterator
        self.model_saver = model_saver
        self.max_rounds = max_rounds
        self.fault_tolerant = fault_tolerant
        self.heartbeat_s = heartbeat_s  # async-mode idle wake interval for
        #                                 failure/deadline checks; aggregation
        #                                 itself is event-driven (the master
        #                                 wakes the moment a worker publishes;
        #                                 ref: MasterActor 1 s tick)
        self.async_timeout_s = async_timeout_s  # optional wall-clock cap for
        #                                         the async path (None = run
        #                                         until the iterator drains,
        #                                         matching the sync path)
        self.early_stopping = early_stopping
        self._no_improve = 0  # evaluation rounds without best-loss progress
        self._es_scores: dict = {}  # worker_id -> latest score this round
        self._requeued: deque = deque()  # jobs orphaned by failed workers
        self._feed_lock = threading.Lock()  # guards iterator+requeued (async)
        self._update_arrived = threading.Event()  # wakes the async master
        self._async_jobs_left = 0  # set by _train_async (max_rounds bound)
        for worker_id in self.performers:
            self.tracker.add_worker(worker_id)

    def _replicate_if_needed(self, worker_id: str) -> None:
        """Pull the latest averaged params when flagged (ref:
        WorkerActor.checkJobAvailable → tracker.getCurrent,
        WorkerActor.java:302-306)."""
        performer: WorkerPerformer = self.performers[worker_id]
        if self.tracker.needs_replicate(worker_id):
            current = self.tracker.get_current()
            if current is not None:
                performer.update(current)
            self.tracker.done_replicating(worker_id)

    def _perform_and_publish(self, worker_id: str, job) -> None:
        """Shared perform→publish protocol for the sync and async paths.

        per-job timing counter (ref: WorkerActor heartbeat ms logging,
        WorkerActor.java:198-202 / YARN WorkerNode StopWatch)."""
        performer: WorkerPerformer = self.performers[worker_id]
        t0 = time.perf_counter()
        performer.perform(job)
        self.tracker.increment("job_ms_total",  # graftlint: allow[untimed-dispatch] heartbeat counter, not a bench: perform() ends in the performer's own score fetch
                               (time.perf_counter() - t0) * 1000.0)
        self.tracker.add_update(worker_id, job)
        self._update_arrived.set()  # wake the async master's heartbeat
        self.tracker.clear_job(worker_id)
        self.tracker.increment("jobs_done")
        self.tracker.increment(f"rounds.{worker_id}")

    def _worker_round(self, worker_id: str) -> None:
        self._replicate_if_needed(worker_id)
        job = self.tracker.job_for(worker_id)
        if job is None:
            return
        self._perform_and_publish(worker_id, job)

    def _check_early_stopping(self, updates) -> None:
        """Update bestLoss from the round's reported scores and trip the
        tracker's early-stop flag after `patience` non-improving evaluation
        rounds (ref: tracker earlyStop/bestLoss semantics). Called with the
        SAME updates snapshot the aggregation consumes, so no score slips
        between two separate tracker reads.

        One evaluation round = a fresh score from EVERY live worker
        (latest-wins per worker): the async master's heartbeat can tick
        with only a fast worker's update pending, and judging patience on
        that worker's noisy loss while a slower peer is mid-job (and
        improving) would trip spuriously — including during startup, before
        the slow worker's first job completes. Consequence: a worker whose
        performer never reports scores (or that silently crashed and has
        not yet been deregistered) disables the policy rather than letting
        it trip on partial evidence; the external tracker.early_stop() flag
        still halts everything immediately."""
        if self.early_stopping is None or self.tracker.is_early_stop():
            return
        for worker_id, j in updates.items():
            if j.score is not None:
                self._es_scores[worker_id] = j.score
        expected = set(self.performers)
        # prune deregistered workers: a dead worker's stale score must not
        # enter a later round's mean
        self._es_scores = {w: s for w, s in self._es_scores.items()
                           if w in expected}
        if not expected or not expected.issubset(self._es_scores):
            return
        loss = sum(self._es_scores.values()) / len(self._es_scores)
        self._es_scores.clear()
        if loss < self.tracker.best_loss() - self.early_stopping.min_delta:
            self.tracker.set_best_loss(loss)
            self._no_improve = 0
        else:
            self._no_improve += 1
            if self._no_improve >= self.early_stopping.patience:
                log.info("early stopping: %d rounds without improvement",
                         self._no_improve)
                self.tracker.early_stop()
                self.tracker.increment("early_stopped")

    def _handle_worker_failure(self, worker_id: str, exc: BaseException) -> None:
        """Dead-worker recovery (ref: MasterActor stale-job GC + tracker
        recentlyCleared re-route, MasterActor.java:115-142): the worker is
        deregistered and its in-flight job requeued for a surviving worker."""
        log.warning("worker %s failed: %s — rerouting its job", worker_id, exc)
        job = self.tracker.job_for(worker_id)
        self.tracker.clear_job(worker_id)
        self.tracker.remove_worker(worker_id)
        self.performers.pop(worker_id, None)
        self.tracker.increment("worker_failures")
        if job is not None:
            # queue for reassignment: assigning directly could clobber a
            # survivor's own in-flight job slot
            self._requeued.append(job)

    def train(self):
        """Run until the JobIterator is exhausted; returns the final
        averaged flat param vector (tracker current).

        Synchronous routers (IterativeReduce) barrier every round; an
        ``asynchronous`` router (HogWild) runs the barrier-free path."""
        if self.router.asynchronous:
            return self._train_async()
        workers = list(self.performers)
        with ThreadPoolExecutor(max_workers=len(workers)) as pool:
            rounds = 0
            while rounds < self.max_rounds:
                if self.tracker.is_early_stop():
                    log.info("sync train: early-stop flag set — stopping")
                    break
                rounds += 1
                # master: feed one job per IDLE worker — orphaned jobs from
                # failed workers first, then fresh ones from the iterator
                fed = False
                for worker_id in workers:
                    if self.tracker.job_for(worker_id) is not None:
                        continue
                    if self._requeued:
                        job = self._requeued.popleft()
                        job.worker_id = worker_id
                        self.tracker.add_job(job)
                        fed = True
                    elif self.job_iterator.has_next():
                        self.tracker.add_job(self.job_iterator.next(worker_id))
                        fed = True
                if (not fed and not self.tracker.has_pending_jobs()
                        and not self._requeued):
                    break
                # workers: one heartbeat each (parallel)
                futures = {w: pool.submit(self._worker_round, w)
                           for w in workers}
                wait(futures.values())
                for w, f in futures.items():
                    exc = f.exception()
                    if exc is None:
                        continue
                    if not self.fault_tolerant:
                        raise exc
                    self._handle_worker_failure(w, exc)
                    workers = list(self.performers)
                    if not workers:
                        raise RuntimeError(
                            "all workers failed"
                        ) from exc
                # master: aggregate when router policy allows (one snapshot
                # feeds both the early-stop check and the aggregation)
                if self.router.send_work():
                    snapshot = self.tracker.updates()
                    self._check_early_stopping(snapshot)
                    self.router.update(snapshot)
                    self.tracker.increment("aggregations")
                    if self.model_saver is not None:
                        current = self.tracker.get_current()
                        if current is not None:
                            self.model_saver.save(current)
            # final aggregation of any straggler updates
            if self.tracker.updates():
                self.router.update()
        self.tracker.finish()
        return self.tracker.get_current()

    # ------------------------------------------------------------------
    # asynchronous (Hogwild) execution — no per-round barrier
    # ------------------------------------------------------------------

    def _next_job(self, worker_id: str):
        """Hand the calling worker its next job (requeued orphans first),
        or None when the iterator is exhausted or the total-job bound
        (max_rounds × initial worker count — the async analogue of the sync
        path's per-round cap) is reached. Lock serializes only the hand-off,
        never the work."""
        with self._feed_lock:
            if self._requeued:
                job = self._requeued.popleft()
                job.worker_id = worker_id
                return job
            if self._async_jobs_left <= 0:
                return None
            if self.job_iterator.has_next():
                self._async_jobs_left -= 1
                return self.job_iterator.next(worker_id)
            return None

    def _worker_loop(self, worker_id: str, stop: threading.Event) -> None:
        """Continuous pull→perform→publish loop (ref: WorkerActor.java:168-206
        heartbeat, minus the barrier: the worker never waits for peers or for
        the master's aggregation)."""
        while not stop.is_set() and not self.tracker.is_early_stop():
            self._replicate_if_needed(worker_id)
            job = self._next_job(worker_id)
            if job is None:
                return
            self.tracker.add_job(job)
            self._perform_and_publish(worker_id, job)

    def _train_async(self):
        """Barrier-free Hogwild execution (ref: HogWildWorkRouter.java +
        MasterActor heartbeat): every worker loops at its own pace; the
        master aggregates whatever updates exist on each heartbeat tick, so
        fast workers fold in many more rounds than slow ones and nobody
        ever waits.

        ``async_timeout_s`` is a GRACEFUL stop: past the deadline no new
        jobs are handed out and the run ends once in-flight performs return
        — a wedged perform() still blocks (exactly as it would block the
        sync path's barrier); Python threads cannot be killed."""
        stop = threading.Event()
        workers = list(self.performers)
        self._async_jobs_left = self.max_rounds * max(len(workers), 1)
        deadline = (time.monotonic() + self.async_timeout_s
                    if self.async_timeout_s is not None else None)
        with ThreadPoolExecutor(max_workers=len(workers)) as pool:
            futures = {w: pool.submit(self._worker_loop, w, stop)
                       for w in workers}
            last_save = 0.0
            try:
                while any(not f.done() for f in futures.values()):
                    # event-driven heartbeat: wake when a worker publishes
                    # (set in _perform_and_publish) instead of busy-polling —
                    # a 2 ms sleep loop costs ~500 GIL wakeups/s that starve
                    # perform() on a 1-core host. heartbeat_s only bounds
                    # failure detection / deadline checks when no updates
                    # flow; aggregation latency does not depend on it.
                    self._update_arrived.wait(timeout=self.heartbeat_s)
                    # clear BEFORE snapshotting: an add_update racing this
                    # line either lands in the snapshot or re-sets the event
                    self._update_arrived.clear()
                    # deregister crashed workers NOW, not after the loop:
                    # a dead worker left in self.performers would block the
                    # early-stopping coverage rule for the whole run (ref
                    # posture: MasterActor's heartbeat clears dead workers'
                    # jobs continuously, MasterActor.java:115-142)
                    for w, f in list(futures.items()):
                        if f.done() and f.exception() is not None:
                            if not self.fault_tolerant:
                                raise f.exception()
                            self._handle_worker_failure(w, f.exception())
                            del futures[w]
                    # master heartbeat: aggregate whatever has arrived (one
                    # snapshot feeds the early-stop check AND the
                    # aggregation, so no score slips between two reads)
                    snapshot = self.tracker.updates()
                    if self.router.send_work() and snapshot:
                        self._check_early_stopping(snapshot)
                        self.router.update(snapshot)
                        self.tracker.increment("aggregations")
                        # save at most once per second (ref: MasterActor's
                        # 1 s tick / ModelSavingActor per MoreWorkMessage) —
                        # the aggregation heartbeat can be far hotter than
                        # any model serialization should be
                        now = time.monotonic()
                        if (self.model_saver is not None
                                and now - last_save >= 1.0):
                            current = self.tracker.get_current()
                            if current is not None:
                                self.model_saver.save(current)
                                last_save = now
                    if deadline is not None and time.monotonic() > deadline:
                        log.warning("async train: async_timeout_s hit, "
                                    "stopping with jobs unfinished")
                        with self._feed_lock:
                            # no fresh jobs after the deadline — the drain
                            # below may still reroute already-issued orphans
                            self._async_jobs_left = 0
                        break
            finally:
                stop.set()
            # failures that raced the loop's last tick
            for w, f in futures.items():
                exc = f.exception()
                if exc is None:
                    continue
                if not self.fault_tolerant:
                    raise exc
                self._handle_worker_failure(w, exc)
            if not self.performers:
                raise RuntimeError("all workers failed")
            # drain jobs orphaned by failed workers on the survivors
            # (repeat in case a survivor fails mid-drain); an early stop
            # abandons orphans deliberately — the run is over, and drain
            # workers would exit immediately anyway (hang otherwise)
            while self._requeued and not self.tracker.is_early_stop():
                if not self.performers:
                    raise RuntimeError("all workers failed")
                stop2 = threading.Event()
                futures = {w: pool.submit(self._worker_loop, w, stop2)
                           for w in list(self.performers)}
                wait(futures.values())
                for w, f in futures.items():
                    exc = f.exception()
                    if exc is not None:
                        if not self.fault_tolerant:
                            raise exc
                        self._handle_worker_failure(w, exc)
        # final aggregation of straggler updates + final model save (the
        # 1 s throttle above may have skipped the last in-loop save)
        if self.tracker.updates():
            self.router.update()
            self.tracker.increment("aggregations")
        if self.model_saver is not None:
            current = self.tracker.get_current()
            if current is not None:
                self.model_saver.save(current)
        self.tracker.finish()
        return self.tracker.get_current()
