"""Scaleout: distributed-training contracts and runtimes.

Parity with the reference's deeplearning4j-scaleout tree (SURVEY.md §2.2):
transport-agnostic contracts (Job, WorkerPerformer, JobAggregator,
StateTracker, WorkRouter, Updateable) plus a local in-process runtime that
replaces the Akka/Hazelcast/Spark/YARN stacks.

TPU-first position: on TPU pods the *data plane* (gradient/param exchange) is
in-graph XLA collectives — parallel/trainer.py — not host serialization. This
package keeps the reference's *control plane* API so orchestration code
(routers, aggregation policy, model saving, job feeding) ports over, and its
workers can drive either host-level fits or the collective trainer.
"""

from deeplearning4j_tpu.scaleout.job import Job  # noqa: F401
from deeplearning4j_tpu.scaleout.statetracker import InMemoryStateTracker  # noqa: F401
from deeplearning4j_tpu.scaleout.runner import (  # noqa: F401
    EarlyStopping,
    LocalDistributedRunner,
)
from deeplearning4j_tpu.scaleout.ckpt import (  # noqa: F401
    Checkpointer,
    CheckpointIterationListener,
)
