"""Job — a unit of distributable work.

Parity with ref: scaleout/job/Job.java:26-29 — (work, result, workerId).
"""

from __future__ import annotations

from typing import Any, Optional


class Job:
    def __init__(self, work: Any, worker_id: str = "", pending: bool = True):
        self.work = work
        self.result: Any = None
        self.worker_id = worker_id
        self.pending = pending
        #: training loss the performer observed for this job (None when the
        #: performer doesn't report one) — feeds the master's bestLoss /
        #: early-stop tracking (ref: StateTracker earlyStop/bestLoss)
        self.score: Optional[float] = None

    def __repr__(self) -> str:
        return f"Job(worker_id={self.worker_id!r}, done={self.result is not None})"


class JobIterator:
    """ref: scaleout/job/JobIterator.java — hands out Jobs per worker."""

    def has_next(self) -> bool:
        raise NotImplementedError

    def next(self, worker_id: str = "") -> Job:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError


class DataSetJobIterator(JobIterator):
    """Wraps a DataSetIterator; each Job's work is one DataSet mini-batch."""

    def __init__(self, it):
        self._it = it

    def has_next(self) -> bool:
        return self._it.has_next()

    def next(self, worker_id: str = "") -> Job:
        return Job(self._it.next(), worker_id)

    def reset(self) -> None:
        self._it.reset()


class CollectionJobIterator(JobIterator):
    """ref: scaleout/job/collection/CollectionJobIterator.java."""

    def __init__(self, items):
        self._items = list(items)
        self._pos = 0

    def has_next(self) -> bool:
        return self._pos < len(self._items)

    def next(self, worker_id: str = "") -> Job:
        job = Job(self._items[self._pos], worker_id)
        self._pos += 1
        return job

    def reset(self) -> None:
        self._pos = 0
