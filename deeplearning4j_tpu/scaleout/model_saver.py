"""ModelSaver SPI — checkpoint the aggregated model.

Parity with ref: actor/core/ModelSaver / DefaultModelSaver (java serialization
to file, saved by ModelSavingActor on every aggregation round). Format here is
the framework checkpoint (conf JSON + flat params npz), the same one
MultiLayerNetwork.save/load uses.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np


class ModelSaver:
    def save(self, model) -> None:
        raise NotImplementedError

    def load(self):
        raise NotImplementedError


class FileModelSaver(ModelSaver):
    """ref: actor/core/DefaultModelSaver.java"""

    def __init__(self, path: str = "nn-model.npz"):
        self.path = path if path.endswith(".npz") else path + ".npz"

    def save(self, model) -> None:
        model.save(self.path)

    def load(self):
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        return MultiLayerNetwork.load(self.path)

    def exists(self) -> bool:
        return os.path.exists(self.path)


class ParamsOnlySaver(ModelSaver):
    """Save just the flat parameter vector (ref: CLI Train writes params
    binary via Nd4j.write, cli/subcommands/Train.java)."""

    def __init__(self, path: str):
        self.path = path

    def save(self, model) -> None:
        np.save(self.path if self.path.endswith(".npy") else self.path + ".npy",
                np.asarray(model.params()))

    def load(self):
        p = self.path if self.path.endswith(".npy") else self.path + ".npy"
        return np.load(p)
