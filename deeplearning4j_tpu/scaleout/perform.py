"""WorkerPerformer — worker-side compute.

Parity with ref: scaleout/perform/WorkerPerformer.java {perform(Job),
update(Object...)} and the Akka BaseMultiLayerNetworkWorkPerformer (fromJson
conf → net.setParameters(current) → net.fit(DataSet) → result = params).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.scaleout.job import Job


class WorkerPerformer:
    def perform(self, job: Job) -> None:
        raise NotImplementedError

    def update(self, *args) -> None:
        raise NotImplementedError


class MultiLayerNetworkWorkPerformer(WorkerPerformer):
    """Fit a network on the job's DataSet; result = flat params
    (ref: perform/BaseMultiLayerNetworkWorkPerformer.java)."""

    def __init__(self, conf_json: str):
        self.conf_json = conf_json
        self._params: Optional[np.ndarray] = None

    def perform(self, job: Job) -> None:
        net = MultiLayerNetwork.from_json(self.conf_json)
        net.init()
        if self._params is not None:
            net.set_params(self._params)
        data = job.work
        if not isinstance(data, DataSet):
            raise TypeError(f"expected DataSet work, got {type(data)}")
        # capture the last training-iteration loss through the listener
        # chain — the master's bestLoss / early-stop signal (ref: tracker
        # bestLoss updates) at zero extra compute (no post-fit forward)
        last_score: list = []
        net.listeners.append(
            lambda _net, _it, s: last_score.append(float(s)))
        net.fit(data)
        job.result = np.asarray(net.params())
        job.score = last_score[-1] if last_score else None

    def update(self, *args) -> None:
        """Receive the averaged master params (ref: performer.update)."""
        if args:
            self._params = np.asarray(args[0])
