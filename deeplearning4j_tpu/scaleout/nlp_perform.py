"""Distributed NLP work performers — Word2Vec / GloVe on the scaleout runner.

Parity surface: the reference trains embeddings on the cluster through
WorkerPerformers in three transports —
- Akka: scaleout/perform/models/word2vec/Word2VecPerformer.java (skip-gram
  worker with exp table, shared lr decay via the tracker counter
  NUM_WORDS_SO_FAR) + Word2VecJobAggregator,
- Spark: dl4j-spark-nlp .../word2vec/Word2VecPerformer.java,
- YARN: hadoop/nlp models/{word2vec,glove} performers.

TPU-first redesign: workers keep full embedding matrices as a flat param
vector (the reference ships per-word vector slices in Word2VecWork jobs —
a host-serialization concern XLA removes), train each job's pair batch with
the SAME jitted batched steps the local models use (_sgns_step /
_glove_step), and the standard ParameterAveragingAggregator averages worker
vectors per IterativeReduce round. The shared lr-decay counter follows the
reference's NUM_WORDS_SO_FAR pattern but counts skip-gram PAIRS (the unit
jobs are denominated in here); pass ``total_pairs`` accordingly
(approx. 2 x window x corpus words). On real silicon prefer the in-graph
mesh path (models/word2vec.py make_sharded_sgns_step); this is the
control-plane-parity path.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.scaleout.job import Job, JobIterator
from deeplearning4j_tpu.scaleout.perform import WorkerPerformer
from deeplearning4j_tpu.text.vocab import VocabCache

NUM_PAIRS_SO_FAR = "num_pairs_so_far"  # ref pattern: Word2VecPerformer NUM_WORDS_SO_FAR (pair-denominated here)


class Word2VecWorkPerformer(WorkerPerformer):
    """Skip-gram negative-sampling worker.

    Job.work = (centers, contexts) int32 arrays (one pair batch).
    Result = flat params: concat(syn0.ravel(), syn1neg.ravel()).
    update(flat) installs the averaged master vector.
    """

    def __init__(self, vocab: VocabCache, layer_size: int = 50,
                 negative: int = 5, lr: float = 0.025, min_lr: float = 1e-4,
                 total_pairs: Optional[int] = None, tracker=None,
                 seed: int = 123):
        from deeplearning4j_tpu.models.embeddings import InMemoryLookupTable
        from deeplearning4j_tpu.models.word2vec import _sgns_step

        self.vocab = vocab
        self.layer_size = layer_size
        self.negative = negative
        self.lr = lr
        self.min_lr = min_lr
        self.total_pairs = total_pairs
        self.tracker = tracker
        self._step = _sgns_step
        table = InMemoryLookupTable(vocab, layer_size, seed=seed,
                                    use_hs=False, negative=negative)
        self._syn0 = jnp.asarray(table.syn0)
        self._syn1neg = jnp.asarray(table.syn1neg)
        from deeplearning4j_tpu.models.word2vec import build_neg_table
        self._neg_table = build_neg_table(table.unigram_probs())
        self._key = jax.random.PRNGKey(seed)
        self._pairs_local = 0

    @property
    def vocab_size(self) -> int:
        return self.vocab.num_words()

    def _current_lr(self) -> float:
        """Linear decay by GLOBAL pairs seen — shared across workers via the
        tracker counter (ref pattern: Word2VecPerformer NUM_WORDS_SO_FAR;
        pair-denominated, matching the unit jobs carry)."""
        if self.total_pairs is None:
            return self.lr
        seen = (self.tracker.count(NUM_PAIRS_SO_FAR)
                if self.tracker is not None else self._pairs_local)
        frac = min(float(seen) / max(self.total_pairs, 1), 1.0)
        return max(self.min_lr, self.lr * (1.0 - frac))

    def perform(self, job: Job) -> None:
        centers, contexts = job.work
        centers = np.asarray(centers, np.int32)
        contexts = np.asarray(contexts, np.int32)
        weights = np.ones(centers.shape[0], np.float32)
        lr = self._current_lr()
        self._key, sub = jax.random.split(self._key)
        # non-donating call: the performer's arrays survive for the next job
        self._syn0, self._syn1neg, _ = self._step(
            jnp.array(self._syn0), jnp.array(self._syn1neg),
            jnp.asarray(centers), jnp.asarray(contexts), jnp.asarray(weights),
            self._neg_table, jnp.float32(lr), sub, negative=self.negative,
        )
        n = int(centers.shape[0])
        self._pairs_local += n
        if self.tracker is not None:
            self.tracker.increment(NUM_PAIRS_SO_FAR, n)
        job.result = np.concatenate([
            np.asarray(self._syn0).ravel(),
            np.asarray(self._syn1neg).ravel(),
        ])

    def update(self, *args) -> None:
        if not args:
            return
        flat = np.asarray(args[0], np.float32)
        v, d = self.vocab_size, self.layer_size
        self._syn0 = jnp.asarray(flat[: v * d].reshape(v, d))
        self._syn1neg = jnp.asarray(flat[v * d:].reshape(v, d))

    # query helpers for tests / model extraction
    def syn0(self) -> np.ndarray:
        return np.asarray(self._syn0)


class GloveWorkPerformer(WorkerPerformer):
    """GloVe worker (ref: scaleout/perform/models/glove/GlovePerformer.java).

    Job.work = (rows, cols, logx, fx) arrays (one co-occurrence batch).
    Result = flat params: concat(w.ravel(), bias). AdaGrad accumulators stay
    worker-local (the reference averages parameter vectors only).
    """

    def __init__(self, vocab_size: int, layer_size: int = 50,
                 lr: float = 0.05, seed: int = 123):
        from deeplearning4j_tpu.models.glove import _glove_step

        self.vocab_size = vocab_size
        self.layer_size = layer_size
        self.lr = lr
        self._step = _glove_step
        rng = np.random.default_rng(seed)
        self._w = jnp.asarray(
            (rng.random((vocab_size, layer_size), np.float32) - 0.5) / layer_size)
        self._b = jnp.zeros((vocab_size,), jnp.float32)
        self._hw = jnp.zeros((vocab_size, layer_size), jnp.float32)
        self._hb = jnp.zeros((vocab_size,), jnp.float32)

    def perform(self, job: Job) -> None:
        rows, cols, logx, fx = job.work
        weights = np.ones(len(rows), np.float32)
        self._w, self._b, self._hw, self._hb, _ = self._step(
            jnp.array(self._w), jnp.array(self._b),
            jnp.array(self._hw), jnp.array(self._hb),
            jnp.asarray(rows, jnp.int32), jnp.asarray(cols, jnp.int32),
            jnp.asarray(logx, jnp.float32), jnp.asarray(fx, jnp.float32),
            jnp.asarray(weights), jnp.float32(self.lr),
        )
        job.result = np.concatenate(
            [np.asarray(self._w).ravel(), np.asarray(self._b)])

    def update(self, *args) -> None:
        if not args:
            return
        flat = np.asarray(args[0], np.float32)
        v, d = self.vocab_size, self.layer_size
        self._w = jnp.asarray(flat[: v * d].reshape(v, d))
        self._b = jnp.asarray(flat[v * d:])

    def syn0(self) -> np.ndarray:
        return np.asarray(self._w)


class SkipGramJobIterator(JobIterator):
    """Slices a (centers, contexts) pair stream into fixed-size pair-batch
    jobs (the reference batches sentences into Word2VecWork jobs)."""

    def __init__(self, centers: np.ndarray, contexts: np.ndarray,
                 batch_size: int = 2048):
        self._c = np.asarray(centers, np.int32)
        self._t = np.asarray(contexts, np.int32)
        self._bsz = batch_size
        self._pos = 0

    def has_next(self) -> bool:
        return self._pos < len(self._c)

    def next(self, worker_id: str = "") -> Job:
        lo, hi = self._pos, min(self._pos + self._bsz, len(self._c))
        self._pos = hi
        return Job((self._c[lo:hi], self._t[lo:hi]), worker_id)

    def reset(self) -> None:
        self._pos = 0


class CoOccurrenceJobIterator(JobIterator):
    """Slices a GloVe co-occurrence list into fixed-size batch jobs."""

    def __init__(self, rows, cols, vals, x_max: float = 100.0,
                 alpha: float = 0.75, batch_size: int = 4096):
        self._rows = np.asarray(rows, np.int32)
        self._cols = np.asarray(cols, np.int32)
        vals = np.asarray(vals, np.float32)
        self._logx = np.log(np.maximum(vals, 1e-12)).astype(np.float32)
        self._fx = np.minimum((vals / x_max) ** alpha, 1.0).astype(np.float32)
        self._bsz = batch_size
        self._pos = 0

    def has_next(self) -> bool:
        return self._pos < len(self._rows)

    def next(self, worker_id: str = "") -> Job:
        lo, hi = self._pos, min(self._pos + self._bsz, len(self._rows))
        self._pos = hi
        return Job((self._rows[lo:hi], self._cols[lo:hi],
                    self._logx[lo:hi], self._fx[lo:hi]), worker_id)

    def reset(self) -> None:
        self._pos = 0


class WordCountWorkPerformer(WorkerPerformer):
    """Distributed word counting (ref: scaleout/perform/text/
    WordCountWorkPerformer.java — each job is a chunk of sentences; the
    result is a token→count Counter the aggregator merges into the vocab).
    """

    def __init__(self, tokenizer_factory=None):
        from deeplearning4j_tpu.text.tokenization import DefaultTokenizerFactory

        self.factory = tokenizer_factory or DefaultTokenizerFactory()

    def perform(self, job: Job) -> None:
        from deeplearning4j_tpu.utils.counter import Counter

        counts = Counter()
        sentences = job.work if isinstance(job.work, (list, tuple)) else [job.work]
        for sentence in sentences:
            for tok in self.factory.create(sentence).get_tokens():
                counts.increment_count(tok, 1.0)
        job.result = counts

    def update(self, *args) -> None:  # stateless between jobs
        pass


class WordCountJobAggregator:
    """Merges per-job Counters (ref: scaleout/perform/text/
    WordCountJobAggregator — accumulate into one vocab count)."""

    def __init__(self):
        from deeplearning4j_tpu.utils.counter import Counter

        self.counts = Counter()

    def accumulate(self, job: Job) -> None:
        if job.result is None:
            return
        for key in job.result.key_set():
            self.counts.increment_count(key, job.result.get_count(key))

    def aggregate(self):
        return self.counts
