"""Job aggregation — combining worker results into the master update.

Parity with ref: scaleout/aggregator/ (JobAggregator, WorkAccumulator) and
the Akka INDArrayAggregator (sum ÷ n parameter averaging).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from deeplearning4j_tpu.scaleout.job import Job


class JobAggregator:
    """ref: scaleout/aggregator/JobAggregator.java — accumulate(Job), aggregate()."""

    def accumulate(self, job: Job) -> None:
        raise NotImplementedError

    def aggregate(self):
        raise NotImplementedError


class ParameterAveragingAggregator(JobAggregator):
    """Running sum of flat param vectors, averaged on aggregate()
    (ref: aggregator/INDArrayAggregator.java)."""

    def __init__(self):
        self._sum: Optional[np.ndarray] = None
        self._count = 0

    def accumulate(self, job: Job) -> None:
        if job.result is None:
            return
        vec = np.asarray(job.result, dtype=np.float32)
        self._sum = vec.copy() if self._sum is None else self._sum + vec
        self._count += 1

    def aggregate(self) -> Optional[np.ndarray]:
        if self._sum is None or self._count == 0:
            return None
        return self._sum / self._count

    def reset(self) -> None:
        self._sum = None
        self._count = 0
