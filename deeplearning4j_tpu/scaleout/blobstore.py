"""Pluggable blob store — the remote-storage seam.

Parity surface: the reference's cloud/remote IO modules —
S3Downloader/S3Uploader/S3ModelSaver/BaseS3DataSetIterator
(ref: deeplearning4j-scaleout/deeplearning4j-aws/src/main/java/org/deeplearning4j/aws/s3/)
and HdfsModelSaver/BaseHdfsDataSetIterator/HdfsUtils
(ref: deeplearning4j-scaleout "hadoop" module). A TPU-pod framework needs the
same seam shaped for object stores (GCS): flat keys in a bucket, whole-object
get/put, list-by-prefix.

Everything above the seam (ModelSaver, checkpoints, DataSet iteration) talks
to the abstract ``BlobStore``; backends plug in below it. The local-directory
and in-memory backends are fully functional; the GCS backend carries the
real URI scheme and fails with a clear message when the client library is
absent (this environment has no egress).
"""

from __future__ import annotations

import io
import os
import re
from typing import Dict, List, Optional

import numpy as np

_KEY_RE = re.compile(r"^[A-Za-z0-9._/\-]+$")


def _check_key(key: str) -> str:
    """Reject traversal and absolute keys (same discipline as the config
    registry, scaleout/registry.py)."""
    if not key or not _KEY_RE.match(key) or key.startswith("/") or ".." in key.split("/"):
        raise ValueError(f"invalid blob key {key!r}")
    return key


class BlobStore:
    """GCS-shaped object store: flat keys, whole-object get/put."""

    def put(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def get(self, key: str) -> bytes:
        raise NotImplementedError

    def try_get(self, key: str) -> Optional[bytes]:
        """``get`` or None if absent — one call, so a poller can't race a
        concurrent delete between ``exists`` and ``get``. Backends whose
        ``get`` raises ``KeyError``/``FileNotFoundError`` get this for
        free; local puts are atomic renames, so a non-None result is
        always a complete object."""
        try:
            return self.get(key)
        except (KeyError, FileNotFoundError):
            return None

    def exists(self, key: str) -> bool:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def list(self, prefix: str = "") -> List[str]:
        raise NotImplementedError


class LocalBlobStore(BlobStore):
    """Objects as files under a root directory (the reference's Hdfs/S3 tests
    run against local filesystems the same way)."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, _check_key(key))

    def put(self, key: str, data: bytes) -> None:
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)  # atomic publish

    def get(self, key: str) -> bytes:
        with open(self._path(key), "rb") as f:
            return f.read()

    def exists(self, key: str) -> bool:
        return os.path.isfile(self._path(key))

    def delete(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass

    def list(self, prefix: str = "") -> List[str]:
        out = []
        for dirpath, _, files in os.walk(self.root):
            for name in files:
                if name.endswith(".tmp"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, name), self.root)
                rel = rel.replace(os.sep, "/")
                if rel.startswith(prefix):
                    out.append(rel)
        return sorted(out)


class InMemoryBlobStore(BlobStore):
    def __init__(self):
        self._data: Dict[str, bytes] = {}

    def put(self, key: str, data: bytes) -> None:
        self._data[_check_key(key)] = bytes(data)

    def get(self, key: str) -> bytes:
        return self._data[key]

    def exists(self, key: str) -> bool:
        return key in self._data

    def delete(self, key: str) -> None:
        self._data.pop(key, None)

    def list(self, prefix: str = "") -> List[str]:
        return sorted(k for k in self._data if k.startswith(prefix))


class GCSBlobStore(BlobStore):
    """Google Cloud Storage backend (the TPU-native analogue of the
    reference's S3 module). Requires google-cloud-storage at runtime; this
    build environment has no egress, so construction fails loudly rather
    than pretending."""

    def __init__(self, bucket: str, prefix: str = ""):
        try:
            from google.cloud import storage  # type: ignore
        except ImportError as exc:  # pragma: no cover - environment-dependent
            raise RuntimeError(
                "GCSBlobStore requires the google-cloud-storage package; "
                "use file:// or mem:// stores in environments without it"
            ) from exc
        self._bucket = storage.Client().bucket(bucket)  # pragma: no cover
        self.prefix = prefix.strip("/")  # pragma: no cover

    def _name(self, key: str) -> str:  # pragma: no cover
        key = _check_key(key)
        return f"{self.prefix}/{key}" if self.prefix else key

    def put(self, key: str, data: bytes) -> None:  # pragma: no cover
        self._bucket.blob(self._name(key)).upload_from_string(data)

    def get(self, key: str) -> bytes:  # pragma: no cover
        return self._bucket.blob(self._name(key)).download_as_bytes()

    def exists(self, key: str) -> bool:  # pragma: no cover
        return self._bucket.blob(self._name(key)).exists()

    def delete(self, key: str) -> None:  # pragma: no cover
        self._bucket.blob(self._name(key)).delete()

    def list(self, prefix: str = "") -> List[str]:  # pragma: no cover
        if prefix:
            _check_key(prefix)
        # anchor on "<store-prefix>/" so a sibling object sharing the prefix
        # string (e.g. "models-old/x" next to store prefix "models") is
        # neither matched nor mis-sliced
        base = self.prefix + "/" if self.prefix else ""
        names = [b.name for b in self._bucket.list_blobs(prefix=base + prefix)]
        return sorted(n[len(base):] for n in names if n.startswith(base))


# mem:// stores live for the life of the process, keyed by the URI's
# authority/path — so `train --model mem://x/params` followed by
# `test --model mem://x/params` in the same process reads the same bytes
# (a fresh store per open_store call would silently drop every write)
_MEM_STORES: Dict[str, InMemoryBlobStore] = {}


def open_store(uri: str) -> BlobStore:
    """URI scheme → store (parity with the CLI's URI Scheme registry,
    ref: cli/api/schemes/): file:///dir, mem://, gs://bucket/prefix."""
    if uri.startswith("file://"):
        return LocalBlobStore(uri[len("file://"):])
    if uri.startswith("mem://"):
        name = uri[len("mem://"):].strip("/")
        return _MEM_STORES.setdefault(name, InMemoryBlobStore())
    if uri.startswith("gs://"):
        rest = uri[len("gs://"):]
        bucket, _, prefix = rest.partition("/")
        return GCSBlobStore(bucket, prefix)
    # bare paths are local directories
    return LocalBlobStore(uri)


def split_store_uri(path: str) -> tuple:
    """Split ``<scheme>://<base>/<key>`` into (store URI, key) scheme-aware:
    a key directly after the scheme (``mem://params.npz``) yields the
    scheme's root store rather than misparsing into a literal local
    directory named ``mem:`` (a naive rpartition('/') does exactly that)."""
    scheme, sep, rest = path.partition("://")
    if not sep:
        base, _, key = path.rpartition("/")
        # '/key.npz' (absolute root): empty base would resolve against CWD
        if not base and path.startswith("/"):
            base = "/"
        return base, key
    if "/" in rest:
        base, _, key = rest.rpartition("/")
        # file:///key (absolute root) must keep the leading '/': an empty
        # base would make LocalBlobStore resolve the key relative to CWD
        if scheme == "file" and not base and rest.startswith("/"):
            base = "/"
    else:
        base, key = "", rest
    return f"{scheme}://{base}", key


# --------------------------------------------------------------- adapters ----

class BlobModelSaver:
    """ModelSaver over a BlobStore (ref: S3ModelSaver / HdfsModelSaver)."""

    def __init__(self, store: BlobStore, key: str = "nn-model.npz"):
        self.store = store
        self.key = key

    def save(self, model) -> None:
        buf = io.BytesIO()
        np.savez(
            buf,
            params=np.asarray(model.params()),
            conf=np.frombuffer(model.conf.to_json().encode(), dtype=np.uint8),
        )
        self.store.put(self.key, buf.getvalue())

    def load(self):
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        with np.load(io.BytesIO(self.store.get(self.key))) as z:
            from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration

            conf = MultiLayerConfiguration.from_json(bytes(z["conf"]).decode())
            net = MultiLayerNetwork(conf).init()
            net.set_params(z["params"])
        return net

    def exists(self) -> bool:
        return self.store.exists(self.key)


def save_checkpoint_to_store(store: BlobStore, key: str, net,
                             iteration: Optional[int] = None) -> str:
    """Full-state checkpoint (params + updater state + iteration + RNG)
    through the blob seam; same payload as scaleout/checkpoint.py."""
    import tempfile

    from deeplearning4j_tpu.scaleout.checkpoint import save_checkpoint

    with tempfile.TemporaryDirectory() as d:
        path = save_checkpoint(os.path.join(d, "ckpt"), net, iteration)
        with open(path, "rb") as f:
            store.put(key, f.read())
    return key


def load_checkpoint_from_store(store: BlobStore, key: str):
    import tempfile

    from deeplearning4j_tpu.scaleout.checkpoint import load_checkpoint

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "ckpt.npz")
        with open(path, "wb") as f:
            f.write(store.get(key))
        return load_checkpoint(path)


class BlobDataSetIterator:
    """DataSet batches from serialized npz blobs under a key prefix
    (ref: BaseS3DataSetIterator / BaseHdfsDataSetIterator). Each blob holds
    one batch: arrays ``features`` and ``labels``."""

    def __init__(self, store: BlobStore, prefix: str = ""):
        from deeplearning4j_tpu.datasets.dataset import DataSet

        self._DataSet = DataSet
        self.store = store
        self.keys = store.list(prefix)
        self._pos = 0

    @staticmethod
    def write_batch(store: BlobStore, key: str, features, labels) -> None:
        buf = io.BytesIO()
        np.savez(buf, features=np.asarray(features), labels=np.asarray(labels))
        store.put(key, buf.getvalue())

    def has_next(self) -> bool:
        return self._pos < len(self.keys)

    def next(self, num=None):
        if not self.has_next():
            raise StopIteration
        key = self.keys[self._pos]
        self._pos += 1
        with np.load(io.BytesIO(self.store.get(key))) as z:
            return self._DataSet(z["features"], z["labels"])

    def reset(self) -> None:
        self._pos = 0

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next()
