"""Configuration registry — service-discovery seam.

Parity with ref deeplearning4j-scaleout-zookeeper
(ZooKeeperConfigurationRegister/Retriever, ZookeeperPathBuilder): the akka
runner publishes the serialized training conf under a well-known path so
workers joining the cluster can retrieve it (DeepLearning4jDistributed.java:258).

Single-controller JAX needs no quorum service; the same contract is an
atomic file store under a shared directory (NFS/GCS-fuse in multi-host
settings). The API mirrors register/retrieve/delete by (namespace, id) path.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Dict, List, Optional


class ConfigurationRegistry:
    def __init__(self, root: Optional[str] = None):
        self.root = root or os.path.join(tempfile.gettempdir(), "dl4j-registry")
        os.makedirs(self.root, exist_ok=True)

    @staticmethod
    def _safe_component(s: str) -> str:
        s = s.replace("/", "_").replace("\\", "_")
        if s in ("", ".", ".."):
            raise ValueError(f"invalid registry path component {s!r}")
        return s

    def _path(self, namespace: str, conf_id: str) -> str:
        safe = [self._safe_component(s) for s in (namespace, conf_id)]
        path = os.path.join(self.root, safe[0], safe[1] + ".json")
        root = os.path.realpath(self.root)
        if not os.path.realpath(path).startswith(root + os.sep):
            raise ValueError("registry path escapes the root")
        return path

    def register(self, namespace: str, conf_id: str, conf: Dict[str, Any]) -> str:
        """Atomically publish a JSON-serializable configuration
        (ref ZooKeeperConfigurationRegister.register)."""
        path = self._path(namespace, conf_id)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump(conf, f)
            os.replace(tmp, path)  # atomic on POSIX
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        return path

    def retrieve(self, namespace: str, conf_id: str) -> Optional[Dict[str, Any]]:
        path = self._path(namespace, conf_id)
        if not os.path.exists(path):
            return None
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f)

    def delete(self, namespace: str, conf_id: str) -> bool:
        path = self._path(namespace, conf_id)
        if os.path.exists(path):
            os.unlink(path)
            return True
        return False

    def list_ids(self, namespace: str) -> List[str]:
        d = os.path.join(self.root, self._safe_component(namespace))
        if not os.path.isdir(d):
            return []
        return sorted(f[:-5] for f in os.listdir(d) if f.endswith(".json"))
