"""Full-state training checkpoints.

The reference checkpoints only conf JSON + flat params (ModelSaver /
MultiLayerNetwork(String conf, INDArray params); SURVEY.md §5: "No
optimizer-state or mid-epoch resume"). This build goes further: a checkpoint
captures the complete training state — per-layer params, per-layer updater
state (AdaGrad accumulators, momentum velocities), and the iteration counter
— so training resumes bit-exactly where it stopped.

Format: one .npz with flattened tree paths as keys plus the conf JSON;
no framework-specific dependency (orbax would add async/multi-host machinery
this single-controller runtime doesn't need yet).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

_CONF_KEY = "__conf_json__"
_ITER_KEY = "__iteration__"
_RNG_KEY = "__rng_key__"
_RNG_IMPL_KEY = "__rng_impl__"
_TREEDEF_PREFIX = "tree::"


def _flatten_with_paths(tree) -> Dict[str, np.ndarray]:
    flat = {}
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(tree)[0]
    for path, leaf in leaves_with_paths:
        key = _TREEDEF_PREFIX + jax.tree_util.keystr(path)
        flat[key] = np.asarray(leaf)
    return flat


def save_checkpoint(path: str, net, iteration: Optional[int] = None) -> str:
    """Write params + updater state + iteration + conf. Returns the path."""
    path = path if path.endswith(".npz") else path + ".npz"
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    payload: Dict[str, Any] = {}
    for k, v in _flatten_with_paths({"params": net.params_tree}).items():
        payload[k] = v
    state = getattr(net, "_train_state", None)
    if state is not None:
        for k, v in _flatten_with_paths({"state": state}).items():
            payload[k] = v
    payload[_CONF_KEY] = np.frombuffer(
        net.conf.to_json().encode(), dtype=np.uint8
    )
    it = iteration if iteration is not None else getattr(net, "_iteration", 0)
    payload[_ITER_KEY] = np.asarray(it, np.int64)
    keys = getattr(net, "_keys", None)
    if keys is not None:
        # persist the host RNG stream position so stochastic confs (dropout,
        # drop-connect, AE corruption) also resume exactly
        if jax.dtypes.issubdtype(keys._key.dtype, jax.dtypes.prng_key):
            payload[_RNG_KEY] = np.asarray(jax.random.key_data(keys._key))
            payload[_RNG_IMPL_KEY] = np.frombuffer(
                str(jax.random.key_impl(keys._key)).encode(), dtype=np.uint8
            )
        else:
            payload[_RNG_KEY] = np.asarray(keys._key)
    tmp = path + ".tmp.npz"
    np.savez(tmp.removesuffix(".npz"), **payload)
    os.replace(tmp, path)
    return path


def load_checkpoint(path: str):
    """Rebuild the network with params, updater state and iteration restored.

    Returns (net, iteration).
    """
    from deeplearning4j_tpu.nn import functional as F
    from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    if not path.endswith(".npz") and os.path.exists(path + ".npz"):
        path = path + ".npz"
    with np.load(path) as z:
        conf = MultiLayerConfiguration.from_json(bytes(z[_CONF_KEY]).decode())
        net = MultiLayerNetwork(conf).init()
        iteration = int(z[_ITER_KEY])

        # rebuild templates, then fill leaves by path key
        params_template = net.params_tree
        state_template = F.init_train_state(conf, params_template)

        def fill(tree, label):
            leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(
                {label: tree}
            )
            new_leaves = []
            for p, leaf in leaves_with_paths:
                key = _TREEDEF_PREFIX + jax.tree_util.keystr(p)
                if key not in z:
                    raise KeyError(f"checkpoint missing leaf {key}")
                new_leaves.append(np.asarray(z[key]).astype(leaf.dtype))
            return jax.tree_util.tree_unflatten(treedef, new_leaves)[label]

        net._params = tuple(fill(params_template, "params"))
        has_state = any(k.startswith(_TREEDEF_PREFIX + "['state']")
                        for k in z.files)
        if has_state:
            net._train_state = tuple(fill(state_template, "state"))
        net._iteration = iteration
        if _RNG_KEY in z.files:
            raw = jax.numpy.asarray(z[_RNG_KEY], dtype=jax.numpy.uint32)
            if _RNG_IMPL_KEY in z.files:
                # key was typed at save time: restore the same key flavor
                impl = bytes(z[_RNG_IMPL_KEY]).decode()
                net._keys._key = jax.random.wrap_key_data(raw, impl=impl)
            else:
                net._keys._key = raw
    return net, iteration
