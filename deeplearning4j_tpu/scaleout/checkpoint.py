"""Single-file training checkpoints — thin wrapper over ``scaleout/ckpt``.

The reference checkpoints only conf JSON + flat params (ModelSaver /
MultiLayerNetwork(String conf, INDArray params); SURVEY.md §5: "No
optimizer-state or mid-epoch resume"). This build captures the complete
training state — per-layer params, per-layer updater state, the host RNG
stream position, and the iteration counter — so training resumes
bit-exactly where it stopped.

WHAT "training state" means lives in ``scaleout/ckpt/net_state.py``
(shared with the sharded subsystem's ``CheckpointIterationListener``);
this module only chooses the container: one self-contained ``.npz`` — the
right shape for single-device nets, blob stores, and byte-oriented
transports. Sharded/composed-mesh runs should use ``scaleout.ckpt``
directly (per-shard files + manifest, mesh-independent resume).

Strictness matches the sharded loader: a shape mismatch or a lossy dtype
narrowing at load time raises instead of silently broadcasting or
``astype``-truncating into the template. The tmp file is unique per
writer (pid + uuid), so concurrent savers to the same path can never
clobber each other's partial writes, and a failed save cleans its tmp up
— the previous checkpoint at ``path`` survives any crash mid-save.
"""

from __future__ import annotations

import json
import os
import uuid
from typing import Any, Dict, Optional

import jax
import numpy as np

_CONF_KEY = "__conf_json__"
_META_KEY = "__meta_json__"
_ITER_KEY = "__iteration__"
_RNG_KEY = "__rng_key__"
_RNG_IMPL_KEY = "__rng_impl__"
_TREEDEF_PREFIX = "tree::"


def _flatten_with_paths(tree) -> Dict[str, np.ndarray]:
    flat = {}
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(tree)[0]
    for path, leaf in leaves_with_paths:
        key = _TREEDEF_PREFIX + jax.tree_util.keystr(path)
        flat[key] = np.asarray(leaf)
    return flat


def save_checkpoint(path: str, net, iteration: Optional[int] = None) -> str:
    """Write params + updater state + iteration + RNG + conf. Returns the
    path. Atomic: payload goes to a unique tmp file first (pid+uuid — two
    concurrent savers cannot collide), then ``os.replace`` commits; on any
    failure the tmp is removed and the existing checkpoint is untouched."""
    from deeplearning4j_tpu.scaleout.ckpt.net_state import capture_net_state

    path = path if path.endswith(".npz") else path + ".npz"
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)

    tree, meta = capture_net_state(net, iteration=iteration)
    payload: Dict[str, Any] = {}
    for k, v in _flatten_with_paths({"params": tree["params"]}).items():
        payload[k] = v
    if "state" in tree:
        for k, v in _flatten_with_paths({"state": tree["state"]}).items():
            payload[k] = v
    payload[_CONF_KEY] = np.frombuffer(meta["conf"].encode(), dtype=np.uint8)
    payload[_ITER_KEY] = np.asarray(meta["iteration"], np.int64)
    if "rng" in tree:
        payload[_RNG_KEY] = np.asarray(tree["rng"])
        if meta.get("rng_impl"):
            payload[_RNG_IMPL_KEY] = np.frombuffer(
                meta["rng_impl"].encode(), dtype=np.uint8)
    extra = {k: v for k, v in meta.items()
             if k not in ("conf", "iteration", "rng_impl")}
    if extra:
        payload[_META_KEY] = np.frombuffer(
            json.dumps(extra).encode(), dtype=np.uint8)

    tmp = f"{path}.tmp-{os.getpid()}-{uuid.uuid4().hex}"
    try:
        with open(tmp, "wb") as f:
            np.savez(f, **payload)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return path


def load_checkpoint(path: str):
    """Rebuild the network with params, updater state, RNG stream and
    iteration restored. Returns (net, iteration).

    Strict: raises ``KeyError`` on a missing leaf, ``ValueError`` on a
    shape mismatch, and ``TypeError`` on a lossy dtype narrowing (saved
    float64 into a float32 template, etc.) — never a silent ``astype``.
    """
    from deeplearning4j_tpu.nn import functional as F
    from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.scaleout.ckpt.net_state import restore_net_state
    from deeplearning4j_tpu.scaleout.ckpt.reshard import check_compatible

    if not path.endswith(".npz") and os.path.exists(path + ".npz"):
        path = path + ".npz"
    with np.load(path) as z:
        conf = MultiLayerConfiguration.from_json(bytes(z[_CONF_KEY]).decode())
        net = MultiLayerNetwork(conf).init()
        iteration = int(z[_ITER_KEY])

        # rebuild templates, then fill leaves by path key — strictly
        params_template = net.params_tree
        state_template = F.init_train_state(conf, params_template)

        def fill(tree, label):
            leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(
                {label: tree}
            )
            new_leaves = []
            for p, leaf in leaves_with_paths:
                keystr = jax.tree_util.keystr(p)
                key = _TREEDEF_PREFIX + keystr
                if key not in z:
                    raise KeyError(f"checkpoint missing leaf {key}")
                saved = np.asarray(z[key])
                dtype = check_compatible(saved.shape, str(saved.dtype),
                                         leaf, keystr)
                new_leaves.append(saved.astype(dtype, copy=False))
            return jax.tree_util.tree_unflatten(treedef, new_leaves)[label]

        tree: Dict[str, Any] = {"params": fill(params_template, "params")}
        has_state = any(k.startswith(_TREEDEF_PREFIX + "['state']")
                        for k in z.files)
        if has_state:
            tree["state"] = fill(state_template, "state")
        meta: Dict[str, Any] = {"conf": conf.to_json(),
                                "iteration": iteration}
        if _RNG_KEY in z.files:
            tree["rng"] = np.asarray(z[_RNG_KEY])
            if _RNG_IMPL_KEY in z.files:
                meta["rng_impl"] = bytes(z[_RNG_IMPL_KEY]).decode()
        if _META_KEY in z.files:
            meta.update(json.loads(bytes(z[_META_KEY]).decode()))
        restore_net_state(net, tree, meta)
    return net, iteration
