"""Classifier evaluation metrics.

Parity with ref: eval/Evaluation.java:48 (eval(realOutcomes, guesses),
stats(), per-class precision/recall/f1, accuracy at :99-270) and
eval/ConfusionMatrix.java. Accumulates across batches like the reference.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, Optional, Sequence

import numpy as np


class ConfusionMatrix:
    """Counts of (actual, predicted) pairs (ref: eval/ConfusionMatrix.java)."""

    def __init__(self, classes: Optional[Sequence[int]] = None):
        self.matrix: Dict[int, Dict[int, int]] = defaultdict(lambda: defaultdict(int))
        self.classes = set(classes or ())

    def add(self, actual: int, predicted: int, count: int = 1) -> None:
        self.matrix[actual][predicted] += count
        self.classes.add(actual)
        self.classes.add(predicted)

    def count(self, actual: int, predicted: int) -> int:
        return self.matrix[actual][predicted]

    def actual_total(self, actual: int) -> int:
        return sum(self.matrix[actual].values())

    def predicted_total(self, predicted: int) -> int:
        return sum(row[predicted] for row in self.matrix.values())

    def to_array(self) -> np.ndarray:
        classes = sorted(self.classes)
        idx = {c: i for i, c in enumerate(classes)}
        out = np.zeros((len(classes), len(classes)), dtype=np.int64)
        for a, row in self.matrix.items():
            for p, n in row.items():
                out[idx[a], idx[p]] = n
        return out


class Evaluation:
    """Accumulating classifier evaluation (ref: eval/Evaluation.java)."""

    def __init__(self):
        self.confusion = ConfusionMatrix()

    def eval(self, real_outcomes, guesses) -> None:
        """Add a batch. Both args are (batch, n_classes) probability/one-hot
        matrices, matching the reference's signature (Evaluation.java:48)."""
        real = np.asarray(real_outcomes)
        guess = np.asarray(guesses)
        actual = real.argmax(axis=-1)
        predicted = guess.argmax(axis=-1)
        for a, p in zip(actual, predicted):
            self.confusion.add(int(a), int(p))

    def eval_classes(self, actual_classes, predicted_classes) -> None:
        for a, p in zip(np.asarray(actual_classes).ravel(), np.asarray(predicted_classes).ravel()):
            self.confusion.add(int(a), int(p))

    # ---- metrics ----
    def true_positives(self, cls: int) -> int:
        return self.confusion.count(cls, cls)

    def false_positives(self, cls: int) -> int:
        return self.confusion.predicted_total(cls) - self.true_positives(cls)

    def false_negatives(self, cls: int) -> int:
        return self.confusion.actual_total(cls) - self.true_positives(cls)

    def precision(self, cls: Optional[int] = None) -> float:
        if cls is not None:
            denom = self.confusion.predicted_total(cls)
            return self.true_positives(cls) / denom if denom else 0.0
        vals = [self.precision(c) for c in sorted(self.confusion.classes)]
        return float(np.mean(vals)) if vals else 0.0

    def recall(self, cls: Optional[int] = None) -> float:
        if cls is not None:
            denom = self.confusion.actual_total(cls)
            return self.true_positives(cls) / denom if denom else 0.0
        vals = [self.recall(c) for c in sorted(self.confusion.classes)]
        return float(np.mean(vals)) if vals else 0.0

    def f1(self, cls: Optional[int] = None) -> float:
        p, r = self.precision(cls), self.recall(cls)
        return 2 * p * r / (p + r) if (p + r) > 0 else 0.0

    def accuracy(self) -> float:
        total = sum(self.confusion.actual_total(c) for c in self.confusion.classes)
        correct = sum(self.true_positives(c) for c in self.confusion.classes)
        return correct / total if total else 0.0

    def stats(self) -> str:
        """Text report (ref: Evaluation.stats())."""
        lines = ["==========================Scores=====================================".rstrip()]
        for c in sorted(self.confusion.classes):
            lines.append(
                f" Class {c}: tp={self.true_positives(c)} fp={self.false_positives(c)} "
                f"fn={self.false_negatives(c)} precision={self.precision(c):.4f} "
                f"recall={self.recall(c):.4f} f1={self.f1(c):.4f}"
            )
        lines.append(f" Accuracy:  {self.accuracy():.4f}")
        lines.append(f" Precision: {self.precision():.4f}")
        lines.append(f" Recall:    {self.recall():.4f}")
        lines.append(f" F1 Score:  {self.f1():.4f}")
        return "\n".join(lines)
