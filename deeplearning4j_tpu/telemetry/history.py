"""Bounded metrics time-series history: the *watch* layer's memory
(ISSUE 15).

The PR 2 registry answers "what is the value NOW"; every alerting
question is about *change* — is the skip counter still climbing, did the
queue depth grow for 30 seconds, what was p99 over the last minute. This
module snapshots a :class:`~deeplearning4j_tpu.telemetry.registry.
MetricsRegistry` on a cadence into per-series ring buffers and answers
exactly those range/rate/delta questions, so telemetry/alerts.py can be a
pure rule evaluator with no storage of its own.

Storage model:

- one **sample** = one ``registry.snapshot()`` + a wall-clock timestamp;
  every instrument in the registry contributes one point per sample to
  its series ``(kind, name, sorted-labels)``;
- counters/gauges store ``(ts, value)``; histograms store the full
  cumulative bucket snapshot per sample, which is what makes **windowed
  percentiles** possible: the bucket-count *delta* between the window's
  edges is a histogram of only the observations inside the window
  (:meth:`MetricsHistory.histogram_window` /
  :meth:`MetricsHistory.percentile_over` — an all-time percentile would
  never resolve, say, a latency regression that started two minutes ago);
- every series is a bounded ``deque(maxlen=window)`` — memory is
  O(series x window), independent of run length.

Spill (crash-readable, write-ahead): with ``spill_path`` set, every
sample is appended to a line-buffered JSONL file BEFORE it lands in the
in-memory rings — the same posture as the PR 7 flight recorder, so a
``kill -9`` leaves every completed sample on disk for
``tools/alert_report.py`` (:func:`read_spill` / :func:`replay_spill`).

Query semantics (shared by every rule kind in telemetry/alerts.py):

- ``labels=None`` matches EVERY label set of the name and sums values
  per sample — right for counters (total rate across label sets) and for
  additive gauges like queue depth; pass explicit labels to pin one
  series;
- :meth:`rate` is the per-second increase from the oldest to the newest
  point inside ``window_s``; a counter reset (negative delta) restarts
  the window at the reset point rather than reporting a negative rate;
- :meth:`delta` is the signed value change over the window (gauges);
- :meth:`last_point` / series timestamps back absence/staleness rules.

Threading: the background sampler (``start()``/``stop()``) follows the
PR 11 discipline — state guarded by a lockwatch-seamed lock, the thread
handle swapped under the lock and joined outside it with a timeout, stop
idempotent, start-after-stop supported — and the spill file handle is
opened in the constructor, never under the lock. Zero-cost unconfigured:
nothing samples until a ``MetricsHistory`` is built, and the module-level
``get_history()`` seam is one attribute read.

Knobs (host-side, blessed ``DL4J_TPU_*`` namespace; read by
:func:`configure` for unset arguments):

- ``DL4J_TPU_HISTORY_INTERVAL_S``: sampler cadence (default 1.0).
- ``DL4J_TPU_HISTORY_WINDOW``: ring size in samples (default 512).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from deeplearning4j_tpu.utils.lockwatch import make_lock

SCHEMA = "dl4j-tpu-history-v1"

_ENV_INTERVAL = "DL4J_TPU_HISTORY_INTERVAL_S"
_ENV_WINDOW = "DL4J_TPU_HISTORY_WINDOW"

DEFAULT_INTERVAL_S = 1.0
DEFAULT_WINDOW = 512

LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Optional[Dict]) -> LabelKey:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class MetricsHistory:
    """Ring-buffered time series over one registry (module docstring)."""

    def __init__(self, registry=None, window: int = DEFAULT_WINDOW,
                 interval_s: float = DEFAULT_INTERVAL_S,
                 spill_path: Optional[str] = None):
        if registry is None:
            from deeplearning4j_tpu.telemetry.registry import default_registry

            registry = default_registry()
        self.registry = registry
        self.window = max(2, int(window))
        self.interval_s = float(interval_s)
        self.spill_path = spill_path
        self._fh = None
        if spill_path is not None:
            parent = os.path.dirname(os.path.abspath(spill_path))
            os.makedirs(parent, exist_ok=True)
            # opened OUTSIDE the lock (graftlint blocking-under-lock);
            # line-buffered so each sample is one durable line
            self._fh = open(spill_path, "a", buffering=1)
        self._lock = make_lock("telemetry.history")  # lockwatch seam
        # (kind, name, label_key) -> deque[(ts, value-or-hist-snapshot)]
        self._series: Dict[Tuple[str, str, LabelKey], deque] = {}
        self._samples = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ sampling ----
    def sample_once(self, now: Optional[float] = None) -> float:
        """Take one registry snapshot into the rings (and the spill,
        write-ahead). Returns the sample timestamp."""
        ts = time.time() if now is None else float(now)
        snap = self.registry.snapshot()
        with self._lock:
            fh = self._fh
            seq = self._samples
        rec = {"schema": SCHEMA, "ts": ts, "seq": seq, "snapshot": snap}
        if fh is not None:
            try:
                fh.write(json.dumps(rec) + "\n")
            # graftlint: allow[swallowed-thread-exception] deliberate: a full disk / just-closed spill degrades history, never the watched run; the in-memory rings below still ingest the sample
            except (OSError, ValueError):
                pass
        with self._lock:
            self._ingest(ts, snap)
            self._samples += 1
            n_series = len(self._series)
        self.registry.counter("history_samples_total").inc()
        self.registry.gauge("history_series").set(float(n_series))
        self.registry.gauge("history_last_sample_unix").set(ts)
        return ts

    def _ingest(self, ts: float, snap: Dict) -> None:
        for kind, rows in (("counter", snap.get("counters", ())),
                           ("gauge", snap.get("gauges", ()))):
            for row in rows:
                key = (kind, row["name"], _label_key(row["labels"]))
                ring = self._series.get(key)
                if ring is None:
                    ring = self._series[key] = deque(maxlen=self.window)
                ring.append((ts, float(row["value"])))
        for row in snap.get("histograms", ()):
            key = ("histogram", row["name"], _label_key(row["labels"]))
            ring = self._series.get(key)
            if ring is None:
                ring = self._series[key] = deque(maxlen=self.window)
            ring.append((ts, {"buckets": [dict(b) for b in row["buckets"]],
                              "sum": row["sum"], "count": row["count"]}))

    # ----------------------------------------------------- sampler thread ----
    def start(self) -> None:
        """Run ``sample_once`` every ``interval_s`` on a background
        thread (first sample immediately — an alert engine attached right
        after start sees a baseline point, not an empty ring)."""
        with self._lock:
            if self._thread is not None:
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="metrics-history")
            self._thread.start()

    def _loop(self) -> None:
        self.sample_once()
        while not self._stop.wait(self.interval_s):
            self.sample_once()

    def stop(self) -> None:
        # handle swap under the lock, join outside (PR 11 discipline:
        # concurrent stop()s race-free, and the join never holds the lock
        # the sampling loop needs)
        with self._lock:
            thread, self._thread = self._thread, None
        self._stop.set()
        if thread is not None:
            thread.join(timeout=10)

    def close(self) -> None:
        self.stop()
        # handle swap under the lock (the sampler thread writes through
        # self._fh), close outside it
        with self._lock:
            fh, self._fh = self._fh, None
        if fh is not None:
            fh.close()

    def __enter__(self) -> "MetricsHistory":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------ queries ----
    def _matching(self, kind: str, name: str, labels: Optional[Dict]
                  ) -> List[deque]:
        want = None if labels is None else _label_key(labels)
        out = []
        for (k, n, lk), ring in self._series.items():
            if k == kind and n == name and (want is None or lk == want):
                out.append(ring)
        return out

    def series_index(self) -> List[Dict]:
        """One row per stored series (the ``/api/history`` listing)."""
        with self._lock:
            rows = []
            for (kind, name, lk), ring in sorted(self._series.items()):
                last_ts, last_v = ring[-1]
                rows.append({
                    "kind": kind, "name": name, "labels": dict(lk),
                    "points": len(ring), "last_ts": last_ts,
                    "last_value": (last_v if kind != "histogram"
                                   else last_v["count"]),
                })
            return rows

    def points(self, name: str, labels: Optional[Dict] = None,
               window_s: Optional[float] = None,
               now: Optional[float] = None) -> List[Tuple[float, float]]:
        """Scalar points ``[(ts, value), ...]`` for counters/gauges.
        ``labels=None`` sums every label set of the name per sample
        timestamp (module docstring); ``window_s`` keeps only points
        newer than ``now - window_s``."""
        now = time.time() if now is None else float(now)
        cut = None if window_s is None else now - float(window_s)
        with self._lock:
            rings = (self._matching("counter", name, labels)
                     or self._matching("gauge", name, labels))
            merged: Dict[float, float] = {}
            for ring in rings:
                for ts, v in ring:
                    if cut is not None and ts < cut:
                        continue
                    merged[ts] = merged.get(ts, 0.0) + v
        return sorted(merged.items())

    def last_point(self, name: str, labels: Optional[Dict] = None
                   ) -> Optional[Tuple[float, float]]:
        pts = self.points(name, labels)
        return pts[-1] if pts else None

    def last_points_by_label(self, name: str
                             ) -> List[Tuple[Dict, float, float]]:
        """Per-label-set latest scalar point ``(labels, ts, value)`` —
        what a labeled staleness rule iterates (one verdict per worker)."""
        out = []
        with self._lock:
            for (kind, n, lk), ring in sorted(self._series.items()):
                if n != name or kind == "histogram" or not ring:
                    continue
                ts, v = ring[-1]
                out.append((dict(lk), ts, v))
        return out

    def rate(self, name: str, labels: Optional[Dict] = None,
             window_s: float = 60.0, now: Optional[float] = None
             ) -> Optional[float]:
        """Per-second increase over the window (counter semantics). A
        reset (negative step between adjacent samples) restarts the
        measurement at the reset point. None with fewer than two points."""
        pts = self.points(name, labels, window_s=window_s, now=now)
        if len(pts) < 2:
            return None
        # walk from the oldest point, restarting after any reset
        start = 0
        for i in range(1, len(pts)):
            if pts[i][1] < pts[i - 1][1]:
                start = i
        (t0, v0), (t1, v1) = pts[start], pts[-1]
        if t1 <= t0:
            return None
        return (v1 - v0) / (t1 - t0)

    def delta(self, name: str, labels: Optional[Dict] = None,
              window_s: float = 60.0, now: Optional[float] = None
              ) -> Optional[float]:
        """Signed value change over the window (gauge semantics: queue
        growth is a positive delta). None with fewer than two points."""
        pts = self.points(name, labels, window_s=window_s, now=now)
        if len(pts) < 2:
            return None
        return pts[-1][1] - pts[0][1]

    # --------------------------------------------------------- histograms ----
    def _hist_points(self, name: str, labels: Optional[Dict]
                     ) -> List[Tuple[float, Dict]]:
        with self._lock:
            rings = self._matching("histogram", name, labels)
            if not rings:
                return []
            if len(rings) == 1:
                return list(rings[0])
            # multiple label sets: merge per-ts (cumulative counts add)
            by_ts: Dict[float, List[Dict]] = {}
            for ring in rings:
                for ts, snap in ring:
                    by_ts.setdefault(ts, []).append(snap)
        out = []
        for ts in sorted(by_ts):
            snaps = by_ts[ts]
            bounds = sorted({b["le"] for s in snaps for b in s["buckets"]})
            merged = {
                "buckets": [{"le": b, "count": sum(_cum_at(s, b)
                                                   for s in snaps)}
                            for b in bounds],
                "sum": sum(s["sum"] for s in snaps),
                "count": sum(s["count"] for s in snaps),
            }
            out.append((ts, merged))
        return out

    def histogram_window(self, name: str, labels: Optional[Dict] = None,
                         window_s: float = 60.0,
                         now: Optional[float] = None) -> Optional[Dict]:
        """The bucket-count DELTA between the window's edge samples — a
        cumulative-bucket histogram of only the observations that landed
        inside the window. None without two samples to difference."""
        now = time.time() if now is None else float(now)
        pts = self._hist_points(name, labels)
        pts = [(ts, s) for ts, s in pts if ts >= now - float(window_s)]
        if len(pts) < 2:
            return None
        (t0, s0), (t1, s1) = pts[0], pts[-1]
        if s1["count"] < s0["count"]:  # restart: the window spans a reset
            s0 = {"buckets": [{"le": b["le"], "count": 0}
                              for b in s1["buckets"]], "sum": 0.0,
                  "count": 0}
        buckets = [{"le": b["le"],
                    "count": b["count"] - _cum_at(s0, b["le"])}
                   for b in s1["buckets"]]
        return {"buckets": buckets, "sum": s1["sum"] - s0["sum"],
                "count": s1["count"] - s0["count"],
                "from_ts": t0, "to_ts": t1}

    def percentile_over(self, name: str, q: float,
                        labels: Optional[Dict] = None,
                        window_s: float = 60.0,
                        now: Optional[float] = None) -> Optional[float]:
        """Approximate q-th percentile of the observations inside the
        window (bucket upper bound covering the rank, same estimator as
        Histogram.percentile — but WINDOWED). None when the window holds
        no observations."""
        win = self.histogram_window(name, labels, window_s, now=now)
        if win is None or win["count"] <= 0:
            return None
        rank = q / 100.0 * win["count"]
        for b in win["buckets"]:
            if b["count"] >= rank:
                return b["le"]
        return win["buckets"][-1]["le"] if win["buckets"] else None

    def fraction_over(self, name: str, bound: float,
                      labels: Optional[Dict] = None,
                      window_s: float = 60.0,
                      now: Optional[float] = None) -> Optional[float]:
        """Fraction of windowed observations strictly above ``bound``
        (the burn-rate numerator). Exact when ``bound`` is a bucket
        bound; otherwise a documented lower bound (counts at the largest
        bucket bound <= ``bound`` are treated as within SLO). None when
        the window holds no observations."""
        win = self.histogram_window(name, labels, window_s, now=now)
        if win is None or win["count"] <= 0:
            return None
        good = 0
        for b in win["buckets"]:
            if b["le"] <= bound:
                good = b["count"]
            else:
                break
        return (win["count"] - good) / win["count"]

    # ----------------------------------------------------------- plumbing ----
    def metrics_record(self) -> Dict[str, float]:
        """The history's own ``history_*`` health metrics as a flat
        step-log record (same contract as the serve/federation/lockwatch
        emitters, so tools/telemetry_report.py renders them)."""
        from deeplearning4j_tpu.telemetry.registry import flat_record

        return flat_record(self.registry, prefixes=("history_",))

    def snapshot(self, name: Optional[str] = None,
                 window_s: Optional[float] = None) -> Dict:
        """The ``/api/history`` payload: the series index, plus the
        scalar points of ``name`` when given."""
        with self._lock:
            samples = self._samples
        out: Dict = {"schema": SCHEMA, "samples": samples,
                     "window": self.window, "interval_s": self.interval_s,
                     "series": self.series_index()}
        if name is not None:
            out["name"] = name
            out["points"] = [[ts, v] for ts, v in
                             self.points(name, window_s=window_s)]
        return out


def _cum_at(snap: Dict, bound: float) -> int:
    """Cumulative count of ``snap`` at ``bound`` (0 below its first
    bucket) — shared by the per-ts merge and the window differencing."""
    best = 0
    for b in snap["buckets"]:
        if b["le"] <= bound:
            best = b["count"]
        else:
            break
    return best


# -------------------------------------------------------------- spill IO ----

def read_spill(path: str) -> List[Dict]:
    """Parse a history spill back into sample records. Tolerates a
    truncated final line (the writer died mid-sample — by the write-ahead
    contract every earlier sample is complete); any other malformed line
    raises ``ValueError`` naming it."""
    out: List[Dict] = []
    with open(path) as fh:
        lines = fh.read().splitlines()
    for lineno, line in enumerate(lines, start=1):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as exc:
            if lineno == len(lines):
                break  # killed mid-write: the torn tail line is expected
            raise ValueError(
                f"history spill {path} is corrupt at line {lineno}: "
                f"{exc}") from exc
        if isinstance(rec, dict) and rec.get("schema") == SCHEMA:
            out.append(rec)
    return out


def replay_spill(path: str, window: int = DEFAULT_WINDOW
                 ) -> "MetricsHistory":
    """Rebuild a queryable history from a spill file — how
    tools/alert_report.py re-answers range/rate questions after the
    watched process is gone."""
    from deeplearning4j_tpu.telemetry.registry import MetricsRegistry

    hist = MetricsHistory(registry=MetricsRegistry(), window=window)
    for rec in read_spill(path):
        with hist._lock:
            hist._ingest(float(rec["ts"]), rec.get("snapshot") or {})
            hist._samples += 1
    return hist


# ------------------------------------------------ process-global history ----
# The ambient seam, mirroring trace.get_tracer(): instrumentation-free —
# the UI server and alert engine read it per call, so history is a
# per-process switch, not a constructor parameter everywhere.

_history: Optional[MetricsHistory] = None
_history_lock = threading.Lock()


def get_history() -> Optional[MetricsHistory]:
    return _history


def set_history(history: Optional[MetricsHistory]
                ) -> Optional[MetricsHistory]:
    """Install (or clear, with None) the process history; returns the
    previous one so tests can restore it."""
    global _history
    with _history_lock:
        prev, _history = _history, history
    return prev


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)  # graftlint: allow[env-read-in-trace] host-side knob reader; every caller passes a DL4J_TPU_*-namespaced name
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def configure(registry=None, spill_path: Optional[str] = None,
              interval_s: Optional[float] = None,
              window: Optional[int] = None,
              start: bool = True) -> MetricsHistory:
    """Build a history (env knobs fill unset arguments), install it as
    the process history, and (by default) start the sampler."""
    if interval_s is None:
        interval_s = _env_float(_ENV_INTERVAL, DEFAULT_INTERVAL_S)
    if window is None:
        window = int(_env_float(_ENV_WINDOW, DEFAULT_WINDOW))
    hist = MetricsHistory(registry=registry, window=window,
                          interval_s=interval_s, spill_path=spill_path)
    if start:
        hist.start()
    set_history(hist)
    return hist
