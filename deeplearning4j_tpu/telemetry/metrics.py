"""In-graph metric computation: small pytrees of device scalars.

These helpers run INSIDE jitted train steps. A metrics pytree is a flat
dict of f32 scalars (plus the (E,) router-load vector) computed from
intermediates the step already has — params, grads, loss — so threading
them through a step adds a handful of reductions and NO extra dispatch:
the step still returns in one XLA program, and the host fetches the
accumulated pytrees only every N steps (telemetry/session.TrainTelemetry).

Bit-parity contract: a metrics-threaded step must produce bit-identical
loss/params to its unthreaded twin (pinned at 0 ulp on CPU in
tests/test_telemetry.py) — these functions therefore only READ step
intermediates, never reorder or perturb the loss/grad computation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jax.Array

_EPS = 1e-12


def global_norm(tree) -> Array:
    """sqrt(sum of squares) over every leaf of a pytree (f32 accumulate)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.float32(0.0)
    total = sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)
    return jnp.sqrt(total)


def train_step_metrics(params, grads, lr: float, loss=None) -> dict:
    """The standard step-health block: grad global-norm, param global-norm,
    and the update/param ratio (||lr·g|| / ||p|| for SGD — the classic
    learning-rate sanity signal; ~1e-3 is healthy, >>1e-2 means the step
    size is fighting the loss surface)."""
    gn = global_norm(grads)
    pn = global_norm(params)
    out = {
        "grad_norm": gn,
        "param_norm": pn,
        "update_ratio": (lr * gn) / (pn + _EPS),
    }
    if loss is not None:
        out["loss"] = jnp.asarray(loss, jnp.float32)
    return out


def update_metrics(params, updates, scale=1.0) -> dict:
    """Update/param ratio from an explicit update pytree (updater-produced
    steps where the update is NOT lr·g — momentum/adagrad/rmsprop paths)."""
    un = global_norm(updates) * scale
    pn = global_norm(params)
    return {"param_norm": pn, "update_ratio": un / (pn + _EPS)}
