"""Span-based distributed tracing + crash flight recorder (ISSUE 7).

The elastic control plane (scaleout/elastic.py) composes one logical round
out of work in K+1 OS processes: master barrier/average/publish, worker
step/publish/sync, tracker RPCs, checkpoint writes. PR 6 made that round
survive faults; this module makes it *explainable* — every phase is a
span, spans from different processes share one trace, and a crash leaves
a bounded forensic artifact instead of silence.

Span model (OpenTelemetry-shaped, zero dependencies):

- A **span** is ``(trace_id, span_id, parent_id, name, attrs, status)``
  plus two clocks: wall (``time.time`` — comparable across processes on
  one host / NTP-synced cluster, what tools/trace_report.py merges on)
  and monotonic (``time.perf_counter`` — what durations are computed
  from, immune to wall-clock steps).
- A **tracer** is per-process. It appends every span to a JSONL sink as
  TWO records — ``{"ev": "B", ...}`` at start and ``{"ev": "E", ...}`` at
  end — so a ``kill -9`` mid-span still leaves the begin record on disk
  (the file is line-buffered; ended spans are always complete pairs).
  tools/trace_report.py treats an unmatched "B" as an *open* span and
  reconstructs the partial round from it.
- **Context propagation**: ``span.context()`` is a small dict
  ``{"trace_id", "span_id"}`` safe to ship over any transport. The
  tracker frame protocol carries it per-RPC (remote_tracker.py), and the
  elastic master embeds its round-span context in every published global
  version blob, so worker round spans parent under the master round that
  will collect them.

Flight recorder: a bounded in-memory ring of the last-N ended spans plus
the currently-open span set. ``dump()`` writes ring + telemetry-counter
snapshot + ``device_memory_stats`` to ``flightrec_<process>.json``
(atomic tmp+replace). Dumps fire on: unhandled exceptions
(``install_crash_hooks`` chains ``sys.excepthook``), SIGTERM, explicit
calls (``ElasticTrainingError`` handlers in elastic.py), and *checkpoint*
calls at round boundaries — the write-ahead posture that makes even a
``kill -9`` (which runs no hooks) leave the previous boundary's dump
behind.

Zero-config is zero-cost: every instrumentation site goes through
``maybe_span()`` / ``get_tracer()``; with no tracer configured those are
a dict lookup and a no-op context manager.
"""

from __future__ import annotations

import contextlib
import json
import math
import os
import signal
import sys
import threading

from deeplearning4j_tpu.utils.lockwatch import make_lock
import time
import uuid
from collections import deque
from typing import Dict, Iterator, List, Optional

SCHEMA = "dl4j-tpu-trace-v1"


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


def _new_trace_id() -> str:
    # W3C-native width (32 hex): a locally-rooted trace propagates over
    # HTTP traceparent without padding, so the SAME id string appears in
    # every process's span file and the report merges them as one tree
    return uuid.uuid4().hex


def _jsonable(v):
    if hasattr(v, "tolist"):
        v = v.tolist()
    if isinstance(v, float) and not math.isfinite(v):
        return repr(v)
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    return str(v)


class Span:
    """One timed operation. Not thread-safe by itself — a span is owned by
    the code path that started it; the tracer's sink/ring writes are the
    shared, locked part."""

    __slots__ = ("tracer", "name", "trace_id", "span_id", "parent_id",
                 "process", "attrs", "events", "status", "error",
                 "start_wall", "start_mono", "end_wall", "dur_ms", "_ended")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 parent_id: Optional[str], attrs: Optional[Dict] = None):
        self.tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.process = tracer.process
        self.attrs: Dict = dict(attrs or {})
        self.events: List[Dict] = []
        self.status = "open"
        self.error: Optional[str] = None
        self.start_wall = time.time()
        self.start_mono = time.perf_counter()
        self.end_wall: Optional[float] = None
        self.dur_ms: Optional[float] = None
        self._ended = False

    # -- enrichment --
    def set_attr(self, key: str, value) -> "Span":
        self.attrs[key] = value
        return self

    def add_event(self, name: str, **attrs) -> None:
        """A point-in-time marker inside the span (retry, reconnect,
        contribution arrival) — cheaper than a child span, still in the
        dump and the Chrome export."""
        self.events.append({"name": name, "ts": time.time(),
                            **{k: _jsonable(v) for k, v in attrs.items()}})

    def context(self) -> Dict[str, str]:
        """The wire-safe propagation context for this span."""
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    # -- lifecycle --
    def end(self, status: str = "ok", error: Optional[BaseException] = None
            ) -> None:
        if self._ended:
            return
        self._ended = True
        self.end_wall = time.time()
        self.dur_ms = (time.perf_counter() - self.start_mono) * 1000.0
        self.status = "error" if error is not None else status
        if error is not None:
            self.error = f"{type(error).__name__}: {error}"
        self.tracer._on_end(self)

    # -- serialization --
    def begin_record(self) -> Dict:
        return {"ev": "B", "schema": SCHEMA, "trace_id": self.trace_id,
                "span_id": self.span_id, "parent_id": self.parent_id,
                "name": self.name, "process": self.process,
                "pid": os.getpid(), "ts": self.start_wall,
                "attrs": {k: _jsonable(v) for k, v in self.attrs.items()}}

    def end_record(self) -> Dict:
        return {"ev": "E", "span_id": self.span_id, "trace_id": self.trace_id,
                "name": self.name, "process": self.process,
                "ts": self.end_wall, "dur_ms": round(self.dur_ms, 3),
                "status": self.status, "error": self.error,
                "attrs": {k: _jsonable(v) for k, v in self.attrs.items()},
                "events": self.events}

    def to_dict(self, now: Optional[float] = None) -> Dict:
        """Full snapshot (open spans report elapsed-so-far durations)."""
        d = {"trace_id": self.trace_id, "span_id": self.span_id,
             "parent_id": self.parent_id, "name": self.name,
             "process": self.process, "start": self.start_wall,
             "status": self.status,
             "attrs": {k: _jsonable(v) for k, v in self.attrs.items()},
             "events": self.events}
        if self._ended:
            d["end"] = self.end_wall
            d["dur_ms"] = round(self.dur_ms, 3)
            d["error"] = self.error
        else:
            d["dur_ms"] = round(
                ((now or time.time()) - self.start_wall) * 1000.0, 3)
            d["open"] = True
        return d


class Tracer:
    """Per-process tracer: span factory + JSONL sink + flight-recorder
    ring. ``current`` span tracking is per *thread* (a heartbeat or ckpt
    writer thread never silently parents under the training thread's
    span; cross-thread parents are passed explicitly)."""

    def __init__(self, process: str, trace_dir: Optional[str] = None,
                 path: Optional[str] = None, ring: int = 256,
                 flight_path: Optional[str] = None, registry=None,
                 min_checkpoint_interval_s: float = 1.0):
        self.process = str(process)
        safe = "".join(c if (c.isalnum() or c in "-_.") else "_"
                       for c in self.process)
        if trace_dir is not None:
            os.makedirs(trace_dir, exist_ok=True)
            path = path or os.path.join(trace_dir, f"spans_{safe}.jsonl")
            flight_path = flight_path or os.path.join(
                trace_dir, f"flightrec_{safe}.json")
        self.path = path
        self.flight_path = flight_path
        self._lock = make_lock("telemetry.trace")  # lockwatch seam
        self._fh = open(path, "a", buffering=1) if path else None
        self._ring: deque = deque(maxlen=max(1, int(ring)))
        self._open: Dict[str, Span] = {}
        self._tls = threading.local()
        if registry is None:
            from deeplearning4j_tpu.telemetry.registry import default_registry

            registry = default_registry()
        self.registry = registry
        # rate limit for flight_checkpoint ONLY (dump() always writes):
        # bounds the write-ahead artifact cost on fast round cadences —
        # the first checkpoint always lands (_last_dump_mono starts -inf)
        self.min_checkpoint_interval_s = float(min_checkpoint_interval_s)
        self._last_dump_mono = float("-inf")
        self._prev_excepthook = None
        self._prev_sigterm = None

    # ------------------------------------------------------------ spans ----
    def _stack(self) -> List[Span]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def current_span(self) -> Optional[Span]:
        st = self._stack()
        return st[-1] if st else None

    def current_context(self) -> Optional[Dict[str, str]]:
        sp = self.current_span()
        return sp.context() if sp is not None else None

    def start_span(self, name: str, parent=None,
                   attrs: Optional[Dict] = None) -> Span:
        """Start (and register) a span. ``parent`` may be a Span, a wire
        context dict, or None — None inherits this thread's current span;
        pass ``parent=False`` for an explicit root."""
        if parent is None:
            parent = self.current_span()
        elif parent is False:
            parent = None
        if isinstance(parent, Span):
            trace_id, parent_id = parent.trace_id, parent.span_id
        elif isinstance(parent, dict) and parent.get("trace_id"):
            trace_id = str(parent["trace_id"])
            parent_id = parent.get("span_id")
        else:
            trace_id, parent_id = _new_trace_id(), None
        span = Span(self, name, trace_id, parent_id, attrs)
        rec = span.begin_record()
        with self._lock:
            self._open[span.span_id] = span
            self._write(rec)
        self.registry.counter("trace_spans_started_total").inc()
        return span

    def _on_end(self, span: Span) -> None:
        rec = span.end_record()
        with self._lock:
            self._open.pop(span.span_id, None)
            self._ring.append(rec)
            self._write(rec)
        self.registry.counter("trace_spans_ended_total").inc()
        if span.status == "error":
            self.registry.counter("trace_spans_error_total").inc()

    def _write(self, rec: Dict) -> None:
        if self._fh is not None:
            try:
                self._fh.write(json.dumps(rec) + "\n")
            except (OSError, ValueError):  # closed/full sink never kills
                pass                       # the traced run

    @contextlib.contextmanager
    def span(self, name: str, parent=None,
             attrs: Optional[Dict] = None) -> Iterator[Span]:
        """Context manager: starts the span, makes it this thread's
        current (so nested spans parent under it), ends it on exit — with
        ``status="error"`` and the exception recorded when one escapes."""
        sp = self.start_span(name, parent=parent, attrs=attrs)
        st = self._stack()
        st.append(sp)
        try:
            yield sp
        except BaseException as exc:
            sp.end(error=exc)
            raise
        finally:
            if st and st[-1] is sp:
                st.pop()
            else:  # defensive: mis-nested exits still unregister the span
                try:
                    st.remove(sp)
                except ValueError:
                    pass
            sp.end()

    # -------------------------------------------------- flight recorder ----
    def snapshot(self, limit: Optional[int] = None) -> Dict:
        """Open + recent spans (the /api/trace payload)."""
        now = time.time()
        with self._lock:
            recent = list(self._ring)
            open_spans = [s.to_dict(now) for s in self._open.values()]
        if limit is not None:
            recent = recent[-int(limit):]
        return {"schema": SCHEMA, "process": self.process, "ts": now,
                "open": open_spans, "recent": recent}

    def dump(self, reason: str, error: Optional[BaseException] = None,
             extra: Optional[Dict] = None) -> Optional[str]:
        """Write the flight-recorder artifact (atomic replace). Never
        raises — a dump is last-breath code; losing it must not mask the
        original failure. Routine ``checkpoint`` dumps skip the
        ``device_memory_stats`` probe (it costs ~ms per call); crash /
        SIGTERM / error dumps always carry it."""
        if self.flight_path is None:
            return None
        try:
            payload = self.snapshot()
            payload.update({
                "reason": str(reason), "pid": os.getpid(),
                "error": (f"{type(error).__name__}: {error}"
                          if error is not None else None),
                "counters": self._counters_snapshot(),
                "device_memory": (self._device_memory()
                                  if reason != "checkpoint" else None),
            })
            if extra:
                payload["extra"] = {k: _jsonable(v) for k, v in extra.items()}
            tmp = f"{self.flight_path}.tmp.{os.getpid()}"
            with open(tmp, "w") as fh:
                json.dump(payload, fh, separators=(",", ":"))
            os.replace(tmp, self.flight_path)
            self._last_dump_mono = time.perf_counter()
            self.registry.counter("trace_flight_dumps_total").inc()
            return self.flight_path
        except Exception:
            return None

    def flight_checkpoint(self, extra: Optional[Dict] = None
                          ) -> Optional[str]:
        """The write-ahead dump at a safe boundary (elastic round commit,
        worker round loop): a later kill -9 leaves THIS artifact even
        though no hook runs. Rate-limited by
        ``min_checkpoint_interval_s`` so a fast round cadence amortizes
        the artifact cost (the first call always writes; explicit
        ``dump()`` is never limited)."""
        if (time.perf_counter() - self._last_dump_mono
                < self.min_checkpoint_interval_s):
            return None
        return self.dump("checkpoint", extra=extra)

    def _counters_snapshot(self) -> Dict:
        try:
            return self.registry.snapshot()
        except Exception:
            return {}

    def _device_memory(self) -> List[Dict]:
        try:
            from deeplearning4j_tpu.utils.profiling import device_memory_stats

            return device_memory_stats()
        except Exception:  # no jax / no backend in a dying process: skip
            return []

    # ------------------------------------------------------ crash hooks ----
    def install_crash_hooks(self, sigterm: bool = True,
                            excepthook: bool = True) -> None:
        """Dump on unhandled exceptions and SIGTERM. Hooks chain to the
        previous handlers; SIGTERM installation is skipped off the main
        thread (signal module restriction) rather than failing."""
        if excepthook and self._prev_excepthook is None:
            self._prev_excepthook = sys.excepthook

            def _hook(exc_type, exc, tb):
                self.dump("unhandled_exception", error=exc)
                (self._prev_excepthook or sys.__excepthook__)(
                    exc_type, exc, tb)

            sys.excepthook = _hook
        if sigterm:
            def _on_term(signum, frame):
                self.dump("SIGTERM")
                prev = self._prev_sigterm
                if callable(prev):
                    prev(signum, frame)
                else:
                    signal.signal(signal.SIGTERM, signal.SIG_DFL)
                    os.kill(os.getpid(), signal.SIGTERM)

            try:
                self._prev_sigterm = signal.signal(signal.SIGTERM, _on_term)
            except ValueError:  # not the main thread
                pass

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


# ------------------------------------------------- process-global tracer ----
# The OTel-style ambient tracer: instrumentation sites (remote_tracker,
# ckpt, elastic) read it per call, so tracing is a per-process switch, not
# a parameter threaded through every constructor.

_tracer: Optional[Tracer] = None
_tracer_lock = threading.Lock()


def get_tracer() -> Optional[Tracer]:
    return _tracer


def set_tracer(tracer: Optional[Tracer]) -> Optional[Tracer]:
    """Install (or clear, with None) the process tracer; returns the
    previous one so tests can restore it."""
    global _tracer
    with _tracer_lock:
        prev, _tracer = _tracer, tracer
    return prev


def configure(process: str, trace_dir: str, ring: int = 256,
              crash_hooks: bool = True, registry=None) -> Tracer:
    """Build a tracer writing under ``trace_dir``, install it as the
    process tracer, and (by default) arm the crash hooks. The one-liner
    for CLIs (``--trace-dir``) and tests."""
    tracer = Tracer(process, trace_dir=trace_dir, ring=ring,
                    registry=registry)
    if crash_hooks:
        tracer.install_crash_hooks()
    set_tracer(tracer)
    return tracer


@contextlib.contextmanager
def maybe_span(name: str, parent=None,
               attrs: Optional[Dict] = None) -> Iterator[Optional[Span]]:
    """``tracer.span(...)`` against the process tracer, or a no-op yield
    of None when tracing is off — the zero-cost seam every instrumented
    call site uses."""
    tracer = _tracer
    if tracer is None:
        yield None
        return
    with tracer.span(name, parent=parent, attrs=attrs) as sp:
        yield sp


def current_trace_context() -> Optional[Dict[str, str]]:
    """The calling thread's current span context (wire-safe dict), or
    None when tracing is off / no span is open."""
    tracer = _tracer
    return tracer.current_context() if tracer is not None else None


# ------------------------------------------------ W3C traceparent (ISSUE 12) ----
# The HTTP serving path propagates context as a ``traceparent`` header
# (https://www.w3.org/TR/trace-context/): ``00-<32 hex trace>-<16 hex
# span>-<2 hex flags>``. Internal ids are 16 hex chars (``_new_id``), so
# formatting left-pads to the W3C width and parsing keeps the full 32-char
# id as-is — trace ids are opaque strings everywhere in this tracer, so a
# caller-minted 32-char id flows through spans, sinks, and reports
# unchanged, and the one trace tree spans loadgen → HTTP → engine.

def format_traceparent(ctx: Dict[str, str]) -> str:
    """A ``traceparent`` header value for a span context dict. Ids shorter
    than the W3C widths are left-padded with zeros (parse→format is
    identity for ids already at full width)."""
    trace_id = str(ctx["trace_id"]).lower().rjust(32, "0")
    span_id = str(ctx["span_id"]).lower().rjust(16, "0")
    return f"00-{trace_id}-{span_id}-01"


def parse_traceparent(header: Optional[str]) -> Optional[Dict[str, str]]:
    """Parse a ``traceparent`` header into a span-context dict, or None
    when the header is absent or malformed. Per the W3C spec a bad header
    is IGNORED (the request proceeds as a fresh root trace), never an
    error — tests/test_ui.py pins that a garbage header cannot 400 a
    generation request."""
    if not header or not isinstance(header, str):
        return None
    parts = header.strip().split("-")
    if len(parts) < 4:
        return None
    version, trace_id, span_id = parts[0], parts[1], parts[2]
    if version == "ff" or len(version) != 2:
        return None
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(version, 16), int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    if set(trace_id) == {"0"} or set(span_id) == {"0"}:
        return None  # all-zero ids are explicitly invalid in the spec
    return {"trace_id": trace_id.lower(), "span_id": span_id.lower()}
