"""Tracker-federated cluster metrics (ISSUE 12).

Every telemetry registry in this repo is process-local by design
(telemetry/registry.py documents the isolation), yet the fleet work
ROADMAP 2/4 stand on needs *cross-replica* signals: a router dispatching
on queue depth, a hot-swap recording which weight version each replica
serves. This module federates the per-process registries through the
SparkNet-lineage StateTracker — the same transport PR 6 reused for
elastic membership — into one cluster view:

- **push side** (:class:`MetricsPusher`): each process periodically
  serializes its registry snapshot as a versioned JSON payload
  (``{"schema": "dl4j-tpu-fedmetrics-v1", "process", "pid", "ts",
  "seq", "snapshot"}``) and writes it to the tracker's KV map under
  ``federation.metrics.<process>`` (``put_kv`` — last-write-wins per key,
  so a retry after an ambiguous transport failure is safe). JSON rather
  than pickled objects keeps the payload wire-inspectable and decouples
  pusher and aggregator versions (the ``schema`` field gates merges).
- **aggregate side** (:class:`ClusterAggregator`): one ``kv_snapshot``
  RPC reads every process's latest payload; :func:`merge_snapshots`
  folds them into a registry-snapshot-shaped cluster view with the
  documented semantics: **counters sum** across processes (same name +
  labels), **gauges stay per-process** (a ``process`` label is added —
  averaging a queue-depth gauge across replicas would destroy exactly
  the signal the router needs), **histograms bucket-merge** (per-``le``
  cumulative counts added; identical bucket bounds merge exactly, and a
  bound one process lacks uses its count at the largest bound ≤ it — a
  documented lower bound, never an invented observation).
- **staleness**: each payload carries the pusher's wall-clock ``ts``; a
  process whose last push is older than ``stale_after_s`` is marked
  ``stale`` in ``/api/cluster`` and exported as
  ``federation_process_up{process=...} 0`` — its last-known data stays
  in the merge (the honest read: "this is what it looked like when we
  last heard from it"), the flag says how much to trust it.

Serving: ``UiServer.attach_federation`` exposes the cluster view at
``GET /api/cluster`` (JSON) and ``GET /metrics?scope=cluster``
(Prometheus text via telemetry/prometheus.render_snapshot, with the
per-process ``federation_process_up`` / ``federation_process_age_seconds``
gauges appended).

Both halves report their own health under ``federation_*`` in their
local registries (pushes, push failures, collects, process/stale-process
gauges) — rendered by tools/telemetry_report.py and pinned by the same
meta-test discipline as the ``serve_*`` metrics.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.utils.lockwatch import make_lock

log = logging.getLogger(__name__)

SCHEMA = "dl4j-tpu-fedmetrics-v1"
KV_PREFIX = "federation.metrics."


# ------------------------------------------------------------- push side ----

class MetricsPusher:
    """Periodically push one registry's snapshot to the tracker.

    ``tracker`` is anything with ``put_kv`` (the in-memory tracker, the
    embedded server handle, or a StateTrackerClient across processes).
    ``start()`` runs the cadence on a background thread; ``push_once()``
    is the synchronous unit (tests and shutdown flushes call it
    directly). Transport faults are absorbed: a failed push counts
    ``federation_push_failures_total`` and the next interval retries —
    a flapping tracker degrades freshness, never the pushing process.
    """

    def __init__(self, tracker, process: str, registry=None,
                 interval_s: float = 1.0):
        if registry is None:
            from deeplearning4j_tpu.telemetry.registry import default_registry

            registry = default_registry()
        self._tracker = tracker
        self.process = str(process)
        self.registry = registry
        self.interval_s = float(interval_s)
        self._lock = make_lock("federation.pusher")  # lockwatch seam
        self._seq = 0
        self._fail_streak = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def payload(self) -> Dict[str, Any]:
        """The next versioned push payload (seq is consumed)."""
        with self._lock:
            seq = self._seq
            self._seq += 1
        return {"schema": SCHEMA, "process": self.process,
                "pid": os.getpid(), "ts": time.time(), "seq": seq,
                "snapshot": self.registry.snapshot()}

    def push_once(self) -> bool:
        """One snapshot push; True on success. The RPC runs outside the
        pusher lock (the lock only guards the seq counter)."""
        payload = self.payload()
        try:
            self._tracker.put_kv(KV_PREFIX + self.process,
                                 json.dumps(payload))
        except (ConnectionError, OSError) as exc:
            # absorbed: freshness degrades, the pushing process survives —
            # but say so once per outage (the counter alone is invisible
            # until someone scrapes it), not once per interval
            self.registry.counter("federation_push_failures_total").inc()
            self.registry.gauge("federation_last_push_error").set(1.0)
            self._fail_streak += 1
            if self._fail_streak == 1:
                log.warning("federation push for %s failing (tracker "
                            "unreachable): %r", self.process, exc)
            return False
        if self._fail_streak:
            log.info("federation push for %s recovered after %d "
                     "failure(s)", self.process, self._fail_streak)
            self._fail_streak = 0
        self.registry.counter("federation_pushes_total").inc()
        self.registry.gauge("federation_last_push_unix").set(payload["ts"])
        self.registry.gauge("federation_last_push_error").set(0.0)
        return True

    def start(self) -> None:
        with self._lock:
            if self._thread is not None:
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"federation-pusher-{self.process}")
            self._thread.start()

    def _loop(self) -> None:
        # first push immediately: an aggregator should see a fresh
        # process within one collect, not one interval later
        self.push_once()
        while not self._stop.wait(self.interval_s):
            self.push_once()

    def stop(self, final_push: bool = True) -> None:
        with self._lock:
            thread, self._thread = self._thread, None
        self._stop.set()
        if thread is not None:
            thread.join(timeout=10)
        if final_push:
            self.push_once()  # the last state lands even mid-interval

    def __enter__(self) -> "MetricsPusher":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


# ------------------------------------------------------------ merge core ----

def _label_key(labels: Dict) -> Tuple:
    return tuple(sorted((str(k), str(v))
                 for k, v in (labels or {}).items()))


def _merge_histograms(snaps: List[Dict]) -> Dict:
    """Bucket-merge: cumulative counts added per ``le`` over the union of
    bounds. A source lacking a bound contributes its cumulative count at
    the largest of its own bounds ≤ that bound (0 below its first) — a
    lower bound on the true value, exact when bounds are identical (the
    repo-wide DEFAULT_BUCKETS case)."""
    bounds = sorted({b["le"] for s in snaps for b in s["buckets"]})

    def cum_at(snap: Dict, bound: float) -> int:
        best = 0
        for b in snap["buckets"]:
            if b["le"] <= bound:
                best = b["count"]
            else:
                break
        return best

    return {
        "buckets": [{"le": b, "count": sum(cum_at(s, b) for s in snaps)}
                    for b in bounds],
        "sum": sum(s["sum"] for s in snaps),
        "count": sum(s["count"] for s in snaps),
    }


def merge_snapshots(named: Sequence[Tuple[str, Dict]]) -> Dict:
    """Fold ``(process, registry.snapshot())`` pairs into one
    registry-snapshot-shaped cluster view (module docstring semantics:
    counter sum / gauge per-process / histogram bucket-merge)."""
    counters: Dict[Tuple, Dict] = {}
    gauges: List[Dict] = []
    histograms: Dict[Tuple, Dict] = {}
    for process, snap in named:
        for row in snap.get("counters", []):
            key = (row["name"], _label_key(row["labels"]))
            if key not in counters:
                counters[key] = {"name": row["name"],
                                 "labels": dict(row["labels"]),
                                 "value": 0.0}
            counters[key]["value"] += row["value"]
        for row in snap.get("gauges", []):
            gauges.append({"name": row["name"],
                           "labels": dict(row["labels"],
                                          process=str(process)),
                           "value": row["value"]})
        for row in snap.get("histograms", []):
            key = (row["name"], _label_key(row["labels"]))
            histograms.setdefault(key, {"name": row["name"],
                                        "labels": dict(row["labels"]),
                                        "snaps": []})
            histograms[key]["snaps"].append(row)
    return {
        "counters": [counters[k] for k in sorted(counters)],
        "gauges": sorted(gauges, key=lambda r: (r["name"],
                                                sorted(r["labels"].items()))),
        "histograms": [
            {"name": h["name"], "labels": h["labels"],
             **_merge_histograms(h["snaps"])}
            for _, h in sorted(histograms.items(), key=lambda kv: kv[0])
        ],
    }


# -------------------------------------------------------- aggregate side ----

class ClusterAggregator:
    """Read every process's pushed payload and build the cluster view.

    ``collect()`` is the ``/api/cluster`` handler's body: one
    ``kv_snapshot`` read, schema-gated parse, staleness marking, merge.
    Unparseable or wrong-schema payloads are skipped and counted
    (``federation_bad_payloads_total``) — one broken pusher must never
    blank the whole cluster view."""

    def __init__(self, tracker, stale_after_s: float = 10.0,
                 registry=None):
        if registry is None:
            from deeplearning4j_tpu.telemetry.registry import default_registry

            registry = default_registry()
        self._tracker = tracker
        self.stale_after_s = float(stale_after_s)
        self.registry = registry

    def collect(self) -> Dict[str, Any]:
        now = time.time()
        try:
            raw = self._tracker.kv_snapshot(KV_PREFIX)
        except (ConnectionError, OSError) as exc:
            self.registry.counter("federation_collect_failures_total").inc()
            return {"schema": SCHEMA, "ts": now, "error": str(exc),
                    "stale_after_s": self.stale_after_s,
                    "processes": [], "merged": merge_snapshots([])}
        processes: List[Dict] = []
        named: List[Tuple[str, Dict]] = []
        for key in sorted(raw):
            try:
                payload = json.loads(raw[key])
            except (TypeError, ValueError):
                self.registry.counter("federation_bad_payloads_total").inc()
                continue
            if (not isinstance(payload, dict)
                    or payload.get("schema") != SCHEMA):
                self.registry.counter("federation_bad_payloads_total").inc()
                continue
            age = now - float(payload.get("ts", 0.0))
            stale = age > self.stale_after_s
            processes.append({
                "process": payload.get("process", key[len(KV_PREFIX):]),
                "pid": payload.get("pid"), "seq": payload.get("seq"),
                "ts": payload.get("ts"), "age_s": round(age, 3),
                "stale": stale,
            })
            named.append((processes[-1]["process"],
                          payload.get("snapshot") or {}))
        self.registry.counter("federation_collects_total").inc()
        self.registry.gauge("federation_processes").set(
            float(len(processes)))
        self.registry.gauge("federation_stale_processes").set(
            float(sum(p["stale"] for p in processes)))
        return {"schema": SCHEMA, "ts": now,
                "stale_after_s": self.stale_after_s,
                "processes": processes,
                "merged": merge_snapshots(named)}

    def prometheus_snapshot(self) -> Dict[str, Any]:
        """The cluster view as a registry-snapshot-shaped dict ready for
        telemetry/prometheus.render_snapshot — the merged instruments
        plus per-process ``federation_process_up`` (1 fresh / 0 stale)
        and ``federation_process_age_seconds`` gauges (how ``/metrics
        ?scope=cluster`` marks a lapsed pusher)."""
        view = self.collect()
        snap = view["merged"]
        # family-grouped (Prometheus wants a family's samples contiguous)
        for p in view["processes"]:
            snap["gauges"].append({"name": "federation_process_up",
                                   "labels": {"process": str(p["process"])},
                                   "value": 0.0 if p["stale"] else 1.0})
        for p in view["processes"]:
            snap["gauges"].append({
                "name": "federation_process_age_seconds",
                "labels": {"process": str(p["process"])},
                "value": p["age_s"]})
        return snap

    def metrics_record(self) -> Dict[str, float]:
        """The aggregator's own ``federation_*`` health metrics as a flat
        step-log record (same contract as DecodeEngine.metrics_record)."""
        from deeplearning4j_tpu.telemetry.registry import flat_record

        return flat_record(self.registry, prefixes=("federation_",))

    # ---------------------------------------------- cluster alerts (ISSUE 15) ----
    def collect_alerts(self) -> Dict[str, Any]:
        """The cluster-wide alert view: one ``kv_snapshot`` read of every
        process's published alert payload (telemetry/alerts.AlertEngine
        publishes under ``federation.alerts.<process>``), schema-gated
        and staleness-marked exactly like the metric payloads. Alerts
        keep their per-process identity (a ``process`` field per row —
        summing verdicts would destroy the routing signal); ``firing``
        is the cluster-wide count of currently-firing rules — the single
        number a router or hot-swap gate dispatches on."""
        from deeplearning4j_tpu.telemetry.alerts import (
            ALERT_KV_PREFIX,
            SCHEMA as ALERTS_SCHEMA,
        )

        now = time.time()
        try:
            raw = self._tracker.kv_snapshot(ALERT_KV_PREFIX)
        except (ConnectionError, OSError) as exc:
            self.registry.counter("federation_collect_failures_total").inc()
            return {"schema": ALERTS_SCHEMA, "ts": now, "error": str(exc),
                    "stale_after_s": self.stale_after_s,
                    "processes": [], "alerts": [], "firing": 0}
        processes: List[Dict] = []
        alerts: List[Dict] = []
        for key in sorted(raw):
            try:
                payload = json.loads(raw[key])
            except (TypeError, ValueError):
                self.registry.counter("federation_bad_payloads_total").inc()
                continue
            if (not isinstance(payload, dict)
                    or payload.get("schema") != ALERTS_SCHEMA):
                self.registry.counter("federation_bad_payloads_total").inc()
                continue
            process = payload.get("process",
                                  key[len(ALERT_KV_PREFIX):])
            age = now - float(payload.get("ts", 0.0))
            stale = age > self.stale_after_s
            processes.append({"process": process,
                              "pid": payload.get("pid"),
                              "seq": payload.get("seq"),
                              "ts": payload.get("ts"),
                              "age_s": round(age, 3), "stale": stale})
            for row in payload.get("alerts") or []:
                if isinstance(row, dict):
                    alerts.append(dict(row, process=process, stale=stale))
        alerts.sort(key=lambda a: (a.get("state") != "firing",
                                   str(a.get("severity")),
                                   str(a.get("rule"))))
        firing = sum(a.get("state") == "firing" for a in alerts)
        self.registry.gauge("federation_cluster_alerts_firing").set(
            float(firing))
        return {"schema": ALERTS_SCHEMA, "ts": now,
                "stale_after_s": self.stale_after_s,
                "processes": processes, "alerts": alerts,
                "firing": firing}
