"""Prometheus text-format exposition for a MetricsRegistry.

Renders exposition format 0.0.4 (the text format every Prometheus scraper
speaks): one ``# TYPE`` header per metric family, ``{label="value"}`` pairs
escaped per the spec, histograms as cumulative ``_bucket{le=...}`` series
plus ``_sum``/``_count``. Counter families get the conventional ``_total``
suffix unless the name already carries it.

Mounted on ui/server.py at ``GET /metrics``; the golden test in
tests/test_telemetry.py pins the exact output.
"""

from __future__ import annotations

import math
import re
from typing import Dict, List

from deeplearning4j_tpu.telemetry.registry import MetricsRegistry

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize_name(name: str) -> str:
    """Legalize a metric name (statetracker counters use dotted keys like
    ``rounds.worker-0`` — dots and dashes become underscores)."""
    name = _NAME_RE.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _escape_label_value(v: str) -> str:
    return v.replace("\\", r"\\").replace("\n", r"\n").replace('"', r'\"')


def _labels_str(labels: Dict[str, str], extra: str = "") -> str:
    parts = [f'{sanitize_name(k)}="{_escape_label_value(str(v))}"'
             for k, v in sorted(labels.items())]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt(value: float) -> str:
    if math.isinf(value):
        return "+Inf" if value > 0 else "-Inf"
    if float(value) == int(value) and abs(value) < 1e15:
        return str(int(value))
    return repr(float(value))


def render_prometheus(registry: MetricsRegistry) -> str:
    """The full exposition page for one registry."""
    return render_snapshot(registry.snapshot())


def render_snapshot(snap: Dict) -> str:
    """Render any registry-snapshot-shaped dict (``{"counters": [...],
    "gauges": [...], "histograms": [...]}``) — the seam that lets the
    federation aggregator's MERGED cluster view (telemetry/federation.py)
    ship over ``/metrics?scope=cluster`` through the exact renderer the
    per-process page uses."""
    lines: List[str] = []
    seen_type: set = set()

    def type_line(family: str, kind: str) -> None:
        if family not in seen_type:
            lines.append(f"# TYPE {family} {kind}")
            seen_type.add(family)

    for row in snap["counters"]:
        family = sanitize_name(row["name"])
        if not family.endswith("_total"):
            family += "_total"
        type_line(family, "counter")
        lines.append(
            f"{family}{_labels_str(row['labels'])} {_fmt(row['value'])}")

    for row in snap["gauges"]:
        family = sanitize_name(row["name"])
        type_line(family, "gauge")
        lines.append(
            f"{family}{_labels_str(row['labels'])} {_fmt(row['value'])}")

    for row in snap["histograms"]:
        family = sanitize_name(row["name"])
        type_line(family, "histogram")
        # trace exemplars (ISSUE 15): OpenMetrics exemplar syntax appended
        # to the owning bucket's sample line — ``... # {trace_id="…"}
        # <value> <ts>``. Only present when the histogram captured trace
        # ids (a tracer was configured), so a strict text-0.0.4 scrape of
        # an untraced process is byte-identical to the pre-exemplar
        # output (the golden test pins that).
        ex_by_le = {e["le"]: e for e in row.get("exemplars", [])}
        for b in row["buckets"]:
            le_label = 'le="%s"' % _fmt(b["le"])
            labels = _labels_str(row["labels"], le_label)
            line = f"{family}_bucket{labels} {b['count']}"
            ex = ex_by_le.get(b["le"])
            if ex is not None:
                line += (f' # {{trace_id="{_escape_label_value(str(ex["trace_id"]))}"}}'
                         f' {_fmt(ex["value"])} {round(float(ex["ts"]), 3)}')
            lines.append(line)
        lines.append(
            f"{family}_sum{_labels_str(row['labels'])} {_fmt(row['sum'])}")
        lines.append(
            f"{family}_count{_labels_str(row['labels'])} {row['count']}")

    return "\n".join(lines) + ("\n" if lines else "")
