"""Continuous runtime profiler (ISSUE 17): measured step-phase
timelines, streaming utilization gauges, and on-demand profiling
sessions.

``telemetry/xprofile.py`` (ISSUE 9) is an *execution-free* cost model:
it knows what the compiled step SHOULD cost, never where a real step's
wall time went. This module is the measured half — DL4J's
``ProfilerIterationListener`` lineage (the per-phase breakdown
methodology of arXiv:2001.04206) rebuilt as a continuous, low-overhead
runtime profiler. Three coordinated pieces:

**1. Step-phase timers** behind the ``runprof=`` seam (mirroring
``with_metrics``/``guard``/``profile`` on every composed step factory,
the elastic worker model, and the ``DecodeEngine`` scheduler loop).
Each armed step records a ring-buffered :class:`StepTiming` with the
phase model::

    ... previous step returns
    |-- host_ms ------|  host prep: data gen, batching, scheduler work
    |-- dispatch_ms --|  fn(*args) returns (JAX async enqueue wall)
    |-- device_ms ----|  block_until_ready fence (device compute wall)

``comm_wait_ms`` is the xprofile collective inventory's implied wire
time (``collective_wire_bytes / ici_bw``, clamped to the measured
device wall) — an *estimate*, model x measurement, not a counter.
``input_wait_ms`` is hook-fed (:meth:`RunProfiler.note_input_wait`) by
host input pipelines; it defaults to 0 and is a subset of ``host_ms``.

Rings feed streaming registry gauges flushed every ``update_every``
steps (batched so the hot path stays two ``perf_counter`` stamps, one
fence, and a deque append), labeled ``{"step": label}``:

- ``runprof_steps_per_s``   — completed steps over the flush window;
- ``runprof_step_ms``       — mean in-call wall (dispatch + device);
- ``runprof_measured_mfu``  — xprofile FLOPs / measured device seconds
  / peak (STAYS UNBORN until a profiled step supplies FLOPs — the
  ``mfu_collapse`` rule (op ``<``) must read "never measured" as
  no-data, the PR 16 "<"-op pre-arm trap);
- ``runprof_host_fraction`` — host_ms / (host_ms + wall_ms);
- ``runprof_input_wait_fraction`` — input_wait / (host_ms + wall_ms);
- ``runprof_steps_total``   — counter, pre-created at arm time so the
  first flush's increment is visible to rate windows (PR 15).

Gauges live in the ordinary registry, so they federate cluster-wide
through the PR 12 pusher and render in every report with zero extra
wiring.

**2. On-demand sessions**: :meth:`RunProfiler.start_session` /
:meth:`RunProfiler.stop_session` (HTTP ``POST /api/profiling`` on the
UI server, env ``DL4J_TPU_RUNPROF=<N>``) capture an N-step dense
timeline. Every step is WRITE-AHEAD appended as one JSONL line to a
line-buffered sidecar (the PR 7 flight-recorder posture: kill -9
mid-session loses at most a torn tail, which :func:`load_session`
reconstructs around); ``stop_session`` dumps the final JSON atomically
(tmp + ``os.replace``) with a summary and Chrome ``X`` trace events.
Each timing stamps the recording thread's CURRENT trace id
(``trace.current_trace_context``), so the Chrome export merges onto
the PR 7/12 span trees — a serve request's span and the decode step's
device time share one timeline.

**3. Watchtower rules** (``alerts.default_rules``):
``step_time_regression`` (rate-of-change on ``runprof_step_ms``),
``mfu_collapse``, ``input_wait_high`` — fixtures per the PR 15
META-TEST discipline; ``tools/profile_report.py --runtime`` renders
sessions next to the AOT roofline.

Knobs (host-side, blessed ``DL4J_TPU_*`` namespace):

- ``DL4J_TPU_RUNPROF``: arms the default profiler for every factory
  built with ``runprof=None`` (the default). ``1``/``true`` = gauges
  only; an integer N > 1 additionally auto-starts an N-step session at
  first use. ``runprof=False`` opts a factory out regardless.
- ``DL4J_TPU_RUNPROF_DIR``: session dump directory (default
  ``runprof_sessions`` under the CWD).

Measured-vs-modeled caveats (the honesty contract): ``device_ms``
fences the WHOLE out pytree, so it includes any transfer the fence
forces; XLA FLOPs count a scanned body once (xprofile), so
``runprof_measured_mfu`` inherits that undercount on scanned models;
``comm_wait_ms`` is an ICI-bandwidth lower bound, not a measured wait.
The tier-1 cross-check (tests/test_runprof.py) pins
``runprof_measured_mfu`` against bench.py's wall-clock MFU arithmetic
within a documented band.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from deeplearning4j_tpu.utils.lockwatch import make_lock

ENV_RUNPROF = "DL4J_TPU_RUNPROF"
ENV_RUNPROF_DIR = "DL4J_TPU_RUNPROF_DIR"
DEFAULT_SESSION_DIR = "runprof_sessions"
SCHEMA = "dl4j-tpu-runprof-v1"

# the gauges every armed label pre-creates at arm time (PR 15: visible
# baseline before the first flush). runprof_measured_mfu is DELIBERATELY
# absent: the mfu_collapse rule is op "<", so a pre-created 0.0 would
# turn "never measured" into a page (the PR 16 trap, pinned in
# tests/test_alerts.py::test_low_op_rules_not_prearmed_into_firing).
_ARM_GAUGES = ("runprof_steps_per_s", "runprof_step_ms",
               "runprof_host_fraction", "runprof_input_wait_fraction")


@dataclasses.dataclass
class StepTiming:
    """One measured step: the phase model in the module docstring.
    ``t_unix`` stamps the END of the device fence (wall clock);
    ``flops`` rides along when the wrapped step carries an xprofile
    ``step_profile`` so session readers can recompute MFU."""

    label: str
    t_unix: float
    wall_ms: float          # dispatch_ms + device_ms (in-call wall)
    host_ms: float          # gap since the previous step returned
    dispatch_ms: float
    device_ms: float
    comm_wait_ms: float = 0.0
    input_wait_ms: float = 0.0
    trace_id: Optional[str] = None
    flops: Optional[float] = None

    def to_dict(self) -> Dict[str, Any]:
        d = {"label": self.label, "t_unix": round(self.t_unix, 6),
             "wall_ms": round(self.wall_ms, 4),
             "host_ms": round(self.host_ms, 4),
             "dispatch_ms": round(self.dispatch_ms, 4),
             "device_ms": round(self.device_ms, 4),
             "comm_wait_ms": round(self.comm_wait_ms, 4),
             "input_wait_ms": round(self.input_wait_ms, 4)}
        if self.trace_id is not None:
            d["trace_id"] = self.trace_id
        if self.flops is not None:
            d["flops"] = self.flops
        return d


class _LabelState:
    """Per-label accumulator between gauge flushes (not thread-safe on
    its own — mutated under the profiler lock)."""

    __slots__ = ("ring", "n", "sum_wall", "sum_host", "sum_dispatch",
                 "sum_device", "sum_input", "window_t0", "flops",
                 "pending_input_wait_s", "total")

    def __init__(self, ring: int):
        self.ring: deque = deque(maxlen=ring)
        self.n = 0
        self.sum_wall = 0.0
        self.sum_host = 0.0
        self.sum_dispatch = 0.0
        self.sum_device = 0.0
        self.sum_input = 0.0
        self.window_t0: Optional[float] = None
        self.flops: Optional[float] = None
        self.pending_input_wait_s = 0.0
        self.total = 0

    def reset_window(self, t: float) -> None:
        self.n = 0
        self.sum_wall = self.sum_host = 0.0
        self.sum_dispatch = self.sum_device = self.sum_input = 0.0
        self.window_t0 = t


class RunProfiler:
    """Per-process runtime-profile aggregator: labeled step rings,
    streaming gauges, and the session recorder. Thread-safe (steps from
    the train loop, the serve scheduler thread, and HTTP session
    control may interleave); the hot path takes the lock once per
    recorded step and NEVER does I/O or fencing under it."""

    def __init__(self, registry=None, ring: int = 512,
                 update_every: int = 8, session_dir: Optional[str] = None,
                 peak_flops: Optional[float] = None):
        from deeplearning4j_tpu.telemetry.xprofile import DEFAULT_PEAK_FLOPS

        if update_every < 1:
            raise ValueError(f"update_every must be >= 1, got "
                             f"{update_every}")
        self._registry = registry
        self.ring = int(ring)
        self.update_every = int(update_every)
        self.session_dir = session_dir
        self.peak_flops = (float(peak_flops) if peak_flops is not None
                           else DEFAULT_PEAK_FLOPS)
        self._lock = make_lock("telemetry.runprof")  # lockwatch seam
        self._labels: Dict[str, _LabelState] = {}
        # session state: all swapped under the lock, written outside it
        self._session_id: Optional[str] = None
        self._session_fh = None
        self._session_path: Optional[str] = None
        self._session_steps = 0          # 0 = unbounded (explicit stop)
        self._session_records: List[Dict] = []
        self._session_seq = 0
        self.sessions_completed: List[str] = []

    # ------------------------------------------------------------ plumbing ----
    def registry(self):
        if self._registry is None:
            from deeplearning4j_tpu.telemetry.registry import (
                default_registry,
            )

            return default_registry()
        return self._registry

    # ----------------------------------------------------------- instruments ----
    def arm(self, label: str) -> None:
        """Pre-create the watched instruments for ``label`` (idempotent;
        called by every seam wrapper/engine at construction — the PR 15
        first-increment discipline). ``runprof_measured_mfu`` stays
        unborn; see module docstring."""
        reg = self.registry()
        labels = {"step": label}
        reg.counter("runprof_steps_total", labels)
        for name in _ARM_GAUGES:
            reg.gauge(name, labels)
        with self._lock:
            if label not in self._labels:
                self._labels[label] = _LabelState(self.ring)

    def note_input_wait(self, seconds: float, label: str) -> None:
        """The input-wait hook: a host input pipeline reports time spent
        WAITING for data (not preparing it) before the next ``label``
        step; attributed to that step's ``input_wait_ms``."""
        if seconds <= 0:
            return
        with self._lock:
            state = self._labels.get(label)
            if state is None:
                state = self._labels[label] = _LabelState(self.ring)
            state.pending_input_wait_s += float(seconds)

    # -------------------------------------------------------------- recording ----
    def record(self, timing: StepTiming) -> None:
        """Ring-append one measured step, flush gauges every
        ``update_every`` steps, and write-ahead the session line. The
        session file write happens OUTSIDE the lock (one full line per
        write call — the buffered writer's own lock keeps concurrent
        lines whole)."""
        line = None
        stop_after = False
        with self._lock:
            state = self._labels.get(timing.label)
            if state is None:
                state = self._labels[timing.label] = _LabelState(self.ring)
            if state.pending_input_wait_s > 0:
                timing.input_wait_ms += state.pending_input_wait_s * 1000.0
                state.pending_input_wait_s = 0.0
            state.ring.append(timing)
            state.total += 1
            if state.window_t0 is None:
                # first record opens the window at the step's own start
                state.window_t0 = timing.t_unix - (
                    timing.wall_ms + timing.host_ms) / 1000.0
            state.n += 1
            state.sum_wall += timing.wall_ms
            state.sum_host += timing.host_ms
            state.sum_dispatch += timing.dispatch_ms
            state.sum_device += timing.device_ms
            state.sum_input += timing.input_wait_ms
            if timing.flops is not None:
                state.flops = timing.flops
            flush = state.n >= self.update_every
            if flush:
                gauges = self._gauge_values(state, timing.t_unix)
                n_flushed = state.n
                state.reset_window(timing.t_unix)
            if self._session_fh is not None:
                rec = {"ev": "step", "pid": os.getpid(),
                       **timing.to_dict()}
                self._session_records.append(rec)
                line = json.dumps(rec) + "\n"
                fh = self._session_fh
                if (self._session_steps
                        and len(self._session_records)
                        >= self._session_steps):
                    stop_after = True
        if flush:
            reg = self.registry()
            labels = {"step": timing.label}
            reg.counter("runprof_steps_total", labels).inc(n_flushed)
            for name, value in gauges.items():
                reg.gauge(name, labels).set(value)
        if line is not None:
            try:
                fh.write(line)
            except ValueError:
                pass  # session closed between the lock and the write
        if stop_after:
            self.stop_session()

    def _gauge_values(self, state: _LabelState,
                      now_unix: float) -> Dict[str, float]:
        dt = max(now_unix - (state.window_t0 or now_unix), 1e-9)
        cycle_ms = state.sum_host + state.sum_wall
        out = {
            "runprof_steps_per_s": state.n / dt,
            "runprof_step_ms": state.sum_wall / state.n,
            "runprof_host_fraction": (state.sum_host / cycle_ms
                                      if cycle_ms > 0 else 0.0),
            "runprof_input_wait_fraction": (state.sum_input / cycle_ms
                                            if cycle_ms > 0 else 0.0),
        }
        if state.flops and state.sum_device > 0:
            device_s = state.sum_device / state.n / 1000.0
            out["runprof_measured_mfu"] = (
                state.flops / max(device_s, 1e-12) / self.peak_flops)
        return out

    def timings(self, label: str) -> List[StepTiming]:
        with self._lock:
            state = self._labels.get(label)
            return list(state.ring) if state is not None else []

    def snapshot(self) -> Dict[str, Any]:
        """Session + per-label state for ``/api/profiling`` GETs."""
        with self._lock:
            return {
                "schema": SCHEMA,
                "session": ({"id": self._session_id,
                             "path": self._session_path,
                             "steps_captured":
                                 len(self._session_records),
                             "steps_target": self._session_steps}
                            if self._session_id is not None else None),
                "sessions_completed": list(self.sessions_completed),
                "labels": {
                    label: {"steps_total": state.total,
                            "ring": len(state.ring)}
                    for label, state in sorted(self._labels.items())},
            }

    # --------------------------------------------------------------- sessions ----
    def _resolve_dir(self, session_dir: Optional[str]) -> str:
        return (session_dir or self.session_dir
                or os.environ.get(ENV_RUNPROF_DIR) or DEFAULT_SESSION_DIR)

    def start_session(self, steps: int = 0,
                      session_dir: Optional[str] = None) -> str:
        """Open an N-step dense capture (``steps=0`` = until
        ``stop_session``). The JSONL sidecar is line-buffered write-ahead
        from the first step — a kill -9 leaves a reconstructable partial
        dump. One session at a time (RuntimeError otherwise)."""
        out_dir = self._resolve_dir(session_dir)
        os.makedirs(out_dir, exist_ok=True)
        with self._lock:
            if self._session_id is not None:
                raise RuntimeError(
                    f"profiling session {self._session_id} already active")
            self._session_seq += 1
            seq = self._session_seq
        sid = f"{os.getpid()}-{int(time.time() * 1000)}-{seq}"
        path = os.path.join(out_dir, f"runprof_{sid}.jsonl")
        # opened here, never under the lock (blocking-under-lock)
        fh = open(path, "a", buffering=1)
        fh.write(json.dumps({
            "ev": "session_start", "schema": SCHEMA, "session": sid,
            "pid": os.getpid(), "started_unix": time.time(),
            "steps": int(steps)}) + "\n")
        with self._lock:
            if self._session_id is not None:  # lost the race
                stale = self._session_id
                fh.close()
                os.unlink(path)
                raise RuntimeError(
                    f"profiling session {stale} already active")
            self._session_id = sid
            self._session_fh = fh
            self._session_path = path
            self._session_steps = int(steps)
            self._session_records = []
        return sid

    def stop_session(self) -> Optional[str]:
        """Close the capture and dump the final JSON atomically (tmp +
        ``os.replace``) next to the JSONL write-ahead (which is kept —
        it is the crash evidence). Returns the final JSON path, or None
        when no session is active (idempotent)."""
        with self._lock:
            if self._session_id is None:
                return None
            sid = self._session_id
            fh = self._session_fh
            jsonl_path = self._session_path
            records = self._session_records
            self._session_id = None
            self._session_fh = None
            self._session_path = None
            self._session_records = []
            self._session_steps = 0
        fh.close()
        final = {"schema": SCHEMA, "session": sid, "pid": os.getpid(),
                 "partial": False, "steps": records,
                 "summary": summarize_session(records,
                                              peak_flops=self.peak_flops),
                 "chrome_trace": chrome_trace_events(records)}
        json_path = jsonl_path[:-len(".jsonl")] + ".json"
        tmp = f"{json_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as out:
            json.dump(final, out)
        os.replace(tmp, json_path)
        with self._lock:
            self.sessions_completed.append(json_path)
        return json_path

    @property
    def session_active(self) -> bool:
        with self._lock:
            return self._session_id is not None


# ------------------------------------------------------------ session readers ----

def _percentile(values: List[float], q: float) -> float:
    s = sorted(values)
    import math

    return s[min(len(s) - 1, max(0, math.ceil(q / 100 * len(s)) - 1))]


def summarize_session(records: List[Dict],
                      peak_flops: Optional[float] = None) -> Dict:
    """Phase breakdown of a session's step records (dict form). MFU is
    recomputed from the per-step ``flops`` stamps so a reconstructed
    partial dump gets the same summary the live dump would have."""
    if peak_flops is None:
        from deeplearning4j_tpu.telemetry.xprofile import DEFAULT_PEAK_FLOPS

        peak_flops = DEFAULT_PEAK_FLOPS
    steps = [r for r in records if r.get("ev", "step") == "step"]
    if not steps:
        return {"steps": 0}
    walls = [float(r.get("wall_ms", 0.0)) for r in steps]
    out: Dict[str, Any] = {
        "steps": len(steps),
        "wall_ms": {"mean": round(sum(walls) / len(walls), 4),
                    "p50": round(_percentile(walls, 50), 4),
                    "p95": round(_percentile(walls, 95), 4)},
    }
    for key in ("host_ms", "dispatch_ms", "device_ms", "comm_wait_ms",
                "input_wait_ms"):
        vals = [float(r.get(key, 0.0)) for r in steps]
        out[f"{key}_mean"] = round(sum(vals) / len(vals), 4)
    span_s = (float(steps[-1].get("t_unix", 0.0))
              - float(steps[0].get("t_unix", 0.0)))
    if span_s > 0 and len(steps) > 1:
        out["steps_per_s"] = round((len(steps) - 1) / span_s, 3)
    cycle = out["host_ms_mean"] + out["wall_ms"]["mean"]
    if cycle > 0:
        out["host_fraction"] = round(out["host_ms_mean"] / cycle, 4)
        out["input_wait_fraction"] = round(
            out["input_wait_ms_mean"] / cycle, 4)
    flops = [float(r["flops"]) for r in steps if r.get("flops")]
    if flops and out["device_ms_mean"] > 0:
        out["measured_mfu"] = (
            flops[-1] / (out["device_ms_mean"] / 1000.0) / peak_flops)
    return out


def chrome_trace_events(records: List[Dict]) -> List[Dict]:
    """Chrome ``X`` (complete) events for the phase slices of every step
    record, epoch-microsecond timestamps — the same clock the tracer's
    span dumps use, so loading both into one viewer lines them up, and
    ``args.trace_id`` carries the span-tree linkage (same trace ids)."""
    events: List[Dict] = []
    for i, r in enumerate(records):
        if r.get("ev", "step") != "step":
            continue
        label = r.get("label", "step")
        pid = r.get("pid", 0)
        end_us = float(r.get("t_unix", 0.0)) * 1e6
        device_us = float(r.get("device_ms", 0.0)) * 1e3
        dispatch_us = float(r.get("dispatch_ms", 0.0)) * 1e3
        host_us = float(r.get("host_ms", 0.0)) * 1e3
        args = {"step_index": i}
        if r.get("trace_id"):
            args["trace_id"] = r["trace_id"]
        for name, ts, dur in (
                ("host", end_us - device_us - dispatch_us - host_us,
                 host_us),
                ("dispatch", end_us - device_us - dispatch_us,
                 dispatch_us),
                ("device", end_us - device_us, device_us)):
            if dur <= 0:
                continue
            events.append({"name": f"{label}.{name}", "cat": "runprof",
                           "ph": "X", "pid": pid, "tid": label,
                           "ts": round(ts, 1), "dur": round(dur, 1),
                           "args": args})
    return events


def load_session(path: str) -> Dict:
    """Load a session dump. A final ``.json`` loads directly; a
    ``.jsonl`` write-ahead (killed session) is reconstructed with torn
    trailing lines tolerated and counted — the report renders a partial
    session rather than refusing the evidence. Given a ``.json`` path
    that does not exist yet, falls back to its ``.jsonl`` sidecar."""
    if path.endswith(".json"):
        if os.path.isfile(path):
            with open(path) as fh:
                out = json.load(fh)
            out.setdefault("partial", False)
            return out
        path = path[:-len(".json")] + ".jsonl"
    sid = None
    records: List[Dict] = []
    torn = 0
    with open(path) as fh:
        for line in fh:
            try:
                rec = json.loads(line)
            except ValueError:
                torn += 1  # kill -9 mid-write: count, keep going
                continue
            if rec.get("ev") == "session_start":
                sid = rec.get("session")
            elif rec.get("ev") == "step":
                records.append(rec)
    return {"schema": SCHEMA, "session": sid, "partial": True,
            "torn_lines": torn, "steps": records,
            "summary": summarize_session(records),
            "chrome_trace": chrome_trace_events(records)}


def find_sessions(session_dir: str) -> List[Dict]:
    """Every session under ``session_dir``, final dumps preferred,
    killed sessions reconstructed from their write-ahead sidecars."""
    out = []
    if not os.path.isdir(session_dir):
        return out
    names = sorted(os.listdir(session_dir))
    finals = {n[:-len(".json")] for n in names if n.endswith(".json")}
    for name in names:
        base = None
        if name.endswith(".json"):
            base = name[:-len(".json")]
        elif name.endswith(".jsonl") and name[:-len(".jsonl")] not in finals:
            base = name[:-len(".jsonl")]
        else:
            continue
        if not base.startswith("runprof_"):
            continue
        try:
            out.append(load_session(os.path.join(session_dir, name)))
        except (OSError, ValueError):
            continue
    return out


# ---------------------------------------------------------------- runprof= seam ----

class RunProfiledStep:
    """The ``runprof=`` seam wrapper: phase-timed execution of a jitted
    step. When the wrapped fn can ``lower`` and does not already carry a
    ``step_profile``, it is composed over an xprofile ``ProfiledStep``
    (ONE AOT compile, shared executable) so the collective inventory and
    FLOPs feed ``comm_wait_ms`` and ``runprof_measured_mfu``; a fn
    without ``lower`` still gets wall/phase timings, and the MFU gauge
    simply stays unborn.

    The fence (``jax.block_until_ready`` on the whole output pytree)
    serializes host and device when armed — that is the measurement
    contract, and why the default (``runprof=None`` without the env
    knob) returns the fn untouched."""

    def __init__(self, fn, label: str = "step",
                 profiler: Optional[RunProfiler] = None):
        from deeplearning4j_tpu.telemetry.xprofile import ProfiledStep

        if (not hasattr(fn, "step_profile") and hasattr(fn, "lower")
                and not isinstance(fn, ProfiledStep)):
            fn = ProfiledStep(fn, label=label)
        self._fn = fn
        self.label = label
        self.profiler = (profiler if profiler is not None
                         else default_runprof())
        self._last_end: Optional[float] = None
        self.profiler.arm(label)

    @property
    def step_profile(self):
        return getattr(self._fn, "step_profile", None)

    def lower(self, *args, **kwargs):
        return self._fn.lower(*args, **kwargs)

    def __call__(self, *args):
        import jax

        from deeplearning4j_tpu.telemetry import trace as _trace
        from deeplearning4j_tpu.telemetry.xprofile import (
            DEFAULT_ICI_BYTES_PER_SEC,
        )

        t0 = time.perf_counter()
        host_ms = ((t0 - self._last_end) * 1000.0
                   if self._last_end is not None else 0.0)
        out = self._fn(*args)
        t_disp = time.perf_counter()  # enqueue returned; device running
        jax.block_until_ready(out)
        t_end = time.perf_counter()
        device_ms = (t_end - t_disp) * 1000.0
        dispatch_ms = (t_disp - t0) * 1000.0
        prof = getattr(self._fn, "step_profile", None)
        comm_wait_ms = 0.0
        flops = None
        if prof is not None:
            flops = prof.flops
            wire = prof.collective_wire_bytes or 0.0
            if wire:
                comm_wait_ms = min(
                    device_ms, wire / DEFAULT_ICI_BYTES_PER_SEC * 1000.0)
        ctx = _trace.current_trace_context()
        self.profiler.record(StepTiming(
            label=self.label, t_unix=time.time(),
            wall_ms=dispatch_ms + device_ms, host_ms=host_ms,
            dispatch_ms=dispatch_ms, device_ms=device_ms,
            comm_wait_ms=comm_wait_ms,
            trace_id=ctx["trace_id"] if ctx else None, flops=flops))
        self._last_end = time.perf_counter()
        return out


# ------------------------------------------------------------- process default ----

_default_profiler: Optional[RunProfiler] = None
_default_profiler_lock = threading.Lock()


def default_runprof() -> RunProfiler:
    """The process-wide profiler (like ``default_profile_store``); the
    one the env knob and the UI route reach. Honors the env knob's
    auto-session request (``DL4J_TPU_RUNPROF=<N>``, N > 1) at creation."""
    global _default_profiler
    with _default_profiler_lock:
        if _default_profiler is None:
            _default_profiler = RunProfiler()
            n = _env_auto_session_steps()
            if n:
                try:
                    _default_profiler.start_session(steps=n)
                except OSError:
                    pass  # an unwritable dump dir must not kill training
        return _default_profiler


def get_runprof() -> Optional[RunProfiler]:
    """The default profiler if one exists (None before first use)."""
    return _default_profiler


def set_runprof(profiler: Optional[RunProfiler]) -> None:
    """Swap the process default (tests; None resets)."""
    global _default_profiler
    with _default_profiler_lock:
        _default_profiler = profiler


def _env_value() -> Optional[str]:
    val = os.environ.get(ENV_RUNPROF, "").strip()
    if not val or val.lower() in ("0", "false", "off", "no"):
        return None
    return val


def _env_auto_session_steps() -> int:
    val = _env_value()
    if val is None:
        return 0
    try:
        n = int(val)
    except ValueError:
        return 0
    return n if n > 1 else 0


def resolve_runprof(runprof) -> Optional[RunProfiler]:
    """Coerce a seam argument to a profiler or None. ``None`` consults
    the env knob (the "always-on when asked" default); any other falsy
    value is an explicit opt-out; ``True``/a string use the process
    default; a :class:`RunProfiler` is used as-is."""
    if runprof is None:
        return default_runprof() if _env_value() is not None else None
    if not runprof:
        return None
    if isinstance(runprof, RunProfiler):
        return runprof
    return default_runprof()


def maybe_runprof(fn, runprof, label: str):
    """Builder helper mirroring ``maybe_profiled``: wrap ``fn`` in a
    :class:`RunProfiledStep` when the seam resolves armed (a string
    overrides the label), else return ``fn`` unchanged — the zero-cost
    default."""
    profiler = resolve_runprof(runprof)
    if profiler is None:
        return fn
    return RunProfiledStep(
        fn, label=runprof if isinstance(runprof, str) else label,
        profiler=profiler)
