"""JSONL step-event log: one line per training step.

Each record is a flat JSON object ``{ts, step, wall_ms, tokens_per_sec,
<metric>: <value>, ...}`` — the machine-readable twin of the reference's
ScoreIterationListener log lines, consumable by tools/telemetry_report.py
and by anything that tails a file. Writes are line-buffered and the writer
is append-safe across close/reopen (a listener chain may be closed by one
fit and reused by the next).
"""

from __future__ import annotations

import json
import math
import os
import statistics
import time
from typing import Dict, List, Optional


def _jsonable(v):
    if hasattr(v, "tolist"):  # numpy / jax scalars and arrays
        v = v.tolist()
    if isinstance(v, float) and not math.isfinite(v):
        return repr(v)  # JSONL stays parseable even on a NaN/inf blow-up
    return v


class StepLogWriter:
    """Append step events to ``path`` as JSONL.

    ``static`` fields (run metadata: mesh shape, attention impl, model dims)
    are merged into every record so each line is self-describing.
    """

    def __init__(self, path: str, static: Optional[Dict] = None):
        self.path = path
        self.static = {k: _jsonable(v) for k, v in (static or {}).items()}
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self._fh = open(path, "a", buffering=1)

    def write(self, step: int, wall_ms: Optional[float] = None,
              tokens_per_sec: Optional[float] = None, **metrics) -> Dict:
        rec = {"ts": time.time(), "step": int(step)}
        if wall_ms is not None:
            rec["wall_ms"] = round(float(wall_ms), 3)
        if tokens_per_sec is not None:
            rec["tokens_per_sec"] = round(float(tokens_per_sec), 1)
        rec.update(self.static)
        for k, v in metrics.items():
            rec[k] = _jsonable(v)
        if self._fh is None:  # reopened chain (close() is not terminal)
            self._fh = open(self.path, "a", buffering=1)
        self._fh.write(json.dumps(rec) + "\n")
        return rec

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self) -> "StepLogWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_step_log(path: str) -> List[Dict]:
    """Parse a JSONL step log back into records (skips blank lines).

    A malformed line raises ``ValueError`` naming the file and line
    number — a truncated log (writer killed mid-line) or a corrupted one
    is a clear diagnostic for callers (tools/telemetry_report.py turns it
    into a message + nonzero exit), never a bare JSONDecodeError
    traceback pointing at nothing.
    """
    out = []
    with open(path) as fh:
        for lineno, line in enumerate(fh, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError as exc:
                raise ValueError(
                    f"step log {path} is truncated or corrupt at line "
                    f"{lineno}: {exc}") from exc
    return out


_NONFINITE_REPRS = ("nan", "inf", "-inf")
_NON_METRIC_KEYS = ("ts", "step")


def _is_nonfinite_value(v) -> bool:
    """A value the writer preserved as a non-finite marker: the repr string
    ``_jsonable`` emits ('nan'/'inf'/'-inf'), or a raw non-finite float
    (records built in-process, never serialized)."""
    if isinstance(v, str):
        return v.strip().lower() in _NONFINITE_REPRS
    return isinstance(v, float) and not math.isfinite(v)


def nonfinite_counts(records: List[Dict]) -> Dict[str, int]:
    """Per-metric count of non-finite values across the log — the
    numerical-fault signal the report must SHOUT about, not silently
    repr (step_log preserves them; this surfaces them)."""
    counts: Dict[str, int] = {}
    for r in records:
        for k, v in r.items():
            if k in _NON_METRIC_KEYS:
                continue
            vals = v if isinstance(v, list) else [v]
            n = sum(1 for vi in vals if _is_nonfinite_value(vi))
            if n:
                counts[k] = counts.get(k, 0) + n
    return counts


def summarize_step_log(records: List[Dict]) -> Dict:
    """Aggregate a step log into the throughput/grad-norm summary the
    bench detail and tools/telemetry_report.py print.

    Returns {steps, wall_ms: {mean, p50, p95, p99}, tokens_per_sec_mean,
    loss: {first, last}, grad_norm: {first, last}, router_load_mean}.
    Absent fields are simply omitted. When any metric carried NaN/Inf
    values a ``nonfinite`` {metric: count} map is included (plus
    ``skipped_steps``/``clipped_steps`` totals when the guardrails
    counters are in the log) — downstream reports flag these loudly.
    """
    if not records:
        return {"steps": 0}
    out: Dict = {"steps": len(records)}

    def series(key):
        return [r[key] for r in records
                if isinstance(r.get(key), (int, float))]

    bad = nonfinite_counts(records)
    if bad:
        out["nonfinite"] = bad
    for key, name in (("nonfinite", "skipped_steps"),
                      ("clipped", "clipped_steps")):
        vals = series(key)
        if vals:
            out[name] = int(sum(v > 0 for v in vals))

    walls = series("wall_ms")
    if walls:
        s = sorted(walls)

        def pct(q):
            return s[min(len(s) - 1, max(0, math.ceil(q / 100 * len(s)) - 1))]

        out["wall_ms"] = {"mean": round(statistics.fmean(walls), 3),
                          "p50": round(pct(50), 3),
                          "p95": round(pct(95), 3),
                          "p99": round(pct(99), 3)}
    tps = series("tokens_per_sec")
    if tps:
        out["tokens_per_sec_mean"] = round(statistics.fmean(tps), 1)
    # moment_norm_* / lamb_trust_ratio: the ISSUE 13 optimizer-health
    # block (optimize/updaters.opt_update(with_metrics=True)) — absent on
    # plain-SGD runs, so the rows are simply omitted (silent-when-absent
    # pinned both ways in tests/test_updaters.py)
    for key in ("loss", "score", "grad_norm", "param_norm", "update_ratio",
                "moe_dropped_frac", "moment_norm_m", "moment_norm_v",
                "lamb_trust_ratio"):
        vals = series(key)
        if vals:
            out[key] = {"first": round(vals[0], 6), "last": round(vals[-1], 6)}
    loads = [r["router_load"] for r in records
             if isinstance(r.get("router_load"), list)]
    if loads:
        n = len(loads)
        out["router_load_mean"] = [
            round(sum(l[e] for l in loads) / n, 4)
            for e in range(len(loads[0]))
        ]
    # lockwatch hold/contention metrics (ISSUE 11): records carrying
    # ``lockwatch_*`` keys (utils.lockwatch.metrics_record) surface as
    # one block — values are cumulative/max, so the latest wins. Absent
    # keys mean the watch was off; the block is simply omitted.
    lockwatch: Dict = {}
    for r in records:
        for k, v in r.items():
            if k.startswith("lockwatch_") and isinstance(v, (int, float)):
                lockwatch[k] = v
    if lockwatch:
        out["lockwatch"] = lockwatch
    # serve + federation registry metrics (ISSUE 12): records carrying
    # ``serve_*`` / ``federation_*`` keys (DecodeEngine.metrics_record /
    # federation metrics_record via registry.flat_record) surface as one
    # block each — cumulative registry values, so the latest record wins;
    # absent keys mean the subsystem never ran and the block is omitted
    # alerts_/history_ (ISSUE 15): the watchtower's own health metrics,
    # same silent-when-absent contract (pinned by the ISSUE 15 meta-test)
    # runprof_ (ISSUE 17): the runtime profiler's gauges, same contract
    # netwatch_ (ISSUE 18): per-endpoint socket-watch counters
    # (utils.netwatch.metrics_record), same contract
    for prefix, block_key in (("serve_", "serve"),
                              ("federation_", "federation"),
                              ("alerts_", "alerts"),
                              ("history_", "history"),
                              ("runprof_", "runprof"),
                              ("netwatch_", "netwatch")):
        block: Dict = {}
        for r in records:
            for k, v in r.items():
                if k.startswith(prefix) and isinstance(v, (int, float)):
                    block[k] = v
        if block:
            out[block_key] = block
    return out
