"""Host-side metrics registry: counters, gauges, histograms with labels.

The reference's observability is scattered (StateTracker counters, StopWatch
fields in the YARN worker, per-listener logging); this registry is the one
place every host-side signal lands so one exporter (telemetry/prometheus.py,
the UI's ``/metrics`` and ``/api/telemetry`` routes) can serve all of it.

Semantics follow the Prometheus client model:

- ``Counter`` — monotically increasing float (``inc``; negative increments
  are rejected).
- ``Gauge`` — a float that can go anywhere (``set``/``inc``).
- ``Histogram`` — cumulative bucket counts over fixed ``le`` upper bounds
  plus ``sum``/``count`` (an implicit ``+Inf`` bucket always exists).

Instruments are identified by (name, labels); ``counter/gauge/histogram``
are get-or-create so call sites never need registration ceremony. All
operations are thread-safe — scaleout workers on many threads report into
one registry (the StateTracker mirror in scaleout/statetracker.py).

Concurrency model (audited for ISSUE 7 — the AsyncCheckpointer writer
thread, tracker server handler threads, UI request threads, and the
tracer all hit one registry concurrently with training-loop writers):

- every instrument guards its state with its own ``threading.Lock``;
  ``inc``/``set``/``observe`` and the read properties are atomic, so N
  threads × M increments always total exactly N·M (pinned in
  tests/test_telemetry.py::TestRegistryConcurrency);
- the registry's get-or-create maps are guarded by one ``RLock``;
  instrument methods never take the registry lock, so there is no
  lock-ordering cycle (``snapshot`` takes registry → instrument, never
  the reverse);
- ``snapshot()`` is per-instrument-consistent, not globally atomic: a
  scrape racing writers sees each instrument's value at *some* point
  during the scrape — fine for monitoring, not a barrier;
- cross-PROCESS isolation is deliberate: elastic worker OS processes
  each have their own ``default_registry()`` (fork/spawn copies share
  nothing after start). Cross-process aggregation goes through the
  tracker's counters (``counters_snapshot``) and the per-process flight
  recorder dumps, never through shared registry memory.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.utils.lockwatch import make_rlock

# per-iteration wall-clock style measurements land in milliseconds
DEFAULT_BUCKETS: Tuple[float, ...] = (
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0,
)

LabelDict = Optional[Dict[str, str]]


def _now() -> float:
    return time.time()


def _current_trace_id() -> Optional[str]:
    """The calling thread's current span's trace id, or None when
    tracing is off / no span is open — the zero-cost exemplar capture
    seam (ISSUE 15). Imported lazily: trace.py pulls default_registry
    from here, so a top-level import would cycle."""
    from deeplearning4j_tpu.telemetry import trace as _trace

    tracer = _trace.get_tracer()
    if tracer is None:
        return None
    sp = tracer.current_span()
    return sp.trace_id if sp is not None else None


def _label_key(labels: LabelDict) -> Tuple[Tuple[str, str], ...]:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, by: float = 1.0) -> None:
        if by < 0:
            raise ValueError(f"counter increment must be >= 0, got {by}")
        with self._lock:
            self._value += by

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, by: float = 1.0) -> None:
        with self._lock:
            self._value += by

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    def __init__(self, buckets: Sequence[float] = DEFAULT_BUCKETS) -> None:
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs:
            raise ValueError("histogram needs at least one bucket bound")
        self._lock = threading.Lock()
        self.bounds = bs
        self._counts = [0] * (len(bs) + 1)  # last slot = +Inf
        self._sum = 0.0
        self._count = 0
        # trace exemplars (ISSUE 15): per-bucket latest {trace_id, value,
        # ts} — the metrics→trace correlation hook. Empty unless a trace
        # id was captured, so snapshots/rendering are unchanged when
        # tracing is off.
        self._exemplars: Dict[int, Dict] = {}

    def observe(self, value: float, exemplar: Optional[str] = None) -> None:
        """Record one observation. ``exemplar`` optionally attaches a
        trace id to the observation's bucket (latest wins per bucket);
        with ``exemplar=None`` the calling thread's CURRENT span (the
        process tracer's) is captured when one is open — a dict lookup
        when tracing is off, nothing stored when no span is current."""
        value = float(value)
        if exemplar is None:
            exemplar = _current_trace_id()
        with self._lock:
            self._sum += value
            self._count += 1
            for i, b in enumerate(self.bounds):
                if value <= b:
                    idx = i
                    break
            else:
                idx = len(self.bounds)
            self._counts[idx] += 1
            if exemplar is not None:
                self._exemplars[idx] = {"trace_id": str(exemplar),
                                        "value": value, "ts": _now()}

    def exemplars(self) -> List[Dict]:
        """Recorded exemplars, one per bucket that has one:
        ``{"le", "trace_id", "value", "ts"}`` sorted by bucket bound."""
        with self._lock:
            bounds = list(self.bounds) + [float("inf")]
            return [{"le": bounds[i], **dict(self._exemplars[i])}
                    for i in sorted(self._exemplars)]

    def snapshot(self) -> Dict:
        """Cumulative bucket counts (Prometheus ``le`` semantics) + sum/count.
        Carries an ``exemplars`` list only when trace exemplars were
        captured (absent otherwise — downstream consumers that predate
        them see the exact old shape)."""
        with self._lock:
            cum, acc = [], 0
            for i, b in enumerate(self.bounds):
                acc += self._counts[i]
                cum.append({"le": b, "count": acc})
            cum.append({"le": float("inf"), "count": acc + self._counts[-1]})
            out = {"buckets": cum, "sum": self._sum, "count": self._count}
            if self._exemplars:
                bounds = list(self.bounds) + [float("inf")]
                out["exemplars"] = [
                    {"le": bounds[i], **dict(self._exemplars[i])}
                    for i in sorted(self._exemplars)]
            return out

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def percentile(self, q: float) -> float:
        """Approximate q-th percentile (bucket upper bound that covers it)."""
        snap = self.snapshot()
        total = snap["count"]
        if total == 0:
            return 0.0
        rank = q / 100.0 * total
        for b in snap["buckets"]:
            if b["count"] >= rank:
                return b["le"]
        return snap["buckets"][-1]["le"]


class MetricsRegistry:
    """Get-or-create instrument store keyed by (name, sorted labels)."""

    def __init__(self) -> None:
        # lockwatch seam (ISSUE 11): the get-or-create map lock is the
        # one every control-plane thread crosses; instrument locks stay
        # plain (hot path, self-contained critical sections)
        self._lock = make_rlock("telemetry.registry")
        self._counters: Dict[Tuple, Counter] = {}
        self._gauges: Dict[Tuple, Gauge] = {}
        self._histograms: Dict[Tuple, Histogram] = {}

    def counter(self, name: str, labels: LabelDict = None) -> Counter:
        key = (name, _label_key(labels))
        with self._lock:
            if key not in self._counters:
                self._counters[key] = Counter()
            return self._counters[key]

    def gauge(self, name: str, labels: LabelDict = None) -> Gauge:
        key = (name, _label_key(labels))
        with self._lock:
            if key not in self._gauges:
                self._gauges[key] = Gauge()
            return self._gauges[key]

    def histogram(self, name: str, labels: LabelDict = None,
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        key = (name, _label_key(labels))
        with self._lock:
            if key not in self._histograms:
                self._histograms[key] = Histogram(buckets)
            return self._histograms[key]

    def snapshot(self) -> Dict:
        """JSON-ready view of every instrument (the UI's /api/telemetry)."""

        def rows(store, value_of) -> List[Dict]:
            return [
                {"name": name, "labels": dict(label_key),
                 **value_of(inst)}
                for (name, label_key), inst in sorted(store.items())
            ]

        with self._lock:
            return {
                "counters": rows(self._counters,
                                 lambda c: {"value": c.value}),
                "gauges": rows(self._gauges, lambda g: {"value": g.value}),
                "histograms": rows(self._histograms,
                                   lambda h: h.snapshot()),
            }


def flat_record(registry: "MetricsRegistry",
                prefixes: Sequence[str] = ()) -> Dict[str, float]:
    """Flatten a registry snapshot into a step-log-ready ``{name: value}``
    dict (ISSUE 12): counters sum across label sets, gauges last-write
    (unlabeled name wins last), histograms contribute ``<name>_count`` /
    ``<name>_sum``. ``prefixes`` restricts to names starting with any of
    them (empty = everything). This is the one flattening every
    ``metrics_record()`` emitter uses, so a NEW instrument under a
    rendered prefix automatically reaches tools/telemetry_report.py."""
    snap = registry.snapshot()

    def keep(name: str) -> bool:
        return not prefixes or any(name.startswith(p) for p in prefixes)

    out: Dict[str, float] = {}
    for row in snap["counters"]:
        if keep(row["name"]):
            out[row["name"]] = out.get(row["name"], 0.0) + row["value"]
    for row in snap["gauges"]:
        if keep(row["name"]):
            out[row["name"]] = row["value"]
    for row in snap["histograms"]:
        if keep(row["name"]):
            out[f"{row['name']}_count"] = (
                out.get(f"{row['name']}_count", 0.0) + row["count"])
            out[f"{row['name']}_sum"] = (
                out.get(f"{row['name']}_sum", 0.0) + row["sum"])
    return out


# process-wide default registry: the zero-ceremony path for listeners, the
# statetracker mirror, and the UI server (explicit registries compose fine)
_default: Optional[MetricsRegistry] = None
_default_lock = threading.Lock()


def default_registry() -> MetricsRegistry:
    global _default
    with _default_lock:
        if _default is None:
            _default = MetricsRegistry()
        return _default
