"""Unified training telemetry (ISSUE 2).

Three layers, one subsystem:

- **in-graph** (telemetry/metrics.py): grad/param global norms, update
  ratio, router load — dicts of device scalars computed inside the jitted
  step, parity-safe (0 ulp vs the unthreaded step);
- **host** (registry.py / step_log.py / session.py): labeled
  counters/gauges/histograms, the JSONL step-event log, and TrainTelemetry
  which buffers device metrics and syncs once per N steps;
- **export** (prometheus.py + ui/server.py routes): Prometheus text format
  at ``/metrics``, JSON snapshot at ``/api/telemetry``, device memory at
  ``/api/memory``.

The listener chain bridges in via optimize/listeners.MetricsIterationListener
and the scaleout counters via the statetracker registry mirror.
"""

from deeplearning4j_tpu.telemetry.metrics import (
    global_norm,
    train_step_metrics,
    update_metrics,
)
from deeplearning4j_tpu.telemetry.prometheus import (
    CONTENT_TYPE as PROMETHEUS_CONTENT_TYPE,
    render_prometheus,
    sanitize_name,
)
from deeplearning4j_tpu.telemetry.registry import (
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
)
from deeplearning4j_tpu.telemetry.session import (
    DEFAULT_INTERVAL,
    TrainTelemetry,
)
from deeplearning4j_tpu.telemetry.step_log import (
    StepLogWriter,
    read_step_log,
    summarize_step_log,
)

__all__ = [
    "Counter",
    "DEFAULT_BUCKETS",
    "DEFAULT_INTERVAL",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "PROMETHEUS_CONTENT_TYPE",
    "StepLogWriter",
    "TrainTelemetry",
    "default_registry",
    "global_norm",
    "read_step_log",
    "render_prometheus",
    "sanitize_name",
    "summarize_step_log",
    "train_step_metrics",
    "update_metrics",
]
