"""Unified training telemetry (ISSUE 2).

Three layers, one subsystem:

- **in-graph** (telemetry/metrics.py): grad/param global norms, update
  ratio, router load — dicts of device scalars computed inside the jitted
  step, parity-safe (0 ulp vs the unthreaded step);
- **host** (registry.py / step_log.py / session.py): labeled
  counters/gauges/histograms, the JSONL step-event log, and TrainTelemetry
  which buffers device metrics and syncs once per N steps;
- **export** (prometheus.py + ui/server.py routes): Prometheus text format
  at ``/metrics``, JSON snapshot at ``/api/telemetry``, device memory at
  ``/api/memory``, live trace spans at ``/api/trace``;
- **tracing** (trace.py, ISSUE 7): span-based distributed tracing across
  the elastic control plane (context propagation over the tracker frame
  protocol and blob metas) + a per-process crash flight recorder dumped
  on error/SIGTERM and checkpointed write-ahead at round boundaries —
  merged into round timelines by tools/trace_report.py;
- **watch** (history.py + alerts.py, ISSUE 15): a bounded time-series
  history sampled from the registry (range/rate/delta queries, windowed
  histogram-delta percentiles, crash-readable JSONL spill) and a
  declarative alert engine over it (threshold / rate-of-change /
  absence-staleness / burn-rate SLO rules with for_s hysteresis) whose
  firing verdicts bump ``alerts_firing``, dump flight-recorder
  forensics, and publish into the tracker KV for the cluster alert view
  — served at ``/api/history`` and ``/api/alerts``, reported by
  tools/alert_report.py;
- **federation** (federation.py, ISSUE 12): per-process registries
  pushed as versioned JSON snapshots through the StateTracker KV map and
  merged into one cluster view (counters sum, gauges per-process,
  histograms bucket-merge, lapsed pushers marked stale) served at
  ``/api/cluster`` and ``/metrics?scope=cluster``;
- **performance attribution** (xprofile.py, ISSUE 9): compile-time
  introspection of every jitted step behind the ``profile=`` seam —
  XLA cost/memory analysis, HLO collective inventory, measured-MFU /
  roofline attribution, live memory watermarks, served at
  ``/api/profile`` and reported by tools/profile_report.py;
- **runtime profiling** (runprof.py, ISSUE 17): measured step-phase
  timelines behind the ``runprof=`` seam — ring-buffered host/dispatch/
  device/comm-wait breakdowns, streaming ``runprof_*`` gauges (steps/s,
  measured MFU, host + input-wait fractions), and on-demand N-step
  capture sessions (write-ahead JSONL + atomic JSON + Chrome trace
  events on the span-tree trace ids) controlled at ``/api/profiling``
  or ``DL4J_TPU_RUNPROF``, rendered by
  ``tools/profile_report.py --runtime``.

The listener chain bridges in via optimize/listeners.MetricsIterationListener
and the scaleout counters via the statetracker registry mirror.
"""

from deeplearning4j_tpu.telemetry.metrics import (
    global_norm,
    train_step_metrics,
    update_metrics,
)
from deeplearning4j_tpu.telemetry.federation import (
    ClusterAggregator,
    MetricsPusher,
    merge_snapshots,
)
from deeplearning4j_tpu.telemetry.alerts import (
    AlertEngine,
    AlertRule,
    Watchtower,
    arm_watchtower,
    default_rules,
    get_engine,
    set_engine,
)
from deeplearning4j_tpu.telemetry.history import (
    MetricsHistory,
    get_history,
    read_spill,
    replay_spill,
    set_history,
)
from deeplearning4j_tpu.telemetry.prometheus import (
    CONTENT_TYPE as PROMETHEUS_CONTENT_TYPE,
    render_prometheus,
    render_snapshot,
    sanitize_name,
)
from deeplearning4j_tpu.telemetry.registry import (
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
    flat_record,
)
from deeplearning4j_tpu.telemetry.session import (
    DEFAULT_INTERVAL,
    TrainTelemetry,
)
from deeplearning4j_tpu.telemetry.trace import (
    Span,
    Tracer,
    current_trace_context,
    format_traceparent,
    get_tracer,
    maybe_span,
    parse_traceparent,
    set_tracer,
)
from deeplearning4j_tpu.telemetry.step_log import (
    StepLogWriter,
    read_step_log,
    summarize_step_log,
)
from deeplearning4j_tpu.telemetry.runprof import (
    RunProfiledStep,
    RunProfiler,
    StepTiming,
    chrome_trace_events,
    default_runprof,
    find_sessions,
    get_runprof,
    load_session,
    maybe_runprof,
    resolve_runprof,
    set_runprof,
    summarize_session,
)
from deeplearning4j_tpu.telemetry.xprofile import (
    MemoryWatermarkSampler,
    ProfiledStep,
    ProfileStore,
    StepProfile,
    attribute,
    default_profile_store,
    profile_compiled,
    profile_lowered,
)

__all__ = [
    "AlertEngine",
    "AlertRule",
    "ClusterAggregator",
    "Counter",
    "DEFAULT_BUCKETS",
    "DEFAULT_INTERVAL",
    "Gauge",
    "Histogram",
    "MemoryWatermarkSampler",
    "MetricsHistory",
    "MetricsPusher",
    "MetricsRegistry",
    "PROMETHEUS_CONTENT_TYPE",
    "ProfileStore",
    "ProfiledStep",
    "RunProfiledStep",
    "RunProfiler",
    "Span",
    "StepLogWriter",
    "StepProfile",
    "StepTiming",
    "Tracer",
    "TrainTelemetry",
    "Watchtower",
    "arm_watchtower",
    "attribute",
    "chrome_trace_events",
    "default_profile_store",
    "default_runprof",
    "find_sessions",
    "load_session",
    "maybe_runprof",
    "profile_compiled",
    "profile_lowered",
    "current_trace_context",
    "default_registry",
    "default_rules",
    "flat_record",
    "format_traceparent",
    "get_engine",
    "get_history",
    "get_runprof",
    "get_tracer",
    "maybe_span",
    "merge_snapshots",
    "parse_traceparent",
    "read_spill",
    "render_snapshot",
    "replay_spill",
    "resolve_runprof",
    "set_engine",
    "set_history",
    "set_runprof",
    "set_tracer",
    "summarize_session",
    "global_norm",
    "read_step_log",
    "render_prometheus",
    "sanitize_name",
    "summarize_step_log",
    "train_step_metrics",
    "update_metrics",
]
