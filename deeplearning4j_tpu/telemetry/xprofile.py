"""Compiled-step performance-attribution profiler (ISSUE 9).

The observability stack so far answers "is training healthy?" (metrics,
traces, guardrails); this module answers "where does the step time, memory,
and network go?" — by introspecting the EXACT program XLA compiled rather
than trusting hand-maintained analytic tables:

- ``profile_compiled(step, *args)`` lowers + compiles a jitted step once
  and returns a :class:`StepProfile`: XLA ``cost_analysis()`` FLOPs and
  bytes-accessed, ``memory_analysis()`` argument/output/temp/alias bytes
  (explicit ``None`` where a backend does not report them), compile wall
  time, donation status parsed from the entry module's
  ``input_output_alias``, and an **HLO collective inventory** — every
  all-reduce / all-gather / all-to-all / collective-permute /
  reduce-scatter in the compiled module with its payload bytes, replica
  groups, and an analytic ring-convention wire-byte estimate.
- ``attribute(profile, step_seconds)`` fuses a profile with a MEASURED
  per-step wall time into derived attribution: measured MFU,
  HBM-bandwidth utilization, roofline position (arithmetic intensity vs
  the ridge point → compute- / memory- / comm-bound), and the comm
  fraction implied by the collective inventory.
- ``ProfiledStep`` is the ``profile=`` seam the train-step builders wrap
  their jitted step in (mirroring ``attn_impl``/``with_metrics``/
  ``guard``): the FIRST call runs the ahead-of-time lower→compile path,
  captures the profile, and every call — including the first — executes
  the SAME compiled executable, so profiling is compile-time-only and the
  steady-state step stays one dispatch (<5% budget pinned by the bench
  ``profile`` stage). Input-signature drift falls back to the plain jit
  cache instead of failing the loop.
- ``ProfileStore`` keeps the last profile per label and mirrors the
  headline numbers into the PR 2 metrics registry as ``profile_*``
  gauges; ``UiServer.attach_profiles`` serves it at ``/api/profile``.
- ``MemoryWatermarkSampler`` samples ``device_memory_stats`` on a
  background thread, exporting live ``profile_memory_*`` gauges plus its
  own high watermark — the headroom signal the ZeRO roadmap item needs.
  Backends without memory_stats (CPU) degrade to empty watermarks, never
  errors.

Wire-byte convention (documented once, used everywhere): for an op whose
printed RESULT buffer is B bytes over a replica group of g devices,

    all-reduce          2·(g−1)/g · B     (ring reduce-scatter+all-gather)
    all-gather          (g−1)/g · B       (B is the gathered result)
    reduce-scatter      (g−1) · B         (B is the 1/g scattered result)
    all-to-all          (g−1)/g · B       (1/g of the buffer stays local)
    collective-permute  B                 (one neighbor hop)

These are per-device estimates of bytes on the wire, the same convention
as bench.py's MoE comm model — analytic, not measured; XProf traces remain
the measured truth.

FLOPs convention caveat (load-bearing — verified on this toolchain in
tests/test_xprofile.py): XLA's ``HloCostAnalysis`` counts a while/scan
BODY ONCE, independent of trip count. A program that scans L decoder
layers (or T LSTM timesteps, or the blockwise-attention K/V blocks)
therefore reports the single-iteration FLOPs, not L× them.
``StepProfile.flops`` keeps XLA's number verbatim; consumers that compare
against per-sample analytic tables must either cross-check at trip count
1 (what the tier-1 FLOPs-table test does) or scan-adjust the analytic
side (what bench.py's profile blobs do, with both numbers recorded).
"""

from __future__ import annotations

import json
import logging
import re
import threading

from deeplearning4j_tpu.utils.lockwatch import make_lock
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

log = logging.getLogger(__name__)

__all__ = [
    "CollectiveOp",
    "MemoryWatermarkSampler",
    "ProfileStore",
    "ProfiledStep",
    "StepProfile",
    "attribute",
    "default_profile_store",
    "maybe_profiled",
    "parse_collectives",
    "profile_compiled",
    "profile_lowered",
    "summarize_collectives",
]

# bytes per element for the HLO shape dtypes this repo's programs produce
_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

# one HLO shape literal: dtype[dims]{layout}? — e.g. f32[4,512]{1,0}
_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\](?:\{[^}]*\})?")

# a collective op DEFINITION line: "%name = <shape(s)> <kind>(operands...)"
# -start variants count (async pair); -done lines don't define new traffic.
_COLLECTIVE_RE = re.compile(
    r"=\s+(\([^)]*\)|[a-z][a-z0-9]*\[[0-9,]*\](?:\{[^}]*\})?)\s+"
    r"(all-reduce|all-gather|all-to-all|collective-permute|reduce-scatter)"
    r"(-start)?\(")

_REPLICA_GROUPS_RE = re.compile(r"replica_groups=\{(\{[^=]*?\})\}")
_SOURCE_TARGET_RE = re.compile(r"source_target_pairs=\{(\{[^=]*?\})\}")

# entry-module donation map: input_output_alias={ {0}: (0, {}, may-alias) }
_ALIAS_ARG_RE = re.compile(r"\((\d+),\s*\{[^}]*\},\s*(?:may|must)-alias\)")

# hardware model for the derived attribution (TPU v5e; see bench.py's
# measured precision notes). Callers on other parts pass their own peaks.
DEFAULT_PEAK_FLOPS = 197e12        # bf16 MXU peak per chip
DEFAULT_HBM_BYTES_PER_SEC = 819e9  # v5e HBM bandwidth per chip
DEFAULT_ICI_BYTES_PER_SEC = 45e9   # v5e ICI per direction per link


def _shape_bytes(shape_text: str) -> int:
    """Total bytes of one shape literal or a tuple of them."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(shape_text):
        per = _DTYPE_BYTES.get(dtype)
        if per is None:  # opaque/token shapes carry no payload
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * per
    return total


def _group_sizes(op_line: str) -> List[int]:
    """Replica-group sizes of a collective line (source_target_pairs for
    collective-permute: the ring a permute cycles over)."""
    m = _REPLICA_GROUPS_RE.search(op_line)
    if m:
        return [len([d for d in grp.split(",") if d.strip() != ""])
                for grp in re.findall(r"\{([^{}]*)\}", m.group(1))]
    m = _SOURCE_TARGET_RE.search(op_line)
    if m:
        # pairs {{0,1},{1,0}} form cycles; the per-device traffic of one
        # permute hop is payload-sized regardless, so record the pair count
        n_pairs = len(re.findall(r"\{([^{}]*)\}", m.group(1)))
        return [n_pairs] if n_pairs else []
    return []


def _wire_bytes(kind: str, payload: int, group: int) -> float:
    """Per-device analytic wire bytes (ring convention, module docstring)."""
    if group <= 1 and kind != "collective-permute":
        return 0.0
    if kind == "all-reduce":
        return 2.0 * (group - 1) / group * payload
    if kind == "all-gather":
        return (group - 1) / group * payload
    if kind == "reduce-scatter":
        return float((group - 1) * payload)
    if kind == "all-to-all":
        return (group - 1) / group * payload
    if kind == "collective-permute":
        return float(payload)
    return 0.0


@dataclass
class CollectiveOp:
    """One collective in the compiled HLO."""

    kind: str
    payload_bytes: int
    group_size: int
    n_groups: int
    wire_bytes: float

    def to_dict(self) -> Dict[str, Any]:
        return {"kind": self.kind, "payload_bytes": self.payload_bytes,
                "group_size": self.group_size, "n_groups": self.n_groups,
                "wire_bytes": round(self.wire_bytes, 1)}


def parse_collectives(hlo_text: str) -> List[CollectiveOp]:
    """Collective inventory of a compiled HLO module's text."""
    ops: List[CollectiveOp] = []
    for line in hlo_text.splitlines():
        m = _COLLECTIVE_RE.search(line)
        if not m:
            continue
        shape_text, kind = m.group(1), m.group(2)
        payload = _shape_bytes(shape_text)
        sizes = _group_sizes(line)
        group = max(sizes) if sizes else 1
        ops.append(CollectiveOp(
            kind=kind, payload_bytes=payload, group_size=group,
            n_groups=len(sizes) or 1,
            wire_bytes=_wire_bytes(kind, payload, group)))
    return ops


def summarize_collectives(ops: List[CollectiveOp]) -> Dict[str, Dict]:
    """Per-kind aggregation: count, total payload/wire bytes, group sizes."""
    out: Dict[str, Dict] = {}
    for op in ops:
        agg = out.setdefault(op.kind, {
            "count": 0, "payload_bytes": 0, "wire_bytes": 0.0,
            "group_sizes": []})
        agg["count"] += 1
        agg["payload_bytes"] += op.payload_bytes
        agg["wire_bytes"] += op.wire_bytes
        if op.group_size not in agg["group_sizes"]:
            agg["group_sizes"].append(op.group_size)
    for agg in out.values():
        agg["wire_bytes"] = round(agg["wire_bytes"], 1)
        agg["group_sizes"].sort()
    return out


@dataclass
class StepProfile:
    """What XLA compiled for one jitted step, captured at compile time.

    Memory fields are ``None`` — explicitly, never silently zero — when the
    backend's ``memory_analysis`` does not report them (pinned in
    tests/test_xprofile.py). ``collectives`` is the per-kind summary;
    ``collective_ops`` keeps the per-op records (bounded to the first
    ``_MAX_OPS_KEPT`` for JSON-size sanity; counts/totals stay exact).
    """

    label: str
    platform: str
    flops: Optional[float] = None
    transcendentals: Optional[float] = None
    bytes_accessed: Optional[float] = None
    argument_bytes: Optional[int] = None
    output_bytes: Optional[int] = None
    temp_bytes: Optional[int] = None
    alias_bytes: Optional[int] = None
    generated_code_bytes: Optional[int] = None
    peak_bytes: Optional[int] = None
    compile_seconds: Optional[float] = None
    donated_args: int = 0
    collectives: Dict[str, Dict] = field(default_factory=dict)
    collective_ops: List[Dict] = field(default_factory=list)
    collective_wire_bytes: float = 0.0
    recorded_at: Optional[float] = None

    _MAX_OPS_KEPT = 32

    def to_dict(self) -> Dict[str, Any]:
        d = {k: v for k, v in self.__dict__.items()
             if not k.startswith("_")}
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "StepProfile":
        known = {f for f in cls.__dataclass_fields__}  # type: ignore[attr-defined]
        return cls(**{k: v for k, v in d.items() if k in known})

    def to_json(self) -> str:
        return json.dumps(self.to_dict())


def _cost_dict(compiled) -> Dict[str, float]:
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca or {})


def _memory_fields(compiled) -> Dict[str, Optional[int]]:
    """argument/output/temp/alias/generated-code bytes, None where absent."""
    names = {
        "argument_bytes": "argument_size_in_bytes",
        "output_bytes": "output_size_in_bytes",
        "temp_bytes": "temp_size_in_bytes",
        "alias_bytes": "alias_size_in_bytes",
        "generated_code_bytes": "generated_code_size_in_bytes",
    }
    out: Dict[str, Optional[int]] = {k: None for k in names}
    try:
        mem = compiled.memory_analysis()
    except Exception:
        mem = None
    if mem is None:
        return out
    for field_name, attr in names.items():
        val = getattr(mem, attr, None)
        out[field_name] = int(val) if val is not None else None
    return out


def profile_lowered(lowered, label: str = "step",
                    compiled=None,
                    compile_seconds: Optional[float] = None) -> StepProfile:
    """Profile an already-``lower()``-ed jitted call. Compiles it (timing
    the compile) unless ``compiled`` is passed; returns the
    :class:`StepProfile`. The compiled executable is stashed on the
    profile as ``profile._compiled`` for AOT callers (ProfiledStep)."""
    import jax

    if compiled is None:
        t0 = time.perf_counter()
        compiled = lowered.compile()
        # graftlint: allow[untimed-dispatch] compile() is host-synchronous — nothing is enqueued inside this window
        compile_seconds = time.perf_counter() - t0

    cost = _cost_dict(compiled)
    mem = _memory_fields(compiled)
    try:
        hlo_text = compiled.as_text()
    except Exception:
        hlo_text = ""
    ops = parse_collectives(hlo_text)
    donated = 0
    for line in hlo_text.splitlines():
        if "input_output_alias=" in line:
            donated = len(set(_ALIAS_ARG_RE.findall(line)))
            break

    peak = None
    if mem["temp_bytes"] is not None:
        # the residency estimate while the program runs: live arguments +
        # outputs + temps, minus the donated (aliased) overlap
        peak = ((mem["argument_bytes"] or 0) + (mem["output_bytes"] or 0)
                + mem["temp_bytes"] - (mem["alias_bytes"] or 0))

    prof = StepProfile(
        label=label,
        platform=jax.devices()[0].platform,
        flops=cost.get("flops"),
        transcendentals=cost.get("transcendentals"),
        bytes_accessed=cost.get("bytes accessed"),
        compile_seconds=(round(compile_seconds, 4)
                         if compile_seconds is not None else None),
        donated_args=donated,
        collectives=summarize_collectives(ops),
        collective_ops=[op.to_dict()
                        for op in ops[:StepProfile._MAX_OPS_KEPT]],
        collective_wire_bytes=round(sum(op.wire_bytes for op in ops), 1),
        peak_bytes=peak,
        recorded_at=time.time(),
        **mem,
    )
    prof._compiled = compiled  # type: ignore[attr-defined]  # AOT handle, excluded from to_dict
    return prof


def profile_compiled(fn, *args, label: str = "step",
                     store: Optional["ProfileStore"] = None,
                     **kwargs) -> StepProfile:
    """Lower + compile a jitted callable against ``*args`` and profile the
    result. This is the one-stop API the bench stages, tests, and the
    ``profile=`` seam all use — ONE compile, no execution."""
    prof = profile_lowered(fn.lower(*args, **kwargs), label=label)
    if store is not None:
        store.record(prof)
    return prof


# ------------------------------------------------------------- attribution ----

def attribute(profile: StepProfile, step_seconds: float,
              peak_flops: float = DEFAULT_PEAK_FLOPS,
              hbm_bytes_per_sec: float = DEFAULT_HBM_BYTES_PER_SEC,
              ici_bytes_per_sec: float = DEFAULT_ICI_BYTES_PER_SEC,
              ) -> Dict[str, Any]:
    """Fuse a compile-time profile with a MEASURED step time.

    Returns measured MFU (XLA-counted FLOPs, not the analytic table),
    HBM-bandwidth utilization, the roofline position (arithmetic
    intensity vs the ridge point ``peak_flops / hbm_bw``), the comm
    fraction implied by the collective wire bytes at ``ici_bytes_per_sec``,
    and the resource whose implied time is largest (``bound``). All three
    implied times are lower bounds — overlap means the real step can beat
    their sum, which is exactly what the comm/compute-overlap roadmap item
    will need this to show."""
    step_seconds = max(float(step_seconds), 1e-12)
    flops = profile.flops or 0.0
    bytes_accessed = profile.bytes_accessed or 0.0
    wire = profile.collective_wire_bytes or 0.0
    t_compute = flops / peak_flops
    t_memory = bytes_accessed / hbm_bytes_per_sec
    t_comm = wire / ici_bytes_per_sec
    implied = {"compute": t_compute, "memory": t_memory, "comm": t_comm}
    bound = max(implied, key=lambda k: implied[k]) if any(
        v > 0 for v in implied.values()) else "unknown"
    ai = (flops / bytes_accessed) if bytes_accessed else None
    ridge = peak_flops / hbm_bytes_per_sec
    return {
        "step_seconds": step_seconds,
        "measured_mfu": flops / step_seconds / peak_flops,
        "hbm_utilization": bytes_accessed / step_seconds / hbm_bytes_per_sec,
        "comm_fraction": t_comm / step_seconds,
        "arithmetic_intensity": ai,
        "ridge_intensity": ridge,
        "bound": bound,
        "implied_seconds": implied,
        "model": {"peak_flops": peak_flops,
                  "hbm_bytes_per_sec": hbm_bytes_per_sec,
                  "ici_bytes_per_sec": ici_bytes_per_sec},
    }


# ------------------------------------------------------------ profile store ----

class ProfileStore:
    """Last StepProfile per label + registry mirror.

    ``record`` keeps the profile dict and mirrors the headline numbers
    into the metrics registry as ``profile_flops`` / ``profile_peak_bytes``
    / ``profile_collective_wire_bytes`` / ``profile_compile_seconds``
    gauges labeled ``{"step": label}`` — so the Prometheus/UI export layer
    (PR 2) serves them with zero extra ceremony. Thread-safe."""

    def __init__(self, registry=None):
        self._lock = make_lock("profile.store")  # lockwatch seam
        self._profiles: Dict[str, Dict] = {}
        self._registry = registry

    def _mirror(self, prof: StepProfile) -> None:
        reg = self._registry
        if reg is None:
            from deeplearning4j_tpu.telemetry.registry import default_registry

            reg = default_registry()
        labels = {"step": prof.label}
        if prof.flops is not None:
            reg.gauge("profile_flops", labels).set(prof.flops)
        if prof.peak_bytes is not None:
            reg.gauge("profile_peak_bytes", labels).set(prof.peak_bytes)
        if prof.compile_seconds is not None:
            reg.gauge("profile_compile_seconds",
                      labels).set(prof.compile_seconds)
        reg.gauge("profile_collective_wire_bytes",
                  labels).set(prof.collective_wire_bytes)

    def record(self, prof: StepProfile) -> None:
        with self._lock:
            self._profiles[prof.label] = prof.to_dict()
        self._mirror(prof)

    def get(self, label: str) -> Optional[Dict]:
        with self._lock:
            return self._profiles.get(label)

    def snapshot(self) -> List[Dict]:
        with self._lock:
            return [self._profiles[k] for k in sorted(self._profiles)]


_default_store: Optional[ProfileStore] = None
_default_store_lock = threading.Lock()


def default_profile_store() -> ProfileStore:
    global _default_store
    with _default_store_lock:
        if _default_store is None:
            _default_store = ProfileStore()
        return _default_store


# ------------------------------------------------------------ profile= seam ----

class ProfiledStep:
    """The ``profile=`` seam: wrap a jitted step so its FIRST call runs the
    ahead-of-time ``lower → compile`` path once, captures the
    :class:`StepProfile` (``self.step_profile``, also recorded in the
    store), and EVERY call — including that first one — executes the same
    compiled executable. Profiling cost is therefore compile-time-only;
    the steady-state path is one attribute load + the AOT dispatch (the
    bench ``profile`` stage pins the <5% budget).

    The AOT executable is shape-pinned — an input-signature drift (new
    batch shape, weak-type scalar) raises before execution; the wrapper
    then falls back to the underlying jit cache so a training loop keeps
    running (at the cost of the recompile the retrace guard exists to
    catch)."""

    def __init__(self, fn, label: str = "step",
                 store: Optional[ProfileStore] = None):
        self._fn = fn
        self.label = label
        self._store = store if store is not None else default_profile_store()
        self._compiled = None
        self.step_profile: Optional[StepProfile] = None
        self.signature_fallbacks = 0

    def __call__(self, *args):
        if self._compiled is None:
            prof = profile_compiled(self._fn, *args, label=self.label,
                                    store=self._store)
            self._compiled = prof._compiled  # type: ignore[attr-defined]
            self.step_profile = prof
        try:
            return self._compiled(*args)
        except TypeError:
            # aval drift — raised BEFORE execution, so the args (donated or
            # not) are intact; route through the jit cache instead
            self.signature_fallbacks += 1
            return self._fn(*args)

    # AOT introspection passthroughs, so a ProfiledStep still quacks like
    # the jitted step for the callers that lower it themselves
    def lower(self, *args, **kwargs):
        return self._fn.lower(*args, **kwargs)


def maybe_profiled(fn, profile, label: str):
    """Builder helper: wrap ``fn`` in a :class:`ProfiledStep` when
    ``profile`` is truthy (a string overrides the label), else return
    ``fn`` unchanged — the zero-cost default, like ``maybe_span``."""
    if not profile:
        return fn
    return ProfiledStep(fn, label=profile if isinstance(profile, str)
                        else label)


# ------------------------------------------------------- memory watermarks ----

class MemoryWatermarkSampler:
    """Background device-memory watermark sampler.

    Samples ``utils.profiling.device_memory_stats`` every ``interval_s``
    on a daemon thread and exports per-device gauges through the metrics
    registry: ``profile_memory_bytes_in_use`` (live),
    ``profile_memory_peak_bytes`` (the backend's own peak counter, when it
    reports one) and ``profile_memory_watermark_bytes`` (the max in-use
    THIS sampler observed — survives a backend whose peak counter resets).
    ``profile_memory_samples_total`` counts sampler ticks, so "the sampler
    ran but this backend reports nothing" (CPU) is distinguishable from
    "the sampler never ran". Use as a context manager around a training
    window, or ``start()``/``stop()`` explicitly."""

    def __init__(self, registry=None, interval_s: float = 0.5):
        self.interval_s = float(interval_s)
        if registry is None:
            from deeplearning4j_tpu.telemetry.registry import default_registry

            registry = default_registry()
        self._registry = registry
        self._lock = make_lock("profile.memwatch")  # lockwatch seam
        self._watermarks: Dict[str, int] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.samples = 0

    def sample_once(self) -> List[Dict]:
        """One sampling pass; returns the raw per-device stats list."""
        from deeplearning4j_tpu.utils.profiling import device_memory_stats

        stats = device_memory_stats()
        with self._lock:
            self.samples += 1
            for entry in stats:
                dev = entry.get("device", "?")
                in_use = entry.get("bytes_in_use")
                if in_use is None:
                    continue
                labels = {"device": dev}
                self._registry.gauge("profile_memory_bytes_in_use",
                                     labels).set(in_use)
                peak = entry.get("peak_bytes_in_use")
                if peak is not None:
                    self._registry.gauge("profile_memory_peak_bytes",
                                         labels).set(peak)
                wm = max(self._watermarks.get(dev, 0), int(in_use))
                self._watermarks[dev] = wm
                self._registry.gauge("profile_memory_watermark_bytes",
                                     labels).set(wm)
        self._registry.counter("profile_memory_samples_total").inc()
        return stats

    def watermarks(self) -> Dict[str, int]:
        """device → max bytes_in_use observed (empty on CPU backends)."""
        with self._lock:
            return dict(self._watermarks)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception as exc:
                # a flaky backend stat must never kill the sampler thread;
                # the samples counter exposes the stall — debug, not
                # warning: some backends flake every interval
                log.debug("memory watermark sample failed: %r", exc)

    def start(self) -> "MemoryWatermarkSampler":
        if self._thread is None:
            self._stop.clear()
            self.sample_once()  # immediate first sample, not interval-late
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()
        return self

    def stop(self) -> Dict[str, int]:
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=10)
            self._thread = None
            self.sample_once()  # closing sample catches the final state
        return self.watermarks()

    def __enter__(self) -> "MemoryWatermarkSampler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
