"""Declarative alert rules over metrics history: the verdict layer
(ISSUE 15).

The repo records everything (registry, traces, profiles, federation) but
until this module nothing *watched* the records — a diverging run or a
p99 SLO burn was only visible to a human reading a report after the
fact. ROADMAP 2's router and ROADMAP 4's hot-swap both need a
machine-readable health verdict; this engine produces it.

Rule kinds, all evaluated over :class:`~deeplearning4j_tpu.telemetry.
history.MetricsHistory` queries (no storage of its own):

- ``threshold`` — the latest sampled value compared against
  ``threshold`` with ``op`` (``>``, ``>=``, ``<``, ``<=``);
- ``rate`` — the per-second increase over ``window_s``
  (``history.rate``: counter semantics, reset-safe) compared against
  ``threshold``; gauges work too (``serve_queue_depth`` growth uses the
  signed ``delta``/``window_s`` — set ``use_delta=True``);
- ``absence`` — heartbeat-timestamp staleness: the metric's value is a
  unix timestamp (the ``*_unix`` convention, e.g.
  ``elastic_worker_heartbeat_unix{worker=…}``); the rule fires when
  ``now - value > stale_s`` for ANY labeled series with a positive value
  (non-positive = the source deliberately retired that series, e.g. a
  buried worker). A missing metric is ``inactive`` unless
  ``fire_on_missing``;
- ``burn_rate`` — SLO burn over a latency histogram: with objective
  "fraction ``slo_target`` of requests complete within ``slo_ms``", the
  error budget is ``1 - slo_target`` and the burn rate is
  ``fraction_over(slo_ms) / budget`` across ``window_s`` (windowed
  bucket-delta, so an old latency regime can't mask a fresh burn). Fires
  when the burn exceeds ``threshold`` (1.0 = exactly eating budget at
  the sustainable pace; 2.0 = budget gone in half the SLO window).

Hysteresis (``for_s``): a true condition moves the rule
``inactive → pending``; only after staying true for ``for_s`` seconds
does it become ``firing`` (``for_s=0`` fires immediately). A false
condition resolves: ``firing → resolved`` (kept visible with its
timestamps; a later true condition re-enters through ``pending``),
``pending → inactive`` (a blip never fires).

A **firing transition** does three things (the ISSUE 15 contract):

1. bumps the registry — ``alerts_firing{rule,severity}`` gauge to 1
   (back to 0 on resolve) and ``alerts_transitions_total{rule,to}``;
2. dumps flight-recorder forensics through the process tracer
   (``reason=alert:<rule>`` with the rule's value/context — the open
   spans and counters AT the moment the rule fired);
3. publishes the alert state into the tracker KV
   (``federation.alerts.<process>``, last-write-wins, schema-gated) so
   :class:`~deeplearning4j_tpu.telemetry.federation.ClusterAggregator.
   collect_alerts` merges a cluster-wide alert view — the master sees a
   worker's divergence, the router-to-be sees a replica's death.

Trace exemplars: for histogram-backed rules (``burn_rate``), the alert
state carries the recent exemplar trace ids above ``slo_ms`` from the
live registry histogram — ``/api/alerts`` links a firing latency rule
straight to offending traces, and ``tools/trace_report.py``
(``find_trace``) resolves them to real spans.

Threading mirrors history's sampler (PR 11 discipline): lockwatch-seamed
lock, handle-swap stop, join outside the lock, idempotent and
restartable. Zero-cost unconfigured — no engine, no evaluation.

Knobs (host-side, blessed ``DL4J_TPU_*`` namespace):

- ``DL4J_TPU_ALERTS_INTERVAL_S``: evaluator cadence (default 1.0).
- ``DL4J_TPU_SERVE_SLO_MS``: the default pack's serve-latency SLO bound
  (default 250.0 — a DEFAULT_BUCKETS bound, so the burn fraction is
  exact).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.utils.lockwatch import make_lock

log = logging.getLogger(__name__)

SCHEMA = "dl4j-tpu-alerts-v1"
ALERT_KV_PREFIX = "federation.alerts."

_ENV_INTERVAL = "DL4J_TPU_ALERTS_INTERVAL_S"
_ENV_SERVE_SLO = "DL4J_TPU_SERVE_SLO_MS"

KINDS = ("threshold", "rate", "absence", "burn_rate")
SEVERITIES = ("info", "warning", "critical")
_OPS: Dict[str, Callable[[float, float], bool]] = {
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
}


@dataclasses.dataclass(frozen=True)
class AlertRule:
    """One declarative rule (kinds + fields in the module docstring)."""

    name: str
    kind: str
    metric: str
    threshold: float = 0.0
    op: str = ">"
    window_s: float = 60.0
    for_s: float = 0.0
    severity: str = "warning"
    labels: Optional[Dict[str, str]] = None
    use_delta: bool = False          # rate kind: signed gauge delta/s
    stale_s: float = 10.0            # absence kind
    fire_on_missing: bool = False    # absence kind
    slo_ms: Optional[float] = None   # burn_rate kind: latency objective
    slo_target: float = 0.99         # burn_rate kind: goodput objective
    description: str = ""

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown rule kind {self.kind!r} "
                             f"(one of {KINDS})")
        if self.severity not in SEVERITIES:
            raise ValueError(f"unknown severity {self.severity!r} "
                             f"(one of {SEVERITIES})")
        if self.op not in _OPS:
            raise ValueError(f"unknown op {self.op!r} (one of "
                             f"{sorted(_OPS)})")
        if self.kind == "burn_rate":
            if self.slo_ms is None:
                raise ValueError(f"burn_rate rule {self.name!r} needs "
                                 "slo_ms")
            if not (0.0 < self.slo_target < 1.0):
                raise ValueError(f"slo_target must be in (0, 1), got "
                                 f"{self.slo_target}")


# ------------------------------------------------------------- conditions ----

def _evaluate_condition(rule: AlertRule, history, now: float
                        ) -> Tuple[bool, Optional[float], Dict]:
    """(active, measured value, context) for one rule against the
    history. No data → (False, None, …): a rule never fires on a metric
    its subsystem hasn't produced (except absence with fire_on_missing)."""
    if rule.kind == "threshold":
        pt = history.last_point(rule.metric, rule.labels)
        if pt is None:
            return False, None, {"reason": "no_data"}
        value = pt[1]
        return _OPS[rule.op](value, rule.threshold), value, {}
    if rule.kind == "rate":
        if rule.use_delta:
            d = history.delta(rule.metric, rule.labels,
                              window_s=rule.window_s, now=now)
            value = None if d is None else d / rule.window_s
        else:
            value = history.rate(rule.metric, rule.labels,
                                 window_s=rule.window_s, now=now)
        if value is None:
            return False, None, {"reason": "no_data"}
        return _OPS[rule.op](value, rule.threshold), value, {}
    if rule.kind == "absence":
        series = history.last_points_by_label(rule.metric)
        series = [(lbl, ts, v) for lbl, ts, v in series
                  if rule.labels is None
                  or all(lbl.get(k) == v2
                         for k, v2 in rule.labels.items())]
        if not series:
            if rule.fire_on_missing:
                return True, None, {"reason": "missing"}
            return False, None, {"reason": "no_data"}
        stale = [(lbl, now - v) for lbl, _ts, v in series
                 if v > 0 and now - v > rule.stale_s]
        if not stale:
            return False, 0.0, {}
        worst = max(age for _, age in stale)
        return True, worst, {"stale_series": [
            {"labels": lbl, "age_s": round(age, 3)} for lbl, age in stale]}
    # burn_rate
    frac = history.fraction_over(rule.metric, float(rule.slo_ms),
                                 rule.labels, window_s=rule.window_s,
                                 now=now)
    if frac is None:
        return False, None, {"reason": "no_data"}
    budget = 1.0 - rule.slo_target
    burn = frac / budget
    return burn > rule.threshold, burn, {
        "bad_fraction": round(frac, 6), "slo_ms": rule.slo_ms,
        "slo_target": rule.slo_target}


# ------------------------------------------------------------ state model ----

INACTIVE, PENDING, FIRING, RESOLVED = ("inactive", "pending", "firing",
                                       "resolved")


class _RuleState:
    __slots__ = ("rule", "state", "since", "pending_since", "fired_at",
                 "resolved_at", "value", "context", "fire_count")

    def __init__(self, rule: AlertRule):
        self.rule = rule
        self.state = INACTIVE
        self.since: Optional[float] = None
        self.pending_since: Optional[float] = None
        self.fired_at: Optional[float] = None
        self.resolved_at: Optional[float] = None
        self.value: Optional[float] = None
        self.context: Dict = {}
        self.fire_count = 0

    def to_dict(self) -> Dict:
        r = self.rule
        return {
            "rule": r.name, "kind": r.kind, "metric": r.metric,
            "severity": r.severity, "state": self.state,
            "value": self.value, "threshold": r.threshold,
            "for_s": r.for_s, "window_s": r.window_s,
            "since": self.since, "fired_at": self.fired_at,
            "resolved_at": self.resolved_at, "fire_count": self.fire_count,
            "description": r.description, "context": dict(self.context),
        }


class AlertEngine:
    """Evaluate a rule pack over a history on demand or on a cadence
    (module docstring). ``tracker`` is anything with ``put_kv`` (the
    in-memory tracker, the embedded server handle, or a
    StateTrackerClient) — None disables publishing; ``log_path`` appends
    every transition as a JSONL line (line-buffered, the write-ahead
    posture) for ``tools/alert_report.py``."""

    def __init__(self, history, rules: Optional[Sequence[AlertRule]] = None,
                 registry=None, tracker=None, process: str = "proc",
                 interval_s: float = 1.0, log_path: Optional[str] = None):
        if registry is None:
            from deeplearning4j_tpu.telemetry.registry import default_registry

            registry = default_registry()
        self.history = history
        self.registry = registry
        self.tracker = tracker
        self.process = str(process)
        self.interval_s = float(interval_s)
        self.rules: List[AlertRule] = list(
            rules if rules is not None else default_rules())
        names = [r.name for r in self.rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate rule names: {sorted(names)}")
        self._fh = None
        if log_path is not None:
            parent = os.path.dirname(os.path.abspath(log_path))
            os.makedirs(parent, exist_ok=True)
            # opened here, never under the lock (blocking-under-lock)
            self._fh = open(log_path, "a", buffering=1)
        self.log_path = log_path
        self._lock = make_lock("telemetry.alerts")  # lockwatch seam
        self._states: Dict[str, _RuleState] = {
            r.name: _RuleState(r) for r in self.rules}
        self._seq = 0
        self._publish_fail_streak = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.registry.gauge("alerts_rules").set(float(len(self.rules)))
        for r in self.rules:
            # the firing gauge exists (at 0) from engine construction, so
            # the cluster view / report can tell "quiet" from "unwatched"
            self.registry.gauge("alerts_firing",
                               {"rule": r.name,
                                "severity": r.severity}).set(0.0)
            # pre-arm the watched instruments (get-or-create at zero):
            # a counter born AFTER the first history sample would hide
            # its birth increment from every rate window — creating the
            # baseline at engine construction makes "the subsystem's
            # first event ever" alertable. Labeled rules skip this
            # (their series appear per label set, e.g. per worker).
            if r.labels is None:
                if r.kind == "burn_rate":
                    self.registry.histogram(r.metric)
                elif r.kind == "threshold" or (r.kind == "rate"
                                               and r.use_delta):
                    # EXCEPT where the pre-armed default (0.0) would
                    # itself satisfy the rule (op "<" on a ratio gauge,
                    # e.g. serve_prefix_cache_hit_rate): creating the
                    # instrument would turn "subsystem never ran" into
                    # a page — those gauges stay unborn until their
                    # subsystem emits a real value, and no-data stays
                    # inactive.
                    if not (r.kind == "threshold"
                            and _OPS[r.op](0.0, r.threshold)):
                        self.registry.gauge(r.metric)
                elif r.kind == "rate":
                    self.registry.counter(r.metric)

    # ---------------------------------------------------------- evaluation ----
    def evaluate_once(self, now: Optional[float] = None,
                      publish: bool = True) -> List[Dict]:
        """One pass over every rule: evaluate conditions, advance the
        state machines, run firing/resolve side effects, publish the
        snapshot to the tracker KV. Returns the state dicts."""
        now = time.time() if now is None else float(now)
        transitions: List[Dict] = []
        with self._lock:
            for st in self._states.values():
                active, value, ctx = _evaluate_condition(
                    st.rule, self.history, now)
                st.value = value
                st.context = ctx
                prev = st.state
                if active:
                    if st.state in (INACTIVE, RESOLVED):
                        st.pending_since = now
                        st.state = PENDING
                        st.since = now
                    if (st.state == PENDING
                            and now - st.pending_since >= st.rule.for_s):
                        st.state = FIRING
                        st.since = now
                        st.fired_at = now
                        st.fire_count += 1
                else:
                    if st.state == PENDING:
                        st.state = INACTIVE
                        st.since = now
                    elif st.state == FIRING:
                        st.state = RESOLVED
                        st.since = now
                        st.resolved_at = now
                if st.state != prev:
                    transitions.append({"ts": now, "rule": st.rule.name,
                                        "from": prev, "to": st.state,
                                        "value": value,
                                        "severity": st.rule.severity,
                                        "context": dict(ctx)})
            states = [st.to_dict() for st in self._states.values()]
        self.registry.counter("alerts_evaluations_total").inc()
        for tr in transitions:
            self._on_transition(tr)
        if publish:
            self.publish(states, now=now)  # no-op without a tracker
        return states

    def _on_transition(self, tr: Dict) -> None:
        rule = tr["rule"]
        sev = tr["severity"]
        self.registry.counter("alerts_transitions_total",
                              {"rule": rule, "to": tr["to"]}).inc()
        if tr["to"] == FIRING:
            self.registry.gauge("alerts_firing",
                               {"rule": rule, "severity": sev}).set(1.0)
            # forensics: the flight recorder snapshot AT the firing
            # moment (open spans, counters, device memory) — never
            # rate-limited, reason names the rule
            from deeplearning4j_tpu.telemetry import trace as _trace

            tracer = _trace.get_tracer()
            if tracer is not None:
                tracer.dump(f"alert:{rule}", extra={
                    "rule": rule, "severity": sev, "value": tr["value"],
                    "context": tr["context"], "process": self.process})
        elif tr["from"] == FIRING:
            self.registry.gauge("alerts_firing",
                               {"rule": rule, "severity": sev}).set(0.0)
        with self._lock:
            fh = self._fh
        if fh is not None:
            try:
                fh.write(json.dumps({"schema": SCHEMA, **tr}) + "\n")
            # graftlint: allow[swallowed-thread-exception] deliberate: a full disk / just-closed log degrades the transition log, never the run (the alert itself already fired through the gauge + tracer above)
            except (OSError, ValueError):
                pass

    # ------------------------------------------------------------- surface ----
    def states(self, now: Optional[float] = None) -> List[Dict]:
        """Current state dicts (NO evaluation — the /api/alerts read
        path; histogram-backed rules get their offending exemplar trace
        ids attached here, read fresh from the live registry)."""
        with self._lock:
            out = [st.to_dict() for st in self._states.values()]
        for d in out:
            rule = self._rule(d["rule"])
            if rule is not None and rule.kind == "burn_rate":
                d["exemplars"] = self.offending_exemplars(rule)
        return out

    def _rule(self, name: str) -> Optional[AlertRule]:
        for r in self.rules:
            if r.name == name:
                return r
        return None

    def offending_exemplars(self, rule: AlertRule) -> List[Dict]:
        """Exemplars above the rule's SLO bound from every live registry
        histogram matching the rule's metric — the trace ids of recent
        requests that actually blew the objective (metrics→trace
        correlation; resolved to spans by tools/trace_report.find_trace)."""
        if rule.slo_ms is None:
            return []
        out: List[Dict] = []
        snap = self.registry.snapshot()
        for row in snap.get("histograms", []):
            if row["name"] != rule.metric:
                continue
            if rule.labels is not None and any(
                    row["labels"].get(k) != v
                    for k, v in rule.labels.items()):
                continue
            for ex in row.get("exemplars", []):
                if ex["value"] > float(rule.slo_ms):
                    out.append(dict(ex, labels=dict(row["labels"])))
        out.sort(key=lambda e: e["ts"], reverse=True)
        return out

    def firing(self) -> List[Dict]:
        return [d for d in self.states() if d["state"] == FIRING]

    # -------------------------------------------------------------- publish ----
    def payload(self, states: Optional[List[Dict]] = None,
                now: Optional[float] = None) -> Dict[str, Any]:
        with self._lock:
            seq = self._seq
            self._seq += 1
        return {"schema": SCHEMA, "process": self.process,
                "pid": os.getpid(),
                "ts": time.time() if now is None else float(now),
                "seq": seq,
                "alerts": states if states is not None else self.states()}

    def publish(self, states: Optional[List[Dict]] = None,
                now: Optional[float] = None) -> bool:
        """Push the alert snapshot into the tracker KV (last-write-wins
        per process, retry-safe). Absorbed transport faults count
        ``alerts_publish_failures_total`` — a flapping tracker degrades
        cluster visibility, never the watched process."""
        if self.tracker is None:
            return False
        payload = self.payload(states, now=now)
        try:
            self.tracker.put_kv(ALERT_KV_PREFIX + self.process,
                                json.dumps(payload))
        except (ConnectionError, OSError) as exc:
            self.registry.counter("alerts_publish_failures_total").inc()
            self._publish_fail_streak += 1
            if self._publish_fail_streak == 1:
                # once per outage, not once per interval: an unpublished
                # alert stream is a blind fleet and nobody would know
                log.warning("alert publish for %s failing (tracker "
                            "unreachable): %r", self.process, exc)
            return False
        if self._publish_fail_streak:
            log.info("alert publish for %s recovered after %d failure(s)",
                     self.process, self._publish_fail_streak)
            self._publish_fail_streak = 0
        self.registry.counter("alerts_publishes_total").inc()
        return True

    # ------------------------------------------------------------- threading ----
    def start(self) -> None:
        """Evaluate every ``interval_s`` on a background thread."""
        with self._lock:
            if self._thread is not None:
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="alert-engine")
            self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.evaluate_once()

    def stop(self) -> None:
        with self._lock:
            thread, self._thread = self._thread, None
        self._stop.set()
        if thread is not None:
            thread.join(timeout=10)

    def close(self) -> None:
        self.stop()
        # handle swap under the lock (the evaluator thread writes through
        # self._fh in _on_transition), close outside it
        with self._lock:
            fh, self._fh = self._fh, None
        if fh is not None:
            fh.close()

    def metrics_record(self) -> Dict[str, float]:
        """The engine's own ``alerts_*`` health metrics as a flat
        step-log record (the serve/federation/lockwatch contract)."""
        from deeplearning4j_tpu.telemetry.registry import flat_record

        return flat_record(self.registry, prefixes=("alerts_",))


# ------------------------------------------------------- default rule pack ----

def _serve_slo_ms() -> float:
    raw = os.environ.get(_ENV_SERVE_SLO)
    try:
        return float(raw) if raw else 250.0
    except ValueError:
        return 250.0


def default_rules() -> List[AlertRule]:
    """The rule pack wired to this repo's live paths (every metric below
    is emitted by shipping code; the tests/test_alerts.py meta-test pins
    a firing + non-firing fixture for EVERY rule here):

    - ``nonfinite_step_rate`` — guardrails (PR 8): the in-graph guard is
      skipping non-finite steps (``guard_skipped_steps_total`` moves).
    - ``worker_divergence`` — elastic quarantine (PR 8): the master
      excluded a worker whose contribution carried NaN/Inf.
    - ``worker_heartbeat_stale`` — elastic membership (PR 6): a worker's
      ``elastic_worker_heartbeat_unix{worker=…}`` timestamp lapsed
      (buried workers retire their series to a non-positive sentinel).
    - ``tracker_reconnect_storm`` — transport (PR 6): the control plane
      is reconnecting faster than occasional blips explain.
    - ``serve_queue_growth`` — serving (PR 10): sustained queue-depth
      growth means offered load exceeds decode capacity.
    - ``serve_latency_slo_burn`` — serving SLO: the p-latency objective
      (``slo_target`` of requests within ``slo_ms``) is burning budget
      at ≥ 2x the sustainable pace over the window.
    - ``lockwatch_contention_spike`` — host concurrency (PR 11): watched
      locks are contending far above the ambient rate.
    - ``cluster_stale_process`` — federation (PR 12): an aggregator sees
      a pusher whose snapshots lapsed (the cluster-level heartbeat).
    - ``serve_cache_hit_rate_low`` — serving fast path (ISSUE 16): the
      prefix page cache is enabled but barely hitting — either traffic
      shares no prefixes (turn it off) or capacity is churning hot
      chains out. The gauge is born on the first lookup, so an engine
      without the cache (or without traffic) stays inactive.
    - ``serve_spec_accept_collapse`` — serving fast path (ISSUE 16):
      the draft LM's proposals stopped matching the flagship —
      speculation is burning k draft steps per verify for ~nothing
      (stale draft after a weight swap, or a draft too weak for the
      traffic). The gauge is born after the engine's warmup floor of
      verify steps, so startup noise can't page.
    - ``step_time_regression`` — runtime profiler (ISSUE 17):
      rate-of-change on the measured step-time gauge
      (``runprof_step_ms``, signed delta/s) — sustained growth means
      creeping retraces, a straggler, or thermal throttling. Sized so
      a gauge BIRTH (0 → one real step time) cannot fire: the birth
      jump leaves the delta window after ``window_s`` seconds, and
      ``for_s > window_s`` means only growth that OUTLASTS the window
      — i.e. step time still climbing — pages.
    - ``mfu_collapse`` — runtime profiler (ISSUE 17): measured MFU
      (xprofile FLOPs / fenced device seconds) collapsed below 1%
      sustained. Op ``<``, so the gauge stays UNBORN until a profiled
      runprof step emits a real value (the pre-arm trap below) — a
      process that never measures MFU stays inactive.
    - ``input_wait_high`` — runtime profiler (ISSUE 17): the
      input-wait hook reports the step spending >30% of its cycle
      starved for host data — the ROADMAP 5 starvation signal.
    - ``fleet_replica_down`` — serving fleet (ISSUE 19): a fleet
      replica's ``fleet_replica_heartbeat_unix{replica=…}`` timestamp
      lapsed on the router — the replica died or wedged. Buried
      replicas retire their series to the -1.0 sentinel (the death was
      handled: in-flight work requeued, cold start dispatched), so
      only UNHANDLED staleness pages.
    - ``fleet_queue_imbalance`` — serving fleet (ISSUE 19): the
      max/mean replica queue-depth ratio the router publishes shows
      one replica hoarding load — session affinity gone pathological
      or a replica decoding far below fleet speed. Gauge is born on
      the first membership sweep with a nonzero mean depth.
    - ``tune_cache_stale`` — autotuner (ISSUE 20): a cache lookup saw
      entries stored under a knob-space version other than the live
      ``tune.space`` version — those winners silently resolve to the
      DEFAULT config until re-searched, so the speedup they promised
      is gone. The ``tune_cache_stale_entries`` gauge is born on the
      first lookup; 0 while every entry is current.
    """
    return [
        AlertRule(
            name="nonfinite_step_rate", kind="rate",
            metric="guard_skipped_steps_total", threshold=0.0, op=">",
            window_s=60.0, for_s=0.0, severity="critical",
            description="guardrails are skipping non-finite steps "
                        "(NaN/Inf loss or grads)"),
        AlertRule(
            name="worker_divergence", kind="rate",
            metric="elastic_workers_quarantined_total", threshold=0.0,
            op=">", window_s=120.0, for_s=0.0, severity="critical",
            description="the elastic master quarantined a worker whose "
                        "contribution carried non-finite params"),
        AlertRule(
            name="worker_heartbeat_stale", kind="absence",
            metric="elastic_worker_heartbeat_unix", stale_s=10.0,
            for_s=0.0, severity="warning",
            description="a live elastic worker's heartbeat timestamp "
                        "stopped advancing"),
        AlertRule(
            name="tracker_reconnect_storm", kind="rate",
            metric="tracker_reconnects_total", threshold=0.5, op=">",
            window_s=30.0, for_s=5.0, severity="warning",
            description="the tracker client is reconnecting >0.5/s "
                        "sustained — flapping control plane"),
        AlertRule(
            name="serve_queue_growth", kind="rate", use_delta=True,
            metric="serve_queue_depth", threshold=0.5, op=">",
            window_s=30.0, for_s=5.0, severity="warning",
            description="serve queue depth growing >0.5 requests/s "
                        "sustained — offered load exceeds capacity"),
        AlertRule(
            name="serve_latency_slo_burn", kind="burn_rate",
            metric="serve_request_ms", slo_ms=_serve_slo_ms(),
            slo_target=0.99, threshold=2.0, window_s=60.0, for_s=0.0,
            severity="critical",
            description="serve request latency is burning the "
                        "99%-within-SLO error budget at >2x the "
                        "sustainable pace"),
        AlertRule(
            name="lockwatch_contention_spike", kind="rate",
            metric="lockwatch_contended_total", threshold=50.0, op=">",
            window_s=30.0, for_s=5.0, severity="warning",
            description="watched control-plane locks contending >50/s "
                        "sustained"),
        AlertRule(
            name="cluster_stale_process", kind="threshold",
            metric="federation_stale_processes", threshold=0.0, op=">",
            for_s=0.0, severity="warning",
            description="a federated process's metric pushes lapsed "
                        "(cluster-level heartbeat)"),
        AlertRule(
            name="serve_cache_hit_rate_low", kind="threshold",
            metric="serve_prefix_cache_hit_rate", threshold=0.1,
            op="<", for_s=60.0, severity="warning",
            description="the serve prefix page cache is hitting on "
                        "<10% of lookups sustained — no prefix "
                        "sharing in traffic, or hot chains are being "
                        "evicted"),
        AlertRule(
            name="serve_spec_accept_collapse", kind="threshold",
            metric="serve_spec_accept_rate", threshold=0.1,
            op="<", for_s=60.0, severity="warning",
            description="speculative-decode draft acceptance "
                        "collapsed below 10% — draft proposals no "
                        "longer track the flagship, verify dispatches "
                        "are wasted"),
        AlertRule(
            name="step_time_regression", kind="rate", use_delta=True,
            metric="runprof_step_ms", threshold=5.0, op=">",
            window_s=30.0, for_s=45.0, severity="warning",
            description="measured step time growing >5 ms/s sustained "
                        "past the delta window — creeping retraces, a "
                        "straggler, or throttling (a one-off jump "
                        "resolves when it leaves the window)"),
        AlertRule(
            name="mfu_collapse", kind="threshold",
            metric="runprof_measured_mfu", threshold=0.01,
            op="<", for_s=120.0, severity="warning",
            description="measured MFU (xprofile FLOPs / fenced device "
                        "seconds) below 1% sustained — the step is "
                        "running but the accelerator is idle"),
        AlertRule(
            name="input_wait_high", kind="threshold",
            metric="runprof_input_wait_fraction", threshold=0.3,
            op=">", for_s=60.0, severity="warning",
            description="steps spend >30% of their cycle waiting on "
                        "host input — the data pipeline is starving "
                        "the device"),
        AlertRule(
            name="fleet_replica_down", kind="absence",
            metric="fleet_replica_heartbeat_unix", stale_s=5.0,
            for_s=0.0, severity="critical",
            description="a fleet replica's heartbeat stopped advancing "
                        "and the router has not yet buried it — "
                        "requests routed there are stalling"),
        AlertRule(
            name="fleet_queue_imbalance", kind="threshold",
            metric="fleet_queue_imbalance_ratio", threshold=3.0,
            op=">", for_s=10.0, severity="warning",
            description="max/mean fleet replica queue depth above 3x "
                        "sustained — routing is piling work onto one "
                        "replica"),
        AlertRule(
            name="tune_cache_stale", kind="threshold",
            metric="tune_cache_stale_entries", threshold=0.0, op=">",
            for_s=0.0, severity="warning",
            description="tuning-cache entries were searched under a "
                        "different knob-space version than the live "
                        "one (tune/space.py) — those winners resolve "
                        "to defaults until re-tuned (run python -m "
                        "deeplearning4j_tpu.tune --store)"),
    ]


# ------------------------------------------------------------- watchtower ----

class Watchtower:
    """History sampler + alert engine as one arm/disarm unit — the shape
    the elastic master (``ElasticMaster(watch=True)``), the worker CLI
    (``--watch-dir``), and the bench twin all use."""

    def __init__(self, history, engine: AlertEngine,
                 owned_tracker=None):
        self.history = history
        self.engine = engine
        self._owned_tracker = owned_tracker

    def start(self) -> None:
        self.history.start()
        self.engine.start()

    def tick(self) -> List[Dict]:
        """One synchronous sample + evaluate + publish — the
        deterministic unit tests and shutdown flushes call."""
        self.history.sample_once()
        return self.engine.evaluate_once()

    def stop(self) -> None:
        self.engine.close()
        self.history.close()
        if self._owned_tracker is not None:
            try:
                self._owned_tracker.close()
            except (ConnectionError, OSError):
                pass
            self._owned_tracker = None

    def __enter__(self) -> "Watchtower":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


def arm_watchtower(registry=None, tracker=None,
                   tracker_address: Optional[str] = None,
                   process: str = "proc",
                   rules: Optional[Sequence[AlertRule]] = None,
                   out_dir: Optional[str] = None,
                   interval_s: Optional[float] = None,
                   start: bool = True) -> Watchtower:
    """Build + start a watchtower over ``registry``: a history sampler,
    an engine on ``rules`` (default pack when None), spill + alert logs
    under ``out_dir`` (``history_<process>.jsonl`` /
    ``alerts_<process>.jsonl`` — what tools/alert_report.py reads), and
    publishing through ``tracker`` (or a fresh StateTrackerClient to
    ``tracker_address`` — its own connection, so alert pushes never ride
    or stall a training loop's RPC slot)."""
    from deeplearning4j_tpu.telemetry.history import (
        _ENV_INTERVAL as _ENV_HIST_INTERVAL,
        DEFAULT_INTERVAL_S,
        MetricsHistory,
        _env_float,
    )

    if interval_s is None:
        interval_s = _env_float(
            _ENV_INTERVAL, _env_float(_ENV_HIST_INTERVAL,
                                      DEFAULT_INTERVAL_S))
    owned = None
    if tracker is None and tracker_address is not None:
        from deeplearning4j_tpu.scaleout.remote_tracker import (
            StateTrackerClient,
        )

        tracker = owned = StateTrackerClient(tracker_address)
    safe = "".join(c if (c.isalnum() or c in "-_.") else "_"
                   for c in str(process))
    spill = (os.path.join(out_dir, f"history_{safe}.jsonl")
             if out_dir else None)
    alog = (os.path.join(out_dir, f"alerts_{safe}.jsonl")
            if out_dir else None)
    history = MetricsHistory(registry=registry, interval_s=interval_s,
                             spill_path=spill)
    engine = AlertEngine(history, rules=rules, registry=registry,
                         tracker=tracker, process=process,
                         interval_s=interval_s, log_path=alog)
    tower = Watchtower(history, engine, owned_tracker=owned)
    if start:
        tower.start()
    return tower


# ------------------------------------------------ process-global engine ----

_engine: Optional[AlertEngine] = None
_engine_lock = threading.Lock()


def get_engine() -> Optional[AlertEngine]:
    return _engine


def set_engine(engine: Optional[AlertEngine]) -> Optional[AlertEngine]:
    """Install (or clear) the process alert engine; returns the previous
    one so tests can restore it (the UiServer /api/alerts fallback)."""
    global _engine
    with _engine_lock:
        prev, _engine = _engine, engine
    return prev
