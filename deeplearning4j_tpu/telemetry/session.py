"""TrainTelemetry — the host side of the in-graph metrics loop.

A metrics-threaded train step returns a pytree of DEVICE scalars each step.
Fetching them eagerly would add a device→host sync per step (through the
axon tunnel that is ~100 ms — more than the step itself); TrainTelemetry
instead buffers the device references and fetches the whole window in ONE
``jax.device_get`` every ``interval`` steps, then fans the values out to:

- the JSONL step-event log (telemetry/step_log.py) — ts, step, wall_ms,
  tokens/s, every metric;
- the MetricsRegistry — gauges (loss/grad_norm/param_norm/update_ratio,
  per-expert ``router_load{expert=...}``), the ``train_steps_total``
  counter, and the ``train_step_ms`` histogram — which the UI serves at
  ``/metrics`` (Prometheus) and ``/api/telemetry`` (JSON).

``static`` metadata (mesh axes, attention impl, model dims) is stamped on
every log line and exported as a ``<prefix>_run_info`` info-gauge.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

import jax

from deeplearning4j_tpu.telemetry.registry import MetricsRegistry
from deeplearning4j_tpu.telemetry.step_log import StepLogWriter

DEFAULT_INTERVAL = 10


class TrainTelemetry:
    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 step_log_path: Optional[str] = None,
                 interval: int = DEFAULT_INTERVAL,
                 tokens_per_step: Optional[int] = None,
                 static: Optional[Dict] = None,
                 prefix: str = "train"):
        self.registry = registry if registry is not None else MetricsRegistry()
        self.interval = max(1, int(interval))
        self.tokens_per_step = tokens_per_step
        self.prefix = prefix
        self.static = dict(static or {})
        self._writer = (StepLogWriter(step_log_path, static=self.static)
                        if step_log_path else None)
        self._buf = []  # (step, wall_ms, device-metrics) — no host sync
        self._last_t: Optional[float] = None
        self.steps_recorded = 0
        self.records = []  # fetched records (host values), for callers
        if self.static:
            self.registry.gauge(
                f"{prefix}_run_info",
                labels={k: str(v) for k, v in self.static.items()}).set(1)

    # ---- hot path ----
    def record(self, step: int, metrics) -> None:
        """Buffer one step's device metrics; syncs only at interval edges."""
        now = time.perf_counter()
        wall_ms = (None if self._last_t is None
                   else (now - self._last_t) * 1000.0)
        self._last_t = now
        self._buf.append((step, wall_ms, metrics))
        self.steps_recorded += 1
        if len(self._buf) >= self.interval:
            self.flush()

    # ---- the one device->host sync per window ----
    def flush(self) -> None:
        if not self._buf:
            return
        fetched = jax.device_get([m for _, _, m in self._buf])
        buf, self._buf = self._buf, []
        for (step, wall_ms, _), vals in zip(buf, fetched):
            host = {k: (v.tolist() if hasattr(v, "tolist") else v)
                    for k, v in vals.items()}
            tps = None
            if wall_ms and self.tokens_per_step:
                tps = self.tokens_per_step / (wall_ms / 1000.0)
            self._export(step, wall_ms, tps, host)

    def _export(self, step, wall_ms, tps, host: Dict) -> None:
        reg, p = self.registry, self.prefix
        reg.counter(f"{p}_steps_total").inc()
        reg.gauge(f"{p}_step").set(step)
        for k, v in host.items():
            if isinstance(v, (list, tuple)):
                for i, vi in enumerate(v):
                    reg.gauge(f"{p}_{k}", labels={"expert": str(i)}
                              if k == "router_load" else
                              {"index": str(i)}).set(float(vi))
            elif isinstance(v, (int, float)):
                reg.gauge(f"{p}_{k}").set(float(v))
        if wall_ms is not None:
            reg.histogram(f"{p}_step_ms").observe(wall_ms)
        if tps is not None:
            reg.gauge(f"{p}_tokens_per_sec").set(tps)
        rec = None
        if self._writer:
            rec = self._writer.write(step, wall_ms=wall_ms,
                                     tokens_per_sec=tps, **host)
        if rec is None:
            rec = {"step": step, "wall_ms": wall_ms,
                   "tokens_per_sec": tps, **host}
        self.records.append(rec)

    def close(self) -> None:
        self.flush()
        if self._writer:
            self._writer.close()

    def __enter__(self) -> "TrainTelemetry":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
