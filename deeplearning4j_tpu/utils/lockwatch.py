"""Runtime lock-order watchdog: the dynamic half of the concurrency lint
(ISSUE 11), mirroring how ``retrace_guard`` backs the static JAX rules.

``tools/graftlint`` proves per-module lock discipline statically; what it
cannot see is the CROSS-module order — a decode-engine step that calls
into the registry, a tracker RPC issued under a caller's lock. This
module wraps ``Lock``/``RLock``/``Condition`` behind a seam so, when
armed, every control-plane lock feeds one process-wide record:

- a **lock-order graph**: per-thread acquisition stacks record an edge
  ``A -> B`` whenever ``B`` is acquired while ``A`` is held; an acquire
  that would close a cycle raises :class:`LockOrderViolation` *before*
  blocking (deadlocks are detected, not demonstrated) — or is counted
  when ``raise_on_cycle`` is off;
- **hold-time and contention telemetry** through the PR 2 registry:
  ``lockwatch_acquires_total``/``lockwatch_contended_total`` counters and
  ``lockwatch_wait_ms``/``lockwatch_hold_ms`` histograms, labeled by the
  seam name;
- a **blocked-too-long watchdog**: an acquire stuck past
  ``watchdog_s`` dumps every thread's stack through the PR 7 flight
  recorder (``trace.get_tracer().dump``; stderr log fallback), then keeps
  waiting — the artifact names both the wanted lock and who is where.

The seam (``make_lock``/``make_rlock``/``make_condition``) is zero-cost
when unarmed: it hands back plain ``threading`` primitives. Arming is
``enable()`` (the ``lockwatch`` pytest fixture) or env
``DL4J_TPU_LOCKWATCH=1`` at lock-creation time. Locks are labeled by
ROLE, not instance — every ``DecodeEngine``'s scheduler lock is one
``serve.engine`` node — which is the granularity a deadlock report
wants.

Knobs (all host-side, read at enable/creation time):

- ``DL4J_TPU_LOCKWATCH``: create watched primitives (``1``/``true``).
- ``DL4J_TPU_LOCKWATCH_WATCHDOG_S``: blocked-too-long threshold
  (default 30).
- ``DL4J_TPU_LOCKWATCH_RAISE``: ``0`` counts cycles instead of raising.
"""

from __future__ import annotations

import logging
import os
import sys
import threading
import time
import traceback
from typing import Dict, List, Optional, Set

log = logging.getLogger(__name__)

__all__ = [
    "LockOrderViolation", "enable", "disable", "enabled", "reset",
    "make_lock", "make_rlock", "make_condition", "graph_snapshot",
    "cycles_detected", "summary", "metrics_record", "WatchedLock",
    "WatchedRLock",
]

_ENV_ON = "DL4J_TPU_LOCKWATCH"
_ENV_WATCHDOG = "DL4J_TPU_LOCKWATCH_WATCHDOG_S"
_ENV_RAISE = "DL4J_TPU_LOCKWATCH_RAISE"

# histogram bounds for lock wait/hold: control-plane critical sections are
# microseconds-to-milliseconds; the default 1ms+ bench buckets would bin
# everything into the first bucket
_LOCK_MS_BUCKETS = (0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 50.0, 100.0,
                    500.0, 2500.0)


class LockOrderViolation(RuntimeError):
    """Acquiring this lock here closes a cycle in the observed lock-order
    graph — two threads taking the same locks in opposite orders can
    deadlock. Raised BEFORE blocking on the reversed acquire."""


class _State:
    """Process-wide watch state. ``active`` gates instrumentation so
    wrappers created while armed go quiet after ``disable()``."""

    def __init__(self) -> None:
        self.active = False
        self.raise_on_cycle = True
        self.watchdog_s = 30.0
        self.registry = None  # None = default_registry() at record time
        self.mu = threading.Lock()  # guards graph/edges/cycles/stats
        self.graph: Dict[str, Set[str]] = {}
        self.edge_sites: Dict[tuple, str] = {}
        self.cycles: List[Dict] = []
        self.stats: Dict[str, Dict[str, float]] = {}
        self.watchdog_dumps = 0


_state = _State()
_tls = threading.local()


def _held_stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def _truthy(val: Optional[str]) -> bool:
    return (val or "").strip().lower() in ("1", "true", "yes", "on")


def _env_armed() -> bool:
    return _truthy(os.environ.get(_ENV_ON))


def enabled() -> bool:
    return _state.active


def enable(raise_on_cycle: Optional[bool] = None,
           watchdog_s: Optional[float] = None, registry=None) -> None:
    """Arm the watcher for locks created from now on (and re-arm existing
    watched primitives)."""
    _state.active = True
    if raise_on_cycle is None:
        raise_on_cycle = _truthy(os.environ.get(_ENV_RAISE, "1"))
    _state.raise_on_cycle = raise_on_cycle
    if watchdog_s is None:
        watchdog_s = float(os.environ.get(_ENV_WATCHDOG, "30"))
    _state.watchdog_s = max(0.05, float(watchdog_s))
    _state.registry = registry


def disable() -> None:
    """Quiesce every watched primitive (they fall through to the plain
    inner lock) and keep the recorded graph for inspection."""
    _state.active = False


def reset() -> None:
    """Drop the recorded graph/stats/cycles (test isolation)."""
    with _state.mu:
        _state.graph.clear()
        _state.edge_sites.clear()
        _state.cycles.clear()
        _state.stats.clear()
        _state.watchdog_dumps = 0


# --------------------------------------------------------------- recording ----

def _stat(name: str) -> Dict[str, float]:
    s = _state.stats.get(name)
    if s is None:
        s = _state.stats[name] = {
            "acquires": 0.0, "contended": 0.0, "wait_ms_total": 0.0,
            "hold_ms_total": 0.0, "wait_ms_max": 0.0, "hold_ms_max": 0.0,
        }
    return s


def _registry():
    if _state.registry is not None:
        return _state.registry
    from deeplearning4j_tpu.telemetry.registry import default_registry

    return default_registry()


def _record_acquire(name: str, wait_s: float, contended: bool) -> None:
    if getattr(_tls, "busy", False):
        return  # re-entrant metric emission (a watched registry lock)
    _tls.busy = True
    try:
        wait_ms = wait_s * 1000.0
        with _state.mu:
            s = _stat(name)
            s["acquires"] += 1
            s["wait_ms_total"] += wait_ms
            s["wait_ms_max"] = max(s["wait_ms_max"], wait_ms)
            if contended:
                s["contended"] += 1
        reg = _registry()
        labels = {"lock": name}
        reg.counter("lockwatch_acquires_total", labels).inc()
        if contended:
            reg.counter("lockwatch_contended_total", labels).inc()
        reg.histogram("lockwatch_wait_ms", labels,
                      buckets=_LOCK_MS_BUCKETS).observe(wait_ms)
    finally:
        _tls.busy = False


def _record_release(name: str, held_s: float) -> None:
    if getattr(_tls, "busy", False):
        return
    _tls.busy = True
    try:
        hold_ms = held_s * 1000.0
        with _state.mu:
            s = _stat(name)
            s["hold_ms_total"] += hold_ms
            s["hold_ms_max"] = max(s["hold_ms_max"], hold_ms)
        _registry().histogram("lockwatch_hold_ms", {"lock": name},
                              buckets=_LOCK_MS_BUCKETS).observe(hold_ms)
    finally:
        _tls.busy = False


# -------------------------------------------------------------- order graph ----

def _path(src: str, dst: str) -> Optional[List[str]]:
    """A path src -> ... -> dst in the recorded graph (None if absent).
    Caller holds ``_state.mu``."""
    prev = {src: None}
    frontier = [src]
    while frontier:
        cur = frontier.pop()
        for nxt in _state.graph.get(cur, ()):
            if nxt in prev:
                continue
            prev[nxt] = cur
            if nxt == dst:
                out = [dst]
                while prev[out[-1]] is not None:
                    out.append(prev[out[-1]])
                return list(reversed(out))
            frontier.append(nxt)
    return None


def _check_order(target: str) -> None:
    """Record held->target edges; detect (and maybe raise on) a cycle
    BEFORE the caller blocks on the reversed acquire."""
    held = [name for _lk, name, _t in _held_stack() if name != target]
    if not held:
        return
    site = "".join(traceback.format_stack(sys._getframe(2), limit=3))
    with _state.mu:
        cycle = None
        for h in dict.fromkeys(held):  # ordered dedup
            rev = _path(target, h)
            if rev is not None and cycle is None:
                cycle = {"holding": h, "acquiring": target,
                         "reversed_path": rev,
                         "first_seen": _state.edge_sites.get(
                             (rev[0], rev[1]) if len(rev) > 1 else None,
                             "?"),
                         "thread": threading.current_thread().name,
                         "site": site}
            _state.graph.setdefault(h, set()).add(target)
            _state.edge_sites.setdefault((h, target), site)
        if cycle is not None:
            _state.cycles.append(cycle)
            raise_it = _state.raise_on_cycle
    if cycle is None:
        return
    _record_cycle_metric()
    msg = (f"lock-order cycle: thread {cycle['thread']!r} acquiring "
           f"{target!r} while holding {cycle['holding']!r}, but the "
           f"reversed order {' -> '.join(cycle['reversed_path'])} was "
           f"already recorded — opposite-order threads deadlock.\n"
           f"acquire site:\n{site}")
    if raise_it:
        raise LockOrderViolation(msg)
    log.error(msg)


def _record_cycle_metric() -> None:
    if getattr(_tls, "busy", False):
        return
    _tls.busy = True
    try:
        _registry().counter("lockwatch_cycles_total").inc()
    finally:
        _tls.busy = False


# ---------------------------------------------------------------- watchdog ----

def _thread_stacks() -> Dict[str, List[str]]:
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for ident, frame in sys._current_frames().items():
        key = f"{names.get(ident, '?')}({ident})"
        out[key] = traceback.format_stack(frame)
    return out


def _watchdog_dump(name: str, waited_s: float) -> None:
    """Blocked-too-long artifact: all thread stacks through the PR 7
    flight recorder when a tracer is configured, stderr log otherwise.
    Never raises — the watchdog must not mask the stall it reports."""
    with _state.mu:
        _state.watchdog_dumps += 1
    extra = {
        "lockwatch": {
            "lock": name,
            "waited_s": round(waited_s, 3),
            "thread": threading.current_thread().name,
            "held_elsewhere": sorted(
                {n for t in threading.enumerate()
                 for n in _held_names_of(t)}),
        },
        "thread_stacks": _thread_stacks(),
    }
    try:
        from deeplearning4j_tpu.telemetry import trace as _trace

        tracer = _trace.get_tracer()
        if tracer is not None:
            tracer.dump("lockwatch_blocked", extra=extra)
            return
    except Exception:
        pass
    try:
        log.error("lockwatch: blocked >%ss acquiring %r\n%s",
                  round(waited_s, 1), name,
                  "\n".join(f"--- {k}\n{''.join(v)}"
                            for k, v in extra["thread_stacks"].items()))
    except Exception:
        pass


def _held_names_of(thread: threading.Thread) -> List[str]:
    # best-effort: only the CURRENT thread's stack is visible through the
    # TLS; other threads' holdings show up in their dumped stacks instead
    if thread is threading.current_thread():
        return [name for _lk, name, _t in _held_stack()]
    return []


# ---------------------------------------------------------------- wrappers ----

class WatchedLock:
    """A ``threading.Lock`` with order/wait/hold recording when the watch
    is armed; a plain passthrough when not."""

    _reentrant = False

    def __init__(self, name: str, inner=None):
        self.name = name
        self._inner = inner if inner is not None else threading.Lock()

    # -- bookkeeping --
    def _depth(self) -> int:
        return sum(1 for lk, _n, _t in _held_stack() if lk is self)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if not _state.active:
            return self._inner.acquire(blocking, timeout)
        reentry = self._reentrant and self._depth() > 0
        # edges taken while EMITTING lockwatch metrics (the registry lock
        # under whatever lock is being recorded) are instrumentation, not
        # program order — they must not pollute the graph
        if not reentry and not getattr(_tls, "busy", False):
            _check_order(self.name)
        if not blocking:
            got = self._inner.acquire(False)
            if got:
                _held_stack().append((self, self.name, time.perf_counter()))
                if not reentry:
                    _record_acquire(self.name, 0.0, contended=False)
            return got
        t0 = time.perf_counter()
        deadline = None if timeout is None or timeout < 0 else t0 + timeout
        got = self._inner.acquire(True, 0.0005)  # fast path probe
        contended = not got
        dumped = False
        waited = 0.0
        while not got:
            # graftlint: allow[untimed-dispatch] host lock-wait clock — no device work in this window
            waited = time.perf_counter() - t0
            if deadline is not None and time.perf_counter() >= deadline:
                _record_acquire(self.name, waited, contended=True)
                return False
            chunk = (_state.watchdog_s if deadline is None
                     else min(_state.watchdog_s,
                              deadline - time.perf_counter()))
            got = self._inner.acquire(True, max(chunk, 0.001))
            if not got and not dumped and waited >= _state.watchdog_s:
                _watchdog_dump(self.name, waited)
                dumped = True  # one artifact per stuck acquire
        if not reentry:
            # graftlint: allow[untimed-dispatch] host lock-wait clock — no device work in this window
            _record_acquire(self.name, time.perf_counter() - t0, contended)
        _held_stack().append((self, self.name, time.perf_counter()))
        return True

    def release(self) -> None:
        # bookkeeping mirrors reality even if the watch was disabled
        # mid-hold — a stale stack entry would fabricate edges later
        stack = _held_stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] is self:
                _lk, name, t_acq = stack.pop(i)
                if _state.active and self._depth() == 0:
                    _record_release(name, time.perf_counter() - t_acq)
                break
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {self.name!r} {self._inner!r}>"


class WatchedRLock(WatchedLock):
    """Reentrant flavor: re-acquires by the owning thread record neither
    edges nor contention. Implements the ``Condition`` integration
    surface (``_is_owned``/``_release_save``/``_acquire_restore``) so
    ``threading.Condition(WatchedRLock(...))`` behaves exactly like one
    built on a plain RLock."""

    _reentrant = True

    def __init__(self, name: str, inner=None):
        super().__init__(name, inner if inner is not None
                         else threading.RLock())

    def locked(self) -> bool:  # RLock has no locked() on older CPythons
        probe = self._inner.acquire(False)
        if probe:
            self._inner.release()
        return not probe

    # -- Condition protocol --
    def _is_owned(self) -> bool:
        return self._inner._is_owned()

    def _release_save(self):
        # Condition.wait: drop ALL recursion levels; close out bookkeeping
        if _state.active:
            stack = _held_stack()
            t_first = None
            for i in range(len(stack) - 1, -1, -1):
                if stack[i][0] is self:
                    t_first = stack[i][2]
                    stack.pop(i)
            if t_first is not None:
                _record_release(self.name, time.perf_counter() - t_first)
        return self._inner._release_save()

    def _acquire_restore(self, saved) -> None:
        self._inner._acquire_restore(saved)
        if _state.active:
            _held_stack().append((self, self.name, time.perf_counter()))


def make_lock(name: str) -> "threading.Lock | WatchedLock":
    """The seam: a watched lock when the watch is armed (or
    ``DL4J_TPU_LOCKWATCH=1``), a plain ``threading.Lock`` otherwise."""
    if _armed_for_creation():
        return WatchedLock(name)
    return threading.Lock()


def make_rlock(name: str) -> "threading.RLock | WatchedRLock":
    if _armed_for_creation():
        return WatchedRLock(name)
    return threading.RLock()


def _armed_for_creation() -> bool:
    """Watched primitives are handed out while armed — and arming via the
    env var (a worker process launched with DL4J_TPU_LOCKWATCH=1) flips
    the full watch on at first lock creation."""
    if _state.active:
        return True
    if _env_armed():
        enable()
        return True
    return False


def make_condition(lock=None, name: str = "condition"):
    """A ``Condition`` over ``lock`` (a watched or plain lock; created via
    ``make_rlock(name)`` when omitted). Waiting on it records the hold
    handoff exactly like releasing the lock."""
    if lock is None:
        lock = make_rlock(name)
    return threading.Condition(lock)


# ---------------------------------------------------------------- snapshots ----

def graph_snapshot() -> Dict[str, List[str]]:
    """The observed lock-order graph, JSON-ready."""
    with _state.mu:
        return {a: sorted(bs) for a, bs in sorted(_state.graph.items())}


def cycles_detected() -> List[Dict]:
    with _state.mu:
        return [dict(c) for c in _state.cycles]


def summary() -> Dict:
    """Aggregate watch state: per-lock stats + graph + cycle/watchdog
    counts (what the bench detail and the stress tests assert on)."""
    with _state.mu:
        return {
            "locks": {n: dict(s) for n, s in sorted(_state.stats.items())},
            "graph": {a: sorted(bs)
                      for a, bs in sorted(_state.graph.items())},
            "cycles": len(_state.cycles),
            "watchdog_dumps": _state.watchdog_dumps,
        }


def metrics_record() -> Dict[str, float]:
    """Flat ``lockwatch_*`` keys for a telemetry step-log record —
    ``tools/telemetry_report.py`` renders these as its lockwatch section
    (silent when a log carries none)."""
    out: Dict[str, float] = {}
    with _state.mu:
        for name, s in sorted(_state.stats.items()):
            safe = name.replace(".", "_")
            out[f"lockwatch_{safe}_acquires"] = s["acquires"]
            out[f"lockwatch_{safe}_contended"] = s["contended"]
            out[f"lockwatch_{safe}_hold_ms_max"] = round(
                s["hold_ms_max"], 3)
            out[f"lockwatch_{safe}_hold_ms_mean"] = round(
                s["hold_ms_total"] / s["acquires"], 4) if s["acquires"] \
                else 0.0
            out[f"lockwatch_{safe}_wait_ms_max"] = round(
                s["wait_ms_max"], 3)
        if _state.cycles:
            out["lockwatch_cycles"] = float(len(_state.cycles))
        if _state.watchdog_dumps:
            out["lockwatch_watchdog_dumps"] = float(_state.watchdog_dumps)
    return out
