"""DiskBasedQueue (ref util/DiskBasedQueue.java, 205 LoC): FIFO whose
elements are spilled to disk so arbitrarily large work queues don't hold
memory. Elements are pickled one file per item under a spool directory."""

from __future__ import annotations

import os
import pickle
import tempfile
import threading
from collections import deque
from typing import Any, Optional


class DiskBasedQueue:
    def __init__(self, spool_dir: Optional[str] = None):
        self._dir = spool_dir or tempfile.mkdtemp(prefix="dl4j-queue-")
        os.makedirs(self._dir, exist_ok=True)
        self._order: deque = deque()
        self._seq = 0
        self._lock = threading.Lock()

    def add(self, item: Any) -> None:
        with self._lock:
            path = os.path.join(self._dir, f"item-{self._seq:012d}.pkl")
            self._seq += 1
            # graftlint: allow[blocking-under-lock] deliberate: disk IO IS this queue's critical section — seq/file/deque must commit atomically (ref DiskBasedQueue semantics)
            with open(path, "wb") as f:
                pickle.dump(item, f)
            self._order.append(path)

    def poll(self) -> Optional[Any]:
        """Remove and return the head, or None when empty."""
        with self._lock:
            if not self._order:
                return None
            path = self._order.popleft()
            # graftlint: allow[blocking-under-lock] deliberate: the read+unlink must be atomic with the dequeue or a concurrent peek() reads a vanishing file
            with open(path, "rb") as f:
                item = pickle.load(f)
            os.unlink(path)
            return item

    def peek(self) -> Optional[Any]:
        # read under the lock: a concurrent poll() may unlink the head file
        with self._lock:
            if not self._order:
                return None
            # graftlint: allow[blocking-under-lock] deliberate: reading the head under the lock is the documented guard against a concurrent poll() unlinking it
            with open(self._order[0], "rb") as f:
                return pickle.load(f)

    def is_empty(self) -> bool:
        with self._lock:
            return not self._order

    def __len__(self) -> int:
        with self._lock:
            return len(self._order)

    def clear(self) -> None:
        with self._lock:
            while self._order:
                os.unlink(self._order.popleft())
