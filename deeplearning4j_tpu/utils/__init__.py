"""Utility subsystem.

Parity with ref util/ (MathUtils, Viterbi, MovingWindowMatrix,
DiskBasedQueue) and the vendored berkeley/ NLP utilities (Counter,
CounterMap). Only the surface other components and user code actually
exercise is reproduced; pure-Java plumbing with no TPU relevance
(Dl4jReflection, StringGrid dedup, …) is intentionally out of scope.
"""

from deeplearning4j_tpu.utils.counter import Counter, CounterMap
from deeplearning4j_tpu.utils.disk_queue import DiskBasedQueue
from deeplearning4j_tpu.utils.math_utils import (
    clamp,
    entropy,
    information_gain,
    normalize_to_range,
    rounded,
    sigmoid,
    sum_of_squares,
    uniform,
)
from deeplearning4j_tpu.utils.moving_window import MovingWindowMatrix
from deeplearning4j_tpu.utils.viterbi import Viterbi

__all__ = [
    "Counter",
    "CounterMap",
    "DiskBasedQueue",
    "MovingWindowMatrix",
    "Viterbi",
    "clamp",
    "entropy",
    "information_gain",
    "normalize_to_range",
    "rounded",
    "sigmoid",
    "sum_of_squares",
    "uniform",
]
