"""Tracing/profiling utilities (SURVEY.md §5).

The reference's tracing story is ad-hoc StopWatch timing in the YARN worker
and per-job millisecond logging in the Akka WorkerActor heartbeat
(ref: impl/multilayer/WorkerNode.java totalRunTimeWatch/batchWatch,
actor/core/actor/WorkerActor.java:198-202). The TPU-native equivalent adds
the XLA profiler on top of those counters (optimize/listeners.py,
statetracker job_ms_total): device traces viewable in XProf/TensorBoard,
scoped host annotations, and device-memory introspection.
"""

from __future__ import annotations

import contextlib
import os
from typing import Dict, List, Optional

import jax


@contextlib.contextmanager
def trace(log_dir: str, *, create_perfetto_link: bool = False):
    """Capture an XLA device+host trace for the enclosed block.

    Produces an XProf/TensorBoard-compatible trace directory — the
    device-side truth for where step time goes (MXU vs HBM vs infeed),
    which host-side StopWatch timing (the reference's tool) cannot see.
    """
    os.makedirs(log_dir, exist_ok=True)
    with jax.profiler.trace(log_dir,
                            create_perfetto_link=create_perfetto_link):
        yield


def annotate(name: str):
    """Scoped host annotation shown on the trace timeline
    (e.g. ``with annotate("pretrain-layer0"): ...``)."""
    return jax.profiler.TraceAnnotation(name)


def named_scope(name: str):
    """In-graph twin of ``annotate``: names the ops traced inside the scope
    so DEVICE timelines (XProf) show the phase — use inside jitted code
    (ring K/V rotation, ulysses AllToAll, pp stage ticks, blockwise tiles),
    where the host-side TraceAnnotation would only mark trace time."""
    return jax.named_scope(name)


def device_memory_stats() -> List[Dict]:
    """Per-device live-memory stats (bytes in use / peak / limit where the
    backend reports them). Empty dict per device on backends without
    memory_stats (CPU)."""
    out = []
    for dev in jax.devices():
        stats = {}
        try:
            stats = dict(dev.memory_stats() or {})
        except Exception:
            pass
        out.append({"device": str(dev), **stats})
    return out


class ProfilerIterationListener:
    """IterationListener that traces a window of a live training run — drop
    it into net.listeners next to ScoreIterationListener (the listener-chain
    hook mirrors ref: optimize/api/IterationListener).

    Window semantics: listeners fire AFTER each iteration's compute, so the
    trace opens once the ``start``-th callback has fired and spans the NEXT
    ``steps`` iterations (callbacks start+1 … start+steps). The very first
    iteration's compile can therefore not be captured through this hook —
    wrap fit() in ``utils.profiling.trace`` for that. ``start=0`` opens the
    window at the first callback."""

    def __init__(self, log_dir: str, start: int = 1, steps: int = 3):
        self.log_dir = log_dir
        self.start = start
        self.steps = steps
        self._active = False
        self._done = False
        self._seen = 0
        self._traced = 0

    def __call__(self, model, iteration: int, score: float) -> None:
        self._seen += 1
        if self._active:
            self._traced += 1
            if self._traced >= self.steps:
                jax.profiler.stop_trace()
                self._active = False
                self._done = True
            return
        if not self._done and self._seen >= self.start:
            os.makedirs(self.log_dir, exist_ok=True)
            jax.profiler.start_trace(self.log_dir)
            self._active = True
            self._traced = 0

    def close(self) -> None:
        """Stop a still-open trace (training ended inside the window)."""
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
            self._done = True


def save_device_memory_profile(path: str) -> str:
    """Dump a pprof-format device memory profile (jax.profiler
    device_memory_profile) — allocation attribution for OOM hunts."""
    blob = jax.profiler.device_memory_profile()
    with open(path, "wb") as f:
        f.write(blob)
    return path
